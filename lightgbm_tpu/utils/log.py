"""Leveled logger mirroring the reference's Log facility.

Reference: include/LightGBM/utils/log.h:43-104 — leveled, thread-local level,
optional callback sink. Here: a thin layer over `logging` with the same levels
(Fatal raises, matching Log::Fatal's process-abort role in a library context).
"""
from __future__ import annotations

import logging

_logger = logging.getLogger("lightgbm_tpu")

# Handler-identity marker: the logging module's logger dict outlives this
# module object, so a re-import (pytest importmode variations, importlib
# reload) sees the logger again. Guarding on `_logger.handlers` truthiness
# is wrong in both directions — a foreign handler (pytest's caplog, an
# embedding app) would suppress OUR handler entirely, while our own handler
# from a previous import is indistinguishable from one. Tag the handler and
# guard on the tag.
_HANDLER_TAG = "_lightgbm_tpu_handler"
if not any(getattr(h, _HANDLER_TAG, False) for h in _logger.handlers):
    _h = logging.StreamHandler()
    _h.setFormatter(logging.Formatter("[LightGBM-TPU] [%(levelname)s] %(message)s"))
    setattr(_h, _HANDLER_TAG, True)
    _logger.addHandler(_h)
    _logger.setLevel(logging.INFO)


class LightGBMError(Exception):
    """Raised where the reference would Log::Fatal (log.h:93)."""


class Log:
    @staticmethod
    def set_level(verbose: int) -> None:
        # reference verbosity semantics: <0 fatal-only, 0 warning, 1 info, >1 debug
        if verbose < 0:
            _logger.setLevel(logging.CRITICAL)
        elif verbose == 0:
            _logger.setLevel(logging.WARNING)
        elif verbose == 1:
            _logger.setLevel(logging.INFO)
        else:
            _logger.setLevel(logging.DEBUG)

    @staticmethod
    def debug(msg: str, *args) -> None:
        _logger.debug(msg, *args)

    @staticmethod
    def info(msg: str, *args) -> None:
        _logger.info(msg, *args)

    @staticmethod
    def warning(msg: str, *args) -> None:
        _logger.warning(msg, *args)

    @staticmethod
    def fatal(msg: str, *args) -> None:
        raise LightGBMError(msg % args if args else msg)
