"""Leveled logger mirroring the reference's Log facility.

Reference: include/LightGBM/utils/log.h:43-104 — leveled, thread-local level,
optional callback sink. Here: a thin layer over `logging` with the same levels
(Fatal raises, matching Log::Fatal's process-abort role in a library context).
"""
from __future__ import annotations

import logging

_logger = logging.getLogger("lightgbm_tpu")
if not _logger.handlers:
    _h = logging.StreamHandler()
    _h.setFormatter(logging.Formatter("[LightGBM-TPU] [%(levelname)s] %(message)s"))
    _logger.addHandler(_h)
    _logger.setLevel(logging.INFO)


class LightGBMError(Exception):
    """Raised where the reference would Log::Fatal (log.h:93)."""


class Log:
    @staticmethod
    def set_level(verbose: int) -> None:
        # reference verbosity semantics: <0 fatal-only, 0 warning, 1 info, >1 debug
        if verbose < 0:
            _logger.setLevel(logging.CRITICAL)
        elif verbose == 0:
            _logger.setLevel(logging.WARNING)
        elif verbose == 1:
            _logger.setLevel(logging.INFO)
        else:
            _logger.setLevel(logging.DEBUG)

    @staticmethod
    def debug(msg: str, *args) -> None:
        _logger.debug(msg, *args)

    @staticmethod
    def info(msg: str, *args) -> None:
        _logger.info(msg, *args)

    @staticmethod
    def warning(msg: str, *args) -> None:
        _logger.warning(msg, *args)

    @staticmethod
    def fatal(msg: str, *args) -> None:
        raise LightGBMError(msg % args if args else msg)
