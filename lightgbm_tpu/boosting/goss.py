"""GOSS: Gradient-based One-Side Sampling (reference: src/boosting/goss.hpp).

Keeps the top `top_rate` fraction of rows by sum-over-classes |grad*hess|
(goss.hpp:88-98), Bernoulli-samples `other_rate` of the rest and up-weights
their gradients/hessians by (1-top_rate)/other_rate-style multiplier
(goss.hpp:100-126). Sampling starts only after 1/learning_rate iterations
(goss.hpp:134-137). Mask-based: selected-out rows get weight 0 instead of
being compacted out of an index array.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..config import Config
from ..utils.log import Log
from .gbdt import GBDT


class GOSS(GBDT):
    # conservative: the sampling warm-up boundary (1/learning_rate) and its
    # interaction with fused batches is unvalidated — GBDT.__init__ falls
    # back to tree_batch=1 with a warning
    supports_tree_batch = False

    def __init__(self, config: Config, train_set, objective=None):
        super().__init__(config, train_set, objective)
        if config.bagging_freq > 0 and config.bagging_fraction != 1.0:
            Log.fatal("Cannot use bagging in GOSS")
        Log.info("Using GOSS")
        self.bagging_on = False

    def _sampling(self, g, h, bag_mask, key, it):
        cfg = self.config
        N = self.num_data
        top_k = max(1, int(N * cfg.top_rate))
        other_k = max(1, int(N * cfg.other_rate))
        warmup = int(1.0 / cfg.learning_rate)

        weights = jnp.sum(jnp.abs(g * h), axis=0) * self.pad_mask  # [Npad]
        # exactly top_k rows even on tied |g*h| (ties broken by row index,
        # like the reference's sort-then-cut, goss.hpp:94-98)
        _, top_idx = jax.lax.top_k(weights, top_k)
        is_top = (jnp.zeros(weights.shape, bool).at[top_idx].set(True)
                  & (self.pad_mask > 0))
        rest = (~is_top) & (self.pad_mask > 0)
        prob = other_k / max(N - top_k, 1)
        sel_other = rest & (jax.random.uniform(key, weights.shape) < prob)
        multiply = (N - top_k) / other_k

        goss_mask = (is_top | sel_other).astype(jnp.float32)
        scale = jnp.where(sel_other, multiply, 1.0)[None, :]

        use_goss = it >= warmup
        mask = jnp.where(use_goss, goss_mask, self.pad_mask)
        g = jnp.where(use_goss, g * scale, g)
        h = jnp.where(use_goss, h * scale, h)
        return mask, g, h
