"""Random Forest mode (reference: src/boosting/rf.hpp).

Bagging is mandatory; gradients are always computed at zero scores so the
trees are independent (rf.hpp:97-104); each tree's leaf outputs go through the
objective's ConvertOutput (rf.hpp:160-167); the maintained score is the
running average of converted tree outputs (rf.hpp:117-121), and prediction
averages tree outputs without a final transform (average_output).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..config import Config
from ..utils.log import Log
from .gbdt import GBDT


class RF(GBDT):
    average_output = True

    def __init__(self, config: Config, train_set, objective=None):
        super().__init__(config, train_set, objective)
        if not (config.bagging_freq > 0 and 0.0 < config.bagging_fraction < 1.0):
            Log.fatal("RF mode requires 0 < bagging_fraction < 1 and bagging_freq > 0")
        if self.num_models != 1:
            Log.fatal("Cannot use RF for multi-class (rf.hpp:42)")
        Log.info("Using random forest")

    def _gradients(self, score):
        # trees are independent: gradients at zero score (rf.hpp:97-104)
        return self.objective.gradients(jnp.zeros_like(score), self.label, self.weight)

    def _tree_output_transform(self, tree):
        return tree._replace(
            leaf_value=self.objective.convert_output(tree.leaf_value))

    def _score_update(self, old_score_k, contrib, it):
        itf = it.astype(jnp.float32)
        return (old_score_k * itf + contrib) / (itf + 1.0)

    def _step_shrinkage(self) -> float:
        # shrinkage is 1 for RF (rf.hpp:44-45); every hook stays
        # device-resident, so RF keeps tree_batch fusion
        return 1.0
