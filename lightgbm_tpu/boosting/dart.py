"""DART: Dropouts meet Multiple Additive Regression Trees
(reference: src/boosting/dart.hpp).

Per iteration: select a drop set among previous trees (weighted or uniform,
dart.hpp:85-112), subtract their contribution from the training/validation
scores, train the new tree with shrinkage lr/(1+k) (xgboost mode: lr/(lr+k)),
then renormalize the dropped trees by k/(k+1) (xgboost mode: k/(k+lr))
(dart.hpp:133-180). Dropped-tree contributions are recomputed by binned
traversal (ops/predict.py) — the TPU analog of ScoreUpdater::AddScore on a
negatively-shrunk tree.
"""
from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..ops.predict import leaves_from_binned
from ..utils.log import Log
from .gbdt import GBDT


class DART(GBDT):
    # host-side per-iteration drop-set selection + score renormalization
    # cannot fuse into a device-resident scan — GBDT.__init__ falls back to
    # tree_batch=1 with a warning
    supports_tree_batch = False
    # the drop-set replay reads the RESIDENT code matrix per tree
    # (_contrib_fn over self.Xb) — out-of-core streaming has no such array
    supports_stream = False

    def __init__(self, config: Config, train_set, objective=None):
        super().__init__(config, train_set, objective)
        Log.info("Using DART")
        if config.nan_policy in ("raise", "skip_iter"):
            # the gated no-op step composes with DART's host-side drop/
            # renormalize arithmetic incorrectly (the post-step correction
            # would re-add dropped contributions a skipped step never took
            # out) — only the in-step policies are sound here
            Log.fatal("nan_policy=%s is not supported with boosting=dart "
                      "(use none or clip)", config.nan_policy)
        self.tree_weight: List[float] = []
        self.sum_weight = 0.0
        self._drop_rng = np.random.default_rng(config.drop_seed)
        # train matrix may be EFB-bundled; valid matrices never are.
        # Feature metadata travels as jit arguments (multi-host forbids
        # closing over arrays spanning non-addressable devices).
        self._contrib_fn = jax.jit(
            lambda tree, Xb, nb, mc, db: self._tree_contrib(
                tree, Xb, nb, mc, db, self.bundle))
        self._contrib_fn_valid = jax.jit(
            lambda tree, Xb, nb, mc, db: self._tree_contrib(
                tree, Xb, nb, mc, db, None))

    def _tree_contrib(self, tree, Xb, num_bins, missing_code, default_bin,
                      bundle):
        leaves = leaves_from_binned(tree, Xb, num_bins, missing_code,
                                    default_bin, bundle=bundle)
        return tree.leaf_value[leaves]

    def _select_drop(self) -> List[int]:
        cfg = self.config
        n = self.iter_
        if n == 0 or self._drop_rng.random() < cfg.skip_drop:
            return []
        drop = []
        if not cfg.uniform_drop:
            inv_avg = len(self.tree_weight) / self.sum_weight if self.sum_weight > 0 else 0.0
            rate = cfg.drop_rate
            if cfg.max_drop > 0 and self.sum_weight > 0:
                rate = min(rate, cfg.max_drop * inv_avg / self.sum_weight)
            for i in range(n):
                if self._drop_rng.random() < rate * self.tree_weight[i] * inv_avg:
                    drop.append(i)
        else:
            rate = cfg.drop_rate
            if cfg.max_drop > 0:
                rate = min(rate, cfg.max_drop / max(n, 1))
            for i in range(n):
                if self._drop_rng.random() < rate:
                    drop.append(i)
        return drop

    def train_one_iter(self) -> None:
        cfg = self.config
        lr = cfg.learning_rate
        drop = self._select_drop()
        k = len(drop)
        if cfg.xgboost_dart_mode:
            shrinkage = lr if k == 0 else lr / (lr + k)
            factor = k / (k + lr) if k else 0.0
        else:
            shrinkage = lr / (1.0 + k)
            factor = k / (k + 1.0) if k else 0.0

        K = self.num_models
        if k:
            drop_train = jnp.zeros_like(self.score)
            drop_valid = [jnp.zeros_like(vs.score) for vs in self.valid_sets]
            nb, mc, db = self.num_bins, self.missing_code, self.default_bin
            for i in drop:
                for c in range(K):
                    tree = self.models[i][c]
                    drop_train = drop_train.at[c].add(
                        self._contrib_fn(tree, self.Xb, nb, mc, db))
                    for vi, vs in enumerate(self.valid_sets):
                        drop_valid[vi] = drop_valid[vi].at[c].add(
                            self._contrib_fn_valid(tree, vs.Xb, nb, mc, db))
            score_adj = self.score - drop_train
            for vi, vs in enumerate(self.valid_sets):
                vs.score = vs.score - drop_valid[vi]
        else:
            score_adj = self.score

        score, out_valid = self._run_step(score_adj, shrinkage)
        if k:
            score = score + drop_train * factor
        self.score = score
        for vi, vs in enumerate(self.valid_sets):
            new_v = jnp.stack(out_valid[vi])
            vs.score = new_v + drop_valid[vi] * factor if k else new_v

        # permanently renormalize the dropped trees (dart.hpp:138-158)
        for i in drop:
            for c in range(K):
                t = self.models[i][c]
                self.models[i][c] = t._replace(leaf_value=t.leaf_value * factor)
            if not cfg.uniform_drop:
                if cfg.xgboost_dart_mode:
                    self.sum_weight -= self.tree_weight[i] * (1.0 / (k + lr))
                else:
                    self.sum_weight -= self.tree_weight[i] * (1.0 / (k + 1.0))
                self.tree_weight[i] *= factor
        self.tree_weight.append(shrinkage)
        self.sum_weight += shrinkage
