"""GBDT boosting driver — the reference's training loop, device-resident.

Reference: src/boosting/gbdt.{cpp,h}. The per-iteration pipeline
(gbdt.cpp:379-473) — Boosting() gradients -> Bagging -> per-class
tree_learner->Train -> Shrinkage -> UpdateScore — is compiled into ONE jitted
`step` whose tree growth runs a device-side while_loop (grower.py). The host
loop only enqueues steps and fetches scores at eval points; on the axon
tunnel a host sync costs ~67ms (exp/RESULTS.md), so nothing in the hot loop
blocks.

Semantics kept from the reference:
- boost-from-average initial score folded into the first tree as a bias
  (gbdt.cpp:357-377 + AddBias :445-447),
- bagging re-sampled every `bagging_freq` iterations (gbdt.cpp:225-270;
  mask-based Bernoulli instead of exact-count index partition — OOB rows are
  excluded from histograms/counts but still routed so score updates stay
  O(N) gathers),
- per-tree feature_fraction sampling (serial_tree_learner.cpp:240-252),
- training stops when no tree in an iteration could split
  (gbdt.cpp:465-471), checked at sync points,
- early stopping on validation metrics (gbdt.cpp:493-518).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as obs
from ..observability import costs as obs_costs
from ..config import Config
from ..dataset import ConstructedDataset, Metadata, MetadataDuckTyping
from ..grower import GrowerSpec, TreeArrays, grow_tree, waves_for_tree
from ..ops.histogram import table_lookup
from ..parallel.comm import make_parallel_context
from ..metrics import Metric, create_metrics
from ..robustness import allowed_host_sync
from ..utils.timer import TIMERS
from ..objectives import Objective, create_objective
from ..ops.predict import leaves_from_binned
from ..tree import Tree, tree_from_device_arrays
from ..utils.log import Log


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


# one-time (per process) EFB-on-TPU throughput warning — the measured loss
# is per-workload, not per-booster, so repeating it per construction is noise
_EFB_TPU_WARNED = [False]


class ValidSet(MetadataDuckTyping):
    # the mixin supplies the duck-typed Dataset surface so user fevals
    # written against the reference python-package contract keep working
    def __init__(self, name: str, Xb_dev: jnp.ndarray, metadata: Metadata,
                 metrics: List[Metric], num_data: int):
        self.name = name
        self.Xb = Xb_dev
        self.metadata = metadata
        self.metrics = metrics
        self.num_data = num_data
        self.score: Optional[jnp.ndarray] = None
        # linear_tree=true only: device raw-feature slice (NaN-sanitized)
        # + missing plane for the valid-score linear epilogue
        self.Xraw: Optional[jnp.ndarray] = None
        self.Xmiss: Optional[jnp.ndarray] = None


from ..analysis.contracts.registry import trace_entry


@trace_entry("train_step.fused")
class GBDT:
    """Boosting driver (reference class GBDT, src/boosting/gbdt.h:25)."""

    average_output = False  # RF overrides (boosting.h average_output_)
    # fused multi-tree steps (tree_batch > 1) need every per-iteration hook
    # to be device-resident; DART/GOSS override to False and fall back to 1
    supports_tree_batch = True

    def __init__(self, config: Config, train_set: ConstructedDataset,
                 objective: Optional[Objective] = None):
        self.config = config
        self.train_set = train_set
        # multi-host wiring FIRST — jax.distributed.initialize must run
        # before anything touches the XLA backend (mirrors the reference's
        # Network::Init-before-LoadData ordering, application.cpp:167-178)
        from ..parallel.comm import init_distributed
        init_distributed(config)
        self.objective = objective if objective is not None else create_objective(config)
        self.num_models = self.objective.num_models if self.objective else max(config.num_class, 1)
        K = self.num_models

        # ---- device mesh / parallel strategy (reference Network::Init,
        #      application.cpp:167-178; tree_learner grid tree_learner.cpp:9).
        #      The training matrix shape rides along so tree_learner=auto can
        #      resolve the mesh axis (rows vs features) from the shape class
        #      (parallel/comm.py choose_tree_learner). --
        self.pctx = make_parallel_context(
            config, shape=(train_set.num_data, train_set.num_features))

        # ---- pre-partitioned data (reference dataset_loader.cpp:159-221 +
        #      Metadata::CheckOrPartition): under is_pre_partition each
        #      process loaded ONLY its own row shard, so the global row space
        #      is assembled as equal per-process blocks — the feature matrix
        #      stays process-local (the memory that matters at scale) while
        #      the cheap metadata (4-8 B/row) is gathered host-side so
        #      boost-from-average / objectives / metrics see global stats. --
        N = train_set.num_data
        meta_global = train_set.metadata
        self._block_counts: Optional[List[int]] = None
        if (config.is_pre_partition and self.pctx.multi_process
                and self.pctx.strategy in ("data", "voting")):
            from ..parallel.comm import host_allgather
            md = train_set.metadata
            blocks = host_allgather(
                dict(n=int(N), label=np.asarray(md.label, np.float32),
                     weight=None if md.weight is None
                     else np.asarray(md.weight, np.float32),
                     qsizes=None if md.query_boundaries is None
                     else np.diff(md.query_boundaries).astype(np.int64),
                     init_score=None if md.init_score is None
                     else np.asarray(md.init_score, np.float32)),
                "pre_partition_meta")
            self._block_counts = [int(b["n"]) for b in blocks]
            N = int(sum(self._block_counts))
            meta_global = Metadata(N)
            meta_global.set_label(np.concatenate([b["label"] for b in blocks]))

            def _all_or_none(key, what):
                have = sum(b[key] is not None for b in blocks)
                if have not in (0, len(blocks)):
                    Log.fatal("is_pre_partition: %d of %d shards have %s — "
                              "every shard must provide them or none",
                              have, len(blocks), what)
                return bool(have)

            if _all_or_none("weight", "weights"):
                meta_global.set_weight(
                    np.concatenate([b["weight"] for b in blocks]))
            # ranking: each shard holds WHOLE queries (the reference loads
            # full queries per machine and rebuilds query_boundaries from
            # the used-row set, metadata.cpp:97-127); the global query list
            # is the block-ordered concatenation of per-shard query sizes
            if _all_or_none("qsizes", "query/group data"):
                meta_global.set_group(
                    np.concatenate([b["qsizes"] for b in blocks]))
            if _all_or_none("init_score", "init_score"):
                # per-shard arrays are (k*n_b,) class-major with a common k
                k = max(len(blocks[0]["init_score"]) // max(blocks[0]["n"], 1),
                        1)
                if any(len(b["init_score"]) != k * b["n"] for b in blocks):
                    Log.fatal("is_pre_partition: init_score length must be "
                              "the same per-row multiple on every shard")
                meta_global.set_init_score(np.concatenate(
                    [b["init_score"].reshape(k, b["n"]) for b in blocks],
                    axis=1).reshape(-1))
            Log.info("pre-partitioned data: %d rows across %d processes %s",
                     N, len(blocks), self._block_counts)
        self._meta_global = meta_global

        if self.objective is not None:
            self.objective.init(meta_global, N)

        F = train_set.num_features
        # feature padding: block-partitioned strategies need F % devices == 0
        F_pad = self.pctx.pad_features_to(max(F, 1))
        # row padding: per-device rows must be a chunk multiple; equal
        # per-process blocks under pre-partition (the largest shard sizes
        # every block so local data always fits its block)
        Drow = self.pctx.pad_rows_multiple()
        n_for_pad = N if self._block_counts is None else \
            max(self._block_counts) * len(self._block_counts)
        per_target = max((n_for_pad + Drow - 1) // Drow, 1)

        meta = train_set.feature_meta_arrays()
        num_leaves = config.max_leaves_by_depth
        Bpad = max(8, _round_up(train_set.max_num_bin, 8))

        # ---- EFB bundling (reference Dataset::Construct enable_bundle path,
        #      dataset.cpp:236-247): pack near-exclusive features into fewer
        #      histogram columns, for EVERY learner strategy — EFB precedes
        #      learner choice in the reference too (dataset.cpp:66-210).
        #      NATIVE default: bundle space is the representation end-to-end
        #      — the split scan runs on bundled bins directly
        #      (ops/split_finder.per_feature_best_bundled, the reference's
        #      FeatureGroup discipline), data-parallel reduce-scatters
        #      bundle-column blocks (DataParallelBundledComm), voting psums
        #      selected bundle columns, and row routing compares bundled
        #      codes against the split's bundle range. The legacy
        #      tpu_efb_unpack arm keeps the pre-redesign layout (unpack to
        #      [T, F, B, 3] before the scan; per-row decode in routing) as
        #      the A/B + parity pin.
        #      - feature-parallel: BUNDLES are the partitioned unit
        #        (FeatureParallelBundledComm — the reference partitions
        #        post-EFB feature groups the same way);
        #      - pre-partitioned: per-shard row samples are KV-allgathered so
        #        every rank plans from the IDENTICAL sample (the reference
        #        plans bundles from the same distributed sample it bins from,
        #        dataset_loader.cpp:820-899), then materializes its local
        #        shard against the common plan. ----
        self.bundle = None
        bundle_plan = None
        # legacy unpack arm (tpu_efb_unpack). The one unsupported native
        # combination — voting + categorical (the PV-Tree phase-2
        # selected-column scan is numerical-only in bundle space,
        # parallel/comm.py scan_slot_b) — forces the legacy arm HERE,
        # before any engagement logging/warning reads the arm, rather
        # than silently dropping categorical candidates; the warning
        # fires below only if bundling actually engages
        self._efb_unpack = bool(config.tpu_efb_unpack)
        _efb_unpack_forced = False
        if (not self._efb_unpack and self.pctx.strategy == "voting"
                and bool(meta["is_categorical"].any())):
            self._efb_unpack = True
            _efb_unpack_forced = True
        if config.enable_bundle != "false" and F >= 2:
            from ..efb import (_SAMPLE_ROWS, plan_bundles,
                               sample_row_indices, sample_rows)
            efb_sample = None
            efb_ndata = None
            X_for_plan = None
            if self._block_counts is not None:
                from ..parallel.comm import host_allgather
                per_rank = max(1, _SAMPLE_ROWS // len(self._block_counts))
                parts = host_allgather(
                    sample_rows(train_set.X_binned, per_rank), "efb_sample")
                efb_sample = np.concatenate(parts, axis=0)
                efb_ndata = N
                X_for_plan = train_set.X_binned
            elif train_set.deferred:
                # deferred device ingest: plan from a host-binned row
                # SAMPLE (the plan is a pure function of the sample, and
                # bin_rows draws the exact rows sample_rows would) — the
                # full host bin matrix is only materialized below if the
                # plan actually wins
                efb_sample = train_set.bin_rows(sample_row_indices(N))
                efb_ndata = N
            else:
                X_for_plan = train_set.X_binned
            plan = plan_bundles(X_for_plan,
                                meta["num_bins"].astype(np.int64),
                                meta["default_bin"].astype(np.int64), config,
                                sample=efb_sample, num_data=efb_ndata)
            if plan is not None:
                Bb_pad = max(8, _round_up(plan.max_bundle_bins, 8))
                # the BundlePlan win ratio: bundling wins when it shrinks
                # the one-hot matmul (G*Bb < F*B), OR when it at least
                # halves the column count without growing the matmul much
                # — the per-wave row gather and the HBM footprint scale
                # with raw column count, so a Bosch-shaped matrix (many
                # low-bin exclusive columns) wins even at equal matmul
                # width, EFB's "densifier" role for sparse data
                # (dataset.cpp:236-247, sparse_bin.hpp:68). With the
                # bundle-space scan the decode tax the round-5 bench
                # measured is gone, so this ratio IS the crossover:
                # enable_bundle=auto resolves per shape class the way
                # tpu_hist_kernel=auto does, enable_bundle=true engages
                # any plan regardless.
                shrinks_matmul = plan.num_groups * Bb_pad < 0.9 * F * Bpad
                shrinks_cols = (plan.num_groups * 2 <= F
                                and plan.num_groups * Bb_pad <= 1.25 * F * Bpad)
                wins = shrinks_matmul or shrinks_cols
                if config.enable_bundle == "auto":
                    Log.debug(
                        "enable_bundle=auto resolved to %s (%d features -> "
                        "%d bundles, matmul %d vs %d columns)",
                        "true" if wins else "false", F, plan.num_groups,
                        plan.num_groups * Bb_pad, F * Bpad)
                if wins or config.enable_bundle == "true":
                    bundle_plan = plan
                    if plan.X_bundled is None:
                        # the plan won under deferred ingest: bundling
                        # needs the host bin matrix after all — pay the
                        # host materialization now (device ingest serves
                        # the unbundled layout only)
                        from ..efb import materialize_bundles
                        plan.X_bundled = materialize_bundles(
                            plan, train_set.X_binned,
                            meta["default_bin"].astype(np.int64))
                    if _efb_unpack_forced:
                        Log.warning(
                            "tree_learner=voting with categorical features "
                            "keeps the legacy EFB unpack arm "
                            "(tpu_efb_unpack=true forced)")
                    Log.info("EFB: %d features bundled into %d columns "
                             "(%d max bundle bins), scan=%s", F,
                             plan.num_groups, plan.max_bundle_bins,
                             "unpack (legacy tpu_efb_unpack arm)"
                             if self._efb_unpack else "bundle-space")
                    if (self.pctx.devices[0].platform == "tpu"
                            and self._efb_unpack
                            and not _EFB_TPU_WARNED[0]):
                        # the round-5 "EFB hurts on TPU" warning is RETIRED
                        # on the default arm: bundle-space split finding
                        # removed the decode gather it measured (1.1 vs 3.8
                        # Mrow-tree/s, exp/HARVEST_r5.jsonl). Only the
                        # legacy unpack arm still pays that layout.
                        _EFB_TPU_WARNED[0] = True
                        Log.warning(
                            "tpu_efb_unpack=true on the TPU backend: the "
                            "legacy unpack arm measured a 3.5x throughput "
                            "LOSS on the round-5 Bosch-shaped benchmark "
                            "(1.1 vs 3.8 Mrow-tree/s — bundle decode "
                            "dominates; docs/TPU-Performance.md). It "
                            "exists as the A/B + parity arm; drop the "
                            "knob for the bundle-space default")

        # ---- histogram kernel choice (needs the FINAL kernel shape class,
        #      hence after EFB planning). "auto" resolves to the MIXED
        #      dispatch (XLA streaming passes, pallas-512 compacted passes —
        #      the round-5 pass-level measured best) on a real TPU whose
        #      on-chip gate (exp/pallas_onchip_check.py — the analog of the
        #      reference's GPU_DEBUG_COMPARE, gpu_tree_learner.cpp:1018-1043)
        #      has validated THIS kernel shape class, and to the XLA one-hot
        #      matmul everywhere else (Mosaic lowering failures are
        #      shape-triggered, round-5 gate log). Explicit pallas/mixed on
        #      an un-gated shape still runs, with the warning below.
        # auto slots: 25 x 5 bf16 channels = 125 matmul columns — one full
        # MXU tile (128) — while quartering the wave count at 255 leaves.
        # User-set slot counts clamp to the leaf budget: the wave loop's
        # top_k over [num_leaves+1] gains requires S <= num_leaves.
        slots = config.tpu_hist_slots or max(1, min(25, num_leaves - 1))
        slots = max(1, min(slots, num_leaves))
        # single source for the kernel shape (cols_pad / Bb_pad are REUSED
        # by the bundle materialization below — recomputing them there
        # risked the dispatched shape diverging from what was decided here)
        if bundle_plan is not None:
            G_raw = bundle_plan.X_bundled.shape[1]
            if self.pctx.strategy == "feature" or (
                    self.pctx.strategy == "data" and not self._efb_unpack):
                # bundle blocks are the partition unit (feature-parallel
                # always; data-parallel on the native arm, where the
                # psum_scatter runs over bundle blocks): G % devices == 0
                cols_pad = self.pctx.pad_features_to(G_raw)
            else:
                cols_pad = G_raw
        else:
            cols_pad = F_pad
        chunk = min(config.tpu_hist_chunk, _round_up(per_target, 256))
        # ONE kernel shape-class key, shared by every gate consult below
        # (the auto->mixed resolution AND the explicit pallas/mixed warning):
        # two hand-synced constructions would let auto trust a different
        # shape class than the one the warning path checks — exactly the
        # Mosaic-failure class the gate exists to prevent.
        from ..utils.cache import pallas_config_key, pallas_validated_on_chip
        _kernel_dtype = (bundle_plan.X_bundled.dtype
                         if bundle_plan is not None
                         else train_set.code_dtype)
        _kernel_bins = Bb_pad if bundle_plan is not None else Bpad
        pallas_shape_key = pallas_config_key(
            int(np.dtype(_kernel_dtype).itemsize), _kernel_bins,
            slots, cols_pad, 5 if config.tpu_hist_hilo else 3)
        # ---- residency (ROADMAP item 3, docs/TPU-Performance.md): decide
        #      BEFORE any device placement whether the binned code matrix
        #      is HBM-resident ("device") or streams from host shards
        #      ("stream", ops/stream.py). "auto" streams iff the analytic
        #      device-residency estimate exceeds the per-device HBM budget
        #      (tpu_hbm_budget_bytes / LGBM_TPU_HBM_BUDGET / reported
        #      capacity) — the PR-6 pre-flight's WARN upgraded to an
        #      automatic fallback. Uses a provisional Npad (the pallas
        #      chunk shrink below can only lower it, and stream forces the
        #      xla kernel anyway). ----
        self.residency = self._resolve_residency(
            config, per_target=per_target, chunk=chunk,
            cols_pad=cols_pad, code_itemsize=int(
                np.dtype(_kernel_dtype).itemsize),
            bins_pad=Bpad, bins_hist=_kernel_bins, slots=slots,
            num_leaves=num_leaves, num_models=K)
        if self.residency == "stream" and config.tpu_row_compact:
            # normalize the config to its EFFECTIVE semantics (stream runs
            # full streaming passes — no compaction) so the checkpoint
            # fingerprint covers what actually trains: a streamed run then
            # resumes into tpu_residency=device + tpu_row_compact=false
            # with bit-identical continued training
            config = config.replace(tpu_row_compact=False)
            self.config = config

        hist_kernel = config.tpu_hist_kernel
        if self.residency == "stream":
            # the streamed shard pass is the XLA one-hot matmul: the pallas
            # kernel only serves COMPACTED passes, and stream mode runs
            # full streaming passes by construction (row compaction needs
            # the packed row matrix device-resident — the very thing
            # streaming removes)
            if hist_kernel in ("pallas", "mixed"):
                Log.warning("tpu_residency=stream streams full histogram "
                            "passes through the xla kernel; overriding "
                            "tpu_hist_kernel=%s", hist_kernel)
            hist_kernel = "xla"
        if hist_kernel == "auto":
            # Round-5 pass-level shootout (exp/kern_bench_r5.py): pallas-512
            # wins COMPACTED passes (18.0 vs 22.1 ms at 25% active) while
            # the XLA one-hot matmul wins full streaming passes (33.7 vs
            # 39.4/55.0) — the measured-best dispatch is MIXED. With the
            # incremental partition (grower.py) removing the per-wave
            # argsort that used to tax every compacted pass, the compacted
            # kernel drives the steady state, so auto now defaults to mixed
            # — but ONLY where the on-chip equality gate has validated this
            # exact kernel shape class on this machine/libtpu (Mosaic
            # lowering failures are shape-triggered, round-5 gate log).
            # Un-gated shape classes and non-TPU platforms keep plain xla.
            hist_kernel = "xla"
            if (not config.tpu_hist_f64
                    and self.pctx.devices[0].platform == "tpu"
                    and pallas_validated_on_chip(pallas_shape_key)):
                hist_kernel = "mixed"
            Log.debug("tpu_hist_kernel=auto resolved to %s%s", hist_kernel,
                      " (on-chip gate validated this shape class)"
                      if hist_kernel == "mixed" else "")
        if config.tpu_hist_f64 and hist_kernel in ("pallas", "mixed"):
            Log.warning("tpu_hist_f64 requires the xla histogram kernel; "
                        "overriding tpu_hist_kernel=%s", hist_kernel)
            hist_kernel = "xla"
        if hist_kernel == "pallas":
            # measured fastest grid step AND safely inside the 16MB scoped
            # VMEM limit (2048-row chunks OOM the in-kernel one-hot
            # intermediates; exp/chain_profile.py)
            chunk = min(chunk, 512)
        Npad = _round_up(per_target, chunk) * Drow
        self.num_data = N
        self.num_data_padded = Npad
        if (self._block_counts is not None and self.objective is not None
                and hasattr(self.objective, "set_row_layout")):
            # pre-partition: real rows sit at interleaved block positions,
            # not [0, N) — give structured objectives (lambdarank) the
            # global-row -> device-position map so their gathers stay valid
            self.objective.set_row_layout(
                np.asarray(self._real_rows()), Npad)

        self._num_bundles_padded = 0
        if bundle_plan is not None:
            # Bb_pad / cols_pad fixed above, with the kernel shape class
            Xb = bundle_plan.X_bundled
            self._num_bundles_padded = cols_pad
            fpad = F_pad - F
            ub = np.pad(bundle_plan.unpack_bin,
                        ((0, fpad), (0, Bpad - bundle_plan.unpack_bin.shape[1])),
                        constant_values=-1)
            from ..efb import build_code_feat
            from ..grower import BundleDecode
            cf = build_code_feat(bundle_plan, cols_pad, Bb_pad,
                                 meta["default_bin"].astype(np.int64))
            self.bundle = BundleDecode(
                col=self._put(np.pad(bundle_plan.col, (0, fpad))),
                lo=self._put(np.pad(bundle_plan.lo, (0, fpad))),
                hi=self._put(np.pad(bundle_plan.hi, (0, fpad))),
                off=self._put(np.pad(bundle_plan.off, (0, fpad))),
                unpack_bin=self._put(ub),
                code_feat=self._put(cf))
            self._hist_bins = Bb_pad
        else:
            self._hist_bins = 0
            if (train_set.deferred and self.residency != "stream"
                    and self._block_counts is None
                    and not self.pctx.multi_process):
                # device ingest engages: raw rows bin+pack on device in
                # the placement build below — host X_binned never exists
                Xb = None
            else:
                if train_set.deferred:
                    Log.info(
                        "deferred ingest falls back to host binning (%s)",
                        "stream residency" if self.residency == "stream"
                        else "pre-partitioned/multi-process layout")
                Xb = train_set.X_binned
        # dataset fingerprint for checkpoint/resume: the config fingerprint
        # deliberately excludes data PATHS, so a resumed run pointed at a
        # different dataset of the same shape must be caught here — a strided
        # sample of the binned codes plus the full label vector, hashed while
        # both are still host arrays (no device fetch, computed once)
        import hashlib
        _fp = hashlib.sha256()
        if Xb is None:
            # deferred device ingest: hash the SAME strided row sample the
            # host path would, binned through the host oracle (bin_rows is
            # byte-identical to X_binned[::stride]) — the fingerprint is
            # invariant to WHERE binning runs, so tpu_ingest stays a
            # checkpoint-VOLATILE knob
            _shape0, _shape1 = train_set.num_data, train_set.num_features
            _fp.update(np.int64([N, _shape0, _shape1]).tobytes())
            _stride = max(1, _shape0 // 256)
            _fp.update(train_set.bin_rows(
                np.arange(0, _shape0, _stride)).tobytes())
        else:
            _fp.update(np.int64([N, Xb.shape[0], Xb.shape[1]]).tobytes())
            _stride = max(1, Xb.shape[0] // 256)
            _fp.update(np.ascontiguousarray(Xb[::_stride]).tobytes())
        _fp.update(np.asarray(meta_global.label, np.float32).tobytes())
        self._data_fingerprint = _fp.hexdigest()

        # device placement of the (possibly bundled) code matrix: rows padded
        # to Npad (equal per-process blocks under pre-partition, where only
        # the LOCAL shard exists on this host), columns to the strategy pad.
        # Placement goes through the Dataset's residency cache
        # (dataset.device_put_cached): the sharded code matrix and padding
        # mask are immutable step CONSTANTS, so every booster built over the
        # same mesh/padding reuses the same on-device buffers — the binned
        # dataset lives on the mesh once, not once per booster.
        _ncols = Xb.shape[1] if Xb is not None else train_set.num_features
        col_pad = (0, cols_pad - _ncols)
        self._stream_store = None
        self._stream = None
        self._streamed_grower = None
        self._stream_fns = None
        self._ingest_report = None
        if self.residency == "stream":
            # out-of-core: the padded (possibly bundled) code matrix is cut
            # into fixed-size host shards, packed to the tightest byte
            # layout the bin range allows (u4 at <16 bins — the
            # "compressed bin codes" of arXiv 1806.11248), and NEVER
            # device_put whole. The shard size divides the padded
            # per-device rows exactly, so Npad, every chunk boundary, and
            # the bagging RNG shapes are identical to device residency —
            # the bit-identity contract (tests/test_stream.py).
            from ..ops.stream import (HostShardStore, ShardPrefetcher,
                                      resolve_shard_rows)
            from ..ops.histogram import code_mode_for
            shard_devs = (self.pctx.num_devices
                          if self.pctx.mesh is not None
                          and self.pctx.strategy in ("data", "voting")
                          else 1)
            local_rd = resolve_shard_rows(Npad // shard_devs, chunk,
                                          config.tpu_stream_shard_rows)
            _max_code = (bundle_plan.max_bundle_bins
                         if bundle_plan is not None
                         else train_set.max_num_bin)
            # the store pads per block at pack time — no full padded copy
            # of a matrix that by definition outgrows memory budgets
            self._stream_store = HostShardStore(
                Xb, n_rows_padded=Npad, num_cols=cols_pad,
                local_shard_rows=local_rd, n_devices=shard_devs,
                code_mode=code_mode_for(int(_max_code), Xb.dtype))
            # chaos hook (robustness/chaos.py): a marker-gated one-shot
            # bit flip right after packing, so the per-shard CRC path is
            # exercisable end-to-end; no-op without the env knob
            from ..robustness.chaos import maybe_corrupt_shard_from_env
            maybe_corrupt_shard_from_env(self._stream_store)
            self._stream = ShardPrefetcher(
                self._stream_store, lambda a: self._put(a, "rows0"),
                verify=config.tpu_stream_verify)
            self.Xb = None
            sd = self._stream_store.describe()
            Log.info(
                "tpu_residency=stream: codes in %d host shards x %d rows "
                "(%s-packed, %.1f MB/shard, %.2f GB total); H2D double-"
                "buffered through the wave loop, row compaction off "
                "(full streaming passes)", sd["n_shards"],
                sd["shard_rows"], sd["code_mode"],
                sd["shard_bytes"] / (1 << 20), sd["total_bytes"] / (1 << 30))
        elif self._block_counts is not None:
            bp = Npad // len(self._block_counts)
            self.Xb = self._put_rows0_local(
                np.pad(Xb, ((0, bp - Xb.shape[0]), col_pad)), Npad)
        else:
            bundle_sig = None
            if bundle_plan is not None:
                # the bundled matrix's content is a pure function of the
                # plan — fingerprint its column maps, not the N*G codes
                import zlib
                bundle_sig = (
                    int(bundle_plan.num_groups),
                    int(bundle_plan.max_bundle_bins),
                    zlib.crc32(np.ascontiguousarray(bundle_plan.col).tobytes()),
                    zlib.crc32(np.ascontiguousarray(bundle_plan.off).tobytes()))
            # the cache key is IDENTICAL for host and device ingest — both
            # produce bit-identical placed codes, so a booster switching
            # tpu_ingest reuses the same on-device buffers
            _code_dtype = Xb.dtype if Xb is not None else train_set.code_dtype
            if Xb is None:
                _build = lambda: self._ingest_device(  # noqa: E731
                    train_set, N, Npad, cols_pad)
            else:
                _build = lambda: self._put(  # noqa: E731
                    np.pad(Xb, ((0, Npad - N), col_pad)), "rows0")
            self.Xb = train_set.device_put_cached(
                ("Xb", Npad, cols_pad, str(_code_dtype), bundle_sig,
                 self.pctx.residency_key()), _build)
        self.label = self._put(self._row_layout(meta_global.label, Npad), "rows")
        w = meta_global.weight
        self.weight = None if w is None else self._put(
            self._row_layout(w, Npad), "rows")
        if self._block_counts is None:
            self.pad_mask = train_set.device_put_cached(
                ("pad_mask", Npad, N, self.pctx.residency_key()),
                lambda: self._put(self._row_layout(np.ones(N, np.float32),
                                                   Npad), "rows"))
        else:
            self.pad_mask = self._put(
                self._row_layout(np.ones(N, np.float32), Npad), "rows")

        fpad = F_pad - F
        self.num_bins = self._put(np.pad(meta["num_bins"], (0, fpad), constant_values=1))
        self.missing_code = self._put(np.pad(meta["missing_code"], (0, fpad)))
        self.default_bin = self._put(np.pad(meta["default_bin"], (0, fpad)))
        self.is_categorical_np = meta["is_categorical"]
        is_cat_pad = np.pad(meta["is_categorical"], (0, fpad))
        self.is_cat = self._put(is_cat_pad)
        ok = np.arange(F_pad) < F                           # padding features off
        self.feature_ok_base = self._put(ok)

        # packed-row code layout for the compacted gather: nibble-pack two
        # codes/byte at <=16 bins, 6-bit-pack four codes/3 bytes at <=64
        # (the reference's Dense4bitsBin analog, dense_nbits_bin.hpp:37, and
        # its own GPU bench config max_bin=63). The Pallas kernel's in-kernel
        # unpack handles plain byte layouts only — keep u8/u16 there.
        from ..ops.histogram import code_mode_for, default_code_mode
        max_code = (bundle_plan.max_bundle_bins if bundle_plan is not None
                    else train_set.max_num_bin)
        _xb_dtype = Xb.dtype if Xb is not None else train_set.code_dtype
        if hist_kernel in ("pallas", "mixed"):
            code_mode = default_code_mode(_xb_dtype)
        else:
            code_mode = code_mode_for(int(max_code), _xb_dtype)

        # explicit pallas/mixed on real hardware: consult the per-shape-class
        # on-chip trust record (utils/cache.pallas_validated_on_chip). An
        # un-gated shape class still RUNS — the kernel is equality-tested in
        # interpret mode on every CI run — but Mosaic lowering failures are
        # shape-triggered, so the operator should know this exact shape
        # never executed on this machine's libtpu.
        if (hist_kernel in ("pallas", "mixed")
                and self.pctx.devices[0].platform == "tpu"
                and not pallas_validated_on_chip(pallas_shape_key)):
            # pallas_shape_key is the SAME key the auto->mixed resolution
            # consulted above — one construction, so the trusted shape and
            # the warned-about shape can never drift apart
            Log.warning(
                "tpu_hist_kernel=%s: shape class %s has never passed "
                "the on-chip equality gate on this machine/libtpu "
                "(exp/pallas_onchip_check.py writes the trust marker) "
                "— Mosaic lowering failures are shape-triggered; run "
                "the gate or use tpu_hist_kernel=xla if results look "
                "wrong", hist_kernel, pallas_shape_key)

        # slots were fixed alongside the kernel choice (they are part of
        # the gated kernel shape class)
        wave = config.tpu_wave_size or slots
        self.spec = GrowerSpec(
            num_leaves=num_leaves,
            num_features=F_pad,
            num_bins_padded=Bpad,
            chunk_rows=chunk,
            hist_slots=slots,
            wave_size=min(wave, slots),
            max_depth=config.max_depth,
            lambda_l1=config.lambda_l1,
            lambda_l2=config.lambda_l2,
            min_data_in_leaf=float(config.min_data_in_leaf),
            min_sum_hessian_in_leaf=config.min_sum_hessian_in_leaf,
            min_gain_to_split=config.min_gain_to_split,
            # stream mode runs full streaming passes: compaction gathers
            # rows from a device-resident packed matrix — the very
            # allocation streaming removes. Bit-identity is therefore
            # against device residency with tpu_row_compact=false.
            row_compact=(config.tpu_row_compact
                         and self.residency != "stream"),
            incremental_partition=config.tpu_incremental_partition,
            compact_frac=config.tpu_compact_frac,
            hist_kernel=hist_kernel,
            hist_hilo=config.tpu_hist_hilo,
            hist_f64=config.tpu_hist_f64,
            hist_bins=self._hist_bins,
            efb_unpack=(self.bundle is not None and self._efb_unpack),
            code_mode=code_mode,
            use_categorical=bool(meta["is_categorical"].any()),
            cat_features=tuple(int(i) for i in np.nonzero(is_cat_pad)[0]),
            cat_smooth=config.cat_smooth,
            cat_l2=config.cat_l2,
            max_cat_threshold=config.max_cat_threshold,
            max_cat_to_onehot=config.max_cat_to_onehot,
            min_data_per_group=float(config.min_data_per_group),
        )
        self.comm = self.pctx.make_comm(
            F_pad,
            # bundle blocks are the partition unit for feature-parallel
            # (both EFB arms) and for data-parallel on the NATIVE arm,
            # where the psum_scatter itself runs in bundle space
            num_bundles=(self._num_bundles_padded
                         if (self.pctx.strategy == "feature"
                             or (self.pctx.strategy == "data"
                                 and self.bundle is not None
                                 and not self._efb_unpack)) else 0),
            bundle_col=None if self.bundle is None else self.bundle.col)
        if self.residency == "stream":
            from ..grower import StreamedGrower
            self._streamed_grower = StreamedGrower(
                self.spec, self.pctx, self.comm,
                n_rows_padded=Npad,
                local_shard_rows=self._stream_store.local_shard_rows,
                n_shards=self._stream_store.n_shards,
                num_cols=cols_pad, code_mode=self._stream_store.code_mode,
                num_bins=self.num_bins, missing_code=self.missing_code,
                default_bin=self.default_bin, is_cat=self.is_cat,
                bundle=self.bundle)

        # ---- piecewise-linear leaves (linear_tree=true, ops/linear.py) -----
        # the per-leaf ridge fit reads RAW f32 feature values the binned
        # matrix discards: a NaN-sanitized [Npad, F_pad] slice plus its
        # missing plane become step constants (cached on the dataset like
        # Xb). v1 scope: single-device, non-streamed, row-replicated —
        # every unsupported combination rejects loudly here, never trains
        # silently-wrong coefficients.
        self.linear_tree = bool(config.linear_tree)
        self.Xraw = None
        self.Xmiss = None
        self._linear_max_steps = 1
        if self.linear_tree:
            if self.pctx.strategy == "feature":
                Log.fatal("linear_tree=true is not supported with "
                          "tree_learner=feature (the raw-feature slice is "
                          "row-aligned; use serial)")
            if self.pctx.num_devices > 1 or self.pctx.multi_process:
                Log.fatal("linear_tree=true is single-device for now "
                          "(%d devices requested): the per-leaf moment "
                          "accumulation is not wired through the mesh "
                          "collectives yet", self.pctx.num_devices)
            if config.is_pre_partition:
                Log.fatal("linear_tree=true is not supported with "
                          "is_pre_partition")
            raw_np = getattr(train_set, "X_raw", None)
            if raw_np is None:
                Log.fatal("linear_tree=true needs the dataset's raw feature "
                          "slice, which this dataset was constructed "
                          "without — rebuild the Dataset with "
                          "linear_tree=true in its params (binary dataset "
                          "files save it only when written under "
                          "linear_tree)")
            raw_pad = np.zeros((Npad, F_pad), np.float32)
            raw_pad[:N, :F] = raw_np
            miss_pad = np.isnan(raw_pad)
            np.nan_to_num(raw_pad, copy=False, nan=0.0,
                          posinf=np.float32(np.finfo(np.float32).max),
                          neginf=np.float32(np.finfo(np.float32).min))
            self.Xraw = train_set.device_put_cached(
                ("Xraw", Npad, F_pad, self.pctx.residency_key()),
                lambda: self._put(raw_pad, "rows0"))
            self.Xmiss = train_set.device_put_cached(
                ("Xmiss", Npad, F_pad, self.pctx.residency_key()),
                lambda: self._put(miss_pad, "rows0"))
            # path depth bound for the leaf->root feature walk
            depth_cap = config.max_depth if config.max_depth > 0 \
                else num_leaves - 1
            self._linear_max_steps = max(1, min(num_leaves - 1, depth_cap))
            Log.info("linear_tree: per-leaf ridge solves on (lambda=%g, "
                     "max_features=%d); raw slice %.2f MB + %.2f MB missing "
                     "plane device-resident", config.linear_lambda,
                     config.linear_max_features,
                     raw_pad.nbytes / (1 << 20), miss_pad.nbytes / (1 << 20))

        # feature_fraction: number of features used per tree
        self.n_feature_sample = max(1, int(round(config.feature_fraction * F)))
        self.use_feature_fraction = config.feature_fraction < 1.0 and self.n_feature_sample < F

        self.train_metrics = create_metrics(config, self.objective.name if self.objective else None)
        for m in self.train_metrics:
            m.init(meta_global, N)
        self.valid_sets: List[ValidSet] = []

        # ---- initial scores -------------------------------------------------
        self.init_score_value = 0.0
        # meta_global, not train_set.metadata: under pre-partition the local
        # shard only holds its own init_score slice
        meta_is = meta_global.init_score
        has_init = meta_is is not None
        if (config.boost_from_average and not has_init and K == 1
                and self.objective is not None):
            avg = self.objective.boost_from_average_score()
            if avg is not None and abs(avg) > 1e-15:
                self.init_score_value = float(avg)

        base = np.full((K, Npad), self.init_score_value, dtype=np.float32)
        if has_init:
            is_arr = np.asarray(meta_is, dtype=np.float32).reshape(K, N, order="C") \
                if len(meta_is) == K * N else np.tile(np.asarray(meta_is, np.float32), (K, 1))
            # _row_layout, not [:N]: real rows sit at block positions under
            # pre-partition
            base += np.stack([self._row_layout(is_arr[k], Npad)
                              for k in range(K)])
        self.score = self._put(base, "rows1")

        self.models: List[List] = []        # per iteration: list of K device TreeArrays
        self._num_leaves_dev: List = []     # per iteration: [K] device array
        self.iter_ = 0
        # telemetry high-water mark: iterations already counted into the
        # monotonic trees.trained/rows.routed counters (publish_telemetry).
        # Checkpoint restore and repeated train() calls on one booster bump
        # it so restored/already-published iterations are never re-counted.
        self._telemetry_iters_base = 0
        # monotonic forest-content counter: iter_ alone can collide after a
        # rollback (explicit or the no-splits pop) followed by a retrain,
        # which would let stale materialized host trees pass a length check
        self.mutations_ = 0
        # device-resident twins of the per-step host scalars: through a
        # remote-device tunnel every host->device scalar costs a round
        # trip (~120 ms/tree of the round-3..5 bench gap between
        # grow_tree alone and a full boosting step, exp/RESULTS.md) — the
        # step carries its own iteration counter and only re-uploads the
        # shrinkage when a learning_rates schedule actually changes it
        self._iter_dev = None               # i32, step output; None = resync
        self._shrink_cache = (None, None)   # (float value, device scalar)
        self.best_iter: Dict[str, int] = {}
        self.best_score: Dict[str, float] = {}
        self._rng_key = self._put(
            jax.random.PRNGKey(config.seed if config.seed else config.bagging_seed))

        self.bagging_on = config.bagging_freq > 0 and config.bagging_fraction < 1.0
        # under bagging the carried mask is DONATED to the step (XLA updates
        # it in place) — it must own its buffer, never alias pad_mask, which
        # travels separately as a step constant
        self.bag_mask = self.pad_mask + 0 if self.bagging_on else self.pad_mask
        self.best_iteration = 0

        # non-finite guard (robustness/numeric.py): a trace-time constant —
        # "none" compiles the exact unguarded step program
        self.nan_policy = config.nan_policy
        self._consecutive_skips = 0

        self._step_fn = None
        self._custom_step_fn = None

        # ---- fused multi-tree dispatch (tree_batch) ------------------------
        # K boosting iterations per jit dispatch via lax.scan: grad/hess,
        # tree growth, and score updates for K trees never leave HBM, and
        # the host pays dispatch overhead once per K trees. Requires the
        # whole per-iteration pipeline to be device-resident, which dart
        # (host-side drop-set selection) and goss (conservatively, per its
        # sampling contract) opt out of via supports_tree_batch.
        tb = max(1, config.tree_batch)
        if tb > 1 and not self.supports_tree_batch:
            Log.warning(
                "tree_batch=%d is not supported with boosting=%s (the "
                "per-iteration pipeline is not fully device-resident); "
                "falling back to tree_batch=1", tb,
                config.boosting_normalized)
            tb = 1
        if tb > 1 and self.residency == "stream":
            # pinned in tests/test_stream.py: the shard loop is driven by
            # the host per wave — fusing K iterations under one lax.scan
            # would trap the H2D transfers inside a traced body, which is
            # exactly what tpu-lint R009 forbids
            Log.warning(
                "tree_batch=%d is not supported with tpu_residency=stream "
                "(the shard prefetch loop is host-driven); falling back "
                "to tree_batch=1", tb)
            tb = 1
        if (tb > 1 and self.average_output
                and config.nan_policy in ("raise", "skip_iter")):
            # RF's running-average score weights by the device iteration
            # counter, which keeps advancing through a batch: a mid-batch
            # gated no-op would leave phantom iterations in the average
            # denominator (skip_iter), and raise's rollback would need
            # trailing trees subtracted — rejected for average_output.
            # The K=1 paths resync the counter and stay exact.
            Log.warning(
                "tree_batch=%d with nan_policy=%s cannot compose with a "
                "mid-batch skip/rollback under boosting=rf (scores are "
                "running averages weighted by the iteration counter); "
                "falling back to tree_batch=1", tb, config.nan_policy)
            tb = 1
        self.tree_batch = tb
        self._batch_step_fns: Dict[int, object] = {}

        # telemetry: the resolved kernel choice and dispatch shape of this
        # booster (observability registry + an instant trace event) — the
        # per-booster facts the next perf session reads first
        reg = obs.get_registry()
        reg.counter(f"booster.kernel.{hist_kernel}").inc()
        reg.counter(f"booster.residency.{self.residency}").inc()
        reg.gauge("booster.tree_batch").set(tb)
        reg.gauge("booster.wave_size").set(self.spec.wave_size)
        reg.gauge("booster.hist_slots").set(self.spec.hist_slots)
        if self._stream_store is not None:
            reg.gauge("stream.n_shards").set(self._stream_store.n_shards)
            reg.gauge("stream.shard_bytes").set(
                self._stream_store.shard_bytes)
        obs.event("booster_init", kernel=hist_kernel, tree_batch=tb,
                  rows=int(N), features=int(F), num_leaves=int(num_leaves),
                  strategy=self.pctx.strategy, nan_policy=self.nan_policy,
                  mesh_axis=self.pctx.axis_kind,
                  n_devices=self.pctx.num_devices,
                  residency=self.residency)
        if self._stream_store is not None:
            obs.event("stream_init", **self._stream_store.describe())
        # MULTICHIP story: the resolved mesh (device count + which dataset
        # axis it shards — the tree_learner=auto outcome) and the analytic
        # per-wave collective payload estimates (parallel/comm.py
        # collective_bytes) — host arithmetic at construction, so the comm
        # budget is inspectable before any distributed dispatch runs
        reg.gauge("comm.mesh.n_devices").set(self.pctx.num_devices)
        reg.gauge("comm.mesh.rows_sharded").set(
            1 if self.pctx.axis_kind == "rows" else 0)
        reg.counter(f"booster.tree_learner.{self.pctx.strategy}").inc()
        if self.pctx.mesh is not None:
            obs.event("mesh_axes", **self.pctx.describe())
        comm_bytes = self.comm.collective_bytes(
            self.spec.hist_slots, Bpad,
            use_categorical=self.spec.use_categorical,
            # native bundled runs move BUNDLE-space histograms through the
            # wave collectives; the legacy unpack arm reduces feature-space
            # histograms (unbundle-early), so it keeps the default widths
            hist_bins=(self._hist_bins
                       if (self.bundle is not None and not self._efb_unpack)
                       else None))
        for cname, nbytes in comm_bytes.items():
            reg.gauge(f"comm.bytes_per_wave.{cname}").set(nbytes)
        if comm_bytes:
            obs.event("comm_cost", strategy=self.pctx.strategy, **comm_bytes)

    # ------------------------------------------------------------------ setup

    # out-of-core streaming capability (tpu_residency=stream): the whole
    # per-iteration pipeline must be drivable through the host-side shard
    # loop; DART opts out (host-side drop-set selection reads the resident
    # code matrix per tree via _contrib_fn)
    supports_stream = True

    def _stream_support(self, config) -> Tuple[bool, str]:
        """(supported, why-not) for tpu_residency=stream under this
        booster's strategy/topology — consulted by the residency
        resolution (forced stream fails loudly; auto never picks an
        unsupported mode)."""
        if not self.supports_stream:
            return False, (f"boosting={config.boosting_normalized} keeps "
                           f"host-side per-tree state that reads the "
                           f"resident code matrix")
        if getattr(config, "linear_tree", False):
            return False, ("linear_tree=true keeps the raw feature slice "
                           "device-resident (the per-leaf fits read raw "
                           "values every tree)")
        if self.pctx.strategy == "feature":
            return False, ("tree_learner=feature replicates rows and "
                           "slices columns at trace time; stream shards "
                           "rows (use data/voting)")
        if self.pctx.multi_process:
            return False, ("multi-host execution streams per-process "
                           "shards is not wired yet (single-process "
                           "meshes only)")
        if config.is_pre_partition:
            return False, "is_pre_partition holds per-process row blocks"
        return True, ""

    def _resolve_residency(self, config, *, per_target: int, chunk: int,
                           cols_pad: int, code_itemsize: int,
                           bins_pad: int, bins_hist: int, slots: int,
                           num_leaves: int, num_models: int) -> str:
        """Resolve ``tpu_residency`` before any device placement.

        ``auto`` compares an analytic DEVICE-residency estimate
        (observability/memory.py estimate_wave_residency, the PR-6
        pre-flight model at provisional padding) against the per-device
        HBM budget and falls back to ``stream`` when it does not fit —
        the warning the pre-flight used to stop at, turned into the fix.
        The decision estimate sizes the histogram cache at full width
        (conservative under data-parallel's block-sharded cache: an
        overestimate can only stream earlier, never OOM later)."""
        from ..observability.memory import (estimate_wave_residency,
                                            hbm_budget_bytes)
        requested = config.tpu_residency
        if requested == "device":
            return "device"
        supported, why = self._stream_support(config)
        if requested == "stream":
            if not supported:
                Log.fatal("tpu_residency=stream is not supported here: %s",
                          why)
            return "stream"
        # auto: estimate full-N device residency per device
        budget = hbm_budget_bytes(config)
        if budget is None:
            return "device"
        rows = _round_up(per_target, chunk)   # padded PER-DEVICE rows
        if config.tpu_hist_f64:
            channels, chb = 3, 4
        elif config.tpu_hist_hilo:
            channels, chb = 5, 2
        else:
            channels, chb = 3, 2
        packed_row_bytes = 0
        if config.tpu_row_compact:
            from ..ops.histogram import code_bytes_total
            mode = "u16" if code_itemsize == 2 else "u8"
            packed_row_bytes = (code_bytes_total(cols_pad, mode)
                                + channels * chb)
        est = estimate_wave_residency(
            rows=rows, cols=cols_pad, code_itemsize=code_itemsize,
            num_models=num_models, num_leaves=num_leaves,
            hist_cols=cols_pad, hist_bins=bins_hist, cache_cols=cols_pad,
            cache_bins=bins_hist, num_bins_padded=bins_pad, slots=slots,
            chunk_rows=chunk, channels=channels, channel_bytes=chb,
            packed_row_bytes=packed_row_bytes,
            row_compact=config.tpu_row_compact,
            incremental=config.tpu_incremental_partition,
            bagging=(config.bagging_freq > 0
                     and config.bagging_fraction < 1.0),
            tree_batch=max(1, config.tree_batch),
            linear_max_features=(config.linear_max_features
                                 if config.linear_tree else 0))
        if est["total_bytes"] <= budget:
            return "device"
        gb = 1 << 30
        if not supported:
            Log.warning(
                "HBM pre-flight: estimated device residency %.3g GB "
                "exceeds the %.3g GB budget but tpu_residency=stream is "
                "unavailable (%s) — staying device-resident; expect an "
                "OOM at first dispatch", est["total_bytes"] / gb,
                budget / gb, why)
            return "device"
        Log.warning(
            "HBM pre-flight: estimated device residency %.3g GB exceeds "
            "the %.3g GB per-device budget — auto-selecting "
            "tpu_residency=stream: the binned codes stay in host-resident "
            "packed shards and stream H2D double-buffered through the "
            "wave loop (docs/TPU-Performance.md \"Out-of-core streaming\")",
            est["total_bytes"] / gb, budget / gb)
        return "stream"

    def _real_rows(self):
        """Index of real (non-padding) rows in the padded device layout, in
        global row order — a plain slice normally, the per-process block
        positions under pre-partition (where [:N] would pick block-0 padding
        and drop block-1's tail)."""
        if self._block_counts is None:
            return slice(0, self.num_data)
        bp = self.num_data_padded // len(self._block_counts)
        return np.concatenate([np.arange(c) + p * bp
                               for p, c in enumerate(self._block_counts)])

    def _row_layout(self, arr, npad: Optional[int] = None, fill=0):
        """Host row array (global row order) -> padded device layout.

        Normally: data first, padding at the tail. Under pre-partition: equal
        per-process blocks of Npad/P rows, each process's rows at the head of
        its block — matching `_put_rows0_local`'s placement of the local
        feature matrix, so row i of the label/mask lines up with row i of X.
        """
        arr = np.asarray(arr)
        npad = self.num_data_padded if npad is None else npad
        out = np.full((npad,) + arr.shape[1:], fill, arr.dtype)
        if self._block_counts is None:
            out[: arr.shape[0]] = arr
        else:
            bp = npad // len(self._block_counts)
            off = 0
            for p, c in enumerate(self._block_counts):
                out[p * bp: p * bp + c] = arr[off: off + c]
                off += c
        return out

    def _put_rows0_local(self, local_block: np.ndarray, npad: int):
        """Assemble the global row-sharded [Npad, F] array from this
        process's padded block — no process ever holds the others' features
        (jax.make_array_from_process_local_data; the reference's
        pre-partitioned load keeps shards local the same way)."""
        sharding = self.pctx.sharding("rows0")
        return jax.make_array_from_process_local_data(
            sharding, local_block, (npad, local_block.shape[1]))

    def _put(self, x, kind: str = "repl"):
        """Place an array on this booster's device(s) with the mesh-resident
        NamedSharding the strategy's axis role dictates
        (``ParallelContext.sharding``): "rows" ([N] sharded), "rows0"
        ([N, F] rows on dim 0), "rows1" ([K, N] rows on dim 1), "repl"
        (replicated). Row sharding only applies to row-partitioned
        strategies (data/voting); the feature strategy replicates rows like
        the reference's FeatureParallel learner (every machine holds all
        data, feature_parallel_tree_learner.cpp)."""
        pctx = self.pctx
        sharding = pctx.sharding(kind)
        if sharding is None:
            return jax.device_put(jnp.asarray(x), pctx.devices[0])
        if pctx.multi_process:
            # every process holds the full (host) array; materialize only the
            # locally-addressable shards of the global sharded array — the
            # multi-host analog of the reference's non-pre-partitioned load
            # (dataset_loader.cpp:159 rank/num_machines row partitioning)
            x = np.asarray(x)
            return jax.make_array_from_callback(x.shape, sharding,
                                                lambda idx: x[idx])
        return jax.device_put(jnp.asarray(x), sharding)

    def _ingest_device(self, train_set, N: int, Npad: int, cols_pad: int):
        """Bin + pack the deferred raw rows on device (ops/ingest.py) —
        the build closure of the Xb residency cache when device ingest
        engages. Bit-identical to host binning + ``np.pad`` + ``_put``
        (tests/test_ingest.py); multi-device layouts reshard the
        device-0 result through the mesh row sharding (a device-to-device
        move, not a second host upload)."""
        from ..ops.ingest import device_ingest
        cfg = self.config
        arr, report = device_ingest(
            train_set.deferred_raw(), train_set.mappers,
            np.asarray(train_set.real_feature_idx),
            n_rows=N, n_rows_padded=Npad, num_cols=cols_pad,
            out_dtype=train_set.code_dtype,
            chunk_rows=int(cfg.tpu_ingest_chunk_rows),
            device=self.pctx.devices[0],
            prefetch_depth=int(cfg.tpu_ingest_prefetch))
        self._ingest_report = report
        Log.info("device ingest: %d rows binned+packed on device "
                 "(%.2f Mrow/s, %d chunks, stall fraction %.2f)",
                 N, (report["rows_per_s"] or 0.0) / 1e6, report["n_chunks"],
                 report["stall_fraction"])
        sharding = self.pctx.sharding("rows0")
        if sharding is not None:
            arr = jax.device_put(arr, sharding)
        return arr

    def add_valid(self, name: str, binned: np.ndarray, metadata: Metadata,
                  raw: Optional[np.ndarray] = None) -> None:
        nv = binned.shape[0]
        metrics = create_metrics(self.config, self.objective.name if self.objective else None)
        for m in metrics:
            m.init(metadata, nv)
        F_pad = self.spec.num_features
        if binned.shape[1] < F_pad:
            binned = np.pad(binned, ((0, 0), (0, F_pad - binned.shape[1])))
        vs = ValidSet(name, self._put(binned), metadata, metrics, nv)
        if self.linear_tree:
            # the valid-score updates run the linear epilogue — they need
            # the same sanitized raw slice the training rows carry
            if raw is None:
                Log.fatal("linear_tree=true: valid set %r needs its raw "
                          "feature values (construct it with "
                          "free_raw_data=False)", name)
            raw_pad = np.zeros((nv, F_pad), np.float32)
            raw_pad[:, : raw.shape[1]] = np.asarray(raw, np.float32)
            miss_pad = np.isnan(raw_pad)
            np.nan_to_num(raw_pad, copy=False, nan=0.0)
            vs.Xraw = self._put(raw_pad)
            vs.Xmiss = self._put(miss_pad)
        base = np.full((self.num_models, nv), self.init_score_value, dtype=np.float32)
        if metadata.init_score is not None:
            base += np.asarray(metadata.init_score, np.float32).reshape(
                self.num_models, nv)
        vs.score = self._put(base)
        self.valid_sets.append(vs)

    # ------------------------------------------------------------- train step

    def _gradients(self, score):
        """Hook: GOSS/DART/RF override pieces of this pipeline."""
        label = self.label
        g, h = self.objective.gradients(score, label, self.weight)
        return g, h

    def _bag_mask_for_iter(self, key, it, prev_mask):
        if not self.bagging_on:
            return self.pad_mask
        resample = (it % self.config.bagging_freq) == 0
        bern = jax.random.uniform(key, (self.num_data_padded,)) < self.config.bagging_fraction
        new_mask = bern.astype(jnp.float32) * self.pad_mask
        return jnp.where(resample, new_mask, prev_mask)

    def _sampling(self, g, h, bag_mask, key, it):
        """Row-sampling hook: returns (mask, g, h). Base = bagging; GOSS
        overrides with gradient-based one-side sampling (goss.hpp:86-131)."""
        mask = self._bag_mask_for_iter(key, it, bag_mask)
        return mask, g, h

    def _tree_output_transform(self, tree):
        """Hook: RF converts leaf outputs via the objective (rf.hpp:160-167)."""
        return tree

    def _score_update(self, old_score_k, contrib, it):
        """Hook: base adds; RF maintains a running average (rf.hpp:117-121)."""
        return old_score_k + contrib

    # Per-tree math blocks shared VERBATIM by the resident ``step_body``
    # and the streamed step legs (``_make_stream_fns``) — like the grower's
    # ``_apply_wave_splits``, each has exactly one home so the two
    # residency modes cannot drift apart (the bit-identity contract of
    # tests/test_stream.py). All three are traced inside whichever jit
    # calls them.

    def _feature_mask(self, fkey, k):
        """Per-model feature_fraction mask (serial_tree_learner.cpp:240)."""
        if not self.use_feature_fraction:
            return self.feature_ok_base
        fk = jax.random.fold_in(fkey, k)
        noise = jax.random.uniform(fk, (self.spec.num_features,))
        # padding features must not consume sample slots
        noise = jnp.where(self.feature_ok_base, noise, -1.0)
        _, top_idx = jax.lax.top_k(noise, self.n_feature_sample)
        fmask = jnp.zeros(self.spec.num_features, bool).at[top_idx].set(True)
        return fmask & self.feature_ok_base

    def _shrink_transform_flag(self, tree, shrinkage):
        """Shrinkage + output transform + (under nan_policy) the leaf
        non-finite flag and clip. Returns ``(tree, bad_leaf_or_None)``.
        Reference Tree::Shrinkage scales internal_value_ too
        (tree.h:137-142) — TreeSHAP reads node means from it."""
        tree = tree._replace(
            leaf_value=tree.leaf_value * shrinkage,
            internal_value=tree.internal_value * shrinkage)
        if tree.leaf_const is not None:
            # linear leaves shrink intercept + coefficients with the
            # constant (the reference scales the whole leaf model)
            tree = tree._replace(leaf_const=tree.leaf_const * shrinkage,
                                 leaf_coeff=tree.leaf_coeff * shrinkage)
        tree = self._tree_output_transform(tree)
        if self.nan_policy == "none":
            return tree, None
        from ..robustness.numeric import clip_nonfinite, nonfinite_flag
        bl = nonfinite_flag(tree.leaf_value)
        if self.nan_policy == "clip":
            tree = tree._replace(
                leaf_value=clip_nonfinite(tree.leaf_value),
                internal_value=clip_nonfinite(tree.internal_value))
        return tree, bl

    def _tree_score_updates(self, score_k, valid_k, valid_Xb, tree,
                            leaf_ids, it):
        """Apply one (shrunk) tree to the train score and every valid
        score: ``(new_score_k, [new_valid_k...])``. Linear trees swap the
        constant-leaf table lookup for the per-row linear epilogue
        (ops/linear.linear_leaf_scores) on both paths."""
        if self.linear_tree:
            from ..ops.linear import linear_leaf_scores
            contrib = linear_leaf_scores(tree, leaf_ids, self.Xraw,
                                         self.Xmiss)
        else:
            contrib = table_lookup(leaf_ids, tree.leaf_value)
        new_score_k = self._score_update(score_k, contrib, it)
        new_valid_k = []
        for vi in range(len(valid_Xb)):
            vleaf = leaves_from_binned(
                tree, valid_Xb[vi], self.num_bins, self.missing_code,
                self.default_bin,
                use_categorical=self.spec.use_categorical)
            if self.linear_tree:
                from ..ops.linear import linear_leaf_scores
                vs = self.valid_sets[vi]
                vcontrib = linear_leaf_scores(tree, vleaf, vs.Xraw, vs.Xmiss)
            else:
                vcontrib = table_lookup(vleaf, tree.leaf_value)
            new_valid_k.append(self._score_update(valid_k[vi], vcontrib, it))
        return new_score_k, new_valid_k

    # device-array attributes captured by the training step; under
    # multi-host they must travel as jit ARGUMENTS (closing over arrays
    # spanning non-addressable devices is rejected), so the step rebinds
    # them onto self for the duration of the trace.
    _STEP_CONSTS = ("Xb", "label", "weight", "pad_mask", "feature_ok_base",
                    "is_cat", "num_bins", "missing_code", "default_bin",
                    "Xraw", "Xmiss")

    def _step_consts(self):
        consts = {a: getattr(self, a) for a in self._STEP_CONSTS}
        # linear_tree: per-valid raw slices ride in the consts pytree (the
        # step rebinds them like vs.Xb, so they travel as jit ARGUMENTS and
        # are never baked into the executable as constants)
        consts["valid_raw"] = tuple((vs.Xraw, vs.Xmiss)
                                    for vs in self.valid_sets) \
            if self.linear_tree else None
        return consts, tuple(vs.Xb for vs in self.valid_sets)

    def _make_step(self, custom_grads: bool = False, batch: int = 1,
                   donate_override: Optional[tuple] = None):
        assert not (custom_grads and batch > 1), \
            "custom gradients need a host round-trip per tree"
        spec = self.spec
        K = self.num_models
        comm = self.comm
        linear_tree = self.linear_tree    # static per booster

        bundle = self.bundle              # EFB: native arm scans/routes in
                                          # bundle space end-to-end; legacy
                                          # tpu_efb_unpack unpacks before
                                          # the collective (grower.py)

        def grow_fn(X, g, h, inc, fok, iscat, nb, mc, db):
            return grow_tree(X, g, h, inc, fok, iscat, nb, mc, db, spec, comm,
                             bundle=bundle)

        grow = self.pctx.shard_grow(grow_fn)

        def step(consts, valid_Xb, score, valid_scores, bag_mask, key, it,
                 shrinkage, *grads):
            # Rebind the captured arrays to this trace's tracers so every
            # hook (_gradients/_sampling/RF/GOSS overrides) reads arguments,
            # not baked-in constants. Python-level state is restored after
            # tracing; compiled executions never run this body again.
            saved = {a: getattr(self, a) for a in self._STEP_CONSTS}
            saved_vXb = [vs.Xb for vs in self.valid_sets]
            saved_vraw = [(vs.Xraw, vs.Xmiss) for vs in self.valid_sets]
            for a in self._STEP_CONSTS:
                setattr(self, a, consts[a])
            for vs, xb in zip(self.valid_sets, valid_Xb):
                vs.Xb = xb
            if linear_tree:     # static: self.linear_tree, fixed per booster
                for vs, (xr, xm) in zip(self.valid_sets,
                                        consts["valid_raw"]):
                    vs.Xraw, vs.Xmiss = xr, xm
            try:
                if batch == 1:
                    return step_body(score, valid_scores, bag_mask, key, it,
                                     shrinkage, *grads)
                return batch_body(score, valid_scores, bag_mask, key, it,
                                  shrinkage)
            finally:
                for a, v in saved.items():
                    setattr(self, a, v)
                for vs, xb in zip(self.valid_sets, saved_vXb):
                    vs.Xb = xb
                for vs, (xr, xm) in zip(self.valid_sets, saved_vraw):
                    vs.Xraw, vs.Xmiss = xr, xm

        def batch_body(score, valid_scores, bag_mask, key, it, shrinkage):
            # tree_batch fusion: `batch` whole iterations under ONE lax.scan
            # — the carry (scores, bagging mask, device iteration counter)
            # stays in HBM between trees; per-iteration trees / leaf counts
            # (/ non-finite flags) stack along the leading batch axis. The
            # scan body IS step_body, so K=1 and K>1 run identical math per
            # iteration (bit-identity is pinned by tests/test_tree_batch.py).
            def scan_step(carry, _):
                score, valid_scores, bag_mask, it = carry
                outs = step_body(score, valid_scores, bag_mask, key, it,
                                 shrinkage)
                score, valid_scores, bag_mask = outs[0], outs[1], outs[2]
                it = outs[5]
                return (score, valid_scores, bag_mask, it), \
                    (outs[3], outs[4]) + tuple(outs[6:])
            (score, valid_scores, bag_mask, it), ys = jax.lax.scan(
                scan_step, (score, valid_scores, bag_mask, it), None,
                length=batch)
            return (score, valid_scores, bag_mask) + tuple(ys[:2]) + (it,) \
                + tuple(ys[2:])

        nan_policy = self.nan_policy
        if nan_policy != "none":
            from ..robustness.numeric import clip_nonfinite, nonfinite_flag

        def step_body(score, valid_scores, bag_mask, key, it, shrinkage, *grads):
            # key arrives RAW; folding by the device iteration counter here
            # reproduces the former host-side fold_in(rng, iter_) stream
            # exactly (fold_in is value-deterministic) with zero per-step
            # host->device transfers
            key = jax.random.fold_in(key, it)
            if custom_grads:
                g, h = grads
            else:
                g, h = self._gradients(score)
            bad_g = bad_h = bad_leaf = None
            if nan_policy != "none":
                # detect BEFORE any sanitizing so every policy can report
                # which of g/h/leaf went non-finite
                bad_g, bad_h = nonfinite_flag(g), nonfinite_flag(h)
                if nan_policy == "clip":
                    g, h = clip_nonfinite(g), clip_nonfinite(h)
            bkey, fkey = jax.random.split(jax.random.fold_in(key, 0))
            mask, g, h = self._sampling(g, h, bag_mask, bkey, it)
            trees = []
            nleaves = []
            new_scores = []
            new_valid = [list(vs) for vs in valid_scores] if valid_scores else []
            vXb = tuple(vs.Xb for vs in self.valid_sets)
            for k in range(K):
                fmask = self._feature_mask(fkey, k)
                tree, leaf_ids = grow(
                    self.Xb, g[k] * mask, h[k] * mask, mask, fmask, self.is_cat,
                    self.num_bins, self.missing_code, self.default_bin)
                if self.linear_tree:
                    # per-leaf ridge fit (ops/linear.py): same masked g/h
                    # the tree grew on, BEFORE shrinkage so the intercept
                    # and coefficients scale together (Tree::Shrinkage)
                    from ..ops.linear import fit_linear_leaves
                    tree = fit_linear_leaves(
                        tree, self.Xraw, self.Xmiss, leaf_ids,
                        g[k] * mask, h[k] * mask, mask, self.is_cat,
                        max_features=self.config.linear_max_features,
                        linear_lambda=self.config.linear_lambda,
                        chunk_rows=spec.chunk_rows,
                        max_steps=self._linear_max_steps)
                tree, bl = self._shrink_transform_flag(tree, shrinkage)
                if bl is not None:
                    bad_leaf = bl if bad_leaf is None else (bad_leaf | bl)
                new_score_k, new_valid_k = self._tree_score_updates(
                    score[k], [new_valid[vi][k] for vi in range(len(vXb))],
                    vXb, tree, leaf_ids, it)
                new_scores.append(new_score_k)
                for vi in range(len(vXb)):
                    new_valid[vi][k] = new_valid_k[vi]
                trees.append(tree)
                nleaves.append(tree.num_leaves)
            out_score = jnp.stack(new_scores)
            out_valid = tuple(tuple(v) for v in new_valid)
            if nan_policy == "none":
                return (out_score, out_valid, mask, tuple(trees),
                        jnp.stack(nleaves), it + 1)
            nf = jnp.stack([bad_g, bad_h, bad_leaf])
            if nan_policy in ("raise", "skip_iter"):
                # hardware-gate every output on the poison flag: a poisoned
                # iteration leaves scores/masks BIT-identical to their
                # pre-step values, so host-side recovery is pure bookkeeping
                # (pop the no-op iteration), never NaN arithmetic
                bad = jnp.any(nf)
                out_score = jnp.where(bad, score, out_score)
                out_valid = tuple(
                    tuple(jnp.where(bad, old_k, new_k)
                          for old_k, new_k in zip(old_vs, new_vs))
                    for old_vs, new_vs in zip(valid_scores, out_valid))
                mask = jnp.where(bad, bag_mask, mask)
            return (out_score, out_valid, mask, tuple(trees),
                    jnp.stack(nleaves), it + 1, nf)

        # donate the training-step carry (positions: score=2,
        # valid_scores=3, and under bagging bag_mask=4) — every one is
        # rebound to the step's outputs immediately after each dispatch, so
        # XLA updates in place instead of allocating + copying a second
        # [K, Npad] f32 array per step (42 MB at bench scale). bag_mask is
        # only donated when bagging resamples it (otherwise the step returns
        # pad_mask, which also travels as a non-donated constant). The
        # grower's per-tree leaf state and histogram cache live inside the
        # while_loop carry, which XLA already aliases in place. CPU ignores
        # donation with a warning, so gate it.
        # donate_override exists for the trace-contract tier
        # (analysis/contracts): the CPU gate would make the donation
        # contract vacuous on the dev box, so the contract compiles the
        # step with the TPU-style donate set forced on and checks the
        # aliases in the HLO header instead of trusting this branch.
        if donate_override is not None:
            donate = tuple(donate_override)
        else:
            donate = () if self.pctx.devices[0].platform == "cpu" else \
                ((2, 3, 4) if self.bagging_on else (2, 3))
        return jax.jit(step, donate_argnums=donate)

    def _dispatch_prep(self, shrinkage: float):
        """Shared pre-dispatch protocol of the K=1 and fused-batch paths:
        device-counter resync, on-device shrinkage cache, valid-score /
        step-constant assembly. ONE copy so the two dispatchers cannot
        drift."""
        if self._iter_dev is None:    # first step / post-rollback resync
            self._iter_dev = jnp.asarray(self.iter_, jnp.int32)
        if self._shrink_cache[0] != shrinkage:
            self._shrink_cache = (shrinkage,
                                  jnp.asarray(shrinkage, jnp.float32))
        valid_scores = tuple(tuple(vs.score[k] for k in range(self.num_models))
                             for vs in self.valid_sets)
        consts, valid_Xb = self._step_consts()
        return consts, valid_Xb, valid_scores

    def _capture_step_cost(self, site: str, fn, args, batch: int) -> None:
        """Cost-report leg of the dispatch protocol (observability/costs.py,
        gated on ``costs.enabled()`` by the callers): lower+compile the SAME
        jitted step with the live arguments once per executable and publish
        FLOPs / bytes-accessed / argument+temp HBM. Compile-time only — no
        steady-state recompile, no host sync (``bench.py --smoke`` A/Bs the
        fused loop with capture on)."""
        obs_costs.capture_jit(
            site, fn, args,
            dims=dict(rows=int(self.num_data),
                      rows_padded=int(self.num_data_padded),
                      features=int(self.spec.num_features),
                      num_leaves=int(self.spec.num_leaves),
                      hist_slots=int(self.spec.hist_slots),
                      tree_batch=int(batch), num_models=int(self.num_models),
                      kernel=self.spec.hist_kernel,
                      strategy=self.pctx.strategy,
                      # gates the measured-collectives HLO scan (costs.py):
                      # serial steps never materialize the HLO text
                      n_devices=int(self.pctx.num_devices)))

    def _run_step(self, score, shrinkage: float, custom_gh=None):
        """Dispatch one compiled step against current state; returns new score
        and per-valid score tuples (device)."""
        if custom_gh is None:
            if self._step_fn is None:
                self._step_fn = self._make_step()
            fn, extra = self._step_fn, ()
        else:
            if self._custom_step_fn is None:
                self._custom_step_fn = self._make_step(custom_grads=True)
            fn, extra = self._custom_step_fn, custom_gh
        consts, valid_Xb, valid_scores = self._dispatch_prep(shrinkage)
        args = (consts, valid_Xb, score, valid_scores, self.bag_mask,
                self._rng_key, self._iter_dev, self._shrink_cache[1], *extra)
        if obs_costs.enabled():
            # compile-time cost report of THIS dispatch signature — captured
            # once per (site, executable), before the first call so the AOT
            # compile primes the persistent cache the dispatch then hits
            self._capture_step_cost(
                "train_step.k1" + (".custom" if custom_gh is not None
                                   else ""), fn, args, 1)
        outs = fn(*args)
        nf = None
        if self.nan_policy != "none":
            score, out_valid, self.bag_mask, trees, nl, self._iter_dev, nf = outs
        else:
            score, out_valid, self.bag_mask, trees, nl, self._iter_dev = outs
        self.models.append(list(trees))
        self._num_leaves_dev.append(nl)
        self.iter_ += 1
        self.mutations_ = getattr(self, "mutations_", 0) + 1
        if nf is not None:
            try:
                self._apply_nan_policy(nf)
            except Exception:
                # the pre-step buffers were DONATED to the step — rebind the
                # (gated, bit-identical) outputs before propagating so the
                # booster stays usable and checkpointable after the failure
                self.score = score
                for vi, vs in enumerate(self.valid_sets):
                    vs.score = jnp.stack(out_valid[vi])
                raise
        return score, out_valid

    def _record_nan_event(self, what: str, iteration: int) -> None:
        """Telemetry leg of the nan_policy guard: per-policy counters plus
        an instant trace event per poisoned iteration — the chaos suite
        asserts these land in the JSONL stream (tests/test_chaos.py)."""
        reg = obs.get_registry()
        reg.counter("nan.events").inc()
        reg.counter({"clip": "nan.clipped", "raise": "nan.raised",
                     "skip_iter": "nan.skipped_iters"}.get(
                         self.nan_policy, "nan.other")).inc()
        obs.event("nan_policy", policy=self.nan_policy, what=what,
                  iteration=int(iteration))

    @allowed_host_sync("nan_policy guard: one 3-bool flag fetch per "
                       "iteration, only while the guard is enabled")
    def _apply_nan_policy(self, nf) -> bool:
        """Host-side leg of the non-finite guard: fetch the step's three
        detection flags and enforce self.nan_policy. Under raise/skip_iter
        the step already gated every array output to its pre-step value, so
        recovery here is pure bookkeeping. Returns True iff the iteration
        was dropped."""
        flags = np.asarray(nf)
        if not flags.any():
            self._consecutive_skips = 0
            return False
        from ..robustness.numeric import FLAG_NAMES, NonFiniteError
        what = ", ".join(n for n, f in zip(FLAG_NAMES, flags) if f)
        self._record_nan_event(what, self.iter_ - 1)
        if self.nan_policy == "clip":
            Log.warning("nan_policy=clip: non-finite %s at iteration %d "
                        "were sanitized (NaN->0, Inf->+/-cap)", what,
                        self.iter_ - 1)
            self._consecutive_skips = 0
            return False
        self._pop_last_iteration()
        if self.nan_policy == "raise":
            raise NonFiniteError(
                f"non-finite {what} detected at iteration {self.iter_} "
                f"(nan_policy=raise); booster state is rolled back to the "
                f"last clean iteration and remains checkpointable")
        self._consecutive_skips += 1
        Log.warning("nan_policy=skip_iter: dropped iteration %d "
                    "(non-finite %s); %d consecutive skip(s)", self.iter_,
                    what, self._consecutive_skips)
        if self._consecutive_skips >= 10:
            raise NonFiniteError(
                f"nan_policy=skip_iter: {self._consecutive_skips} "
                f"consecutive iterations produced non-finite {what} — the "
                f"poison is deterministic, aborting instead of spinning")
        return True

    def train_one_iter(self) -> None:
        # span nesting mirrors the fused path: one dispatch ("tree_batch",
        # k=1) holding one iteration — host-side bookkeeping only, no device
        # value is read (the recompile-free steady state is preserved)
        with TIMERS("train_step"), obs.span("tree_batch", k=1), \
                obs.span("iteration", iteration=self.iter_):
            if self.residency == "stream":
                score, out_valid = self._run_streamed_step(
                    self._step_shrinkage())
            else:
                score, out_valid = self._run_step(self.score,
                                                  self._step_shrinkage())
            self.score = score
            for vi, vs in enumerate(self.valid_sets):
                vs.score = jnp.stack(out_valid[vi])

    def _step_shrinkage(self) -> float:
        """Hook: per-tree shrinkage (RF overrides to 1.0, rf.hpp:44-45)."""
        return self.config.learning_rate

    # ------------------------------------- streamed step (tpu_residency=stream)

    def _make_stream_fns(self) -> Dict:
        """Jitted legs of the streamed training step. The resident step is
        ONE jit; in stream mode the shard loop is host-driven, so the step
        splits at the grower boundary into ``pre`` (RNG fold + gradients +
        non-finite detection + bagging), ``prep`` (per-model masked grads +
        feature_fraction mask), ``shrink`` (shrinkage + output transform +
        leaf flag), and ``apply`` (train/valid score updates, nan gating,
        device iteration counter). Each leg traces through the SAME hook
        methods ``step_body`` uses, in the same order, so a streamed
        iteration is bit-identical to a resident one. All shapes are fixed
        — the whole set compiles once per booster (RecompileGuard-pinned in
        tests/test_stream.py)."""
        spec = self.spec
        K = self.num_models
        nan_policy = self.nan_policy
        if nan_policy != "none":
            from ..robustness.numeric import clip_nonfinite, nonfinite_flag

        def make_pre(custom: bool):
            def pre_body(score, bag_mask, key, it, *grads):
                key = jax.random.fold_in(key, it)
                if custom:
                    g, h = grads
                else:
                    g, h = self._gradients(score)
                bad = ()
                if nan_policy != "none":
                    bad_g, bad_h = nonfinite_flag(g), nonfinite_flag(h)
                    if nan_policy == "clip":
                        g, h = clip_nonfinite(g), clip_nonfinite(h)
                    bad = (bad_g, bad_h)
                bkey, fkey = jax.random.split(jax.random.fold_in(key, 0))
                mask, g, h = self._sampling(g, h, bag_mask, bkey, it)
                return (g, h, mask, fkey) + bad
            return pre_body

        def prep_body(g, h, mask, fkey, k):
            return g[k] * mask, h[k] * mask, self._feature_mask(fkey, k)

        def shrink_body(tree, shrinkage):
            return self._shrink_transform_flag(tree, shrinkage)

        def apply_body(score, valid_scores, valid_Xb, bag_mask, mask,
                       trees, leaf_ids, it, flags):
            new_scores = []
            new_valid = [list(vs) for vs in valid_scores] if valid_scores \
                else []
            for k in range(K):
                new_score_k, new_valid_k = self._tree_score_updates(
                    score[k],
                    [new_valid[vi][k] for vi in range(len(valid_Xb))],
                    valid_Xb, trees[k], leaf_ids[k], it)
                new_scores.append(new_score_k)
                for vi in range(len(valid_Xb)):
                    new_valid[vi][k] = new_valid_k[vi]
            out_score = jnp.stack(new_scores)
            out_valid = tuple(tuple(v) for v in new_valid)
            nl = jnp.stack([t.num_leaves for t in trees])
            if nan_policy == "none":
                return out_score, out_valid, mask, nl, it + 1
            bad_g, bad_h, bad_leafs = flags
            bad_leaf = bad_leafs[0]
            for bl in bad_leafs[1:]:
                bad_leaf = bad_leaf | bl
            nf = jnp.stack([bad_g, bad_h, bad_leaf])
            if nan_policy in ("raise", "skip_iter"):
                # hardware-gate every output on the poison flag, exactly
                # like the resident step: a poisoned iteration leaves
                # scores/masks BIT-identical to their pre-step values
                bad = jnp.any(nf)
                out_score = jnp.where(bad, score, out_score)
                out_valid = tuple(
                    tuple(jnp.where(bad, old_k, new_k)
                          for old_k, new_k in zip(old_vs, new_vs))
                    for old_vs, new_vs in zip(valid_scores, out_valid))
                mask = jnp.where(bad, bag_mask, mask)
            return out_score, out_valid, mask, nl, it + 1, nf

        # donate the carried score/valid-scores (and, under bagging, the
        # previous mask) into apply — the streamed twin of _make_step's
        # donate_argnums, with the same rebind-immediately discipline
        donate = () if self.pctx.devices[0].platform == "cpu" else \
            ((0, 1, 3) if self.bagging_on else (0, 1))
        return dict(pre=jax.jit(make_pre(False)),
                    pre_custom=jax.jit(make_pre(True)),
                    prep=jax.jit(prep_body),
                    shrink=jax.jit(shrink_body),
                    apply=jax.jit(apply_body, donate_argnums=donate))

    def _run_streamed_step(self, shrinkage: float, custom_gh=None):
        """One streamed boosting iteration: pre -> per-model (prep ->
        StreamedGrower.grow over the shard prefetcher -> shrink) -> apply,
        with the SAME host bookkeeping contract as ``_run_step`` (models
        appended, counters advanced, then the nan policy fetch)."""
        if self._stream_fns is None:
            self._stream_fns = self._make_stream_fns()
        fns = self._stream_fns
        if self._iter_dev is None:    # first step / post-rollback resync
            self._iter_dev = jnp.asarray(self.iter_, jnp.int32)
        if self._shrink_cache[0] != shrinkage:
            self._shrink_cache = (shrinkage,
                                  jnp.asarray(shrinkage, jnp.float32))
        valid_scores = tuple(tuple(vs.score[k] for k in range(self.num_models))
                             for vs in self.valid_sets)
        valid_Xb = tuple(vs.Xb for vs in self.valid_sets)
        if custom_gh is not None:
            outs = fns["pre_custom"](self.score, self.bag_mask,
                                     self._rng_key, self._iter_dev,
                                     *custom_gh)
        else:
            outs = fns["pre"](self.score, self.bag_mask, self._rng_key,
                              self._iter_dev)
        if self.nan_policy != "none":
            g, h, mask, fkey, bad_g, bad_h = outs
        else:
            g, h, mask, fkey = outs
            bad_g = bad_h = None
        trees, leaf_ids, bad_leafs = [], [], []
        for k in range(self.num_models):
            gk, hk, fmask = fns["prep"](g, h, mask, fkey, np.int32(k))
            tree_raw, lid = self._streamed_grower.grow(
                self._stream, gk, hk, mask, fmask)
            tree, bl = fns["shrink"](tree_raw, self._shrink_cache[1])
            if bl is not None:
                bad_leafs.append(bl)
            trees.append(tree)
            leaf_ids.append(lid)
        flags = ((bad_g, bad_h, tuple(bad_leafs))
                 if self.nan_policy != "none" else None)
        outs = fns["apply"](self.score, valid_scores, valid_Xb,
                            self.bag_mask, mask, tuple(trees),
                            tuple(leaf_ids), self._iter_dev, flags)
        nf = None
        if self.nan_policy != "none":
            score, out_valid, self.bag_mask, nl, self._iter_dev, nf = outs
        else:
            score, out_valid, self.bag_mask, nl, self._iter_dev = outs
        self.models.append(list(trees))
        self._num_leaves_dev.append(nl)
        self.iter_ += 1
        self.mutations_ = getattr(self, "mutations_", 0) + 1
        if nf is not None:
            try:
                self._apply_nan_policy(nf)
            except Exception:
                # the pre-step score/valid buffers were DONATED to apply —
                # rebind the (gated, bit-identical) outputs before
                # propagating, exactly like the resident path
                self.score = score
                for vi, vs in enumerate(self.valid_sets):
                    vs.score = jnp.stack(out_valid[vi])
                raise
        return score, out_valid

    # --------------------------------------------- fused multi-tree dispatch

    def train_batch(self, n: int) -> None:
        """Run ``n`` boosting iterations in ONE jit dispatch (tree_batch).

        Equivalent to ``n`` calls of :meth:`train_one_iter` (bit-identical —
        the scan body is the same ``step_body``), but score updates, tree
        growth, and leaf application never leave HBM between trees and the
        host pays dispatch + bookkeeping cost once per batch. Metric eval /
        callbacks happen at the caller's batch boundaries (engine.py)."""
        if n <= 1:
            return self.train_one_iter()
        if self.residency == "stream":
            # tree_batch is forced to 1 at construction (the shard loop is
            # host-driven); a direct caller still gets the equivalent
            # semantics, unfused
            for _ in range(n):
                self.train_one_iter()
            return
        base_iter = self.iter_
        with TIMERS("train_step"), obs.span("tree_batch", k=n):
            self._run_fused_batch(n)
        # the fused scan is ONE dispatch — per-iteration spans inside it are
        # derived (even slices of the batch span, labeled as such); recorded
        # after the span closes, host-side only
        obs.get_tracer().subdivide_last("tree_batch", "iteration", n,
                                        base_iteration=base_iter)

    def _run_fused_batch(self, n: int) -> None:
        fn = self._batch_step_fns.get(n)
        if fn is None:
            fn = self._make_step(batch=n)
            self._batch_step_fns[n] = fn
        consts, valid_Xb, valid_scores = self._dispatch_prep(
            self._step_shrinkage())
        args = (consts, valid_Xb, self.score, valid_scores, self.bag_mask,
                self._rng_key, self._iter_dev, self._shrink_cache[1])
        if obs_costs.enabled():
            self._capture_step_cost(f"train_step.k{n}", fn, args, n)
        outs = fn(*args)
        nf = None
        if self.nan_policy != "none":
            score, out_valid, self.bag_mask, trees, nl, self._iter_dev, nf = outs
        else:
            score, out_valid, self.bag_mask, trees, nl, self._iter_dev = outs
        # per-iteration bookkeeping from the stacked batch outputs: lazy
        # device-side slices (no host sync), so checkpoints / rollback /
        # finalize keep their list-of-iterations contract unchanged
        base_iter = self.iter_
        base_len = len(self.models)
        for i in range(n):
            self.models.append([
                jax.tree.map(lambda x, i=i: x[i], tk) for tk in trees])
            self._num_leaves_dev.append(nl[i])
        self.iter_ += n
        self.mutations_ = getattr(self, "mutations_", 0) + n
        self.score = score
        for vi, vs in enumerate(self.valid_sets):
            vs.score = jnp.stack(out_valid[vi])
        if nf is not None:
            self._apply_nan_policy_batch(nf, base_iter, base_len, n)

    @allowed_host_sync("nan_policy guard: one [K, 3] flag fetch per fused "
                       "batch, only while the guard is enabled")
    def _apply_nan_policy_batch(self, nf, base_iter: int, base_len: int,
                                n: int) -> None:
        """Batch-boundary leg of the non-finite guard under tree_batch>1:
        fetch the stacked per-iteration flags once and enforce the policy
        per inner iteration. A poisoned inner step was already hardware-
        gated to a bit-identical no-op inside the scan, so recovery drops
        its (zero-contribution) bookkeeping entry. Unlike the K=1 path, a
        skipped iteration's RNG draw is consumed — ``iter_`` and the device
        counter keep advancing through the batch (so no same-key retry
        spin), which means ``iter_`` counts attempted steps and can exceed
        ``len(models)`` after drops."""
        flags = np.asarray(nf)                              # [n, 3]
        if not flags.any():
            self._consecutive_skips = 0
            return
        from ..robustness.numeric import FLAG_NAMES, NonFiniteError

        def _what(i):
            return ", ".join(nm for nm, f in zip(FLAG_NAMES, flags[i]) if f)

        for i in np.nonzero(flags.any(axis=1))[0]:
            self._record_nan_event(_what(int(i)), base_iter + int(i))
        if self.nan_policy == "clip":
            for i in np.nonzero(flags.any(axis=1))[0]:
                Log.warning("nan_policy=clip: non-finite %s at iteration %d "
                            "were sanitized (NaN->0, Inf->+/-cap)",
                            _what(i), base_iter + int(i))
            self._consecutive_skips = 0
            return
        if self.nan_policy == "raise":
            i = int(np.nonzero(flags.any(axis=1))[0][0])
            what = _what(i)
            # roll the batch back to the last clean iteration: trailing
            # CLEAN trees are subtracted (they trained from the gated carry
            # and are valid, but "raise" promises state at the failure
            # point); trailing POISONED entries were gated no-ops whose
            # trees may hold non-finite leaf values — subtracting those
            # would NaN-poison the "rolled back" scores, so they are popped
            # without arithmetic. Finally the first poisoned entry drops.
            for j in range(n - 1, i, -1):
                if flags[j].any():
                    self._pop_last_iteration()
                else:
                    self.rollback_one_iter()
            self._pop_last_iteration()
            raise NonFiniteError(
                f"non-finite {what} detected at iteration {base_iter + i} "
                f"(nan_policy=raise, tree_batch={n}); booster state is "
                f"rolled back to the last clean iteration and remains "
                f"checkpointable")
        # skip_iter: drop poisoned entries (their steps were gated no-ops,
        # so the carried scores already exclude them); iter_ / the device
        # counter stay advanced so the RNG stream never reuses a key
        for i in sorted(np.nonzero(flags.any(axis=1))[0], reverse=True):
            Log.warning("nan_policy=skip_iter: dropped iteration %d "
                        "(non-finite %s)", base_iter + int(i), _what(i))
            del self.models[base_len + int(i)]
            del self._num_leaves_dev[base_len + int(i)]
        self.mutations_ = getattr(self, "mutations_", 0) + 1
        # consecutive-skip accounting walks the batch in order
        for i in range(n):
            if flags[i].any():
                self._consecutive_skips += 1
                if self._consecutive_skips >= 10:
                    raise NonFiniteError(
                        f"nan_policy=skip_iter: {self._consecutive_skips} "
                        f"consecutive iterations produced non-finite values "
                        f"— the poison is deterministic, aborting instead "
                        f"of spinning")
            else:
                self._consecutive_skips = 0

    # ---------------------------------------------------- custom objective

    def train_one_iter_custom(self, fobj) -> None:
        """One iteration with user-supplied gradients (reference
        LGBM_BoosterUpdateOneIterCustom, c_api.cpp:892): fobj(preds, dataset)
        -> (grad, hess) as numpy [K*N] in class-major order."""
        K, Npad, N = self.num_models, self.num_data_padded, self.num_data
        if self._block_counts is not None:
            Log.fatal("custom objectives are not supported with "
                      "is_pre_partition (host gradients need the full score "
                      "vector on every process)")
        with obs.span("tree_batch", k=1, custom_fobj=True), \
                obs.span("iteration", iteration=self.iter_):
            preds = self._fetch(self.score)[:, :N].reshape(-1)
            grad, hess = fobj(preds, self.train_set)
            g = np.zeros((K, Npad), np.float32)
            h = np.zeros((K, Npad), np.float32)
            g[:, :N] = np.asarray(grad, np.float32).reshape(K, N)
            h[:, :N] = np.asarray(hess, np.float32).reshape(K, N)
            custom_gh = (self._put(g, "rows1"), self._put(h, "rows1"))
            if self.residency == "stream":
                score, out_valid = self._run_streamed_step(
                    self.config.learning_rate, custom_gh=custom_gh)
            else:
                score, out_valid = self._run_step(
                    self.score, self.config.learning_rate,
                    custom_gh=custom_gh)
            self.score = score
            for vi, vs in enumerate(self.valid_sets):
                vs.score = jnp.stack(out_valid[vi])

    def add_base_score(self, raw_scores: np.ndarray,
                       valid_raw: Optional[List[np.ndarray]] = None) -> None:
        """Seed scores with a loaded model's predictions — continued training
        (reference: input_model re-predicted onto the data via PredictFunction,
        application.cpp:90-93 / boosting.h:281-284)."""
        K, Npad, N = self.num_models, self.num_data_padded, self.num_data
        add = np.zeros((K, Npad), np.float32)
        add[:, :N] = np.asarray(raw_scores, np.float32).reshape(K, N)
        self.score = self.score + self._put(add, "rows1")
        for vi, vs in enumerate(self.valid_sets):
            if valid_raw is not None and vi < len(valid_raw):
                vs.score = vs.score + self._put(
                    np.asarray(valid_raw[vi], np.float32).reshape(K, vs.num_data))

    def rollback_one_iter(self) -> None:
        """Reference GBDT::RollbackOneIter (gbdt.cpp:475-491): pop the last
        iteration's trees and subtract their contribution from all scores."""
        if self.average_output:
            Log.fatal("rollback_one_iter is not supported for rf boosting "
                      "(scores are running averages, not additive)")
        if self.residency == "stream":
            # subtracting a tree's contribution replays leaves_from_binned
            # over the full resident code matrix — which stream mode never
            # materializes. The nan_policy=raise path does not need it
            # (streamed steps gate their outputs before committing).
            Log.fatal("rollback_one_iter is not supported with "
                      "tpu_residency=stream (no resident code matrix to "
                      "replay leaf assignments from)")
        if not self.models:
            return
        trees = self.models.pop()
        self._num_leaves_dev.pop()
        self.iter_ -= 1
        self.mutations_ = getattr(self, "mutations_", 0) + 1
        self._iter_dev = None           # device counter resyncs next step
        score = self.score
        new_scores = []
        for k, tree in enumerate(trees):
            leaves = leaves_from_binned(tree, self.Xb, self.num_bins,
                                        self.missing_code, self.default_bin,
                                        bundle=self.bundle)
            if self.linear_tree:
                # subtract the SAME per-row linear output the step added
                from ..ops.linear import linear_leaf_scores
                contrib = linear_leaf_scores(tree, leaves, self.Xraw,
                                             self.Xmiss)
            else:
                contrib = tree.leaf_value[leaves]
            new_scores.append(score[k] - contrib)
            for vs in self.valid_sets:
                vleaves = leaves_from_binned(tree, vs.Xb, self.num_bins,
                                             self.missing_code, self.default_bin)
                if self.linear_tree:
                    from ..ops.linear import linear_leaf_scores
                    vcontrib = linear_leaf_scores(tree, vleaves, vs.Xraw,
                                                  vs.Xmiss)
                else:
                    vcontrib = tree.leaf_value[vleaves]
                vs.score = vs.score.at[k].add(-vcontrib)
        self.score = jnp.stack(new_scores)

    def reset_config(self, new_config: Config) -> None:
        """Apply per-iteration tunable parameters (reference
        LGBM_BoosterResetParameter). Structural parameters (num_leaves,
        max_bin, ...) are compiled into the grower and cannot change here;
        learning_rate & bagging settings take effect next iteration."""
        old = self.config
        self.config = new_config
        self.bagging_on = (new_config.bagging_freq > 0
                           and new_config.bagging_fraction < 1.0)
        if self.bagging_on and self.bag_mask is self.pad_mask:
            # bagging enabled mid-training: the carried mask is about to be
            # DONATED by the retraced step, so it must stop aliasing
            # pad_mask (the same invariant __init__ establishes)
            self.bag_mask = self.pad_mask + 0
        # Hyperparameters baked into GrowerSpec as trace-time constants take
        # effect by rebuilding the spec and dropping the cached executable.
        spec_changes = {}
        for field, attr in (
                ("lambda_l1", "lambda_l1"), ("lambda_l2", "lambda_l2"),
                ("min_gain_to_split", "min_gain_to_split"),
                ("cat_smooth", "cat_smooth"), ("cat_l2", "cat_l2"),
                ("max_cat_threshold", "max_cat_threshold"),
                ("max_cat_to_onehot", "max_cat_to_onehot")):
            if getattr(old, attr) != getattr(new_config, attr):
                spec_changes[field] = getattr(new_config, attr)
        if old.min_data_in_leaf != new_config.min_data_in_leaf:
            spec_changes["min_data_in_leaf"] = float(new_config.min_data_in_leaf)
        if old.min_sum_hessian_in_leaf != new_config.min_sum_hessian_in_leaf:
            spec_changes["min_sum_hessian_in_leaf"] = new_config.min_sum_hessian_in_leaf
        if old.min_data_per_group != new_config.min_data_per_group:
            spec_changes["min_data_per_group"] = float(new_config.min_data_per_group)
        retrace = bool(spec_changes)
        if old.linear_tree != new_config.linear_tree:
            # structural: the raw slice placement and every score-update
            # epilogue are decided at construction
            Log.fatal("linear_tree cannot change via reset_parameter "
                      "(rebuild the Booster)")
        if (old.linear_lambda != new_config.linear_lambda
                or old.linear_max_features != new_config.linear_max_features):
            retrace = True
        if spec_changes:
            import dataclasses
            self.spec = dataclasses.replace(self.spec, **spec_changes)
        # bagging fraction/freq are also compiled-in constants (learning_rate
        # is a traced argument — per-iteration schedules must not re-trace)
        if (old.bagging_freq != new_config.bagging_freq
                or old.bagging_fraction != new_config.bagging_fraction
                or old.feature_fraction != new_config.feature_fraction):
            retrace = True
        if old.nan_policy != new_config.nan_policy:
            # the guard is a trace-time constant: toggling it changes the
            # step program (and its output arity)
            self.nan_policy = new_config.nan_policy
            retrace = True
        if old.feature_fraction != new_config.feature_fraction:
            F = self.train_set.num_features
            self.n_feature_sample = max(
                1, int(round(new_config.feature_fraction * F)))
            self.use_feature_fraction = (new_config.feature_fraction < 1.0
                                         and self.n_feature_sample < F)
        if retrace:
            self._step_fn = None
            self._custom_step_fn = None
            self._batch_step_fns = {}
            self._stream_fns = None

    def _pop_last_iteration(self) -> None:
        """Drop the last appended iteration's bookkeeping WITHOUT score
        arithmetic — for iterations whose contribution never reached the
        scores (the no-splits pop; a nan_policy-gated no-op step). Contrast
        rollback_one_iter, which also subtracts the trees' contribution."""
        self.models.pop()
        self._num_leaves_dev.pop()
        self.iter_ -= 1
        self.mutations_ = getattr(self, "mutations_", 0) + 1
        self._iter_dev = None           # device counter resyncs next step

    def _check_no_splits(self) -> bool:
        """Reference gbdt.cpp:465-471: pop the no-split iteration(s) and stop
        when no tree could split. Checked at eval/batch boundaries, so ALL
        trailing degenerate iterations are popped — under tree_batch>1 (or
        metric_freq>1) several zero-value single-leaf trees can accumulate
        between checks."""
        popped = False
        while self._num_leaves_dev and \
                (np.asarray(self._num_leaves_dev[-1]) <= 1).all():
            self._pop_last_iteration()
            popped = True
        if popped:
            Log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements.")
        return popped

    # ------------------------------------------------------------------- eval

    def _fetch(self, arr) -> np.ndarray:
        """Device->host fetch that works for row-sharded arrays under
        multi-host execution (reassembles the global value on every process
        — the analog of the reference's metric eval running on each rank's
        local rows + allreduce; here metrics are computed on the full vector)."""
        if self.pctx.multi_process and not arr.is_fully_replicated:
            from jax.experimental import multihost_utils
            return np.asarray(multihost_utils.process_allgather(arr, tiled=True))
        return np.asarray(arr)

    def eval_all(self, force_training=False, only=None
                 ) -> List[Tuple[str, str, float, bool]]:
        """only=<dataset name>: evaluate just that dataset (single-dataset
        entry points must not pay for every attached valid set)."""
        with TIMERS("metric_eval"), obs.span("eval", only=only):
            return self._eval_all(force_training, only)

    def _eval_all(self, force_training=False, only=None
                  ) -> List[Tuple[str, str, float, bool]]:
        """Metric evaluation with a DEVICE scalar path for the pointwise
        family: the weighted-average loss reduces on device and only one
        scalar per metric crosses to the host (VERDICT r2 weak #9 — the
        full-score fetch per eval was the next bottleneck). Rank/AUC/
        multiclass metrics still fetch the converted scores."""
        from ..metrics import _PointwiseRegressionMetric
        out: List = []
        pending: List[Tuple[int, object]] = []   # (out index, device scalar)

        def eval_dataset(dname, metrics, score_dev, label_dev, weight_dev,
                         mask_dev, fetch_conv):
            conv_dev = None
            conv_host = None
            for m in metrics:
                use_dev = (isinstance(m, _PointwiseRegressionMetric)
                           and self.num_models == 1)
                if use_dev:
                    if conv_dev is None:
                        conv_dev = self._convert(score_dev)
                    loss = m.loss(conv_dev[0], label_dev)
                    if weight_dev is None and mask_dev is None:
                        val = jnp.mean(loss)
                    else:
                        w = mask_dev if weight_dev is None else (
                            weight_dev if mask_dev is None
                            else weight_dev * mask_dev)
                        val = jnp.sum(loss * w) / jnp.sum(w)
                    out.append([dname, m.name, None, m.is_higher_better, m])
                    pending.append((len(out) - 1, val))
                else:
                    if conv_host is None:
                        conv_host = fetch_conv()
                    for name, value, hib in m.eval(conv_host):
                        out.append([dname, name, value, hib, None])

        if (self.config.is_training_metric or force_training) \
                and self.train_metrics and only in (None, "training"):
            eval_dataset(
                "training", self.train_metrics, self.score, self.label,
                self.weight, self.pad_mask,
                lambda: self._fetch(self._convert(self.score))[:, self._real_rows()])
        for vs in self.valid_sets:
            if only is not None and vs.name != only:
                continue
            if not hasattr(vs, "label_dev"):
                vs.label_dev = self._put(
                    np.asarray(vs.metadata.label, np.float32))
                w = vs.metadata.weight
                vs.weight_dev = None if w is None else self._put(
                    np.asarray(w, np.float32))
            eval_dataset(
                vs.name, vs.metrics, vs.score, vs.label_dev, vs.weight_dev,
                None, lambda vs=vs: self._fetch(self._convert(vs.score)))

        if pending:
            fetched = jax.device_get([v for (_i, v) in pending])
            for (i, _v), raw in zip(pending, fetched):
                m = out[i][4]
                out[i][2] = m.transform(float(raw))
        return [(d, n, v, h) for (d, n, v, h, _m) in out]

    def _convert(self, score):
        if self.objective is None or self.average_output:
            # RF scores are already averages of converted outputs (rf.hpp)
            return score
        return self.objective.convert_output(score)

    # ------------------------------------- checkpoint (robustness/checkpoint)

    @allowed_host_sync("checkpoint snapshot: full training-state fetch at an "
                       "iteration boundary, on demand only")
    def checkpoint_state(self) -> Dict:
        """Every array/counter the training step reads or writes, as host
        values (the ``state`` field of a checkpoint payload): raw scores,
        the carried bagging mask, the raw RNG key, the device forest
        (TreeArrays pytrees), per-iteration leaf counts, and the iteration/
        mutation counters. ``restore_checkpoint_state`` replays them so
        continued training is bit-identical to a never-interrupted run."""
        return {
            "iter": int(self.iter_),
            "data_fingerprint": self._data_fingerprint,
            "mutations": int(getattr(self, "mutations_", 0)),
            "consecutive_skips": int(self._consecutive_skips),
            "num_data": int(self.num_data),
            "num_data_padded": int(self.num_data_padded),
            "num_models": int(self.num_models),
            # mesh provenance: restore rejects a device-count change loudly
            # (or re-shards deliberately under tpu_reshard_on_resume) —
            # sharded state must never produce a silent shape error
            "n_devices": int(self.pctx.num_devices),
            "tree_learner": self.pctx.strategy,
            "block_layout": (None if self._block_counts is None
                             else list(self._block_counts)),
            "init_score_value": float(self.init_score_value),
            "score": np.asarray(self._fetch(self.score), np.float32),
            "bag_mask": np.asarray(self._fetch(self.bag_mask), np.float32),
            "rng_key": np.asarray(self._rng_key),
            "models": jax.device_get(self.models),
            "num_leaves": jax.device_get(self._num_leaves_dev),
            "valid_scores": {vs.name: np.asarray(vs.score)
                             for vs in self.valid_sets},
            "best_iteration": int(getattr(self, "best_iteration", 0)),
        }

    def restore_checkpoint_state(self, state: Dict) -> None:
        """Inverse of ``checkpoint_state``: replay a snapshot into this
        booster. Shape mismatches fail loudly. Restored arrays are placed
        with the same sharding kinds construction used, so an
        already-compiled step keeps hitting its executable — resume costs
        the normal first-step compile and nothing more (RecompileGuard-
        verified in ``bench.py --smoke``).

        Device-count changes are checked FIRST: a snapshot written on a
        different mesh is rejected loudly (the padded row layout, and under
        pre-partition the block layout, are functions of the device count —
        letting it through would surface as an opaque shape error). Setting
        ``tpu_reshard_on_resume=true`` re-shards deliberately instead: the
        training state is global-semantics (scores/masks in global row
        order, trees replicated), so the padded rows are re-laid-out onto
        this booster's mesh. Pre-partitioned snapshots never re-shard."""
        saved_d = state.get("n_devices")
        reshard = (saved_d is not None
                   and int(saved_d) != int(self.pctx.num_devices))
        if reshard:
            if not getattr(self.config, "tpu_reshard_on_resume", False):
                Log.fatal(
                    "checkpoint/mesh mismatch: the snapshot was written on "
                    "%d device(s) (tree_learner=%s) but this booster runs "
                    "on %d (%s) — sharded training state does not resume "
                    "across device counts. Rerun on the original mesh, or "
                    "set tpu_reshard_on_resume=true to re-shard the global "
                    "state deliberately", int(saved_d),
                    state.get("tree_learner", "?"), self.pctx.num_devices,
                    self.pctx.strategy)
            if state.get("block_layout") or self._block_counts is not None:
                Log.fatal(
                    "tpu_reshard_on_resume: pre-partitioned snapshots hold "
                    "per-process row blocks and cannot re-shard — resume on "
                    "the original process count")
            Log.warning("tpu_reshard_on_resume: re-sharding checkpoint "
                        "state written on %d device(s) onto %d (%s)",
                        int(saved_d), self.pctx.num_devices,
                        self.pctx.strategy)
        saved_tl = state.get("tree_learner")
        if saved_tl is not None and saved_tl != self.pctx.strategy \
                and not reshard:
            # as loud as the device-count guard above: a strategy swap at
            # the SAME device count changes what the carried row state
            # means (row-sharded vs replicated scores/masks) — never
            # silently reinterpretable. Only an authorized reshard (device
            # count changed + tpu_reshard_on_resume) may re-resolve the
            # strategy, e.g. data -> serial when a gang shrinks to one
            # device.
            Log.fatal(
                "checkpoint/learner mismatch: the snapshot was written "
                "under tree_learner=%s but this booster runs %s on the "
                "same device count — resume needs the same tree_learner "
                "(a strategy change is only honored through an elastic "
                "reshard: device count change + tpu_reshard_on_resume=true)",
                saved_tl, self.pctx.strategy)
        shape_checks = [("num_data", self.num_data),
                        ("num_models", self.num_models)]
        if not reshard:
            shape_checks.append(("num_data_padded", self.num_data_padded))
        for name, mine in shape_checks:
            if int(state[name]) != int(mine):
                Log.fatal("checkpoint/booster mismatch: %s is %d in the "
                          "snapshot but %d here — resume needs the same "
                          "dataset and training config", name,
                          int(state[name]), int(mine))
        fp = state.get("data_fingerprint")
        if fp and fp != self._data_fingerprint:
            Log.fatal("checkpoint/dataset mismatch: the snapshot was written "
                      "against different training data (binned-code/label "
                      "fingerprint differs) — a shape-compatible but "
                      "different dataset would silently corrupt the resumed "
                      "model")

        def _relayout(arr):
            # deliberate re-shard: the saved padded layout ([..., Npad_old],
            # real rows at the head — block layouts were rejected above) is
            # re-laid-out onto this booster's padding. Padding positions
            # carry no training signal (gradients are pad-masked; scores of
            # padding rows never reach metrics), so a zero refill is exact.
            arr = np.asarray(arr, np.float32)
            if not reshard or arr.shape[-1] == self.num_data_padded:
                return arr
            real = arr[..., : self.num_data]
            if real.ndim == 1:
                return self._row_layout(real)
            return np.stack([self._row_layout(r) for r in real])

        self.score = self._put(_relayout(state["score"]), "rows1")
        self.bag_mask = self._put(_relayout(state["bag_mask"]), "rows")
        self._rng_key = self._put(np.asarray(state["rng_key"]))
        self.models = [[jax.tree.map(self._put, t) for t in it_trees]
                       for it_trees in state["models"]]
        self._num_leaves_dev = [self._put(nl) for nl in state["num_leaves"]]
        self.iter_ = int(state["iter"])
        # restored iterations were trained (and counted) by the run that
        # wrote the snapshot — telemetry must only count what THIS run adds
        self._telemetry_iters_base = len(self.models)
        self.mutations_ = int(state["mutations"])
        self._consecutive_skips = int(state.get("consecutive_skips", 0))
        self.init_score_value = float(state["init_score_value"])
        self.best_iteration = int(state.get("best_iteration", 0))
        self._iter_dev = None           # device counter resyncs next step
        self._shrink_cache = (None, None)
        restored = state.get("valid_scores", {})
        for vs in self.valid_sets:
            if vs.name in restored:
                vs.score = self._put(
                    np.asarray(restored[vs.name], np.float32))
            else:
                Log.warning("checkpoint has no saved scores for valid set "
                            "%r — its eval scores restart from the initial "
                            "model", vs.name)

    # -------------------------------------------------------------- telemetry

    @allowed_host_sync("telemetry flush: one per-training-run leaf-count "
                       "fetch at an iteration boundary, only while span "
                       "recording is enabled")
    def publish_telemetry(self) -> None:
        """Flush this booster's per-run training facts into the telemetry
        subsystem (engine.train calls it once, after the loop): trained-tree
        and routed-row counters always; with span recording enabled, one
        batched leaf-count fetch derives the per-tree wave counts
        (grower.waves_for_tree — a host-side model of the wave loop, no
        per-wave device traffic) that become the ``wave`` child spans of
        each recorded ``iteration`` span and the ``tree.waves``/
        ``tree.leaves`` histograms."""
        reg = obs.get_registry()
        base = min(self._telemetry_iters_base, len(self.models))
        n_new = len(self.models) - base
        self._telemetry_iters_base = len(self.models)
        if n_new:
            # only the iterations THIS run trained: restored-checkpoint and
            # already-published iterations sit below the high-water mark
            reg.counter("trees.trained").inc(n_new * self.num_models)
            reg.counter("rows.routed").inc(
                n_new * self.num_models * self.num_data)
        if not obs.enabled() or not n_new:
            return
        leaves = jax.device_get(self._num_leaves_dev[base:])  # [n_new][K]
        wave_hist = reg.histogram("tree.waves")
        leaf_hist = reg.histogram("tree.leaves")
        counts = []
        for nl in leaves:
            nl = np.atleast_1d(np.asarray(nl))
            # K trees grow concurrently inside one iteration's dispatch;
            # the iteration's wave count is the deepest tree's
            counts.append(max(waves_for_tree(int(v), self.spec.wave_size,
                                             self.spec.hist_slots)
                              for v in nl))
            wave_hist.observe(counts[-1])
            for v in nl:
                leaf_hist.observe(int(v))
        obs.get_tracer().derive_children("iteration", "wave", counts)

    # ------------------------------------------------------------------ model

    def finalize_model(self) -> List[List[Tree]]:
        """Fetch device trees to host Tree objects (one transfer), fold the
        boost-from-average bias into the first tree (gbdt.cpp:445-447)."""
        with TIMERS("finalize_fetch"):
            host = jax.device_get(self.models)
        mappers = self.train_set.mappers
        rfi = self.train_set.real_feature_idx
        forest: List[List[Tree]] = []
        for it_trees in host:
            forest.append([tree_from_device_arrays(t, mappers, rfi) for t in it_trees])
        if forest and abs(self.init_score_value) > 1e-15:
            for k in range(self.num_models):
                forest[0][k].add_bias(self.init_score_value)
        if self.linear_tree and forest:
            # loud degrade accounting: every leaf either fitted a linear
            # model or serialized with an EMPTY feature list (constant
            # fallback) — surface the split so a silently-degraded run is
            # visible in the log and the metrics registry. High-water
            # mark: finalize_model re-runs on every _ensure_finalized, so
            # only iterations not yet accounted count (rollback lowers the
            # mark; retrained iterations count again like new trees).
            base = min(getattr(self, "_linear_counted_iters", 0),
                       len(forest))
            n_lin = n_const = 0
            for it_trees in forest[base:]:
                for t in it_trees:
                    for li in range(t.num_leaves):
                        if t.leaf_features is not None and \
                                len(t.leaf_features[li]):
                            n_lin += 1
                        else:
                            n_const += 1
            self._linear_counted_iters = len(forest)
            if n_lin or n_const:
                reg = obs.get_registry()
                reg.counter("linear.leaves.linear").inc(n_lin)
                reg.counter("linear.leaves.constant").inc(n_const)
                if n_lin == 0 and self.config.tpu_linear_warn_fallback \
                        and not getattr(self, "_linear_warned", False):
                    self._linear_warned = True
                    Log.warning(
                        "linear_tree: every one of the %d leaves degraded "
                        "to constant output (categorical paths, too few "
                        "rows, or ill-conditioned solves) — the model is "
                        "valid but carries no linear leaves; raise "
                        "linear_lambda or check the feature set", n_const)
                else:
                    Log.info("linear_tree: %d linear leaves, %d constant-"
                             "fallback leaves", n_lin, n_const)
        return forest


def create_boosting(config: Config, train_set: ConstructedDataset) -> GBDT:
    """Factory (reference: boosting.cpp:42-66)."""
    btype = config.boosting_normalized
    if btype == "gbdt":
        return GBDT(config, train_set)
    if btype == "goss":
        from .goss import GOSS
        return GOSS(config, train_set)
    if btype == "dart":
        from .dart import DART
        return DART(config, train_set)
    if btype == "rf":
        from .rf import RF
        return RF(config, train_set)
    Log.fatal("Unknown boosting type %s", config.boosting_type)
