"""`python -m lightgbm_tpu` — CLI entry (reference src/main.cpp)."""
from .cli import main

if __name__ == "__main__":
    raise SystemExit(main())
