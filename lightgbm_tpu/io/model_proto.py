"""Protobuf model format — the reference fork's differentiator.

Reference: proto/model.proto + src/proto/gbdt_model_proto.cpp
(SaveModelToProto/LoadModelFromProto, boosting.h:194-208). Wire-compatible:
same message layout and field numbers (see proto/model.proto here), so models
serialize/parse across implementations.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ..tree import Tree
from . import model_pb2
from .model_text import _feature_infos, _objective_string


def _tree_to_proto(t: Tree, msg) -> None:
    M = t.num_internal
    msg.num_leaves = t.num_leaves
    num_cat = 0 if t.cat_boundaries is None else len(t.cat_boundaries) - 1
    msg.num_cat = num_cat
    msg.split_feature.extend(int(v) for v in t.split_feature[:M])
    msg.split_gain.extend(float(v) for v in t.split_gain[:M])
    msg.threshold.extend(float(v) for v in t.threshold[:M])
    msg.decision_type.extend(int(v) for v in t.decision_type[:M])
    msg.left_child.extend(int(v) for v in t.left_child[:M])
    msg.right_child.extend(int(v) for v in t.right_child[:M])
    msg.leaf_value.extend(float(v) for v in t.leaf_value[: t.num_leaves])
    msg.leaf_count.extend(int(v) for v in t.leaf_count[: t.num_leaves])
    msg.internal_value.extend(float(v) for v in t.internal_value[:M])
    msg.internal_count.extend(float(v) for v in t.internal_count[:M])
    if num_cat > 0:
        msg.cat_boundaries.extend(int(v) for v in t.cat_boundaries)
        msg.cat_threshold.extend(int(v) for v in t.cat_threshold)
    if t.leaf_features is not None:
        # linear leaves: flattened pools + per-leaf counts (proto fields
        # 16-20; doubles are wire-exact, so the round trip is bit-exact)
        msg.is_linear = True
        msg.leaf_const.extend(float(v) for v in t.leaf_const[: t.num_leaves])
        msg.leaf_num_features.extend(
            len(f) for f in t.leaf_features[: t.num_leaves])
        msg.leaf_features.extend(
            int(v) for f in t.leaf_features[: t.num_leaves] for v in f)
        msg.leaf_coeff.extend(
            float(v) for c in t.leaf_coeff[: t.num_leaves] for v in c)
    msg.shrinkage = float(t.shrinkage)


def _tree_from_proto(msg) -> Tree:
    num_leaves = msg.num_leaves
    M = num_leaves - 1
    thresholds = np.array(msg.threshold[:M], dtype=np.float64)
    decision_types = np.array(msg.decision_type[:M], dtype=np.uint8)
    # categorical nodes store their cat_boundaries index in `threshold`
    # (same convention as the text format, tree.cpp ToString) — it must
    # come back as threshold_bin or every categorical split dereferences
    # bitset 0 after a proto round trip
    is_cat_node = (decision_types & 1).astype(bool)
    threshold_bin = np.zeros(M, dtype=np.int32)
    threshold_bin[is_cat_node] = thresholds[is_cat_node].astype(np.int32)
    tree = Tree(
        num_leaves=num_leaves,
        split_feature=np.array(msg.split_feature[:M], dtype=np.int32),
        threshold_bin=threshold_bin,
        threshold=thresholds,
        decision_type=decision_types,
        left_child=np.array(msg.left_child[:M], dtype=np.int32),
        right_child=np.array(msg.right_child[:M], dtype=np.int32),
        split_gain=np.array(msg.split_gain[:M], dtype=np.float64),
        internal_value=np.array(msg.internal_value[:M], dtype=np.float64),
        internal_count=np.array(msg.internal_count[:M], dtype=np.int64),
        leaf_value=np.array(msg.leaf_value[:num_leaves], dtype=np.float64),
        leaf_count=np.array(msg.leaf_count[:num_leaves], dtype=np.int64),
        leaf_parent=np.full(max(num_leaves, 1), -1, dtype=np.int32),
        shrinkage=msg.shrinkage or 1.0,
    )
    if msg.num_cat > 0:
        tree.cat_boundaries = np.array(msg.cat_boundaries, dtype=np.int32)
        tree.cat_threshold = np.array(msg.cat_threshold, dtype=np.uint32)
    if msg.is_linear:
        flat_f = np.array(msg.leaf_features, dtype=np.int32)
        flat_c = np.array(msg.leaf_coeff, dtype=np.float64)
        feats, coeffs, off = [], [], 0
        for k in msg.leaf_num_features:
            feats.append(flat_f[off: off + k])
            coeffs.append(flat_c[off: off + k])
            off += int(k)
        tree.leaf_features = feats
        tree.leaf_coeff = coeffs
        tree.leaf_const = np.array(msg.leaf_const, dtype=np.float64)
    return tree


def save_model_proto(booster, filename: str, num_iteration: Optional[int] = None) -> None:
    K = max(booster.num_model_per_iteration, 1)
    trees = booster.trees
    if num_iteration is not None and num_iteration > 0:
        trees = trees[: num_iteration * K]
    m = model_pb2.Model()
    m.name = "tree"
    m.num_class = booster.config.num_class
    m.num_tree_per_iteration = K
    m.label_index = 0
    m.max_feature_idx = booster.num_total_features - 1
    m.objective = _objective_string(booster)
    m.average_output = booster.config.boosting_normalized == "rf"
    m.feature_names.extend(booster.feature_names or
                           [f"Column_{i}" for i in range(booster.num_total_features)])
    m.feature_infos.extend(_feature_infos(booster))
    for t in trees:
        _tree_to_proto(t, m.trees.add())
    # atomic, like the text writer: concurrent same-host ranks must not
    # interleave into a truncated file
    tmp = f"{filename}.tmp.{os.getpid()}"
    with open(tmp, "wb") as fh:
        fh.write(m.SerializeToString())
    os.replace(tmp, filename)


def load_model_proto(booster, filename: str) -> None:
    with open(filename, "rb") as fh:
        m = model_pb2.Model.FromString(fh.read())
    booster.trees = [_tree_from_proto(t) for t in m.trees]
    booster._forest_rev = getattr(booster, "_forest_rev", 0) + 1
    booster.num_model_per_iteration = m.num_tree_per_iteration or 1
    booster.num_total_features = m.max_feature_idx + 1
    booster.feature_names = list(m.feature_names)
    from .model_text import apply_model_header
    apply_model_header(booster, m.objective, m.num_class, m.average_output)
