"""JSON model dump (reference: GBDT::DumpModel gbdt_model_text.cpp:13-48,
Tree::ToJSON / NodeToJSON src/io/tree.cpp)."""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ..tree import K_CATEGORICAL_MASK, K_DEFAULT_LEFT_MASK, Tree

_MISSING_NAMES = {0: "None", 1: "Zero", 2: "NaN"}


def _node_to_dict(tree: Tree, index: int) -> Dict:
    if index >= 0:
        dt = int(tree.decision_type[index])
        node = {
            "split_index": index,
            "split_feature": int(tree.split_feature[index]),
            "split_gain": float(tree.split_gain[index]),
        }
        if dt & K_CATEGORICAL_MASK:
            cat_idx = int(tree.threshold_bin[index])
            lo, hi = tree.cat_boundaries[cat_idx], tree.cat_boundaries[cat_idx + 1]
            bitset = tree.cat_threshold[lo:hi]
            cats = [i * 32 + j for i in range(len(bitset)) for j in range(32)
                    if (bitset[i] >> j) & 1]
            node["threshold"] = "||".join(str(c) for c in cats)
            node["decision_type"] = "=="
        else:
            thr = float(tree.threshold[index])
            node["threshold"] = 1e308 if np.isinf(thr) else thr
            node["decision_type"] = "<="
        node["default_left"] = bool(dt & K_DEFAULT_LEFT_MASK)
        node["missing_type"] = _MISSING_NAMES[(dt >> 2) & 3]
        node["internal_value"] = float(tree.internal_value[index])
        node["internal_count"] = int(tree.internal_count[index])
        node["left_child"] = _node_to_dict(tree, int(tree.left_child[index]))
        node["right_child"] = _node_to_dict(tree, int(tree.right_child[index]))
        return node
    leaf = ~index
    return {
        "leaf_index": leaf,
        "leaf_value": float(tree.leaf_value[leaf]),
        "leaf_count": int(tree.leaf_count[leaf]),
    }


def _tree_to_dict(tree: Tree) -> Dict:
    num_cat = 0 if tree.cat_boundaries is None else len(tree.cat_boundaries) - 1
    out = {"num_leaves": tree.num_leaves, "num_cat": num_cat,
           "shrinkage": tree.shrinkage}
    if tree.num_leaves == 1:
        out["tree_structure"] = {"leaf_value": float(tree.leaf_value[0])}
    else:
        out["tree_structure"] = _node_to_dict(tree, 0)
    return out


def dump_model_dict(booster, num_iteration: Optional[int] = None) -> Dict:
    K = max(booster.num_model_per_iteration, 1)
    trees = booster.trees
    if num_iteration is not None and num_iteration > 0:
        trees = trees[: num_iteration * K]
    names = booster.feature_names or \
        [f"Column_{i}" for i in range(booster.num_total_features)]
    return {
        "name": "tree",
        "version": "v2",
        "num_class": booster.config.num_class,
        "num_tree_per_iteration": K,
        "label_index": 0,
        "max_feature_idx": booster.num_total_features - 1,
        "objective": booster.config.objective,
        "average_output": booster.config.boosting_normalized == "rf",
        "feature_names": names,
        "tree_info": [dict(tree_index=i, **_tree_to_dict(t))
                      for i, t in enumerate(trees)],
    }
