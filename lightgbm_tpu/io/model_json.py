"""JSON model dump + load (reference: GBDT::DumpModel
gbdt_model_text.cpp:13-48, Tree::ToJSON / NodeToJSON src/io/tree.cpp).

The loader re-hydrates the dump into model-space ``Tree`` objects so the
serving engine can ingest JSON artifacts next to text/proto. The
objective serializes as the full parameterized string
(``binary sigmoid:2.5``) exactly like the text/proto writers, so
prediction transforms survive the round trip; the one lossy corner (the
reference's own convention) is infinite thresholds clamping to 1e308 —
prefer protobuf for production round trips."""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..tree import K_CATEGORICAL_MASK, K_DEFAULT_LEFT_MASK, Tree
from .model_text import _objective_string

_MISSING_NAMES = {0: "None", 1: "Zero", 2: "NaN"}
_MISSING_CODES = {v: k for k, v in _MISSING_NAMES.items()}


def _node_to_dict(tree: Tree, index: int) -> Dict:
    if index >= 0:
        dt = int(tree.decision_type[index])
        node = {
            "split_index": index,
            "split_feature": int(tree.split_feature[index]),
            "split_gain": float(tree.split_gain[index]),
        }
        if dt & K_CATEGORICAL_MASK:
            cat_idx = int(tree.threshold_bin[index])
            lo, hi = tree.cat_boundaries[cat_idx], tree.cat_boundaries[cat_idx + 1]
            bitset = tree.cat_threshold[lo:hi]
            cats = [i * 32 + j for i in range(len(bitset)) for j in range(32)
                    if (bitset[i] >> j) & 1]
            node["threshold"] = "||".join(str(c) for c in cats)
            node["decision_type"] = "=="
        else:
            thr = float(tree.threshold[index])
            node["threshold"] = 1e308 if np.isinf(thr) else thr
            node["decision_type"] = "<="
        node["default_left"] = bool(dt & K_DEFAULT_LEFT_MASK)
        node["missing_type"] = _MISSING_NAMES[(dt >> 2) & 3]
        node["internal_value"] = float(tree.internal_value[index])
        node["internal_count"] = int(tree.internal_count[index])
        node["left_child"] = _node_to_dict(tree, int(tree.left_child[index]))
        node["right_child"] = _node_to_dict(tree, int(tree.right_child[index]))
        return node
    leaf = ~index
    out = {
        "leaf_index": leaf,
        "leaf_value": float(tree.leaf_value[leaf]),
        "leaf_count": int(tree.leaf_count[leaf]),
    }
    if tree.leaf_features is not None and len(tree.leaf_features[leaf]):
        # linear leaf (later-LightGBM dump_model convention): intercept +
        # per-feature coefficients; leaf_value stays the NaN fallback
        out["leaf_const"] = float(tree.leaf_const[leaf])
        out["leaf_features"] = [int(f) for f in tree.leaf_features[leaf]]
        out["leaf_coeff"] = [float(c) for c in tree.leaf_coeff[leaf]]
    return out


def _tree_to_dict(tree: Tree) -> Dict:
    num_cat = 0 if tree.cat_boundaries is None else len(tree.cat_boundaries) - 1
    out = {"num_leaves": tree.num_leaves, "num_cat": num_cat,
           "shrinkage": tree.shrinkage}
    if tree.num_leaves == 1:
        out["tree_structure"] = {"leaf_value": float(tree.leaf_value[0])}
    else:
        out["tree_structure"] = _node_to_dict(tree, 0)
    return out


def dump_model_dict(booster, num_iteration: Optional[int] = None) -> Dict:
    K = max(booster.num_model_per_iteration, 1)
    trees = booster.trees
    if num_iteration is not None and num_iteration > 0:
        trees = trees[: num_iteration * K]
    names = booster.feature_names or \
        [f"Column_{i}" for i in range(booster.num_total_features)]
    return {
        "name": "tree",
        "version": "v2",
        "num_class": booster.config.num_class,
        "num_tree_per_iteration": K,
        "label_index": 0,
        "max_feature_idx": booster.num_total_features - 1,
        # full objective string WITH params (``binary sigmoid:2.5``), like
        # the text/proto writers — the bare name loses sigmoid/num_class
        # and a reloaded model would transform predictions differently
        "objective": _objective_string(booster),
        "average_output": booster.config.boosting_normalized == "rf",
        "feature_names": names,
        "tree_info": [dict(tree_index=i, **_tree_to_dict(t))
                      for i, t in enumerate(trees)],
    }


# ------------------------------------------------------------------ loading

def _tree_from_dict(d: Dict) -> Tree:
    """Inverse of ``_tree_to_dict``: flatten the nested node dict back into
    model-space arrays (pre-order over split_index/leaf_index)."""
    num_leaves = int(d["num_leaves"])
    M = max(num_leaves - 1, 0)
    split_feature = np.zeros(M, np.int32)
    threshold_bin = np.zeros(M, np.int32)
    threshold = np.zeros(M, np.float64)
    decision_type = np.zeros(M, np.uint8)
    left_child = np.zeros(M, np.int32)
    right_child = np.zeros(M, np.int32)
    split_gain = np.zeros(M, np.float64)
    internal_value = np.zeros(M, np.float64)
    internal_count = np.zeros(M, np.int64)
    leaf_value = np.zeros(max(num_leaves, 1), np.float64)
    leaf_count = np.zeros(max(num_leaves, 1), np.int64)
    cat_boundaries: List[int] = [0]
    cat_words: List[np.ndarray] = []
    leaf_const = np.zeros(max(num_leaves, 1), np.float64)
    leaf_features: List[np.ndarray] = [np.zeros(0, np.int32)
                                       for _ in range(max(num_leaves, 1))]
    leaf_coeff: List[np.ndarray] = [np.zeros(0, np.float64)
                                    for _ in range(max(num_leaves, 1))]
    has_linear = [False]

    def child_index(node: Dict) -> int:
        return int(node["split_index"]) if "split_index" in node \
            else ~int(node.get("leaf_index", 0))

    def walk(node: Dict) -> None:
        if "split_index" not in node:
            leaf = int(node.get("leaf_index", 0))
            leaf_value[leaf] = float(node["leaf_value"])
            leaf_count[leaf] = int(node.get("leaf_count", 0))
            if node.get("leaf_features"):
                has_linear[0] = True
                leaf_const[leaf] = float(node.get("leaf_const", 0.0))
                leaf_features[leaf] = np.asarray(node["leaf_features"],
                                                 np.int32)
                leaf_coeff[leaf] = np.asarray(
                    node.get("leaf_coeff", []), np.float64)
            return
        i = int(node["split_index"])
        split_feature[i] = int(node["split_feature"])
        split_gain[i] = float(node.get("split_gain", 0.0))
        internal_value[i] = float(node.get("internal_value", 0.0))
        internal_count[i] = int(node.get("internal_count", 0))
        dt = 0
        if node.get("decision_type") == "==":
            dt |= K_CATEGORICAL_MASK
            cats = [int(c) for c in str(node["threshold"]).split("||") if c]
            n_words = (max(cats) // 32 + 1) if cats else 1
            words = np.zeros(n_words, np.uint32)
            for c in cats:
                words[c // 32] |= np.uint32(1) << np.uint32(c % 32)
            cat_idx = len(cat_boundaries) - 1
            threshold_bin[i] = cat_idx
            threshold[i] = float(cat_idx)
            cat_boundaries.append(cat_boundaries[-1] + n_words)
            cat_words.append(words)
        else:
            threshold[i] = float(node["threshold"])
        if node.get("default_left"):
            dt |= K_DEFAULT_LEFT_MASK
        dt |= _MISSING_CODES.get(node.get("missing_type", "None"), 0) << 2
        decision_type[i] = dt
        left_child[i] = child_index(node["left_child"])
        right_child[i] = child_index(node["right_child"])
        walk(node["left_child"])
        walk(node["right_child"])

    root = d.get("tree_structure") or {}
    if num_leaves <= 1:
        leaf_value[0] = float(root.get("leaf_value", 0.0))
    else:
        walk(root)
    has_cat = len(cat_words) > 0
    return Tree(
        num_leaves=num_leaves,
        split_feature=split_feature, threshold_bin=threshold_bin,
        threshold=threshold, decision_type=decision_type,
        left_child=left_child, right_child=right_child,
        split_gain=split_gain, internal_value=internal_value,
        internal_count=internal_count, leaf_value=leaf_value,
        leaf_count=leaf_count,
        leaf_parent=np.full(max(num_leaves, 1), -1, np.int32),
        shrinkage=float(d.get("shrinkage", 1.0)),
        cat_boundaries=np.asarray(cat_boundaries, np.int32)
        if has_cat else None,
        cat_threshold=np.concatenate(cat_words).astype(np.uint32)
        if has_cat else None,
        leaf_features=leaf_features if has_linear[0] else None,
        leaf_coeff=leaf_coeff if has_linear[0] else None,
        leaf_const=leaf_const if has_linear[0] else None,
    )


def load_model_dict(booster, doc: Dict) -> None:
    """Re-hydrate a ``dump_model``-shaped dict into ``booster``."""
    from .model_text import apply_model_header
    booster.trees = [_tree_from_dict(t) for t in doc.get("tree_info", [])]
    booster._forest_rev = getattr(booster, "_forest_rev", 0) + 1
    booster.num_model_per_iteration = int(
        doc.get("num_tree_per_iteration", 1)) or 1
    booster.num_total_features = int(doc.get("max_feature_idx", -1)) + 1
    booster.feature_names = list(doc.get("feature_names", []))
    apply_model_header(booster, doc.get("objective"),
                       int(doc.get("num_class", 1)) or 1,
                       doc.get("average_output"))


def save_model_json(booster, filename: str,
                    num_iteration: Optional[int] = None) -> None:
    """Write the ``dump_model`` dict as a .json artifact (atomic, like the
    text/proto writers) — the symmetric half of ``load_model_json`` so
    ``save_model("m.json")`` round-trips through its own loader."""
    from ..observability.export import atomic_write_json
    atomic_write_json(filename, dump_model_dict(booster, num_iteration))


def load_model_json(booster, filename: str) -> None:
    import json
    with open(filename, "r") as fh:
        load_model_dict(booster, json.load(fh))
