"""LightGBM text model format, round-trippable with the reference.

Writers/readers for the `Tree=i` block format of
src/boosting/gbdt_model_text.cpp:169-239 (SaveModelToString) /
:241-330 (LoadModelFromString) and src/io/tree.cpp Tree::ToString/:414
(parsing constructor). A model saved here loads in the reference C++ and
vice versa (same keys, same array encodings, same decision_type bit packing).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..tree import Tree
from ..utils.log import Log


def _fmt_double(v: float) -> str:
    if not np.isfinite(v):
        return repr(float(v))
    s = np.format_float_positional(v, precision=17, trim="0", unique=True)
    if float(s) == float(v):
        return s
    # positional precision counts FRACTIONAL digits, so small magnitudes
    # with long mantissas (|v| < ~1e-3, e.g. linear-leaf coefficients, or
    # the -1e-20 zero-boundary threshold) silently truncate — fall back to
    # the exact scientific form (the reference's %.17g does the same)
    return np.format_float_scientific(v, trim="0", unique=True)


def _arr_str(arr, fmt=str) -> str:
    return " ".join(fmt(v) for v in arr)


def _tree_to_string(tree: Tree) -> str:
    M = tree.num_internal
    num_cat = 0 if tree.cat_boundaries is None else len(tree.cat_boundaries) - 1
    lines = [
        f"num_leaves={tree.num_leaves}",
        f"num_cat={num_cat}",
        "split_feature=" + _arr_str(tree.split_feature[:M]),
        "split_gain=" + _arr_str(tree.split_gain[:M], _fmt_double),
        "threshold=" + _arr_str(tree.threshold[:M], _fmt_double),
        "decision_type=" + _arr_str(tree.decision_type[:M].astype(np.int64)),
        "left_child=" + _arr_str(tree.left_child[:M]),
        "right_child=" + _arr_str(tree.right_child[:M]),
        "leaf_value=" + _arr_str(tree.leaf_value[: tree.num_leaves], _fmt_double),
        "leaf_count=" + _arr_str(tree.leaf_count[: tree.num_leaves]),
        "internal_value=" + _arr_str(tree.internal_value[:M], _fmt_double),
        "internal_count=" + _arr_str(tree.internal_count[:M]),
    ]
    if num_cat > 0:
        lines.append("cat_boundaries=" + _arr_str(tree.cat_boundaries))
        lines.append("cat_threshold=" + _arr_str(tree.cat_threshold))
    if tree.leaf_features is not None:
        # piecewise-linear leaves — the later-LightGBM linear_tree block
        # (src/io/tree.cpp Tree::ToString is_linear path): per-leaf counts
        # unflatten the feature/coefficient pools; 17-digit doubles keep
        # the round trip bit-exact like every other float field here
        L = tree.num_leaves
        lines.append("is_linear=1")
        lines.append("leaf_const=" + _arr_str(tree.leaf_const[:L],
                                              _fmt_double))
        lines.append("num_features=" + _arr_str(
            [len(f) for f in tree.leaf_features[:L]]))
        lines.append("leaf_features=" + _arr_str(
            [v for f in tree.leaf_features[:L] for v in f]))
        lines.append("leaf_coeff=" + _arr_str(
            [v for c in tree.leaf_coeff[:L] for v in c], _fmt_double))
    lines.append(f"shrinkage={_fmt_double(tree.shrinkage)}")
    lines.append("")
    return "\n".join(lines)


def _objective_string(booster) -> str:
    from ..objectives import OBJECTIVE_ALIASES
    cfg = booster.config
    name = OBJECTIVE_ALIASES.get(cfg.objective, cfg.objective)
    if name == "binary":
        return f"binary sigmoid:{cfg.sigmoid:g}"
    if name == "multiclass":
        return f"multiclass num_class:{cfg.num_class}"
    if name == "multiclassova":
        return f"multiclassova num_class:{cfg.num_class} sigmoid:{cfg.sigmoid:g}"
    if name == "lambdarank":
        return "lambdarank"
    return name


def _feature_infos(booster) -> List[str]:
    """Per-raw-feature info strings (dataset.h:518-530, bin.h:175-184)."""
    out = []
    mapper_of_real: Dict[int, object] = {}
    if booster.mappers:
        # booster.mappers is indexed by inner feature; map back to raw columns
        for inner, m in enumerate(booster.mappers):
            real = int(booster._real_feature_idx[inner]) if hasattr(
                booster, "_real_feature_idx") else inner
            mapper_of_real[real] = m
    for i in range(booster.num_total_features):
        m = mapper_of_real.get(i)
        if m is None:
            out.append("none")
        elif m.bin_type == "categorical":
            out.append(":".join(str(c) for c in m.bin_2_categorical))
        else:
            out.append(f"[{m.min_val:.17g}:{m.max_val:.17g}]")
    return out


def model_to_string(booster, num_iteration: Optional[int] = None) -> str:
    K = max(booster.num_model_per_iteration, 1)
    trees = booster.trees
    if num_iteration is not None and num_iteration > 0:
        trees = trees[: num_iteration * K]
    ss = ["tree"]
    ss.append(f"num_class={booster.config.num_class}")
    ss.append(f"num_tree_per_iteration={K}")
    ss.append("label_index=0")
    ss.append(f"max_feature_idx={booster.num_total_features - 1}")
    ss.append(f"objective={_objective_string(booster)}")
    if booster.config.boosting_normalized == "rf":
        ss.append("average_output")
    names = booster.feature_names or [f"Column_{i}" for i in range(booster.num_total_features)]
    if any(any(c.isspace() for c in n) for n in names):
        # the text format is space-delimited (reference
        # gbdt_model_text.cpp:190 joins with " " and never validates), so
        # whitespace inside a name mis-splits on reload — warn loudly
        Log.warning("feature names contain whitespace; the text model "
                    "format is space-delimited and will mis-split them "
                    "on load — rename features to round-trip names")
    ss.append("feature_names=" + " ".join(names))
    ss.append("feature_infos=" + " ".join(_feature_infos(booster)))
    ss.append("")
    for i, t in enumerate(trees):
        ss.append(f"Tree={i}")
        ss.append(_tree_to_string(t))
    imp = booster.feature_importance("split")
    pairs = sorted(((int(imp[i]), names[i]) for i in range(len(imp)) if imp[i] > 0),
                   reverse=True)
    ss.append("")
    ss.append("feature importances:")
    for cnt, nm in pairs:
        ss.append(f"{nm}={cnt}")
    if getattr(booster, "pandas_categorical", None) is not None:
        # trailing JSON line, the reference python package's convention for
        # persisting pandas category mappings (basic.py:226-268 save path);
        # default= handles numpy scalars / Timestamps like the reference's
        # json_default_with_numpy
        import json

        def _json_default(o):
            return o.item() if hasattr(o, "item") else str(o)

        ss.append("pandas_categorical:"
                  + json.dumps(booster.pandas_categorical, default=_json_default))
    ss.append("")
    return "\n".join(ss)


def save_model_file(booster, filename: str, num_iteration: Optional[int] = None) -> None:
    if booster.config.model_format == "proto" or str(filename).endswith(".proto"):
        from .model_proto import save_model_proto
        save_model_proto(booster, filename, num_iteration)
        return
    if str(filename).endswith(".json"):
        # mirror of the loader's .json dispatch: a model SAVED under a
        # .json name must be the dump_model artifact the loader parses —
        # writing text here would break its own round trip
        from .model_json import save_model_json
        save_model_json(booster, filename, num_iteration)
        return
    # atomic write: every rank of a distributed run saves (the reference's
    # behavior — each machine keeps a local copy), and same-host ranks must
    # not interleave into a truncated file; tmp-per-pid + rename means the
    # last complete writer wins
    import os
    tmp = f"{filename}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        fh.write(model_to_string(booster, num_iteration))
    os.replace(tmp, filename)


def _parse_tree_block(lines: Dict[str, str]) -> Tree:
    num_leaves = int(lines["num_leaves"])
    num_cat = int(lines.get("num_cat", "0"))
    M = num_leaves - 1

    def ints(key, n, default=0):
        if key not in lines or not lines[key].strip():
            return np.full(n, default, dtype=np.int64)
        return np.array([int(float(t)) for t in lines[key].split()], dtype=np.int64)[:n]

    def floats(key, n):
        if key not in lines or not lines[key].strip():
            return np.zeros(n)
        return np.array([float(t) for t in lines[key].split()], dtype=np.float64)[:n]

    thresholds = floats("threshold", M)
    decision_types = ints("decision_type", M).astype(np.uint8)
    # for categorical nodes `threshold` holds the cat_boundaries index
    # (reference tree.cpp ToString); keep it addressable via threshold_bin.
    # Numerical thresholds may be inf (top bin) — cast only cat nodes.
    is_cat_node = (decision_types & 1).astype(bool)
    threshold_bin = np.zeros(M, dtype=np.int32)
    threshold_bin[is_cat_node] = thresholds[is_cat_node].astype(np.int32)
    tree = Tree(
        num_leaves=num_leaves,
        split_feature=ints("split_feature", M).astype(np.int32),
        threshold_bin=threshold_bin,
        threshold=thresholds,
        decision_type=decision_types,
        left_child=ints("left_child", M).astype(np.int32),
        right_child=ints("right_child", M).astype(np.int32),
        split_gain=floats("split_gain", M),
        internal_value=floats("internal_value", M),
        internal_count=ints("internal_count", M),
        leaf_value=floats("leaf_value", num_leaves),
        leaf_count=ints("leaf_count", num_leaves),
        leaf_parent=np.full(num_leaves, -1, dtype=np.int32),
        shrinkage=float(lines.get("shrinkage", "1")),
    )
    if num_cat > 0:
        tree.cat_boundaries = ints("cat_boundaries", num_cat + 1).astype(np.int32)
        nthr = int(tree.cat_boundaries[-1])
        tree.cat_threshold = ints("cat_threshold", nthr).astype(np.uint32)
    if int(lines.get("is_linear", "0")):
        nf = ints("num_features", num_leaves).astype(np.int64)
        total = int(nf.sum())
        flat_f = ints("leaf_features", total).astype(np.int32)
        flat_c = floats("leaf_coeff", total)
        feats, coeffs, off = [], [], 0
        for k in nf:
            feats.append(flat_f[off: off + k])
            coeffs.append(flat_c[off: off + k])
            off += int(k)
        tree.leaf_features = feats
        tree.leaf_coeff = coeffs
        tree.leaf_const = floats("leaf_const", num_leaves)
    return tree


def apply_model_header(booster, objective_str, num_class, average_output
                       ) -> None:
    """Shared booster-metadata rehydration tail of every model loader
    (text/proto/JSON): split the objective string into its name and
    ``key:value`` params (``binary sigmoid:2.5``), restore num_class, and
    apply the rf/average_output bagging defaults — then rebuild the
    Config so prediction transforms (sigmoid, softmax, rf averaging) match
    the model that was saved. One implementation: the three formats cannot
    drift on what a loaded model's objective means."""
    params = dict(booster.params)
    toks = (objective_str or "regression").split()
    params["objective"] = toks[0]
    for tok in toks[1:]:
        if ":" in tok:
            k, v = tok.split(":", 1)
            params[k] = v
    params["num_class"] = int(num_class or 1)
    if average_output:
        params["boosting_type"] = "rf"
        params.setdefault("bagging_freq", 1)
        params.setdefault("bagging_fraction", 0.5)
    from ..config import Config
    booster.config = Config.from_params(params)
    booster.params = params


def load_model_string(booster, model_str: str) -> None:
    lines = model_str.splitlines()
    header: Dict[str, str] = {}
    i = 0
    trees: List[Tree] = []
    average_output = False
    while i < len(lines):
        line = lines[i].strip()
        if line.startswith("Tree="):
            block: Dict[str, str] = {}
            i += 1
            while i < len(lines) and lines[i].strip() and "=" in lines[i]:
                k, v = lines[i].split("=", 1)
                block[k.strip()] = v.strip()
                i += 1
            trees.append(_parse_tree_block(block))
            continue
        if line == "average_output":
            average_output = True
        elif "=" in line and not line.startswith("feature importances"):
            k, v = line.split("=", 1)
            header[k.strip()] = v.strip()
        elif line == "feature importances:":
            break
        i += 1

    booster.trees = trees
    booster._forest_rev = getattr(booster, "_forest_rev", 0) + 1
    booster.num_model_per_iteration = int(header.get("num_tree_per_iteration", "1"))
    booster.num_total_features = int(header.get("max_feature_idx", "-1")) + 1
    booster.feature_names = header.get("feature_names", "").split()
    apply_model_header(booster, header.get("objective", "regression"),
                       int(header.get("num_class", "1")), average_output)
    for line in reversed(lines[-5:]):        # trailing JSON convention
        if line.startswith("pandas_categorical:"):
            import json
            try:
                booster.pandas_categorical = json.loads(
                    line[len("pandas_categorical:"):])
            except ValueError:
                pass
            break


def load_model_file(booster, filename: str) -> None:
    if str(filename).endswith(".proto") or booster.params.get("model_format") == "proto":
        from .model_proto import load_model_proto
        load_model_proto(booster, filename)
        return
    if str(filename).endswith(".json"):
        # dump_model() artifact — re-hydrated so the serving engine (and
        # Booster(model_file=...)) ingest JSON next to text/proto
        from .model_json import load_model_json
        load_model_json(booster, filename)
        return
    with open(filename, "r") as fh:
        load_model_string(booster, fh.read())
