"""Text data loading: CSV / TSV / LibSVM with auto-detection, at scale.

Reference: src/io/parser.{cpp,hpp} (CreateParser format sniffing) and
src/io/dataset_loader.cpp:
- column specs by index or ``name:`` for label/weight/group/ignore
  (dataset_loader.cpp column resolution, dataset.h:36-248 Metadata columns),
- side files ``<data>.query`` / ``.weight`` / ``.init`` picked up when
  present (metadata.cpp conventions),
- two-round loading for big files (dataset_loader.cpp:159-265): round one
  streams the file to sample rows for bin finding, round two streams again
  pushing bin codes straight into the binned matrix — peak memory is one
  chunk of floats plus the uint8/16 bin matrix, never the full float matrix.

The chunked text parser is pandas' C reader — the Python-stack equivalent of
the reference's OMP row-parallel C++ Parser (dataset_loader.cpp:906-1101).
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils.log import Log

_NA_VALUES = ["", "na", "NA", "nan", "NaN", "null", "N/A"]
_CHUNK_ROWS = 1 << 19


def _sniff_format(sample_lines: List[str]) -> str:
    for line in sample_lines:
        line = line.strip()
        if not line:
            continue
        tokens = line.replace("\t", " ").split()
        if any(":" in t for t in tokens[1:]):
            return "libsvm"
        if "\t" in line:
            return "tsv"
        if "," in line:
            return "csv"
    return "tsv"


def _head_lines(path: str, n: int = 20) -> List[str]:
    out = []
    with open(path, "r") as fh:
        for _ in range(n):
            line = fh.readline()
            if not line:
                break
            out.append(line.rstrip("\n"))
    return out


def is_binary_dataset(path: str) -> bool:
    """Binary dataset auto-detect (reference: token check on load,
    dataset_loader.cpp:265 LoadFromBinFile)."""
    try:
        with open(path, "rb") as fh:
            head = fh.read(4096)
    except OSError:
        return False
    return head[:1] == b"\x80" and b"lightgbm_tpu.dataset" in head


def _resolve_col(spec: str, header: Optional[List[str]], default: int = -1) -> int:
    spec = str(spec or "").strip()
    if not spec:
        return default
    if spec.startswith("name:"):
        if header is None:
            Log.fatal("Column spec %s requires has_header=true", spec)
        name = spec[5:]
        if name not in header:
            Log.fatal("Column name %s not found in header", name)
        return header.index(name)
    return int(spec)


def _resolve_cols(spec: str, header: Optional[List[str]]) -> List[int]:
    if not spec:
        return []
    return [_resolve_col(tok, header) for tok in str(spec).split(",") if tok.strip()]


def _group_ids_to_sizes(ids: np.ndarray) -> np.ndarray:
    """Query-id column -> per-query sizes (reference metadata.cpp: rows with
    the same consecutive query id form one group)."""
    if len(ids) == 0:
        return np.zeros(0, np.int64)
    change = np.nonzero(np.diff(ids))[0]
    bounds = np.concatenate([[0], change + 1, [len(ids)]])
    return np.diff(bounds)


def _read_chunks(path: str, fmt: str, has_header: bool):
    """Yield float64 [rows, cols] chunks via pandas' C parser."""
    import pandas as pd
    sep = "\t" if fmt == "tsv" else ","
    reader = pd.read_csv(path, sep=sep, header=None,
                         skiprows=1 if has_header else 0,
                         na_values=_NA_VALUES, keep_default_na=True,
                         dtype=np.float64, chunksize=_CHUNK_ROWS,
                         engine="c")
    for chunk in reader:
        yield chunk.to_numpy(dtype=np.float64, copy=False)


def _parse_libsvm_rows(lines) -> Tuple[List[float], List[Dict[int, float]], int]:
    """(labels, per-row {feature: value} dicts, max feature index)."""
    labels: List[float] = []
    rows: List[Dict[int, float]] = []
    max_idx = -1
    for line in lines:
        line = line.strip()
        if not line:
            continue
        toks = line.split()
        labels.append(float(toks[0]))
        feats = {}
        for t in toks[1:]:
            k, v = t.split(":", 1)
            k = int(k)
            feats[k] = float(v)
            max_idx = max(max_idx, k)
        rows.append(feats)
    return labels, rows, max_idx


def _parse_libsvm(lines) -> Tuple[np.ndarray, np.ndarray]:
    labels, rows, max_idx = _parse_libsvm_rows(lines)
    X = np.zeros((len(rows), max_idx + 1), dtype=np.float64)
    for i, feats in enumerate(rows):
        for k, v in feats.items():
            X[i, k] = v
    return X, np.asarray(labels, dtype=np.float64)


def _libsvm_line_chunks(path: str, chunk_lines: int = 100_000):
    with open(path, "r") as fh:
        buf: List[str] = []
        for line in fh:
            buf.append(line)
            if len(buf) >= chunk_lines:
                yield buf
                buf = []
        if buf:
            yield buf


def _split_columns(mat: np.ndarray, header: Optional[List[str]], params: Dict
                   ) -> Tuple[np.ndarray, Optional[np.ndarray], Dict,
                              Optional[List[str]]]:
    """Extract label/weight/group columns (file coordinates) from a parsed
    matrix; returns (features, label, side, feature_names)."""
    label_idx = _resolve_col(params.get("label_column", ""), header, default=0)
    weight_idx = _resolve_col(params.get("weight_column", ""), header)
    group_idx = _resolve_col(params.get("group_column", ""), header)
    ignore = set(_resolve_cols(params.get("ignore_column", ""), header))

    side: Dict = {}
    label = mat[:, label_idx] if label_idx >= 0 else None
    if weight_idx >= 0:
        side["weight"] = mat[:, weight_idx]
    if group_idx >= 0:
        side["group"] = _group_ids_to_sizes(mat[:, group_idx])
    drop = sorted({label_idx} | ({weight_idx} if weight_idx >= 0 else set())
                  | ({group_idx} if group_idx >= 0 else set()) | ignore
                  - {-1})
    drop = [d for d in drop if d >= 0]
    keep = [j for j in range(mat.shape[1]) if j not in drop]
    X = mat[:, keep]
    names = None if header is None else [header[j] for j in keep]
    return X, label, side, names


def load_data_file(path: str, params: Dict
                   ) -> Tuple[np.ndarray, Optional[np.ndarray], Dict]:
    """Returns (features, label, side_metadata).

    Label column handling follows the reference: default column 0, or
    ``label_column`` index / ``name:`` spec; ``weight_column`` /
    ``group_column`` / ``ignore_column`` extract in-file metadata columns
    (reference dataset.h:36-248 Metadata init from columns).
    """
    has_header = bool(params.get("has_header") or params.get("header"))
    head = _head_lines(path)
    fmt = _sniff_format(head[1 if has_header else 0:])

    header_names: Optional[List[str]] = None
    if has_header and fmt != "libsvm":
        sep = "\t" if fmt == "tsv" else ","
        header_names = [t.strip() for t in head[0].split(sep)]

    if fmt == "libsvm":
        with open(path, "r") as fh:
            X, label = _parse_libsvm(fh)
        side: Dict = {}
        names = None
    else:
        chunks = list(_read_chunks(path, fmt, has_header))
        mat = np.vstack(chunks) if len(chunks) != 1 else chunks[0]
        del chunks
        X, label, side, names = _split_columns(mat, header_names, params)

    side.setdefault("feature_names", names)
    for suffix, key in ((".query", "group"), (".weight", "weight"),
                        (".init", "init_score")):
        side_path = path + suffix
        if os.path.exists(side_path) and key not in side:
            side[key] = np.loadtxt(side_path, dtype=np.float64)
    return X, label, side


def stream_construct_dataset(path: str, config, feature_names=None,
                             categorical_features=None):
    """Two-round streaming construction (use_two_round_loading=true;
    reference dataset_loader.cpp:159-265):

    round 1: stream chunks, reservoir-sample rows for bin finding, count rows;
    round 2: stream again, push per-chunk bin codes into the preallocated
    binned matrix. Peak memory = one float chunk + the uint8/16 bin matrix.
    """
    from ..binning import BIN_CATEGORICAL, BIN_NUMERICAL, BinMapper
    from ..dataset import ConstructedDataset, FeatureInfo, Metadata

    params = config.to_dict() if hasattr(config, "to_dict") else dict(config)
    has_header = bool(params.get("has_header"))
    head = _head_lines(path)
    fmt = _sniff_format(head[1 if has_header else 0:])
    if fmt == "libsvm":
        return _stream_construct_libsvm(path, config, categorical_features)
    header_names: Optional[List[str]] = None
    if has_header:
        sep = "\t" if fmt == "tsv" else ","
        header_names = [t.strip() for t in head[0].split(sep)]

    sample_cnt = int(params.get("bin_construct_sample_cnt", 200000))
    rng = np.random.RandomState(int(params.get("data_random_seed", 1)))

    # ---- round 1: reservoir sample + row count (vectorized algorithm R:
    # each later row replaces a random reservoir slot w.p. sample/t) --------
    reservoir = None
    n_seen = 0
    for mat in _read_chunks(path, fmt, has_header):
        if reservoir is None:
            reservoir = mat[:sample_cnt].copy()
            rest = mat[sample_cnt:]
            n_seen = len(reservoir)
        else:
            rest = mat
        if len(rest):
            t = n_seen + np.arange(1, len(rest) + 1)
            accept = rng.random_sample(len(rest)) < (sample_cnt / t)
            picked = rest[accept]
            if len(picked):
                slots = rng.randint(0, sample_cnt, size=len(picked))
                reservoir[slots] = picked
            n_seen += len(rest)
    if reservoir is None:
        Log.fatal("Empty data file %s", path)
    total_rows = n_seen

    Xs, label_s, side_s, names = _split_columns(reservoir, header_names, params)
    num_total_features = Xs.shape[1]
    if feature_names is None:
        feature_names = names or [f"Column_{i}" for i in range(num_total_features)]

    cat_set = set()
    if categorical_features is not None:
        for c in categorical_features:
            cat_set.add(feature_names.index(c) if isinstance(c, str) else int(c))
    from ..dataset import _parse_column_spec
    cat_set.update(_parse_column_spec(config.categorical_column, feature_names))

    sample_n = Xs.shape[0]
    filter_cnt = int(config.min_data_in_leaf * sample_n / max(total_rows, 1))

    def _find_one(j: int) -> BinMapper:
        mapper = BinMapper()
        bin_type = BIN_CATEGORICAL if j in cat_set else BIN_NUMERICAL
        mapper.find_bin(Xs[:, j], sample_n, config.max_bin,
                        config.min_data_in_bin, filter_cnt, bin_type,
                        config.use_missing, config.zero_as_missing)
        return mapper

    # feature-sharded + exchanged under distributed training, so machines
    # loading pre-partitioned files agree on bin boundaries (the reference's
    # distributed FindBin + Allgather, dataset_loader.cpp:820-899)
    from ..dataset import _find_bins
    active = list(range(num_total_features))
    mappers_by_idx = _find_bins(active, _find_one, config)
    features: List[FeatureInfo] = [
        FeatureInfo(j, mappers_by_idx[j]) for j in active
        if not mappers_by_idx[j].is_trivial]
    if not features:
        Log.warning("There are no meaningful features in %s", path)

    dtype = np.uint8 if all(f.mapper.num_bin <= 256 for f in features) else np.uint16
    X_binned = np.zeros((total_rows, max(len(features), 1)), dtype=dtype)
    label = np.zeros(total_rows, np.float64)
    weight = np.zeros(total_rows, np.float64) if "weight" in side_s else None
    group_ids = np.zeros(total_rows, np.float64) if "group" in side_s else None

    # ---- round 2: bin per chunk -------------------------------------------
    group_col = _resolve_col(params.get("group_column", ""), header_names)
    row0 = 0
    for mat in _read_chunks(path, fmt, has_header):
        Xc, lab_c, side_c, _ = _split_columns(mat, header_names, params)
        r = slice(row0, row0 + len(Xc))
        for inner, f in enumerate(features):
            X_binned[r, inner] = f.mapper.value_to_bin(
                Xc[:, f.real_index]).astype(dtype)
        if lab_c is not None:
            label[r] = lab_c
        if weight is not None:
            weight[r] = side_c["weight"]
        if group_ids is not None:
            group_ids[r] = mat[:, group_col]
        row0 += len(Xc)

    metadata = Metadata(total_rows)
    metadata.set_label(label)
    if weight is not None:
        metadata.set_weight(weight)
    if group_ids is not None:
        metadata.set_group(_group_ids_to_sizes(group_ids))
    _apply_side_files(metadata, path)

    return ConstructedDataset(X_binned, features, num_total_features, metadata,
                              feature_names, config)


def _stream_construct_libsvm(path: str, config, categorical_features=None):
    """Two-round streaming construction for LibSVM files (the reference's
    two-round loading applies to every Parser format,
    dataset_loader.cpp:159-265; here sparse rows are reservoir-sampled as
    {feature: value} dicts, bin mappers come from the per-feature NON-ZERO
    sample values — exactly BinMapper::FindBin's contract, zeros implied by
    the sample count (bin.cpp:232) — and round two bins each line chunk
    straight into the uint8/16 matrix)."""
    from ..binning import BIN_CATEGORICAL, BIN_NUMERICAL, BinMapper, K_EPSILON
    from ..dataset import ConstructedDataset, FeatureInfo, Metadata, _find_bins

    sample_cnt = int(getattr(config, "bin_construct_sample_cnt", 200000))
    rng = np.random.RandomState(int(getattr(config, "data_random_seed", 1)))

    # ---- round 1: reservoir-sample sparse rows + count + max feature -----
    reservoir_rows: List[Dict[int, float]] = []
    n_seen = 0
    max_idx = -1
    for lines in _libsvm_line_chunks(path):
        _, rows, mi = _parse_libsvm_rows(lines)
        max_idx = max(max_idx, mi)
        for feats in rows:
            if len(reservoir_rows) < sample_cnt:
                reservoir_rows.append(feats)
            else:
                j = rng.randint(0, n_seen + 1)
                if j < sample_cnt:
                    reservoir_rows[j] = feats
            n_seen += 1
    if n_seen == 0:
        Log.fatal("Empty data file %s", path)
    total_rows, num_total_features = n_seen, max_idx + 1
    feature_names = [f"Column_{i}" for i in range(num_total_features)]

    cat_set = set()
    if categorical_features is not None:
        for c in categorical_features:
            cat_set.add(feature_names.index(c) if isinstance(c, str)
                        else int(c))
    from ..dataset import _parse_column_spec
    cat_set.update(_parse_column_spec(config.categorical_column, feature_names))

    sample_n = len(reservoir_rows)
    filter_cnt = int(config.min_data_in_leaf * sample_n / max(total_rows, 1))
    # find_bin's contract is the NONZERO sample (zeros implied by sample_n,
    # bin.cpp:232) — an explicitly stored 'j:0' entry must be filtered like
    # sample_for_binning does, or the zero bin double-counts
    per_feature: Dict[int, List[float]] = {}
    for feats in reservoir_rows:
        for k, v in feats.items():
            if abs(v) > K_EPSILON or np.isnan(v):
                per_feature.setdefault(k, []).append(v)

    def _find_one(j: int) -> BinMapper:
        mapper = BinMapper()
        mapper.find_bin(np.asarray(per_feature.get(j, []), np.float64),
                        sample_n, config.max_bin, config.min_data_in_bin,
                        filter_cnt,
                        BIN_CATEGORICAL if j in cat_set else BIN_NUMERICAL,
                        config.use_missing, config.zero_as_missing)
        return mapper

    mappers_by_idx = _find_bins(list(range(num_total_features)), _find_one,
                                config)
    features = [FeatureInfo(j, mappers_by_idx[j])
                for j in range(num_total_features)
                if not mappers_by_idx[j].is_trivial]
    if not features:
        Log.warning("There are no meaningful features in %s", path)

    dtype = np.uint8 if all(f.mapper.num_bin <= 256 for f in features) \
        else np.uint16
    X_binned = np.zeros((total_rows, max(len(features), 1)), dtype=dtype)
    label = np.zeros(total_rows, np.float64)

    # zero-bin per used feature (find_bin caches value_to_bin(0) as
    # default_bin, binning.py:215) — most entries are implicit zeros
    zero_bins = np.array([f.mapper.default_bin for f in features],
                         dtype=dtype)

    # ---- round 2: bin each chunk ----------------------------------------
    row0 = 0
    inner_of = {f.real_index: i for i, f in enumerate(features)}
    for lines in _libsvm_line_chunks(path):
        labs, rows, _ = _parse_libsvm_rows(lines)
        n = len(rows)
        if features:
            block = np.tile(zero_bins, (n, 1))
            # bin stored values column-wise: group (row, value) by feature
            cols: Dict[int, Tuple[List[int], List[float]]] = {}
            for i, feats in enumerate(rows):
                for k, v in feats.items():
                    inner = inner_of.get(k)
                    if inner is not None:
                        cols.setdefault(inner, ([], []))[0].append(i)
                        cols[inner][1].append(v)
            for inner, (ridx, vals) in cols.items():
                block[np.asarray(ridx), inner] = features[inner].mapper \
                    .value_to_bin(np.asarray(vals, np.float64)).astype(dtype)
            X_binned[row0:row0 + n] = block
        label[row0:row0 + n] = labs
        row0 += n

    metadata = Metadata(total_rows)
    metadata.set_label(label)
    _apply_side_files(metadata, path)

    return ConstructedDataset(X_binned, features, num_total_features,
                              metadata, feature_names, config)


def _apply_side_files(metadata, path: str) -> None:
    """Pick up <data>.query / .weight / .init side files (reference
    metadata.cpp conventions) — shared by both two-round paths."""
    qpath = path + ".query"
    if os.path.exists(qpath) and metadata.query_boundaries is None:
        metadata.set_group(np.loadtxt(qpath, dtype=np.int64))
    wpath = path + ".weight"
    if os.path.exists(wpath) and metadata.weight is None:
        metadata.set_weight(np.loadtxt(wpath, dtype=np.float64))
    ipath = path + ".init"
    if os.path.exists(ipath) and metadata.init_score is None:
        metadata.set_init_score(np.loadtxt(ipath, dtype=np.float64))
