"""Text data loading: CSV / TSV / LibSVM with auto-detection.

Reference: src/io/parser.{cpp,hpp} (CreateParser format sniffing), plus the
side-file conventions of src/io/metadata.cpp / dataset_loader.cpp:
`<data>.query` (query group sizes), `<data>.weight`, `<data>.init` (initial
scores) are picked up automatically when present.

Host-side preprocessing in NumPy; a native C++ parser is the planned
replacement for very large files (reference's is C++ too).
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils.log import Log


def _sniff_format(sample_lines: List[str]) -> str:
    for line in sample_lines:
        line = line.strip()
        if not line:
            continue
        tokens = line.replace("\t", " ").split()
        if any(":" in t for t in tokens[1:]):
            return "libsvm"
        if "\t" in line:
            return "tsv"
        if "," in line:
            return "csv"
    return "tsv"


def _parse_libsvm(lines: List[str]) -> Tuple[np.ndarray, np.ndarray]:
    labels = []
    rows = []
    max_idx = -1
    for line in lines:
        line = line.strip()
        if not line:
            continue
        toks = line.split()
        labels.append(float(toks[0]))
        feats = {}
        for t in toks[1:]:
            k, v = t.split(":", 1)
            k = int(k)
            feats[k] = float(v)
            max_idx = max(max_idx, k)
        rows.append(feats)
    X = np.zeros((len(rows), max_idx + 1), dtype=np.float64)
    for i, feats in enumerate(rows):
        for k, v in feats.items():
            X[i, k] = v
    return X, np.asarray(labels, dtype=np.float64)


def load_data_file(path: str, params: Dict) -> Tuple[np.ndarray, Optional[np.ndarray], Dict]:
    """Returns (features, label, side_metadata). Label column handling follows
    the reference: default column 0, or `label_column` index / `name:` spec."""
    with open(path, "r") as fh:
        lines = fh.read().splitlines()
    has_header = bool(params.get("has_header") or params.get("header"))
    header_names: Optional[List[str]] = None
    fmt = _sniff_format(lines[:20][1 if has_header else 0:])
    if has_header and fmt != "libsvm":
        sep = "\t" if fmt == "tsv" else ","
        header_names = [t.strip() for t in lines[0].split(sep)]
        lines = lines[1:]

    if fmt == "libsvm":
        X, label = _parse_libsvm(lines)
    else:
        sep = "\t" if fmt == "tsv" else ","
        mat = np.array(
            [[float(v) if v not in ("", "na", "NA", "nan", "NaN", "null") else np.nan
              for v in line.split(sep)]
             for line in lines if line.strip()], dtype=np.float64)
        label_spec = str(params.get("label_column", "") or "0")
        if label_spec.startswith("name:"):
            if header_names is None:
                Log.fatal("label_column name: spec requires has_header=true")
            label_idx = header_names.index(label_spec[5:])
        else:
            label_idx = int(label_spec)
        label = mat[:, label_idx]
        X = np.delete(mat, label_idx, axis=1)
        if header_names is not None:
            header_names = [h for i, h in enumerate(header_names) if i != label_idx]

    side: Dict = {"feature_names": header_names}
    for suffix, key in ((".query", "group"), (".weight", "weight"), (".init", "init_score")):
        side_path = path + suffix
        if os.path.exists(side_path):
            side[key] = np.loadtxt(side_path, dtype=np.float64)
    return X, label, side
