"""Model -> PMML converter (reference capability: pmml/pmml.py, which walks
the model text file and prints per-tree <TreeModel> segments).

Re-designed over this package's in-memory model: the forest renders as a
PMML 4.2 MiningModel with sum-segmentation of TreeModels; each node carries
its score/recordCount and the predicate of the edge from its parent
(SimplePredicate on the real threshold; SimpleSetPredicate for categorical
splits). Usage:

    from lightgbm_tpu.io.pmml import model_to_pmml
    xml_text = model_to_pmml(booster)           # or a model file path
    # CLI parity with the reference script:
    python -m lightgbm_tpu.io.pmml model.txt > model.pmml
"""
from __future__ import annotations

import itertools
import xml.etree.ElementTree as ET
from xml.dom import minidom


def _split_predicates(tree, node_id, feature_names):
    """(left_pred, right_pred) of an internal node's outgoing edges."""
    f = feature_names[int(tree.split_feature[node_id])]
    if int(tree.decision_type[node_id]) & 1:
        cat_idx = int(tree.threshold_bin[node_id])
        lo, hi = tree.cat_boundaries[cat_idx], tree.cat_boundaries[cat_idx + 1]
        bits = tree.cat_threshold[lo:hi]
        values = [str(v) for v in range(32 * len(bits))
                  if (bits[v // 32] >> (v % 32)) & 1]
        preds = []
        for op in ("isIn", "isNotIn"):
            p = ET.Element("SimpleSetPredicate", field=f, booleanOperator=op)
            arr = ET.SubElement(p, "Array", type="int", n=str(len(values)))
            arr.text = " ".join(values)
            preds.append(p)
        return preds[0], preds[1]
    thr = repr(float(tree.threshold[node_id]))
    return (ET.Element("SimplePredicate", field=f, operator="lessOrEqual",
                       value=thr),
            ET.Element("SimplePredicate", field=f, operator="greaterThan",
                       value=thr))


def _emit_node(parent_el, tree, node_id, feature_names, predicate, ids):
    """Emit `node_id` (< 0 encodes leaf ~node_id) under parent_el with the
    predicate of the edge that reaches it; recurse into children."""
    if node_id < 0:
        leaf = ~node_id
        el = ET.SubElement(parent_el, "Node", id=str(next(ids)),
                           score=repr(float(tree.leaf_value[leaf])),
                           recordCount=str(int(tree.leaf_count[leaf])))
        el.append(predicate)
        return
    el = ET.SubElement(parent_el, "Node", id=str(next(ids)),
                       score=repr(float(tree.internal_value[node_id])),
                       recordCount=str(int(tree.internal_count[node_id])))
    el.append(predicate)
    lp, rp = _split_predicates(tree, node_id, feature_names)
    _emit_node(el, tree, int(tree.left_child[node_id]), feature_names, lp, ids)
    _emit_node(el, tree, int(tree.right_child[node_id]), feature_names, rp, ids)


def model_to_pmml(model, name: str = "lightgbm_tpu") -> str:
    """Render a Booster (or model text file path) as a PMML string."""
    from ..basic import Booster
    if isinstance(model, str):
        model = Booster(model_file=model)
    if any(t.is_linear for t in model.trees):
        # PMML TreeModel nodes carry one scalar score: a per-leaf linear
        # model would need a nested RegressionModel per leaf segment —
        # reject LOUDLY rather than export constants that silently drop
        # the linear terms (use protobuf/text/JSON, or codegen, instead)
        raise ValueError(
            "PMML export does not support linear-tree models "
            "(linear_tree=true): TreeModel leaves are scalar scores. "
            "Export via protobuf/text/JSON, or C++ codegen.")

    feature_names = model.feature_name()
    pmml = ET.Element("PMML", version="4.2",
                      xmlns="http://www.dmg.org/PMML-4_2")
    header = ET.SubElement(pmml, "Header", copyright=name)
    ET.SubElement(header, "Application", name=name)

    dd = ET.SubElement(pmml, "DataDictionary",
                       numberOfFields=str(len(feature_names) + 1))
    for f in feature_names:
        ET.SubElement(dd, "DataField", name=f, optype="continuous",
                      dataType="double")
    ET.SubElement(dd, "DataField", name="prediction", optype="continuous",
                  dataType="double")

    mm = ET.SubElement(pmml, "MiningModel", functionName="regression",
                       modelName=name)
    schema = ET.SubElement(mm, "MiningSchema")
    for f in feature_names:
        ET.SubElement(schema, "MiningField", name=f)
    ET.SubElement(schema, "MiningField", name="prediction",
                  usageType="target")

    seg = ET.SubElement(mm, "Segmentation", multipleModelMethod="sum")
    for i, tree in enumerate(model.trees):
        s = ET.SubElement(seg, "Segment", id=str(i + 1))
        ET.SubElement(s, "True")
        tm = ET.SubElement(s, "TreeModel", functionName="regression",
                           modelName=f"tree_{i}",
                           splitCharacteristic="binarySplit")
        ts = ET.SubElement(tm, "MiningSchema")
        for f in feature_names:
            ET.SubElement(ts, "MiningField", name=f)
        ids = itertools.count(1)
        if tree.num_leaves <= 1:
            root = ET.SubElement(
                tm, "Node", id=str(next(ids)),
                score=repr(float(tree.leaf_value[0])
                           if len(tree.leaf_value) else 0.0))
            ET.SubElement(root, "True")
        else:
            _emit_node(tm, tree, 0, feature_names, ET.Element("True"), ids)

    rough = ET.tostring(pmml, encoding="unicode")
    return minidom.parseString(rough).toprettyxml(indent="  ")


def main(argv=None) -> None:
    import sys
    args = argv if argv is not None else sys.argv[1:]
    if not args:
        print("usage: python -m lightgbm_tpu.io.pmml <model.txt> [out.pmml]",
              file=sys.stderr)
        raise SystemExit(2)
    xml_text = model_to_pmml(args[0])
    if len(args) > 1:
        with open(args[1], "w") as fh:
            fh.write(xml_text)
    else:
        print(xml_text)


if __name__ == "__main__":
    main()
