"""scikit-learn API wrappers (reference: python-package/lightgbm/sklearn.py:137-770)."""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset
from .engine import train as _train
from .utils.log import Log

# Inherit sklearn's base classes when available (the reference does the same
# through its compat shim, sklearn.py _LGBMModelBase): BaseEstimator supplies
# __sklearn_tags__/clone support for GridSearchCV & friends, the mixins set
# the estimator type. Without sklearn the wrappers still work standalone.
try:
    from sklearn.base import (BaseEstimator as _SKBase,
                              ClassifierMixin as _SKClassifier,
                              RegressorMixin as _SKRegressor)
except ImportError:                                       # pragma: no cover
    _SKBase = object

    class _SKClassifier:                                  # noqa: D401
        pass

    class _SKRegressor:
        pass


class LGBMModel(_SKBase):
    """Base estimator (reference sklearn.py:137 LGBMModel)."""

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[str] = None, class_weight=None,
                 min_split_gain: float = 0.0, min_child_weight: float = 1e-3,
                 min_child_samples: int = 20, subsample: float = 1.0,
                 subsample_freq: int = 0, colsample_bytree: float = 1.0,
                 reg_alpha: float = 0.0, reg_lambda: float = 0.0,
                 random_state: Optional[int] = None, n_jobs: int = -1,
                 silent: bool = True, importance_type: str = "split",
                 linear_tree: bool = False, linear_lambda: float = 0.0,
                 linear_max_features: int = 8, **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.silent = silent
        self.importance_type = importance_type
        # piecewise-linear leaves (docs/Linear-Trees.md): first-class so
        # get_params/set_params round-trip them for GridSearchCV & clone
        self.linear_tree = linear_tree
        self.linear_lambda = linear_lambda
        self.linear_max_features = linear_max_features
        self._other_params = dict(kwargs)
        self._Booster: Optional[Booster] = None
        self._n_features = None
        self._classes = None
        self._n_classes = None
        self._objective = objective

    # sklearn plumbing
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = {
            "boosting_type": self.boosting_type, "num_leaves": self.num_leaves,
            "max_depth": self.max_depth, "learning_rate": self.learning_rate,
            "n_estimators": self.n_estimators,
            "subsample_for_bin": self.subsample_for_bin, "objective": self.objective,
            "class_weight": self.class_weight, "min_split_gain": self.min_split_gain,
            "min_child_weight": self.min_child_weight,
            "min_child_samples": self.min_child_samples, "subsample": self.subsample,
            "subsample_freq": self.subsample_freq,
            "colsample_bytree": self.colsample_bytree, "reg_alpha": self.reg_alpha,
            "reg_lambda": self.reg_lambda, "random_state": self.random_state,
            "n_jobs": self.n_jobs, "silent": self.silent,
            "importance_type": self.importance_type,
            "linear_tree": self.linear_tree,
            "linear_lambda": self.linear_lambda,
            "linear_max_features": self.linear_max_features,
        }
        params.update(self._other_params)
        return params

    def set_params(self, **params) -> "LGBMModel":
        for key, value in params.items():
            if hasattr(self, key):
                setattr(self, key, value)
            else:
                self._other_params[key] = value
        return self

    def _lgb_params(self) -> Dict[str, Any]:
        params = {
            "boosting_type": self.boosting_type,
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "bin_construct_sample_cnt": self.subsample_for_bin,
            "min_gain_to_split": self.min_split_gain,
            "min_sum_hessian_in_leaf": self.min_child_weight,
            "min_data_in_leaf": self.min_child_samples,
            "bagging_fraction": self.subsample,
            "bagging_freq": self.subsample_freq,
            "feature_fraction": self.colsample_bytree,
            "lambda_l1": self.reg_alpha,
            "lambda_l2": self.reg_lambda,
            "verbose": 0 if self.silent else 1,
            "linear_tree": self.linear_tree,
            "linear_lambda": self.linear_lambda,
            "linear_max_features": self.linear_max_features,
        }
        if self._objective is not None:
            params["objective"] = self._objective
        if self.random_state is not None:
            params["seed"] = self.random_state
        params.update(self._other_params)
        return params

    # ---- input validation (sklearn estimator-check contract) -----------

    def _validate_fit_inputs(self, X, y):
        """Shape/finiteness checks with sklearn's expected error phrasing
        (check_estimator: fit1d, inconsistent lengths, empty data, complex
        data, y None, y NaN/inf, 2-D column-vector y warning). X NaN is
        ALLOWED — missing values are a modeled feature (tags allow_nan)."""
        if y is None:
            raise ValueError(
                f"This {type(self).__name__} estimator requires y to be "
                "passed, but the target y is None.")
        shape = getattr(X, "shape", None)
        if shape is None:
            X = np.asarray(X)
            shape = X.shape
        # complex check only on dtype-bearing containers: sklearn's
        # not-an-array inputs refuse __array_function__ dispatch
        x_cplx = getattr(X, "dtype", None) is not None and np.iscomplexobj(X)
        y_cplx = getattr(y, "dtype", None) is not None and np.iscomplexobj(y)
        if x_cplx or y_cplx:
            raise ValueError("Complex data not supported")
        if len(shape) != 2:
            raise ValueError(
                f"Expected 2D array, got {len(shape)}D array instead. "
                "Reshape your data either using array.reshape(-1, 1) or "
                "array.reshape(1, -1).")
        n_samples, n_feat = int(shape[0]), int(shape[1])
        if n_samples == 0:
            raise ValueError(
                f"Found array with 0 sample(s) (shape={tuple(shape)}) while "
                "a minimum of 1 is required.")
        if n_feat == 0:
            raise ValueError(
                f"Found array with 0 feature(s) (shape={tuple(shape)}) "
                "while a minimum of 1 is required.")
        if n_samples < 2:
            raise ValueError(
                f"Found array with {n_samples} sample(s) while a minimum "
                "of 2 is required: histogram split finding needs at least "
                "two rows.")
        y = np.asarray(y)
        if y.ndim == 2 and y.shape[1] == 1:
            import warnings
            try:
                from sklearn.exceptions import DataConversionWarning
            except ImportError:                       # pragma: no cover
                DataConversionWarning = UserWarning
            warnings.warn(
                "A column-vector y was passed when a 1d array was "
                "expected. Please change the shape of y to "
                "(n_samples,), for example using ravel().",
                DataConversionWarning)
            y = y.ravel()
        if y.ndim != 1:
            raise ValueError(f"y must be 1d, got shape {y.shape}")
        if y.shape[0] != n_samples:
            raise ValueError(
                "Found input variables with inconsistent numbers of "
                f"samples: [{n_samples}, {y.shape[0]}]")
        if np.issubdtype(y.dtype, np.floating) and \
                not np.isfinite(y).all():
            raise ValueError(
                "Input y contains NaN or infinity; supervised targets "
                "must be finite.")
        return X, y, n_feat

    def _validate_predict_input(self, X) -> int:
        """Fitted/shape/width checks; returns X's row count."""
        if self._Booster is None and \
                getattr(self, "_single_class", None) is None:
            try:
                from sklearn.exceptions import NotFittedError
            except ImportError:                       # pragma: no cover
                NotFittedError = ValueError
            raise NotFittedError(
                f"This {type(self).__name__} instance is not fitted yet. "
                "Call 'fit' with appropriate arguments before using this "
                "estimator.")
        shape = getattr(X, "shape", None)
        if shape is None:
            # np.asarray goes through __array__, which sklearn's
            # not-an-array test containers allow (np.shape does not)
            shape = np.asarray(X).shape
        if len(shape) != 2:
            raise ValueError(
                f"Expected 2D array, got {len(shape)}D array instead. "
                "Reshape your data either using array.reshape(-1, 1) or "
                "array.reshape(1, -1).")
        if self._n_features is not None and int(shape[1]) != self._n_features:
            raise ValueError(
                f"X has {int(shape[1])} features, but "
                f"{type(self).__name__} is expecting {self._n_features} "
                "features as input.")
        return int(shape[0])

    def __sklearn_tags__(self):                       # sklearn >= 1.6
        tags = super().__sklearn_tags__()
        tags.input_tags.sparse = True      # CSR/CSC ingested natively
        tags.input_tags.allow_nan = True   # NaN in X = missing values
        return tags

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None, eval_metric=None,
            early_stopping_rounds=None, verbose=False, feature_name="auto",
            categorical_feature="auto", callbacks=None):
        if getattr(self, "_fit_prevalidated", False):
            # LGBMClassifier.fit already validated and label-encoded
            self._fit_prevalidated = False
        else:
            X, y, n_feat = self._validate_fit_inputs(X, y)
            self.n_features_in_ = n_feat
        params = self._lgb_params()
        params.update(self.__dict__.pop("_fit_params_extra", {}))
        # reference verbosity semantics: `silent`/`verbose` params reach
        # Log.set_level (utils/log.py) — silent=True estimators train at
        # warning level, verbose=-1 in **kwargs silences warnings too
        _v = params.get("verbose", params.get("verbosity"))
        if _v is not None:
            try:
                from .utils.log import Log
                Log.set_level(int(_v))
            except (TypeError, ValueError):
                pass
        # callable objective: the reference sklearn wrapper accepts
        # objective(y_true, y_pred) -> (grad, hess) and routes it as a
        # custom fobj (sklearn.py:137-213 _ObjectiveFunctionWrapper)
        fobj = None
        if callable(params.get("objective")):
            user_obj = params.pop("objective")

            def fobj(preds, dataset):
                return user_obj(dataset.get_label(), preds)

            params["objective"] = "none"
        self._used_custom_obj = fobj is not None
        if eval_metric is not None:
            params["metric"] = eval_metric
        if self.class_weight is not None and sample_weight is None:
            sample_weight = self._class_weights_to_sample_weight(y)
        train_set = Dataset(X, label=y, weight=sample_weight, group=group,
                            init_score=init_score, params=params,
                            feature_name=feature_name,
                            categorical_feature=categorical_feature)
        valid_sets = []
        valid_names = []
        if eval_set is not None:
            for i, (vx, vy) in enumerate(eval_set):
                if vx is X and vy is y:
                    valid_sets.append(train_set)
                else:
                    vw = eval_sample_weight[i] if eval_sample_weight else None
                    vg = eval_group[i] if eval_group else None
                    vi = eval_init_score[i] if eval_init_score else None
                    valid_sets.append(Dataset(vx, label=vy, reference=train_set,
                                              weight=vw, group=vg, init_score=vi))
                valid_names.append(eval_names[i] if eval_names else f"valid_{i}")
        self.evals_result_ = {}
        self._Booster = _train(
            params, train_set, num_boost_round=self.n_estimators,
            valid_sets=valid_sets, valid_names=valid_names,
            early_stopping_rounds=early_stopping_rounds,
            evals_result=self.evals_result_, fobj=fobj,
            verbose_eval=verbose, callbacks=callbacks)
        self._n_features = train_set.num_feature()
        self.best_iteration_ = self._Booster.best_iteration
        return self

    def _class_weights_to_sample_weight(self, y):
        y = np.asarray(y)
        classes, counts = np.unique(y, return_counts=True)
        if self.class_weight == "balanced":
            weights = {c: len(y) / (len(classes) * cnt) for c, cnt in zip(classes, counts)}
        else:
            weights = dict(self.class_weight)
        return np.asarray([weights.get(v, 1.0) for v in y], dtype=np.float32)

    def predict(self, X, raw_score: bool = False, num_iteration: Optional[int] = None,
                pred_leaf: bool = False, pred_contrib: bool = False, **kwargs):
        self._validate_predict_input(X)
        return self._Booster.predict(X, raw_score=raw_score,
                                     num_iteration=num_iteration,
                                     pred_leaf=pred_leaf, pred_contrib=pred_contrib)

    @property
    def booster_(self) -> Booster:
        return self._Booster

    @property
    def feature_importances_(self) -> np.ndarray:
        return self._Booster.feature_importance(self.importance_type)

    @property
    def n_features_(self):
        return self._n_features


class LGBMRegressor(_SKRegressor, LGBMModel):
    def __init__(self, **kwargs):
        kwargs.setdefault("objective", "regression")
        super().__init__(**kwargs)
        self._objective = kwargs.get("objective", "regression")

    def fit(self, X, y, **kwargs):
        return super().fit(X, y, **kwargs)


class LGBMClassifier(_SKClassifier, LGBMModel):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def fit(self, X, y, **kwargs):
        # base-class shape/None/NaN validation FIRST — the label encoding
        # below would otherwise turn malformed y into confusing errors
        X, y, n_feat = self._validate_fit_inputs(X, y)
        if np.issubdtype(y.dtype, np.floating) and \
                not np.array_equal(y, np.round(y)):
            raise ValueError(
                f"Unknown label type: continuous targets are not supported "
                "by classifiers; use LGBMRegressor for regression.")
        self._classes = np.unique(y)
        self._n_classes = len(self._classes)
        # classes that still carry training signal after sample_weight
        # zeroing (sklearn contract: a problem reduced to one class must
        # predict that class; the reference core faithfully emits no trees
        # there — gbdt.cpp:438-448 contributes nothing for 1-leaf trees —
        # so the constant-class answer lives in the wrapper)
        effective = self._classes
        sw = kwargs.get("sample_weight")
        if sw is not None:
            sw = np.asarray(sw, dtype=np.float64)
            effective = np.asarray(
                [c for c in self._classes if np.any((y == c) & (sw > 0))])
        if len(effective) < 2:
            self.n_features_in_ = n_feat
            self._n_features = n_feat
            self._Booster = None
            self._single_class = (effective[0] if len(effective)
                                  else self._classes[0])
            self._used_custom_obj = False
            self.evals_result_ = {}
            self.best_iteration_ = 0
            return self
        self._single_class = None
        self.n_features_in_ = n_feat
        self._fit_prevalidated = True
        # class_weight must be resolved against ORIGINAL labels, before
        # encoding remaps them to 0..k-1 (a dict keyed by user classes
        # would otherwise silently miss every row) — and it COMPOSES with a
        # user sample_weight multiplicatively (reference sklearn wrapper's
        # np.multiply of the two)
        if self.class_weight is not None:
            cw = self._class_weights_to_sample_weight(y)
            sw = kwargs.get("sample_weight")
            kwargs["sample_weight"] = cw if sw is None else \
                np.asarray(sw, dtype=np.float64) * cw
        # vectorized encode: _classes is sorted (np.unique), so the map
        # c -> index is exactly searchsorted — no per-row dict lookups
        y_enc = np.searchsorted(self._classes, y).astype(np.float64)
        # eval_set targets go through the SAME encoding (metrics compare
        # against the encoded training space); the (X, y) identity pair is
        # rewritten to (X, y_enc) so the base fit's train_set-reuse
        # shortcut still fires
        eval_set = kwargs.get("eval_set")
        if eval_set is not None:
            enc_set = []
            for vx, vy in eval_set:
                if vx is X and vy is y:
                    enc_set.append((X, y_enc))
                    continue
                vy_arr = np.asarray(vy).ravel()
                unknown = ~np.isin(vy_arr, self._classes)
                if unknown.any():
                    raise ValueError(
                        "eval_set contains labels unseen in training: "
                        f"{np.unique(vy_arr[unknown])[:5]}")
                enc_set.append(
                    (vx, np.searchsorted(self._classes,
                                         vy_arr).astype(np.float64)))
            kwargs["eval_set"] = enc_set
        if self._n_classes > 2:
            self._objective = self.objective or "multiclass"
            self._other_params["num_class"] = self._n_classes
        else:
            self._objective = self.objective or "binary"
        return super().fit(X, y_enc, **kwargs)

    def predict_proba(self, X, raw_score=False, num_iteration=None, **kwargs):
        n_rows = self._validate_predict_input(X)
        if getattr(self, "_single_class", None) is not None:
            proba = np.zeros((n_rows, max(self._n_classes, 1)))
            proba[:, int(np.searchsorted(self._classes,
                                         self._single_class))] = 1.0
            return proba
        result = self._Booster.predict(X, raw_score=raw_score,
                                       num_iteration=num_iteration)
        if getattr(self, "_used_custom_obj", False) and not raw_score:
            # reference sklearn.py: class probabilities cannot be computed
            # under a customized objective — warn and return raw scores
            # (signed margins for binary, so argmax keeps the 0 boundary)
            Log.warning("Cannot compute class probabilities due to the "
                        "customized objective function; returning raw scores")
            # reference contract: the raw score array is returned UNCHANGED
            # (1-D for binary) — downstream code written against the
            # reference wrapper depends on that shape
            return result
        if self._n_classes <= 2 and result.ndim == 1:
            return np.vstack([1.0 - result, result]).T
        return result

    def predict(self, X, raw_score=False, num_iteration=None, **kwargs):
        if getattr(self, "_single_class", None) is not None:
            n_rows = self._validate_predict_input(X)
            return np.full(n_rows, self._single_class)
        if raw_score:
            return self._Booster.predict(X, raw_score=True, num_iteration=num_iteration)
        proba = self.predict_proba(X, num_iteration=num_iteration)
        if proba.ndim == 1 or getattr(self, "_used_custom_obj", False):
            # custom objective: predict_proba returned raw margins (and
            # warned); the reference wrapper returns them unchanged from
            # predict() too — class labels cannot be derived without the
            # objective's link function (multiclass margins included: a
            # custom per-class link need not be argmax-preserving)
            return proba
        return self._classes[np.argmax(proba, axis=1)]

    @property
    def classes_(self):
        return self._classes

    @property
    def n_classes_(self):
        return self._n_classes


class LGBMRanker(LGBMModel):
    def __init__(self, **kwargs):
        kwargs.setdefault("objective", "lambdarank")
        super().__init__(**kwargs)
        self._objective = kwargs.get("objective", "lambdarank")

    def fit(self, X, y, group=None, eval_at=None, **kwargs):
        if group is None:
            Log.fatal("Should set group for ranking task")
        # NDCG truncation positions (reference sklearn.py LGBMRanker.fit's
        # eval_at -> params['ndcg_eval_at']): fit-scoped — must not leak
        # into get_params()/clone or override constructor params when
        # omitted
        if eval_at is not None:
            self._fit_params_extra = {"ndcg_eval_at": list(
                eval_at if hasattr(eval_at, "__iter__") else [eval_at])}
        return super().fit(X, y, group=group, **kwargs)
