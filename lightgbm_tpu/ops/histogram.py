"""Gradient/hessian histogram construction as MXU one-hot matmuls.

The TPU replacement for the reference's histogram kernels:
- CPU scatter-add: Bin::ConstructHistogram (src/io/dense_bin.hpp:66-130)
- OpenCL local-memory atomics (src/treelearner/ocl/histogram256.cl:95-125)

TPUs have no fast scatter (measured ~400x slower than matmul formulation —
exp/RESULTS.md), so the histogram is computed as a chunked one-hot matmul:

    hist[f, b, s*ch+j] = sum_r (X[r,f] == b) * rhs[r, s*ch+j]

where `rhs` carries per-leaf-slot weight columns: rows whose leaf is assigned
slot `s` contribute their (gradient, hessian, count) channels to that slot's
columns, everyone else contributes zero. One pass over the data therefore
builds histograms for up to S leaves at once — the TPU analog of the
reference's "histogram for the smaller leaf, sibling by subtraction" pipeline
(src/treelearner/serial_tree_learner.cpp:354-362).

Precision: the one-hot matrix is exact in bf16; gradients/hessians are split
into bf16 hi+lo pairs accumulated in f32, giving ~f32-accurate sums at full
MXU speed (the reference GPU path used plain f32 atomics and accepted small
accuracy deltas: docs/GPU-Performance.rst:131-133).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

# Weight-channel modes (the `hilo` parameter throughout):
#   True  — g_hi, g_lo, h_hi, h_lo, count bf16 hi/lo pairs (~f32 sums)
#   False — g, h, count single bf16 (the reference GPU path's
#           f32-and-accept-tiny-deltas tradeoff at 40% fewer columns)
#   "f32" — g, h, count full f32 columns contracted at Precision.HIGHEST
#           (exact per-element products; tpu_hist_f64's exactness half —
#           the Kahan carry in build_histograms is the other)
NUM_CHANNELS = 5
NUM_CHANNELS_FAST = 3


def num_channels(hilo) -> int:
    return NUM_CHANNELS if hilo is True else NUM_CHANNELS_FAST


def weight_channels(grad, hess, included, hilo):
    """[N, ch] weight channels for the one-hot matmul (dtype by mode)."""
    if hilo is True:
        g_hi, g_lo = _split_hi_lo(grad)
        h_hi, h_lo = _split_hi_lo(hess)
        # every input cast explicitly (R003): a dtype change upstream in
        # _split_hi_lo must not silently widen the packed channel matrix
        return jnp.stack([g_hi.astype(jnp.bfloat16),
                          g_lo.astype(jnp.bfloat16),
                          h_hi.astype(jnp.bfloat16),
                          h_lo.astype(jnp.bfloat16),
                          included.astype(jnp.bfloat16)], axis=-1)
    if hilo == "f32":
        return jnp.stack([grad.astype(jnp.float32),
                          hess.astype(jnp.float32),
                          included.astype(jnp.float32)], axis=-1)
    return jnp.stack([grad.astype(jnp.bfloat16), hess.astype(jnp.bfloat16),
                      included.astype(jnp.bfloat16)], axis=-1)


def combine_channels(acc, hilo):
    """[..., ch] f32 accumulated channels -> [..., 3] (sum_g, sum_h, cnt)."""
    if hilo is True:
        return jnp.stack([acc[..., 0] + acc[..., 1],
                          acc[..., 2] + acc[..., 3], acc[..., 4]], axis=-1)
    return acc[..., :3]


def _split_hi_lo(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    hi = x.astype(jnp.bfloat16)
    lo = (x - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


# ---- packed-row form for the compacted gather -------------------------------
# A random row access to HBM costs ~25-55 ns regardless of width (measured,
# exp/chain_profile.py), so the compacted pass gathers ONE packed array
# holding everything it needs per row instead of four separate gathers of
# X/grad/hess/included. The packed dtype is uint8, NOT int32: TPU tiling
# pads the minor dimension to 128 lanes, so ANY [N, small] i32 array
# materializes at N x 512 B (5.4 GB at the 10.5M-row bench) while u8 pays
# N x 128 B. Layout per row: F code bytes (2F little-endian for uint16
# codes) then 2*ch bf16 weight bytes. Packing itself is a sequential O(N)
# write, paid once per tree (grow_tree builds it and passes packed=).

def code_bytes(dtype) -> int:
    return 1 if dtype == jnp.uint8 else 2


# Code packing modes for the per-row byte layout (the reference's analog is
# the Dense4bitsBin storage, src/io/dense_nbits_bin.hpp:37 — two codes per
# byte at <=16 bins; "u6" additionally serves the reference's own GPU bench
# config max_bin=63, docs/GPU-Performance.rst:105-125, at 3 bytes per 4
# codes):
#   "u8"  1 byte/code   (any codes < 256)
#   "u16" 2 bytes/code  (max_bin > 255)
#   "u4"  1 byte/2 codes (codes < 16)
#   "u6"  3 bytes/4 codes (codes < 64)
# Packed gathers are priced per ROW BYTE by the HBM random-access tax, so
# u4/u6 cut the compacted pass's gather traffic 2x / 1.33x.

def default_code_mode(dtype) -> str:
    """Plain byte layout for a bin-code dtype (no bit packing)."""
    return "u16" if dtype == jnp.uint16 else "u8"


def code_mode_for(max_code: int, dtype) -> str:
    if dtype == jnp.uint16 or max_code > 256:
        return "u16"
    if max_code <= 16:
        return "u4"
    if max_code <= 64:
        return "u6"
    return "u8"


def code_bytes_total(F: int, code_mode: str) -> int:
    return {"u8": F, "u16": 2 * F, "u4": (F + 1) // 2,
            "u6": ((F + 3) // 4) * 3}[code_mode]


def _pack_codes(X: jnp.ndarray, code_mode: str) -> jnp.ndarray:
    """[N, F] codes -> [N, code_bytes_total(F)] u8 bytes."""
    N, F = X.shape
    if code_mode == "u8":
        return X
    if code_mode == "u16":
        x16 = X.astype(jnp.uint16)
        return jax.lax.bitcast_convert_type(x16, jnp.uint8).reshape(N, 2 * F)
    x = X.astype(jnp.uint8)
    if code_mode == "u4":
        if F % 2:
            x = jnp.pad(x, ((0, 0), (0, 1)))
        return x[:, 0::2] | (x[:, 1::2] << 4)
    # u6: 4 six-bit codes -> 3 bytes
    if F % 4:
        x = jnp.pad(x, ((0, 0), (0, 4 - F % 4)))
    q = x.reshape(N, -1, 4)
    c0, c1, c2, c3 = q[..., 0], q[..., 1], q[..., 2], q[..., 3]
    b0 = c0 | (c1 << 6)
    b1 = (c1 >> 2) | (c2 << 4)
    b2 = (c2 >> 4) | (c3 << 2)
    return jnp.stack([b0, b1, b2], axis=-1).reshape(N, -1)


def pack_rows(X, grad, hess, included, hilo,
              code_mode: str = None) -> Tuple[jnp.ndarray, int]:
    """Returns (packed [N, ncb + weight bytes] u8, code byte count ncb)."""
    N, F = X.shape
    if code_mode is None:
        code_mode = default_code_mode(X.dtype)
    codes = _pack_codes(X, code_mode)
    w = weight_channels(grad, hess, included, hilo)     # [N, ch] bf16 or f32
    wb = jax.lax.bitcast_convert_type(w, jnp.uint8).reshape(N, -1)
    return jnp.concatenate([codes, wb], axis=1), codes.shape[1]


def unpack_codes(xb: jnp.ndarray, F: int, code_mode: str) -> jnp.ndarray:
    """[R, ncb] u8 code bytes -> [R, F] i32 bin codes (inverse of
    _pack_codes)."""
    if code_mode == "u8":
        return xb.astype(jnp.int32)
    if code_mode == "u16":
        return jax.lax.bitcast_convert_type(
            xb.reshape(xb.shape[0], F, 2), jnp.uint16).astype(jnp.int32)
    R = xb.shape[0]
    if code_mode == "u4":
        out = jnp.stack([xb & 15, xb >> 4], axis=-1).reshape(R, -1)
        return out[:, :F].astype(jnp.int32)
    assert code_mode == "u6", code_mode
    t = xb.reshape(R, -1, 3)
    b0, b1, b2 = t[..., 0], t[..., 1], t[..., 2]
    c0 = b0 & 63
    c1 = (b0 >> 6) | ((b1 & 15) << 2)
    c2 = (b1 >> 4) | ((b2 & 3) << 4)
    c3 = b2 >> 2
    out = jnp.stack([c0, c1, c2, c3], axis=-1).reshape(R, -1)
    return out[:, :F].astype(jnp.int32)


def unpack_weights(wb: jnp.ndarray, ch: int, f32: bool = False) -> jnp.ndarray:
    """[R, bytes*ch] u8 -> [R, ch] bf16 (or f32) weight channels."""
    if f32:
        return jax.lax.bitcast_convert_type(
            wb.reshape(wb.shape[0], ch, 4), jnp.float32)
    return jax.lax.bitcast_convert_type(
        wb.reshape(wb.shape[0], ch, 2), jnp.bfloat16)


def slot_from_position(pos: jnp.ndarray, slot_cum: jnp.ndarray) -> jnp.ndarray:
    """Slot of each compacted position when row_idx is slot-grouped: slot s
    spans positions [cum[s-1], cum[s]) — a VPU compare-sum, no row gather."""
    return jnp.sum((pos[:, None] >= slot_cum[None, :]).astype(jnp.int32),
                   axis=1)


def slot_position_base(raw_slot: jnp.ndarray, slot_cum: jnp.ndarray,
                       slot_starts: jnp.ndarray) -> jnp.ndarray:
    """Additive base mapping a slot-grouped virtual position into a
    leaf-contiguous permutation: ``src = pos + base[raw_slot]``.

    The grower's incremental partition (grower.py GrowState.perm) keeps each
    pending leaf's rows contiguous at ``slot_starts[s]`` instead of
    materializing a compacted prefix; compacted histogram chunks translate
    their positions on the fly, so only ACTIVE chunks ever touch the
    permutation. Integer one-hot multiply-sum: exact at any N (no f32 2^24
    ceiling) and no per-row table gather. Positions past the last slot
    (raw_slot == S, garbage masked downstream) get base 0."""
    S = slot_cum.shape[0]
    cum_before = jnp.concatenate(
        [jnp.zeros(1, slot_cum.dtype), slot_cum[:-1]])
    base = slot_starts - cum_before                                 # [S]
    onehot = raw_slot[:, None] == jnp.arange(S, dtype=jnp.int32)[None, :]
    return jnp.sum(onehot * base[None, :], axis=1)


def table_lookup(idx: jnp.ndarray, table: jnp.ndarray) -> jnp.ndarray:
    """table[idx] for a SMALL table ([T<=1024, C]) as a one-hot f32 matmul.

    XLA's TPU gather prices a per-row dynamic lookup at the random-access
    tax (~15-25 ms for 2M rows — measured, exp/chain_profile.py) even when
    the table is tiny; the one-hot [N, T] x [T, C] contraction is ~0.1 ms
    on the MXU. Exact for values with |v| < 2^24 (f32 integer range) —
    callers keep table entries inside that. Returns table.dtype.

    CAVEAT: rows of the table that are never selected still flow through
    the contraction with weight 0 — a non-finite entry there would poison
    the result (0 * Inf = NaN). Callers must keep garbage rows finite
    (grow_tree zeroes its scratch row before returning)."""
    T = table.shape[0]
    if T > 1024:          # one-hot width no longer trivial; gather wins back
        return table[idx]
    squeeze = table.ndim == 1
    t2 = (table[:, None] if squeeze else table).astype(jnp.float32)
    N = idx.shape[0]
    # bound the materialized [N_c, T] one-hot operand to ~64 MB f32 — at
    # bench scale (N=10.5M, T=256) an unchunked one-hot would be ~10.7 GB
    n_chunk = max(256, (1 << 24) // T)

    def lookup_block(ib):
        onehot = (ib[:, None] == jnp.arange(T, dtype=ib.dtype)[None, :]
                  ).astype(jnp.float32)
        # HIGHEST precision: the f32 operand is decomposed into bf16
        # triples whose reconstruction is exact (3x8 mantissa bits >=
        # f32's 24), and the one-hot side is 0/1 — so the selected value
        # comes back BIT-EXACT.
        return jax.lax.dot_general(
            onehot, t2,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)

    if N <= n_chunk:
        out = lookup_block(idx)
    else:
        n_blocks = (N + n_chunk - 1) // n_chunk
        pad = n_blocks * n_chunk - N
        idx_p = jnp.pad(idx, (0, pad)).reshape(n_blocks, n_chunk)
        out = jax.lax.map(lookup_block, idx_p).reshape(-1, t2.shape[1])[:N]
    if jnp.issubdtype(table.dtype, jnp.integer):
        out = jnp.round(out)
    out = out.astype(table.dtype)
    return out[:, 0] if squeeze else out


def compact_rows(leaf_id: jnp.ndarray, slot_of_leaf: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Prefix-compact the indices of rows whose leaf is pending a histogram.

    Returns (row_idx [N] i32, n_active i32): the first `n_active` entries of
    `row_idx` are the indices of rows in pending leaves (original order); the
    rest are garbage and masked out downstream. The TPU analog of the
    reference's leaf-contiguous DataPartition (data_partition.hpp:94):
    instead of maintaining a permutation across splits, we rebuild the
    pending-rows prefix each wave with one cumsum + one monotonic scatter —
    both cheap VPU streams next to the histogram matmul they gate.
    """
    n = leaf_id.shape[0]
    pending = slot_of_leaf[leaf_id] >= 0                          # [N] bool
    pos = jnp.cumsum(pending.astype(jnp.int32)) - 1               # [N]
    n_active = jnp.where(n > 0, pos[-1] + 1, 0)
    row_idx = jnp.zeros(n, jnp.int32).at[
        jnp.where(pending, pos, n)                                # invalid -> dropped
    ].set(jnp.arange(n, dtype=jnp.int32), mode="drop")
    return row_idx, n_active


def build_histograms(
    X: jnp.ndarray,          # [N, F] uint8/uint16 bin codes (N padded to chunk multiple)
    grad: jnp.ndarray,       # [N] f32 (bagging-masked)
    hess: jnp.ndarray,       # [N] f32 (bagging-masked)
    included: jnp.ndarray,   # [N] f32 0/1 bagging/padding mask (count channel)
    leaf_id: jnp.ndarray,    # [N] i32 current leaf of each row (padding rows masked)
    slot_of_leaf: jnp.ndarray,  # [L+1] i32 leaf -> histogram slot, -1 = not pending
    num_slots: int,
    num_bins_padded: int,
    chunk_rows: int,
    row_idx: jnp.ndarray = None,   # [N] i32 from compact_rows (optional)
    n_active: jnp.ndarray = None,  # i32 count of valid row_idx entries
    hilo: bool = True,             # hi/lo bf16 channel pairs (~f32 sums)
    slot_counts: jnp.ndarray = None,  # [S] i32: rows per slot when row_idx is
                                   # SLOT-GROUPED — slots derive from position
                                   # (2 fewer random gathers per active row)
    slot_starts: jnp.ndarray = None,  # [S] i32: row_idx is a LEAF-CONTIGUOUS
                                   # permutation (grower incremental
                                   # partition) — slot s's rows live at
                                   # row_idx[slot_starts[s]:...+counts[s]];
                                   # chunks remap positions via
                                   # slot_position_base. Requires slot_counts
    packed: jnp.ndarray = None,    # pre-built pack_rows(X, grad, hess,
                                   # included) — pass to amortize the O(N)
                                   # pack across waves of one tree
    code_mode: str = None,         # packed-row code layout; None = by dtype
    compensated: bool = False,     # Kahan-compensate the chunk accumulation:
                                   # ~f64-accurate bin sums (the reference
                                   # accumulates bins in f64, bin.h:29-31)
                                   # without f64 hardware — config
                                   # tpu_hist_f64
    acc_init: jnp.ndarray = None,  # [F, B, S*ch] f32 accumulator carried in
                                   # from a PREVIOUS shard of the same wave
                                   # (out-of-core streaming, ops/stream.py):
                                   # chunk partials keep folding into it in
                                   # order, so a sharded pass is bit-identical
                                   # to one resident pass over the same rows
    comp_init: jnp.ndarray = None, # Kahan carry matching acc_init
    raw_output: bool = False,      # return the raw (acc, comp) fold state
                                   # instead of the finalized histogram —
                                   # streaming callers finalize once per wave
                                   # via finalize_histograms
) -> jnp.ndarray:
    """Returns hist [num_slots, F, num_bins_padded, 3] f32 (sum_g, sum_h, count).

    With (row_idx, n_active) the pass is *row-compacted*: only
    ceil(n_active/chunk_rows) chunks run (a dynamic-trip-count while_loop),
    each gathering its rows through row_idx — the analog of the reference
    histogramming only the smaller leaf's rows
    (serial_tree_learner.cpp:354-362) instead of a full-data pass per wave.

    With ``acc_init``/``raw_output`` the pass is one *shard leg* of a
    streamed wave (tpu_residency=stream): the accumulator threads through
    every shard in row order — the identical chunk-partial add sequence the
    resident pass produces — and ``finalize_histograms`` combines once at
    the end of the wave.
    """
    n_rows, num_features = X.shape
    assert n_rows % chunk_rows == 0, (n_rows, chunk_rows)
    n_chunks = n_rows // chunk_rows
    ch = num_channels(hilo)
    compact = row_idx is not None
    assert slot_starts is None or slot_counts is not None, \
        "slot_starts (leaf-contiguous row_idx) needs slot_counts"
    iota_bins = jnp.arange(num_bins_padded, dtype=jnp.int32)[None, None, :]
    iota_slots = jnp.arange(num_slots, dtype=jnp.int32)[None, :]
    iota_chunk = jnp.arange(chunk_rows, dtype=jnp.int32)
    slot_cum = (jnp.cumsum(slot_counts) if slot_counts is not None else None)
    if compact:
        if code_mode is None:
            code_mode = default_code_mode(X.dtype)
        if packed is None:
            packed, _ = pack_rows(X, grad, hess, included, hilo, code_mode)
        ncb = code_bytes_total(num_features, code_mode)

    def chunk_part(i):
        sl = jax.lax.dynamic_slice_in_dim
        if compact:
            pos = i * chunk_rows + iota_chunk
            valid = pos < n_active
            if slot_starts is not None:
                # leaf-contiguous permutation: translate compacted positions
                # into the pending segments (incremental partition) — the
                # slot is position-derived exactly as in the prefix layout
                raw = slot_from_position(pos, slot_cum)
                src = pos + slot_position_base(raw, slot_cum, slot_starts)
                idx = jnp.take(row_idx, jnp.clip(src, 0, n_rows - 1))
            else:
                idx = sl(row_idx, i * chunk_rows, chunk_rows)
                if slot_cum is not None:
                    raw = slot_from_position(pos, slot_cum)
                else:
                    raw = table_lookup(jnp.take(leaf_id, idx), slot_of_leaf)
            pk = jnp.take(packed, idx, axis=0)                    # [R, Wb] u8
            xc = unpack_codes(pk[:, :ncb], num_features, code_mode)
            w = unpack_weights(pk[:, ncb:], ch, f32=(hilo == "f32"))  # [R, ch]
            slot = jnp.where(valid, raw, -1)                       # [R]
        else:
            xc = sl(X, i * chunk_rows, chunk_rows)
            gc = sl(grad, i * chunk_rows, chunk_rows)
            hc = sl(hess, i * chunk_rows, chunk_rows)
            mc = sl(included, i * chunk_rows, chunk_rows)
            lc = sl(leaf_id, i * chunk_rows, chunk_rows)
            slot = table_lookup(lc, slot_of_leaf)                  # [R]
            w = weight_channels(gc, hc, mc, hilo)                  # [R, ch]

        slot_onehot = (slot[:, None] == iota_slots)               # [R, S] bool
        rhs = (slot_onehot[:, :, None].astype(w.dtype) * w[:, None, :]
               ).reshape(chunk_rows, num_slots * ch)              # [R, S*ch]

        onehot = (xc.astype(jnp.int32)[:, :, None] == iota_bins
                  ).astype(w.dtype)                               # [R, F, B]
        part = jax.lax.dot_general(
            onehot, rhs,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            # f32 mode: HIGHEST decomposes each f32 operand into bf16
            # triples, so every one-hot x weight product is EXACT (the
            # one-hot side is 0/1); bf16 modes use the default fast path
            precision=(jax.lax.Precision.HIGHEST if hilo == "f32" else None),
        )                                                         # [F, B, S*ch]
        return part

    acc0 = (acc_init if acc_init is not None else
            jnp.zeros((num_features, num_bins_padded, num_slots * ch),
                      jnp.float32))
    if compensated:
        # Kahan two-sum across chunk partials: the lost low-order bits of
        # every f32 add are carried forward, so the accumulated bin sums are
        # ~f64-accurate — the numerical effect of the reference's double
        # HistogramBinEntry sums (bin.h:29-31) on f32-native hardware. XLA
        # does not reassociate float arithmetic, so (t - acc) - y survives.
        def accumulate(carry, i):
            acc, comp = carry
            y = chunk_part(i) - comp
            t = acc + y
            return t, (t - acc) - y
    else:
        def accumulate(carry, i):
            acc, comp = carry
            return acc + chunk_part(i), comp
    if comp_init is not None:
        comp0 = comp_init
    else:
        comp0 = jnp.zeros_like(acc0) if compensated \
            else jnp.zeros((), jnp.float32)
    if compact:
        n_chunks_active = jnp.minimum(
            (n_active + chunk_rows - 1) // chunk_rows, n_chunks)

        def while_body(carry):
            i, acc, comp = carry
            acc, comp = accumulate((acc, comp), i)
            return i + 1, acc, comp

        _, acc, comp = jax.lax.while_loop(
            lambda c: c[0] < n_chunks_active, while_body,
            (jnp.asarray(0, n_chunks_active.dtype), acc0, comp0))
    else:
        (acc, comp), _ = jax.lax.scan(
            lambda c, i: (accumulate(c, i), ()), (acc0, comp0),
            jnp.arange(n_chunks))

    if raw_output:
        return acc, comp
    return finalize_histograms(acc, num_slots, hilo)


def finalize_histograms(acc: jnp.ndarray, num_slots: int, hilo
                        ) -> jnp.ndarray:
    """[F, B, S*ch] f32 fold state -> [S, F, B, 3] (sum_g, sum_h, count).

    The combine/transpose tail of ``build_histograms``, split out so a
    streamed wave (which folds shard legs with ``raw_output=True``) runs it
    exactly once — the identical ops the resident pass ends with."""
    num_features, num_bins_padded, _ = acc.shape
    ch = acc.shape[-1] // num_slots
    acc = acc.reshape(num_features, num_bins_padded, num_slots, ch)
    acc = jnp.transpose(acc, (2, 0, 1, 3))                        # [S, F, B, ch]
    return combine_channels(acc, hilo)                            # [S, F, B, 3]


def histogram_cost_report(n_rows: int, num_features: int,
                          num_bins_padded: int, num_slots: int,
                          chunk_rows: int, hilo=True, dtype=None,
                          site: str = None) -> dict:
    """Compile-time cost probe of the streaming histogram kernel at one
    shape class: lower+compile a standalone jitted ``build_histograms`` on
    zero inputs (values never affect the HLO) and publish the normalized
    FLOPs/bytes/HBM report through observability/costs.py. This is the
    kernel's dispatch-site cost leg — in production the kernel is fused
    into the train step, so its isolated cost is only observable here
    (golden-pinned in tests/test_costs.py). Explicit call = intent: runs
    regardless of the ``costs.enabled()`` gate."""
    from ..observability import costs as obs_costs
    dtype = jnp.uint8 if dtype is None else dtype
    n_rows = ((n_rows + chunk_rows - 1) // chunk_rows) * chunk_rows
    X = jnp.zeros((n_rows, num_features), dtype)
    zf = jnp.zeros(n_rows, jnp.float32)
    leaf_id = jnp.zeros(n_rows, jnp.int32)
    slot_of_leaf = jnp.zeros(num_slots + 1, jnp.int32)

    def run(X, g, h, inc, lid, sol):
        return build_histograms(X, g, h, inc, lid, sol, num_slots=num_slots,
                                num_bins_padded=num_bins_padded,
                                chunk_rows=chunk_rows, hilo=hilo)

    site = site or f"histogram.stream.s{num_slots}"
    dims = dict(rows=int(n_rows), features=int(num_features),
                bins=int(num_bins_padded), slots=int(num_slots),
                chunk_rows=int(chunk_rows))
    try:
        compiled = jax.jit(run).lower(X, zf, zf, zf, leaf_id,
                                      slot_of_leaf).compile()
        rep = obs_costs.report_from_compiled(compiled, site, dims)
    except Exception as e:                                   # noqa: BLE001
        rep = dict(dims, site=site, error=f"{type(e).__name__}: {e}"[:300])
    obs_costs.publish(rep)
    return rep


def root_sums(grad: jnp.ndarray, hess: jnp.ndarray, included: jnp.ndarray
              ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Total (sum_g, sum_h, count) over included rows — root LeafSplits init
    (reference: src/treelearner/leaf_splits.hpp Init)."""
    return (jnp.sum(grad, dtype=jnp.float32),
            jnp.sum(hess, dtype=jnp.float32),
            jnp.sum(included, dtype=jnp.float32))
