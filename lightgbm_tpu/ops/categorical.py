"""Categorical best-split search over histograms (device-side).

TPU re-formulation of FeatureHistogram::FindBestThresholdCategorical
(reference: src/treelearner/feature_histogram.hpp:104-259). Two modes, chosen
per feature by ``num_bin <= max_cat_to_onehot``:

- **one-hot**: every category is a candidate singleton left-set; fully
  vectorized gain over (slot, feature, bin).
- **sorted prefix (many categories)**: categories with count >= cat_smooth
  are sorted by gradient/hessian ratio ``sum_g / (sum_h + cat_smooth)``
  (:163-172); candidate left-sets are prefixes of that order from both ends
  (dir=+1 from smallest ctr, dir=-1 from largest), at most
  ``min(max_cat_threshold, (used+1)/2)`` categories (:180); ``cat_l2`` is
  added to lambda_l2 (:161); ``min_data_per_group`` gates evaluation on the
  count accumulated since the last evaluated prefix (:185-210) — a stateful
  rule kept exact here via a short `lax.scan` over prefix positions
  (max_cat_threshold is 32 by default, so the scan is tiny).

The winning left-set is returned as a per-(slot) boolean mask over bins —
the device analog of the reference's ``cat_threshold`` bitset
(split_info.hpp, tree.h:257-284); the grower routes rows by mask lookup and
the host finalize converts masks to raw-category bitsets.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from .split_finder import PerFeatureBest, leaf_split_gain

NEG_INF = -jnp.inf
K_EPS = 1e-15                     # kEpsilon (reference meta.h)


def per_feature_best_categorical(
    hist: jnp.ndarray,            # [S, F, B, 3] (sum_g, sum_h, count)
    parent_g: jnp.ndarray,        # [S]
    parent_h: jnp.ndarray,        # [S]
    parent_c: jnp.ndarray,        # [S]
    num_bins: jnp.ndarray,        # [F] i32
    missing_code: jnp.ndarray,    # [F] i32 (0=none, 1=zero, 2=nan)
    cat_ok: jnp.ndarray,          # [F] bool: categorical & usable this tree
    *,
    lambda_l1: float,
    lambda_l2: float,
    min_data_in_leaf: float,
    min_sum_hessian_in_leaf: float,
    min_gain_to_split: float,
    cat_smooth: float,
    cat_l2: float,
    max_cat_threshold: int,
    max_cat_to_onehot: int,
    min_data_per_group: float,
) -> Tuple[PerFeatureBest, jnp.ndarray]:
    """Best categorical split per (slot, feature) + left-set mask [S, F, B]."""
    S, F, B, _ = hist.shape
    g = hist[..., 0]
    h = hist[..., 1]
    c = hist[..., 2]
    bins = jnp.arange(B, dtype=jnp.int32)[None, None, :]            # [1,1,B]
    # used_bin = num_bin - 1 + (missing_type == None): the trailing bin is the
    # NaN/overflow bin unless the feature is fully categorical (:114-115)
    used_bin = num_bins + jnp.where(missing_code == 0, 0, -1)       # [F]
    in_range = bins < used_bin[None, :, None]                       # [1,F,B]

    mdl = min_data_in_leaf
    msh = min_sum_hessian_in_leaf
    pg = parent_g[:, None, None]
    ph = parent_h[:, None, None]
    pc = parent_c[:, None, None]
    min_gain_shift = (leaf_split_gain(parent_g, parent_h, lambda_l1, lambda_l2)
                      + min_gain_to_split)                          # [S]

    # ---------------- one-hot mode (:122-155) ------------------------------
    oh_lh = h + K_EPS
    oh_rg, oh_rh, oh_rc = pg - g, ph - oh_lh, pc - c
    oh_ok = (in_range & (c >= mdl) & (oh_rc >= mdl)
             & (h >= msh) & (oh_rh >= msh))
    oh_gain = (leaf_split_gain(g, oh_lh, lambda_l1, lambda_l2)
               + leaf_split_gain(oh_rg, oh_rh, lambda_l1, lambda_l2))
    oh_gain = jnp.where(oh_ok, oh_gain, NEG_INF)                    # [S,F,B]
    oh_best = jnp.argmax(oh_gain, axis=2)                           # [S,F]
    oh_best_gain = jnp.take_along_axis(oh_gain, oh_best[..., None], axis=2)[..., 0]

    # ---------------- sorted-prefix mode (:156-231) ------------------------
    l2s = lambda_l2 + cat_l2
    valid = in_range & (c >= cat_smooth)                            # [S,F,B]
    ctr = g / (h + cat_smooth)
    sort_key = jnp.where(valid, ctr, jnp.inf)
    order = jnp.argsort(sort_key, axis=2)                           # [S,F,B]
    rank = jnp.argsort(order, axis=2)                               # bin -> position
    vmask = jnp.take_along_axis(valid, order, axis=2).astype(jnp.float32)
    sg = jnp.take_along_axis(g, order, axis=2) * vmask
    sh = jnp.take_along_axis(h, order, axis=2) * vmask
    sc = jnp.take_along_axis(c, order, axis=2) * vmask
    cum_g = jnp.cumsum(sg, axis=2)
    cum_h = jnp.cumsum(sh, axis=2)
    cum_c = jnp.cumsum(sc, axis=2)
    tot_g, tot_h, tot_c = cum_g[..., -1], cum_h[..., -1], cum_c[..., -1]
    used_cnt = jnp.sum(valid, axis=2).astype(jnp.int32)             # [S,F]
    max_num_cat = jnp.minimum(max_cat_threshold, (used_cnt + 1) // 2)

    n_scan = max(1, min(int(max_cat_threshold), B))

    def prefix(i):
        """Left sums after taking i+1 categories, for both directions.
        dir 0 = +1 (from smallest ctr), dir 1 = -1 (from largest).
        ``i`` is a traced scan counter with i < n_scan <= B."""
        at = lambda a, idx: jax.lax.dynamic_index_in_dim(a, idx, axis=2,
                                                         keepdims=False)
        fwd = (at(cum_g, i), at(cum_h, i), at(cum_c, i))
        j = jnp.clip(used_cnt - 2 - i, -1, B - 1)                   # [S,F]
        take = lambda a: jnp.where(
            j < 0, 0.0, jnp.take_along_axis(a, jnp.maximum(j, 0)[..., None],
                                            axis=2)[..., 0])
        rev = (tot_g - take(cum_g), tot_h - take(cum_h), tot_c - take(cum_c))
        lg = jnp.stack([fwd[0], rev[0]])                            # [2,S,F]
        lh = jnp.stack([fwd[1], rev[1]])
        lc = jnp.stack([fwd[2], rev[2]])
        # count of the single category taken at step i per direction
        cnt_i_fwd = at(sc, i)
        jj = jnp.clip(used_cnt - 1 - i, 0, B - 1)
        cnt_i_rev = jnp.take_along_axis(sc, jj[..., None], axis=2)[..., 0]
        return lg, lh, lc, jnp.stack([cnt_i_fwd, cnt_i_rev])

    def scan_body(carry, i):
        ccg, broke, best_gain, best_k = carry                        # [2,S,F] each
        lg, lh, lc, cnt_i = prefix(i)
        lh_eps = lh + K_EPS
        step_ok = (i < max_num_cat) & (i < used_cnt)                 # [S,F]
        ccg = ccg + cnt_i
        cont1 = (lc < mdl) | (lh_eps < msh)                          # :195-196 continue
        rc = pc[..., 0] - lc
        rh = ph[..., 0] - lh_eps
        brk = (~cont1) & ((rc < mdl) | (rc < min_data_per_group)     # :198-201 break
                          | (rh < msh))
        broke = broke | (step_ok[None] & brk)
        can_eval = step_ok[None] & ~broke & ~cont1 & (ccg >= min_data_per_group)
        ccg = jnp.where(can_eval, 0.0, ccg)                          # :205-207
        gain_i = (leaf_split_gain(lg, lh_eps, lambda_l1, l2s)
                  + leaf_split_gain(pg[..., 0] - lg, ph[..., 0] - lh_eps,
                                    lambda_l1, l2s))
        better = can_eval & (gain_i > min_gain_shift[None, :, None]) \
            & (gain_i > best_gain)
        best_gain = jnp.where(better, gain_i, best_gain)
        best_k = jnp.where(better, i, best_k)
        return (ccg, broke, best_gain, best_k), None

    init = (jnp.zeros((2, S, F)), jnp.zeros((2, S, F), bool),
            jnp.full((2, S, F), NEG_INF), jnp.zeros((2, S, F), jnp.int32))
    (_, _, sp_gain, sp_k), _ = jax.lax.scan(
        scan_body, init, jnp.arange(n_scan, dtype=jnp.int32))

    # pick direction (dir=+1 wins ties: argmax picks the first)
    sp_dir = jnp.argmax(sp_gain, axis=0)                             # [S,F]
    sp_best_gain = jnp.take_along_axis(sp_gain, sp_dir[None], axis=0)[0]
    sp_best_k = jnp.take_along_axis(sp_k, sp_dir[None], axis=0)[0]   # [S,F]

    # ---------------- merge modes + build outputs --------------------------
    use_onehot = (num_bins <= max_cat_to_onehot)[None, :]            # [1,F]
    raw_gain = jnp.where(use_onehot, oh_best_gain, sp_best_gain)
    gate = cat_ok[None, :]
    gain = jnp.where(gate & (raw_gain > min_gain_shift[:, None]),
                     raw_gain - min_gain_shift[:, None], NEG_INF)    # [S,F]

    # left-set mask over bins
    oh_mask = bins == oh_best[..., None]                             # [S,F,B]
    is_fwd = (sp_dir == 0)[..., None]
    sp_mask = jnp.where(
        is_fwd, rank <= sp_best_k[..., None],
        rank >= (used_cnt - 1 - sp_best_k)[..., None]) & valid
    mask = jnp.where(use_onehot[..., None], oh_mask, sp_mask)
    mask = mask & (gain > NEG_INF)[..., None]

    # left sums of the winner
    def sp_left(arr_cum):
        fwd_v = jnp.take_along_axis(
            arr_cum, jnp.clip(sp_best_k, 0, B - 1)[..., None], axis=2)[..., 0]
        j = jnp.clip(used_cnt - 2 - sp_best_k, -1, B - 1)
        tot = arr_cum[..., -1]
        rev_v = tot - jnp.where(
            j < 0, 0.0, jnp.take_along_axis(arr_cum, jnp.maximum(j, 0)[..., None],
                                            axis=2)[..., 0])
        return jnp.where(is_fwd[..., 0], fwd_v, rev_v)

    oh_lg = jnp.take_along_axis(g, oh_best[..., None], axis=2)[..., 0]
    oh_lh2 = jnp.take_along_axis(h, oh_best[..., None], axis=2)[..., 0]
    oh_lc = jnp.take_along_axis(c, oh_best[..., None], axis=2)[..., 0]
    left_g = jnp.where(use_onehot, oh_lg, sp_left(cum_g))
    left_h = jnp.where(use_onehot, oh_lh2, sp_left(cum_h))
    left_c = jnp.where(use_onehot, oh_lc, sp_left(cum_c))

    pf = PerFeatureBest(
        gain=gain,
        threshold=jnp.zeros((S, F), jnp.int32),
        default_left=jnp.zeros((S, F), bool),                        # :105
        left_g=left_g,
        left_h=left_h,
        left_c=left_c,
    )
    return pf, mask
