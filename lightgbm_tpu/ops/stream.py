"""Out-of-core shard transport: host-resident packed bin codes, streamed
H2D through a double-buffered prefetcher (``tpu_residency=stream``).

The design point comes straight from the out-of-core GBDT literature:
"Out-of-Core GPU Gradient Boosting" (arXiv 2005.09148) shows a chunked
host-resident pipeline loses only a few percent when transfers overlap
compute, and "XGBoost: Scalable GPU Accelerated Learning" (arXiv
1806.11248) pins what to stream — keep gradients/partition state
device-resident and move ONLY the compressed bin codes. Three pieces:

- :func:`pack_codes_host` — numpy twin of ``ops/histogram._pack_codes``
  (u8 | u16 | u4 | u6 byte layouts), so shards transfer at 0.5-2 bytes per
  code and ``unpack_codes`` on device restores the exact integer codes
  (parity pinned in tests/test_stream.py).
- :class:`HostShardStore` — the padded code matrix cut into fixed-size row
  shards. Under row-sharded strategies (tree_learner=data|voting) each
  shard interleaves the per-DEVICE blocks of the resident layout, so
  ``device_put`` with the booster's row sharding hands device d exactly
  the rows it would hold resident — the per-device histogram fold order
  (and therefore the trained model) is bit-identical to device residency.
- :class:`ShardPrefetcher` — double-buffered ``jax.device_put``: the
  driver (grower.StreamedGrower) calls ``prefetch(i+1)`` right after
  dispatching shard i's compute, so the H2D copy of the next shard rides
  under the current shard's histogram matmul. ``get(i)`` that finds no
  prefetched buffer is a *stall* — counted (``stream.stalls``) and timed
  (``stream.stall_seconds``) so the overlap is measured, not assumed
  (``bench.py --stream`` reports the stall fraction). Buffers are NEVER
  donated to jitted fns (the same buffer is handed out again next wave),
  which is what makes the ping-pong donation-safe.

Integrity: each packed shard carries a CRC32 taken at pack time, re-checked
before EVERY transfer (``tpu_stream_verify``, on by default). A mismatch
raises the typed :class:`ShardCorruptionError` instead of folding
bit-rotted codes into histograms; the chaos harness (robustness/chaos.py
``corrupt_host_shard``) flips shard bytes in flight to exercise exactly
this path. The check is NOT free: zlib.crc32 runs ~1 GB/s on one host
core — the same order as the copy it precedes — and it is synchronous in
the training thread, so at host-RAM-scale stores it is a measurable tax
(``bench.py --stream`` prices it on the real shape); set
``tpu_stream_verify=false`` to trade detection for that throughput.

This module and ``dataset.py`` are the only sanctioned homes of
``jax.device_put`` reachable from wave/scan bodies — tpu-lint R009
enforces that the prefetcher stays the single choke point for mid-loop
host->device traffic.
"""
from __future__ import annotations

import zlib
from typing import Callable, Dict, List, Optional

import numpy as np

from ..utils.log import Log


class ShardCorruptionError(RuntimeError):
    """A host-resident code shard failed its CRC32 integrity check at
    transfer time: the bytes about to be fed to the histogram fold are not
    the bytes that were packed (host memory corruption, a stray writer).
    Training must stop — a silently corrupted shard poisons every later
    tree. The store is rebuilt from the dataset at construction, so a
    restart (the crash supervisor relaunches with ``resume_from=auto``)
    self-heals; the CLI exits with status 144 on this error."""

def pack_codes_host(X: np.ndarray, code_mode: str) -> np.ndarray:
    """[N, F] uint8/uint16 bin codes -> [N, code_bytes_total(F, mode)] u8.

    Byte-for-byte identical to the device-side ``_pack_codes``
    (ops/histogram.py) so ``unpack_codes`` inverts it exactly; numpy so the
    host shard store never touches a device. Little-endian u16, low-nibble-
    first u4, and the 4-codes-in-3-bytes u6 layout all match."""
    X = np.ascontiguousarray(X)
    N, F = X.shape
    if code_mode == "u8":
        return X.astype(np.uint8, copy=False)
    if code_mode == "u16":
        return X.astype("<u2", copy=False).view(np.uint8).reshape(N, 2 * F)
    x = X.astype(np.uint8, copy=False)
    if code_mode == "u4":
        if F % 2:
            x = np.pad(x, ((0, 0), (0, 1)))
        return (x[:, 0::2] | (x[:, 1::2] << 4)).astype(np.uint8)
    assert code_mode == "u6", code_mode
    if F % 4:
        x = np.pad(x, ((0, 0), (0, 4 - F % 4)))
    q = x.reshape(N, -1, 4).astype(np.uint8)
    c0, c1, c2, c3 = q[..., 0], q[..., 1], q[..., 2], q[..., 3]
    b0 = c0 | (c1 << 6)
    b1 = (c1 >> 2) | (c2 << 4)
    b2 = (c2 >> 4) | (c3 << 2)
    return np.stack([b0, b1, b2], axis=-1).reshape(N, -1).astype(np.uint8)


# ------------------------------------------------------------ shard geometry

def resolve_shard_rows(per_device_rows: int, chunk_rows: int,
                       requested_rows: int = 0) -> int:
    """Per-device rows of one shard: a multiple of ``chunk_rows`` that
    DIVIDES ``per_device_rows`` exactly.

    Divisibility is a correctness constraint, not a convenience: the
    padded row count (and with it every chunk boundary and the bagging
    RNG's draw shapes) must be IDENTICAL to device residency, or streamed
    training would not be bit-identical. ``requested_rows`` (config
    ``tpu_stream_shard_rows``, interpreted per device) rounds to the
    NEAREST achievable divisor (ties break toward finer shards — more
    prefetch slack, smaller buffers); 0 auto-sizes toward ~8 shards.
    Since shard size never changes the math, a checkpoint resumes under
    ANY shard size (docs/Fault-Tolerance.md)."""
    assert per_device_rows % chunk_rows == 0, (per_device_rows, chunk_rows)
    m = per_device_rows // chunk_rows          # total chunks per device
    if requested_rows <= 0:
        want = m / 8.0                         # ~8 shards by default
    else:
        want = min(float(m), requested_rows / chunk_rows)
    # divisor of m NEAREST to want (not largest-below: a prime-ish m
    # would otherwise degenerate to m single-chunk shards)
    best = 1
    for c in range(1, int(m ** 0.5) + 1):
        if m % c == 0:
            for d in (c, m // c):
                if (abs(d - want), d) < (abs(best - want), best):
                    best = d
    return best * chunk_rows


class HostShardStore:
    """The padded, packed code matrix as fixed-size host row shards.

    ``X`` is the RAW [N, F] host code matrix; padding (rows to
    ``n_rows_padded``, columns to ``num_cols`` — exactly what device
    residency would ``np.pad`` before ``device_put``) is applied
    per-block at pack time, so the store never materializes a full padded
    copy: at >HBM dataset scale (the whole point of streaming) the host
    working set is the packed shards (0.5-2 B/code) plus ONE transient
    unpacked block. ``local_shard_rows`` is the PER-DEVICE rows of one
    shard; a shard's global row count is ``local_shard_rows *
    n_devices``. Under ``n_devices > 1`` shard i interleaves each
    device's i-th sub-block so the booster's row sharding places device
    d's resident rows back on device d (see module doc).
    """

    def __init__(self, X: np.ndarray, *, n_rows_padded: int, num_cols: int,
                 local_shard_rows: int, n_devices: int, code_mode: str):
        n_real, f_real = X.shape
        assert n_rows_padded >= n_real and num_cols >= f_real
        assert n_rows_padded % n_devices == 0
        per_dev = n_rows_padded // n_devices
        assert per_dev % local_shard_rows == 0, (per_dev, local_shard_rows)
        self.n_rows_padded = n_rows_padded
        self.num_cols = num_cols
        self.n_devices = n_devices
        self.local_shard_rows = local_shard_rows
        self.n_shards = per_dev // local_shard_rows
        self.code_mode = code_mode
        self.dtype = X.dtype
        R = local_shard_rows

        # ONE reused [shard_rows, num_cols] staging buffer: each shard's
        # device sub-blocks are strided writes into it (no per-block zeros
        # allocation, no per-shard concatenate — the transient unpacked
        # working set is exactly one shard). Padding rows/cols are the
        # zeros device residency pads with; the buffer only needs
        # re-zeroing when padding exists at all (otherwise every element
        # is overwritten).
        needs_zero = n_rows_padded > n_real or num_cols > f_real
        block = np.zeros((R * n_devices, num_cols), X.dtype)
        shards: List[np.ndarray] = []
        for i in range(self.n_shards):
            if needs_zero and i:
                block[:] = 0
            for d in range(n_devices):
                a = d * per_dev + i * R
                if a < n_real:
                    rows = X[a:min(a + R, n_real)]
                    block[d * R: d * R + rows.shape[0], :f_real] = rows
            packed = pack_codes_host(block, code_mode)
            if packed is block or packed.base is not None:
                # u8/u16 packing returns the input (or a bitcast view of
                # it) — materialize a copy or the next shard's strided
                # writes would clobber this one
                packed = packed.copy()
            shards.append(np.ascontiguousarray(packed))
        self.shards = shards
        self.shard_bytes = int(shards[0].nbytes) if shards else 0
        # per-shard content checksum, taken at pack time: the prefetcher
        # re-hashes each shard before every H2D transfer, so a bit flipped
        # in host RAM between packing and streaming is DETECTED (typed
        # ShardCorruptionError) instead of silently folded into histograms
        self.checksums: List[int] = [self._crc(s) for s in shards]

    @staticmethod
    def _crc(shard: np.ndarray) -> int:
        return zlib.crc32(shard) & 0xFFFFFFFF

    def verify_shard(self, i: int) -> bool:
        """Recompute shard ``i``'s CRC32 and compare with the pack-time
        value. Costs ~shard_bytes / 1 GB/s of synchronous host CPU — see
        the module docstring for the honest per-iteration price."""
        return self._crc(self.shards[i]) == self.checksums[i]

    @property
    def total_bytes(self) -> int:
        return self.shard_bytes * self.n_shards

    def describe(self) -> Dict:
        return {"n_shards": self.n_shards,
                "shard_rows": self.local_shard_rows * self.n_devices,
                "shard_bytes": self.shard_bytes,
                "code_mode": self.code_mode,
                "total_bytes": self.total_bytes}


class ShardPrefetcher:
    """Double-buffered H2D feed over a :class:`HostShardStore`.

    ``put_fn(np_shard) -> jax.Array`` is supplied by the booster and
    applies its row sharding (``jax.device_put`` with the mesh
    NamedSharding) — this class never decides placement. At most two shard
    buffers are live: the one compute is consuming and the one in flight.

    Access pattern contract: shards are read cyclically 0..n-1 (one cycle
    per wave, plus the trailing route pass). ``get(i)`` returns shard i's
    device buffer, preferring the prefetched one; ``prefetch(j)`` issues
    shard ``j % n_shards``'s transfer and is a no-op when it is already
    pending. A ``get`` that finds nothing pending is a STALL: the transfer
    runs synchronously in the caller's critical path, counted and timed
    into the registry (``stream.stalls`` / ``stream.stall_seconds``
    histogram) under a ``prefetch_stall`` span. ``stream.bytes_h2d``
    counts every transferred byte either way.

    ``LGBM_TPU_STREAM_NO_PREFETCH=1`` turns ``prefetch`` into a no-op —
    every shard transfer becomes a measured stall. That is the honesty
    knob behind ``bench.py --stream``'s overlap-vs-no-overlap comparison
    and the forced-stall tests.
    """

    def __init__(self, store: HostShardStore, put_fn: Callable,
                 prefetch_enabled: Optional[bool] = None,
                 verify: bool = True):
        import os
        self.store = store
        self.put_fn = put_fn
        if prefetch_enabled is None:
            prefetch_enabled = os.environ.get(
                "LGBM_TPU_STREAM_NO_PREFETCH", "") not in ("1", "true")
        self.prefetch_enabled = prefetch_enabled
        self.verify_enabled = verify
        self._pending: Dict[int, object] = {}
        self.stalls = 0
        self.hits = 0
        self.stall_seconds = 0.0
        self.bytes_h2d = 0

    def _registry(self):
        from .. import observability as obs
        return obs

    def _put(self, i: int):
        if self.verify_enabled and not self.store.verify_shard(i):
            obs = self._registry()
            obs.inc("fault.shard_corrupt")
            obs.event("shard_corrupt", shard=i)
            raise ShardCorruptionError(
                f"host shard {i} failed its CRC32 integrity check "
                f"(expected {self.store.checksums[i]:#010x}) — the packed "
                f"codes changed in host memory since construction; "
                f"restart the run (resume_from=auto rebuilds the shard "
                f"store from the dataset; tpu_stream_verify=false disables "
                f"this check)")
        self.bytes_h2d += self.store.shard_bytes
        self._registry().inc("stream.bytes_h2d", self.store.shard_bytes)
        return self.put_fn(self.store.shards[i])

    def prefetch(self, j: int) -> None:
        """Issue shard ``j % n_shards``'s H2D copy if not already pending.
        Called right AFTER the driver dispatches compute on the current
        shard, so the copy overlaps it; at most one transfer is kept in
        flight (double buffering — buffer 3 would just pin host+device
        memory without hiding any more latency)."""
        if not self.prefetch_enabled or not self.store.n_shards:
            return
        j = j % self.store.n_shards
        if j not in self._pending:
            if len(self._pending) >= 2:      # defensive: contract is <= 1
                self._pending.clear()
            self._pending[j] = self._put(j)

    def get(self, i: int):
        """Device buffer of shard ``i`` — prefetched if the overlap worked,
        synchronously transferred (a counted, timed stall) if not."""
        obs = self._registry()
        arr = self._pending.pop(i, None)
        if arr is not None:
            self.hits += 1
            obs.inc("stream.prefetch_hits")
            return arr
        self.stalls += 1
        obs.inc("stream.stalls")
        t0 = obs.clock()
        with obs.span("prefetch_stall", shard=i):
            arr = self._put(i)
            # block on THIS transfer only (compute stays queued): the wait
            # is the measurable cost the double buffer exists to hide
            try:
                arr.block_until_ready()
            except AttributeError:
                pass
        dt = obs.clock() - t0
        self.stall_seconds += dt
        obs.get_registry().histogram("stream.stall_seconds").observe(dt)
        return arr

    def report(self) -> Dict:
        return {"n_shards": self.store.n_shards,
                "shard_bytes": self.store.shard_bytes,
                "stalls": self.stalls, "prefetch_hits": self.hits,
                "stall_seconds": round(self.stall_seconds, 6),
                "bytes_h2d": self.bytes_h2d,
                "prefetch_enabled": self.prefetch_enabled,
                "verify_enabled": self.verify_enabled}
