"""Vectorized best-split search over histograms.

TPU re-formulation of FeatureHistogram::FindBestThreshold
(reference: src/treelearner/feature_histogram.hpp:72-101,314-455): the
reference's two sequential scans per feature (dir=+1 / dir=-1 with
missing-value default-direction learning) become masked cumulative sums over
the bin axis, evaluated for all (slot, feature, threshold, direction)
candidates at once, followed by one argmax.

Semantics preserved:
- gain = GetLeafSplitGain(l) + GetLeafSplitGain(r) with L1 thresholding
  (feature_histogram.hpp:290-296), candidate valid iff
  gain > parent_gain + min_gain_to_split (:101,362),
- MissingType::NaN — the NaN bin (last) is excluded from the accumulating
  side, so missing rows follow the scan direction's remainder: dir=-1 sends
  them left (default_left=true), dir=+1 right (:349-357,375-386),
- MissingType::Zero — the zero bin is excluded likewise and its threshold
  skipped (skip_default_bin, :338,399),
- features with num_bin<=2 or MissingType::None scan only dir=-1
  (:86-99), with the 2-bin NaN default-direction fix (:96-98),
- min_data_in_leaf / min_sum_hessian_in_leaf constraints on both children.

Categorical features are handled by find_best_splits_categorical (one-hot and
sorted-prefix modes, feature_histogram.hpp:104-259).
"""
from __future__ import annotations

from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -jnp.inf


class SplitCandidates(NamedTuple):
    """Best split per histogram slot (device arrays, all [S])."""
    gain: jnp.ndarray          # f32, improvement over parent (-inf if none)
    feature: jnp.ndarray       # i32 inner feature index
    threshold: jnp.ndarray     # i32 bin threshold (left: bin <= threshold)
    default_left: jnp.ndarray  # bool
    left_g: jnp.ndarray        # f32 sum of gradients in left child
    left_h: jnp.ndarray        # f32
    left_c: jnp.ndarray        # f32 row count in left child


def leaf_split_gain(sum_g, sum_h, l1: float, l2: float):
    """(|g|-l1)_+^2 / (h+l2) — feature_histogram.hpp:290-296."""
    reg = jnp.maximum(jnp.abs(sum_g) - l1, 0.0)
    return reg * reg / (sum_h + l2)


def leaf_output(sum_g, sum_h, l1: float, l2: float):
    """-sign(g)(|g|-l1)_+ / (h+l2) — feature_histogram.hpp:304-310."""
    reg = jnp.maximum(jnp.abs(sum_g) - l1, 0.0)
    return -jnp.sign(sum_g) * reg / (sum_h + l2)


def find_best_splits_numerical(
    hist: jnp.ndarray,        # [S, F, B, 3] (sum_g, sum_h, count)
    parent_g: jnp.ndarray,    # [S]
    parent_h: jnp.ndarray,    # [S]
    parent_c: jnp.ndarray,    # [S]
    num_bins: jnp.ndarray,    # [F] i32
    missing_code: jnp.ndarray,  # [F] i32: 0=none, 1=zero, 2=nan
    default_bin: jnp.ndarray,   # [F] i32
    feature_ok: jnp.ndarray,    # [F] bool (non-categorical & feature_fraction mask)
    *,
    lambda_l1: float,
    lambda_l2: float,
    min_data_in_leaf: float,
    min_sum_hessian_in_leaf: float,
    min_gain_to_split: float,
) -> SplitCandidates:
    S, F, B, _ = hist.shape
    g = hist[..., 0]
    h = hist[..., 1]
    c = hist[..., 2]
    bins = jnp.arange(B, dtype=jnp.int32)[None, :]                 # [1, B]
    nb = num_bins[:, None]                                         # [F, 1]
    valid_bin = bins < nb                                          # [F, B]

    is_nan = missing_code[:, None] == 2
    is_zero = missing_code[:, None] == 1
    full_mode = (num_bins > 2) & (missing_code != 0)               # [F]

    # bins excluded from directional accumulation in full mode
    excl_full = (is_nan & (bins == nb - 1)) | (is_zero & (bins == default_bin[:, None]))
    excl = jnp.where(full_mode[:, None], excl_full, False) | ~valid_bin  # [F, B]
    inc = (~excl).astype(jnp.float32)[None, :, :]                  # [1, F, B]

    cum_g = jnp.cumsum(g * inc, axis=2)
    cum_h = jnp.cumsum(h * inc, axis=2)
    cum_c = jnp.cumsum(c * inc, axis=2)
    tot_g = cum_g[..., -1:]
    tot_h = cum_h[..., -1:]
    tot_c = cum_c[..., -1:]
    pg = parent_g[:, None, None]
    ph = parent_h[:, None, None]
    pc = parent_c[:, None, None]

    def child_gains(lg, lh, lc, rg, rh, rc):
        ok = ((lc >= min_data_in_leaf) & (rc >= min_data_in_leaf)
              & (lh >= min_sum_hessian_in_leaf) & (rh >= min_sum_hessian_in_leaf))
        gains = (leaf_split_gain(lg, lh, lambda_l1, lambda_l2)
                 + leaf_split_gain(rg, rh, lambda_l1, lambda_l2))
        return jnp.where(ok, gains, NEG_INF)

    # --- forward scan (dir=+1): left = included bins <= t, missing -> right
    fwd_lg, fwd_lh, fwd_lc = cum_g, cum_h, cum_c
    fwd_rg, fwd_rh, fwd_rc = pg - fwd_lg, ph - fwd_lh, pc - fwd_lc
    fwd_thr_ok = (full_mode[:, None]                                # fwd only in full mode
                  & (bins <= nb - 2)
                  & ~(is_zero & (bins == default_bin[:, None])))    # skip_default_bin
    fwd_gain = jnp.where(fwd_thr_ok[None], child_gains(fwd_lg, fwd_lh, fwd_lc,
                                                       fwd_rg, fwd_rh, fwd_rc), NEG_INF)

    # --- reverse scan (dir=-1): right = included bins > t, missing -> left
    rev_rg, rev_rh, rev_rc = tot_g - cum_g, tot_h - cum_h, tot_c - cum_c
    rev_lg, rev_lh, rev_lc = pg - rev_rg, ph - rev_rh, pc - rev_rc
    rev_max_thr = jnp.where(full_mode & (missing_code == 2), nb[:, 0] - 3, nb[:, 0] - 2)
    rev_thr_ok = ((bins <= rev_max_thr[:, None]) & (bins >= 0)
                  & ~(full_mode[:, None] & is_zero & (bins == default_bin[:, None] - 1)))
    rev_gain = jnp.where(rev_thr_ok[None], child_gains(rev_lg, rev_lh, rev_lc,
                                                       rev_rg, rev_rh, rev_rc), NEG_INF)

    # default direction: rev sends missing left, except the 2-bin NaN fix
    # (feature_histogram.hpp:96-98) where missing is the last bin on the right.
    rev_default_left = ~(~full_mode & (missing_code == 2))          # [F]

    feature_gate = jnp.where(feature_ok[None, :, None], 0.0, NEG_INF)
    parent_gain_shift = (leaf_split_gain(parent_g, parent_h, lambda_l1, lambda_l2)
                         + min_gain_to_split)[:, None, None]
    rev_gain = rev_gain + feature_gate
    fwd_gain = fwd_gain + feature_gate
    rev_gain = jnp.where(rev_gain > parent_gain_shift, rev_gain - parent_gain_shift, NEG_INF)
    fwd_gain = jnp.where(fwd_gain > parent_gain_shift, fwd_gain - parent_gain_shift, NEG_INF)

    # --- pick best over (dir, feature, threshold); rev first to mirror the
    # reference's dir=-1-then-dir=+1 strict-improvement ordering (:89-93)
    all_gain = jnp.stack([rev_gain, fwd_gain], axis=1)              # [S, 2, F, B]
    flat = all_gain.reshape(S, 2 * F * B)
    best_idx = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best_idx[:, None], axis=1)[:, 0]
    d_idx = best_idx // (F * B)
    f_idx = (best_idx // B) % F
    t_idx = best_idx % B

    def gather(arr):  # arr [S, F, B] -> [S] at (f_idx, t_idx)
        return arr[jnp.arange(S), f_idx, t_idx]

    is_rev = d_idx == 0
    left_g = jnp.where(is_rev, gather(rev_lg), gather(fwd_lg))
    left_h = jnp.where(is_rev, gather(rev_lh), gather(fwd_lh))
    left_c = jnp.where(is_rev, gather(rev_lc), gather(fwd_lc))
    default_left = jnp.where(is_rev, rev_default_left[f_idx], False)

    return SplitCandidates(
        gain=best_gain,
        feature=f_idx.astype(jnp.int32),
        threshold=t_idx.astype(jnp.int32),
        default_left=default_left,
        left_g=left_g,
        left_h=left_h,
        left_c=left_c,
    )
