"""Vectorized best-split search over histograms.

TPU re-formulation of FeatureHistogram::FindBestThreshold
(reference: src/treelearner/feature_histogram.hpp:72-101,314-455): the
reference's two sequential scans per feature (dir=+1 / dir=-1 with
missing-value default-direction learning) become masked cumulative sums over
the bin axis, evaluated for all (slot, feature, threshold, direction)
candidates at once, followed by one argmax.

The search is split into two stages so the distributed tree learners
(parallel/comm.py) can compose them the way the reference composes
FindBestSplitsFromHistograms with its network reductions:

1. ``per_feature_best_numerical`` — best threshold *per feature*
   (the reference's per-feature OMP loop, serial_tree_learner.cpp:451-516),
2. ``reduce_features`` — argmax over the feature axis
   (the reference's ``best_split_per_leaf_`` update); feature-parallel
   learners instead all-gather per-device winners and argmax across devices
   (SyncUpGlobalBestSplit, parallel_tree_learner.h:184-207), voting learners
   use the per-feature gains for PV-Tree vote collection.

Semantics preserved:
- gain = GetLeafSplitGain(l) + GetLeafSplitGain(r) with L1 thresholding
  (feature_histogram.hpp:290-296), candidate valid iff
  gain > parent_gain + min_gain_to_split (:101,362),
- MissingType::NaN — the NaN bin (last) is excluded from the accumulating
  side, so missing rows follow the scan direction's remainder: dir=-1 sends
  them left (default_left=true), dir=+1 right (:349-357,375-386),
- MissingType::Zero — the zero bin is excluded likewise and its threshold
  skipped (skip_default_bin, :338,399),
- features with num_bin<=2 or MissingType::None scan only dir=-1
  (:86-99), with the 2-bin NaN default-direction fix (:96-98),
- min_data_in_leaf / min_sum_hessian_in_leaf constraints on both children.

Categorical features are handled by ops/categorical.py (one-hot and
sorted-prefix modes, feature_histogram.hpp:104-259), which produces the same
``PerFeatureBest`` shape and is merged before ``reduce_features``.
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -jnp.inf


class SplitCandidates(NamedTuple):
    """Best split per histogram slot (device arrays, all [S] unless noted).

    Slot-order invariant: slots are assigned to pending leaves in ASCENDING
    leaf-id order (grower.py wave step 1, a cumsum over `needs_hist`), and
    three consumers rely on that one ordering staying consistent — the
    grower's `leaf_of_slot` scatter/gather pair, the compacted histogram
    pass's position->slot derivation (`slot_from_position` /
    `slot_position_base`, which index the SAME per-leaf segment tables the
    incremental partition maintains), and the scan here, whose outputs are
    written back through `scan_leaves = leaf_of_slot ++ siblings`. The scan
    itself is row-order-independent (it reads finished histograms), so the
    incremental partition changes nothing below this line — but a re-order
    of slot assignment would silently mis-route all three.
    """
    gain: jnp.ndarray          # f32, improvement over parent (-inf if none)
    feature: jnp.ndarray       # i32 inner feature index (GLOBAL)
    threshold: jnp.ndarray     # i32 bin threshold (left: bin <= threshold)
    default_left: jnp.ndarray  # bool
    left_g: jnp.ndarray        # f32 sum of gradients in left child
    left_h: jnp.ndarray        # f32
    left_c: jnp.ndarray        # f32 row count in left child
    is_cat: jnp.ndarray        # bool: categorical split
    cat_mask: jnp.ndarray      # bool [S, B]: left-set over bins (cat splits)


class PerFeatureBest(NamedTuple):
    """Best split per (slot, feature); all arrays [S, F]."""
    gain: jnp.ndarray          # f32, improvement over parent (-inf if none)
    threshold: jnp.ndarray     # i32
    default_left: jnp.ndarray  # bool
    left_g: jnp.ndarray
    left_h: jnp.ndarray
    left_c: jnp.ndarray


def leaf_split_gain(sum_g, sum_h, l1: float, l2: float):
    """(|g|-l1)_+^2 / (h+l2) — feature_histogram.hpp:290-296."""
    reg = jnp.maximum(jnp.abs(sum_g) - l1, 0.0)
    return reg * reg / (sum_h + l2)


def leaf_output(sum_g, sum_h, l1: float, l2: float):
    """-sign(g)(|g|-l1)_+ / (h+l2) — feature_histogram.hpp:304-310.

    A zero denominator (legal under min_sum_hessian_in_leaf=0, lambda_l2=0
    with vanishing hessians) yields 0, not Inf: the score update resolves
    leaf values through table_lookup's one-hot contraction, which touches
    every table row, so a single Inf/NaN leaf would poison all rows.
    """
    reg = jnp.maximum(jnp.abs(sum_g) - l1, 0.0)
    denom = sum_h + l2
    out = -jnp.sign(sum_g) * reg / denom
    return jnp.where((denom > 0) & jnp.isfinite(out), out, 0.0)


def per_feature_best_numerical(
    hist: jnp.ndarray,        # [S, F, B, 3] (sum_g, sum_h, count)
    parent_g: jnp.ndarray,    # [S]
    parent_h: jnp.ndarray,    # [S]
    parent_c: jnp.ndarray,    # [S]
    num_bins: jnp.ndarray,    # [F] i32
    missing_code: jnp.ndarray,  # [F] i32: 0=none, 1=zero, 2=nan
    default_bin: jnp.ndarray,   # [F] i32
    feature_ok: jnp.ndarray,    # [F] bool (numerical & feature_fraction mask)
    *,
    lambda_l1: float,
    lambda_l2: float,
    min_data_in_leaf: float,
    min_sum_hessian_in_leaf: float,
    min_gain_to_split: float,
) -> PerFeatureBest:
    """Best numerical threshold for every (slot, feature) pair.

    Gains are already shifted by the parent gain + min_gain_to_split
    (feature_histogram.hpp:101), so a finite value means "valid improvement".
    """
    S, F, B, _ = hist.shape
    g = hist[..., 0]
    h = hist[..., 1]
    c = hist[..., 2]
    bins = jnp.arange(B, dtype=jnp.int32)[None, :]                 # [1, B]
    nb = num_bins[:, None]                                         # [F, 1]
    valid_bin = bins < nb                                          # [F, B]

    is_nan = missing_code[:, None] == 2
    is_zero = missing_code[:, None] == 1
    full_mode = (num_bins > 2) & (missing_code != 0)               # [F]

    # bins excluded from directional accumulation in full mode
    excl_full = (is_nan & (bins == nb - 1)) | (is_zero & (bins == default_bin[:, None]))
    excl = jnp.where(full_mode[:, None], excl_full, False) | ~valid_bin  # [F, B]
    inc = (~excl).astype(jnp.float32)[None, :, :]                  # [1, F, B]

    cum_g = jnp.cumsum(g * inc, axis=2)
    cum_h = jnp.cumsum(h * inc, axis=2)
    cum_c = jnp.cumsum(c * inc, axis=2)
    tot_g = cum_g[..., -1:]
    tot_h = cum_h[..., -1:]
    tot_c = cum_c[..., -1:]
    pg = parent_g[:, None, None]
    ph = parent_h[:, None, None]
    pc = parent_c[:, None, None]

    def child_gains(lg, lh, lc, rg, rh, rc):
        ok = ((lc >= min_data_in_leaf) & (rc >= min_data_in_leaf)
              & (lh >= min_sum_hessian_in_leaf) & (rh >= min_sum_hessian_in_leaf))
        gains = (leaf_split_gain(lg, lh, lambda_l1, lambda_l2)
                 + leaf_split_gain(rg, rh, lambda_l1, lambda_l2))
        return jnp.where(ok, gains, NEG_INF)

    # --- forward scan (dir=+1): left = included bins <= t, missing -> right
    fwd_lg, fwd_lh, fwd_lc = cum_g, cum_h, cum_c
    fwd_rg, fwd_rh, fwd_rc = pg - fwd_lg, ph - fwd_lh, pc - fwd_lc
    fwd_thr_ok = (full_mode[:, None]                                # fwd only in full mode
                  & (bins <= nb - 2)
                  & ~(is_zero & (bins == default_bin[:, None])))    # skip_default_bin
    fwd_gain = jnp.where(fwd_thr_ok[None], child_gains(fwd_lg, fwd_lh, fwd_lc,
                                                       fwd_rg, fwd_rh, fwd_rc), NEG_INF)

    # --- reverse scan (dir=-1): right = included bins > t, missing -> left
    rev_rg, rev_rh, rev_rc = tot_g - cum_g, tot_h - cum_h, tot_c - cum_c
    rev_lg, rev_lh, rev_lc = pg - rev_rg, ph - rev_rh, pc - rev_rc
    rev_max_thr = jnp.where(full_mode & (missing_code == 2), nb[:, 0] - 3, nb[:, 0] - 2)
    rev_thr_ok = ((bins <= rev_max_thr[:, None]) & (bins >= 0)
                  & ~(full_mode[:, None] & is_zero & (bins == default_bin[:, None] - 1)))
    rev_gain = jnp.where(rev_thr_ok[None], child_gains(rev_lg, rev_lh, rev_lc,
                                                       rev_rg, rev_rh, rev_rc), NEG_INF)

    # default direction: rev sends missing left, except the 2-bin NaN fix
    # (feature_histogram.hpp:96-98) where missing is the last bin on the right.
    rev_default_left = ~(~full_mode & (missing_code == 2))          # [F]

    feature_gate = jnp.where(feature_ok[None, :, None], 0.0, NEG_INF)
    parent_gain_shift = (leaf_split_gain(parent_g, parent_h, lambda_l1, lambda_l2)
                         + min_gain_to_split)[:, None, None]
    rev_gain = rev_gain + feature_gate
    fwd_gain = fwd_gain + feature_gate
    rev_gain = jnp.where(rev_gain > parent_gain_shift, rev_gain - parent_gain_shift, NEG_INF)
    fwd_gain = jnp.where(fwd_gain > parent_gain_shift, fwd_gain - parent_gain_shift, NEG_INF)

    # --- per feature: pick best over (dir, threshold); rev first to mirror the
    # reference's dir=-1-then-dir=+1 strict-improvement ordering (:89-93)
    dir_gain = jnp.stack([rev_gain, fwd_gain], axis=2)              # [S, F, 2, B]
    flat = dir_gain.reshape(S, F, 2 * B)
    best_idx = jnp.argmax(flat, axis=2)                             # [S, F]
    best_gain = jnp.take_along_axis(flat, best_idx[..., None], axis=2)[..., 0]
    is_rev = best_idx < B
    t_idx = (best_idx % B).astype(jnp.int32)

    def pick(rev_arr, fwd_arr):  # [S, F, B] -> [S, F] at t_idx per direction
        r = jnp.take_along_axis(rev_arr, t_idx[..., None], axis=2)[..., 0]
        f = jnp.take_along_axis(fwd_arr, t_idx[..., None], axis=2)[..., 0]
        return jnp.where(is_rev, r, f)

    return PerFeatureBest(
        gain=best_gain,
        threshold=t_idx,
        default_left=jnp.where(is_rev, rev_default_left[None, :], False),
        left_g=pick(rev_lg, fwd_lg),
        left_h=pick(rev_lh, fwd_lh),
        left_c=pick(rev_lc, fwd_lc),
    )


def unpack_bundled_hist(hist_g: jnp.ndarray, col: jnp.ndarray,
                        unpack_bin: jnp.ndarray,
                        pg: jnp.ndarray, ph: jnp.ndarray, pc: jnp.ndarray,
                        default_bin: jnp.ndarray) -> jnp.ndarray:
    """EFB unpack: [T, G, Bb, 3] bundle-space histograms -> [T, F, B, 3]
    original-feature space, reconstructing each feature's default bin by
    subtraction from the leaf totals (reference Dataset::FixHistogram,
    dataset.cpp:750-769 — applied per scanned feature there too).

    This is the LEGACY scan representation (``tpu_efb_unpack=true``, the
    A/B + parity arm): the default path never materializes the [T, F, B]
    decode — :func:`per_feature_best_bundled` scans the bundle-space
    histogram directly."""
    ub = unpack_bin                                  # [F, B]
    h = hist_g[:, col]                               # [T, F, Bb, 3]
    idx = jnp.maximum(ub, 0)[None, :, :, None]
    hf = jnp.take_along_axis(h, idx, axis=2)         # [T, F, B, 3]
    hf = jnp.where((ub >= 0)[None, :, :, None], hf, 0.0)
    totals = jnp.stack([pg, ph, pc], axis=-1)        # [T, 3]
    deficit = totals[:, None, :] - hf.sum(axis=2)    # [T, F, 3]
    F = ub.shape[0]
    return hf.at[:, jnp.arange(F), default_bin, :].add(deficit)


_BIG_T = 2 ** 30                # threshold sentinel for the min-scatter
                                # (plain int: jnp casts lazily at trace time
                                # — no import-time backend init, R006)


def per_feature_best_bundled(
    hist: jnp.ndarray,        # [T, G, Bb, 3] BUNDLE-space (sum_g, sum_h, cnt)
    parent_g: jnp.ndarray,    # [T]
    parent_h: jnp.ndarray,    # [T]
    parent_c: jnp.ndarray,    # [T]
    num_bins: jnp.ndarray,    # [F] i32 (ORIGINAL feature space)
    missing_code: jnp.ndarray,  # [F] i32: 0=none, 1=zero, 2=nan
    default_bin: jnp.ndarray,   # [F] i32
    feature_ok: jnp.ndarray,    # [F] bool (numerical & feature_fraction mask)
    col: jnp.ndarray,         # [F] i32 bundled column of feature f
    lo: jnp.ndarray,          # [F] i32 first bundle code of f's range
    hi: jnp.ndarray,          # [F] i32 one-past-last bundle code
    off: jnp.ndarray,         # [F] i32 orig_bin = code - off inside [lo, hi)
    code_feat: jnp.ndarray,   # [G, Bb] i32 owner feature of each bundle
                              # code; -1 = unowned (code 0 / padding / the
                              # default-bin hole at off+db)
    *,
    lambda_l1: float,
    lambda_l2: float,
    min_data_in_leaf: float,
    min_sum_hessian_in_leaf: float,
    min_gain_to_split: float,
) -> PerFeatureBest:
    """Best numerical threshold per (slot, feature) WITHOUT leaving bundle
    space — the TPU analog of the reference finding splits on FeatureGroup
    bins natively (feature_histogram.hpp over the group-encoded histogram;
    it never unpacks a bundle either, src/io/dataset.cpp:750-769 only
    reconstructs the shared default bin by subtraction).

    The cumulative gain scan runs over the [G, Bb] bundle axis — G*Bb
    positions instead of the F*B the unpack path pays — and respects member
    boundaries through the BundlePlan lo/hi tables:

    - each owned code c of column g belongs to exactly one member feature
      ``code_feat[g, c]`` with original bin ``c - off[f]`` (EFB codes are
      monotone in the original bin, efb.py), so a per-column cumulative sum
      minus the member's base ``CC[lo-1]`` is the member's own prefix sum;
    - the shared default bin has no code: its mass is reconstructed per
      member as ``parent - (CC_raw[hi-1] - CC_raw[lo-1])`` (FixHistogram by
      subtraction, exactly what the unpack path's deficit computes) and
      spliced into every prefix at ``t >= default_bin``;
    - the default-bin THRESHOLD (t == db, which has no code position when
      the member's bin 0 is the default) is evaluated in a [T, F] side
      channel and merged with the per-code candidates.

    Tie-break order is pinned to the feature-space scan's flat argmax:
    within a feature, rev-direction candidates beat fwd on equal gain and
    the LOWEST threshold wins within a direction; across features the
    caller's `reduce_features` argmax keeps lowest-feature-index wins.
    Bit-identity with the unpack arm holds whenever the histogram sums are
    exactly representable (tests plant dyadic gradients for the pinned
    axes); on arbitrary float data the two arms differ only in summation
    order inside the cumulative sums.
    """
    T, G, Bb, _ = hist.shape
    F = num_bins.shape[0]
    iota_b = jnp.arange(Bb, dtype=jnp.int32)[None, :]              # [1, Bb]
    owned = code_feat >= 0
    cfs = jnp.where(owned, code_feat, 0)                           # safe idx
    # per-code owner metadata (gathers of [F] tables — G*Bb elements)
    nb_c = num_bins[cfs]
    mc_c = missing_code[cfs]
    db_c = default_bin[cfs]
    t_c = iota_b - off[cfs]                                        # orig bin
    full_c = (nb_c > 2) & (mc_c != 0)
    # codes excluded from directional accumulation (mirrors the
    # feature-space `excl_full`): the nan bin in full mode; the zero bin
    # never has a code (the owner rule drops c == off+db), so its clause
    # is vacuous here but kept for symmetry with the unpack path
    excl_c = full_c & (((mc_c == 2) & (t_c == nb_c - 1))
                       | ((mc_c == 1) & (t_c == db_c)))
    inc_c = (owned & ~excl_c).astype(hist.dtype)
    raw_c = owned.astype(hist.dtype)
    # two code-axis cumulative sums: scan-included mass (drives the
    # threshold prefix sums) and raw owned mass (drives FixHistogram's
    # deficit — the unpack path sums ALL unpacked bins incl. the nan bin)
    CCs = jnp.cumsum(hist * inc_c[None, :, :, None], axis=2)
    CCu = jnp.cumsum(hist * raw_c[None, :, :, None], axis=2)
    flatS = CCs.reshape(T, G * Bb, 3)
    flatU = CCu.reshape(T, G * Bb, 3)

    def at_pos(flat, cpos):
        """CC value at per-feature column position [F] -> [T, F, 3];
        positions < 0 read as zero mass (a member starting at code 0)."""
        idx = col * Bb + jnp.clip(cpos, 0, Bb - 1)
        v = jnp.take(flat, idx, axis=1)
        return jnp.where((cpos >= 0)[None, :, None], v, 0.0)

    base_s = at_pos(flatS, lo - 1)                                 # [T, F, 3]
    base_u = at_pos(flatU, lo - 1)
    member_u = at_pos(flatU, hi - 1) - base_u      # raw non-default mass
    fullF = (num_bins > 2) & (missing_code != 0)
    # deficit included in the accumulating scan unless the zero bin is
    # excluded in full mode (skip_default_bin's accumulation half)
    dincF = ~(fullF & (missing_code == 1))
    totals = jnp.stack([parent_g, parent_h, parent_c], axis=-1)[:, None, :]
    deficit = totals - member_u                                    # [T, F, 3]
    def_inc = jnp.where(dincF[None, :, None], deficit, 0.0)
    tot_f = (at_pos(flatS, hi - 1) - base_s) + def_inc             # [T, F, 3]

    def per_code(fv):
        """Broadcast a [T, F, ...] per-feature value to code positions."""
        return jnp.take(fv, cfs.reshape(-1), axis=1).reshape(
            (T, G, Bb) + fv.shape[2:])

    # prefix sum at threshold t_c for the owning member: column cumsum
    # minus the member base, plus the reconstructed default-bin mass once
    # the prefix crosses it
    cum_c = (CCs - per_code(base_s)
             + jnp.where((t_c >= db_c)[None, :, :, None],
                         per_code(def_inc), 0.0))
    tot_c = per_code(tot_f)
    pg = parent_g[:, None, None]
    ph = parent_h[:, None, None]
    pc = parent_c[:, None, None]

    def child_gains(lg, lh, lc, rg, rh, rc):
        ok = ((lc >= min_data_in_leaf) & (rc >= min_data_in_leaf)
              & (lh >= min_sum_hessian_in_leaf)
              & (rh >= min_sum_hessian_in_leaf))
        gains = (leaf_split_gain(lg, lh, lambda_l1, lambda_l2)
                 + leaf_split_gain(rg, rh, lambda_l1, lambda_l2))
        return jnp.where(ok, gains, NEG_INF)

    lg_c, lh_c, lc_c = cum_c[..., 0], cum_c[..., 1], cum_c[..., 2]
    # --- forward (dir=+1): left = included bins <= t, missing -> right.
    # t == db never appears at an owned code, so skip_default_bin's
    # threshold half is structural here; the side channel re-checks it.
    fwd_ok_c = owned & full_c & (t_c <= nb_c - 2)
    fwd_gain_c = jnp.where(
        fwd_ok_c[None], child_gains(lg_c, lh_c, lc_c,
                                    pg - lg_c, ph - lh_c, pc - lc_c),
        NEG_INF)
    # --- reverse (dir=-1): right = included bins > t, missing -> left
    rev_r = tot_c - cum_c
    rg_c, rh_c, rc_c = rev_r[..., 0], rev_r[..., 1], rev_r[..., 2]
    rev_max_c = jnp.where(full_c & (mc_c == 2), nb_c - 3, nb_c - 2)
    rev_ok_c = (owned & (t_c <= rev_max_c) & (t_c >= 0)
                & ~(full_c & (mc_c == 1) & (t_c == db_c - 1)))
    rev_gain_c = jnp.where(
        rev_ok_c[None], child_gains(pg - rg_c, ph - rh_c, pc - rc_c,
                                    rg_c, rh_c, rc_c),
        NEG_INF)

    # --- per-feature reduction over the code grid: max gain, then the
    # LOWEST threshold achieving it (the flat-argmax first-occurrence rule)
    idxF = jnp.where(owned, code_feat, F).reshape(-1)              # [G*Bb]
    tflat = t_c.reshape(-1)

    def seg_best(gain_c):
        gflat = gain_c.reshape(T, G * Bb)
        mg = jnp.full((T, F + 1), NEG_INF, jnp.float32) \
            .at[:, idxF].max(gflat)[:, :F]
        back = jnp.take(mg, cfs.reshape(-1), axis=1)               # [T, G*Bb]
        tcand = jnp.where((gflat == back) & jnp.isfinite(gflat),
                          tflat[None, :], _BIG_T)
        bt = jnp.full((T, F + 1), _BIG_T, jnp.int32) \
            .at[:, idxF].min(tcand)[:, :F]
        return mg, bt

    # --- default-bin threshold side channel ([T, F]): t == db has no code
    # when the member's bin 0 is its default (EFB's shift), and is the
    # zero-mass hole otherwise — evaluate it directly from the same CC
    # gathers so its floats match the grid's construction
    dbF = default_bin
    cum_db = (at_pos(flatS, off + dbF) - base_s) + def_inc
    lgd, lhd, lcd = cum_db[..., 0], cum_db[..., 1], cum_db[..., 2]
    pgF, phF, pcF = (parent_g[:, None], parent_h[:, None], parent_c[:, None])
    fwd_db_ok = fullF & (dbF <= num_bins - 2) & (missing_code != 1)
    fwd_db_gain = jnp.where(
        fwd_db_ok[None], child_gains(lgd, lhd, lcd,
                                     pgF - lgd, phF - lhd, pcF - lcd),
        NEG_INF)
    rev_maxF = jnp.where(fullF & (missing_code == 2),
                         num_bins - 3, num_bins - 2)
    rev_db_ok = (dbF <= rev_maxF) & (dbF >= 0)
    rev_rd = tot_f - cum_db
    rgd, rhd, rcd = rev_rd[..., 0], rev_rd[..., 1], rev_rd[..., 2]
    rev_db_gain = jnp.where(
        rev_db_ok[None], child_gains(pgF - rgd, phF - rhd, pcF - rcd,
                                     rgd, rhd, rcd),
        NEG_INF)

    def combine(mg_bt, gdb):
        mg, bt = mg_bt
        use_db = (gdb > mg) | ((gdb == mg) & jnp.isfinite(gdb)
                               & (dbF[None, :] < bt))
        return (jnp.where(use_db, gdb, mg),
                jnp.where(use_db, dbF[None, :], bt))

    rev_g, rev_t = combine(seg_best(rev_gain_c), rev_db_gain)
    fwd_g, fwd_t = combine(seg_best(fwd_gain_c), fwd_db_gain)
    # rev first on ties — the feature-space [rev..., fwd...] flat argmax
    use_rev = rev_g >= fwd_g
    best_g = jnp.where(use_rev, rev_g, fwd_g)
    best_t = jnp.where(use_rev, rev_t, fwd_t).astype(jnp.int32)
    best_t = jnp.where(jnp.isfinite(best_g), best_t, 0)  # argmax's idx-0 rule

    # --- winner left sums, rebuilt from the SAME CC gathers the gains used
    p_win = off[None, :] + best_t                                  # [T, F]
    idx_win = col[None, :] * Bb + jnp.clip(p_win, 0, Bb - 1)
    cw = jnp.take_along_axis(
        flatS, jnp.broadcast_to(idx_win[:, :, None], (T, F, 3)), axis=1)
    cw = jnp.where((p_win >= 0)[..., None], cw, 0.0)
    cum_w = (cw - base_s) + jnp.where((best_t >= dbF[None, :])[..., None],
                                      def_inc, 0.0)
    rev_l = totals - (tot_f - cum_w)       # pg - rev_rg, the rev pick() path
    left = jnp.where(use_rev[..., None], rev_l, cum_w)

    feature_gate = jnp.where(feature_ok, 0.0, NEG_INF)[None, :]
    parent_gain_shift = (leaf_split_gain(parent_g, parent_h,
                                         lambda_l1, lambda_l2)
                         + min_gain_to_split)[:, None]
    best_g = best_g + feature_gate
    best_g = jnp.where(best_g > parent_gain_shift,
                       best_g - parent_gain_shift, NEG_INF)
    rev_dl = ~(~fullF & (missing_code == 2))
    return PerFeatureBest(
        gain=best_g,
        threshold=best_t,
        default_left=jnp.where(use_rev, rev_dl[None, :], False),
        left_g=left[..., 0],
        left_h=left[..., 1],
        left_c=left[..., 2],
    )


def reduce_features(pf: PerFeatureBest, feature_offset=0, is_cat=None,
                    cat_mask=None, num_bins_padded: int = 0) -> SplitCandidates:
    """Argmax over the feature axis -> one candidate per slot.

    ``feature_offset`` maps local feature indices to global ones when the
    caller holds only a feature shard (parallel/comm.py feature-parallel
    learner; reference feature_parallel_tree_learner.cpp:31-50).
    ``is_cat`` [F] / ``cat_mask`` [S, F, B] carry categorical left-sets
    (ops/categorical.py) through to the winner.
    """
    S, F = pf.gain.shape
    f_idx = jnp.argmax(pf.gain, axis=1)                             # [S]
    srange = jnp.arange(S)

    def gather(arr):
        return arr[srange, f_idx]

    if is_cat is None:
        B = num_bins_padded or 1
        win_cat = jnp.zeros(S, bool)
        win_mask = jnp.zeros((S, B), bool)
    else:
        win_cat = is_cat[f_idx]
        win_mask = cat_mask[srange, f_idx]                          # [S, B]

    return SplitCandidates(
        gain=gather(pf.gain),
        feature=(f_idx + feature_offset).astype(jnp.int32),
        threshold=gather(pf.threshold).astype(jnp.int32),
        default_left=gather(pf.default_left),
        left_g=gather(pf.left_g),
        left_h=gather(pf.left_h),
        left_c=gather(pf.left_c),
        is_cat=win_cat,
        cat_mask=win_mask,
    )


def find_best_splits_numerical(
    hist, parent_g, parent_h, parent_c, num_bins, missing_code, default_bin,
    feature_ok, **kwargs,
) -> SplitCandidates:
    """Single-shard numerical-only best split per slot (test/bench path)."""
    pf = per_feature_best_numerical(
        hist, parent_g, parent_h, parent_c, num_bins, missing_code,
        default_bin, feature_ok, **kwargs)
    return reduce_features(pf, num_bins_padded=hist.shape[2])
