"""Piecewise-linear leaves: per-leaf ridge solves inside the training step.

The `linear_tree=true` workload of PAPERS.md "Gradient Boosting With
Piece-Wise Linear Regression Trees" (arXiv 1802.05640): after a tree's
structure is grown, every leaf fits a linear model over the numerical
features on its root-to-leaf PATH (bounded by ``linear_max_features``)
instead of a single constant. The fit minimizes the same second-order
objective the constant leaf does,

    sum_r [ g_r * f(x_r) + 1/2 h_r * f(x_r)^2 ] + 1/2 lambda |beta|^2

whose normal equations are ``(X^T H X + lambda I) beta = -X^T g`` with
``X = [1, x_f1, .., x_fK]`` over the leaf's rows — so the per-leaf
Gram/moment matrices accumulate with EXACTLY the histogram build's
chunked segment-sum shape (ops/histogram.py: a one-hot leaf matmul over
row chunks), and all leaves solve at once with one batched Cholesky.
Everything here is traced inside the training step (boosting/gbdt.py
``step_body``): zero extra dispatches, zero host syncs, 0 recompiles in
steady state, and ``tree_batch`` fusion keeps working because the fit is
ordinary traced math.

Reference semantics (later-LightGBM ``linear_tree``,
src/treelearner/linear_tree_learner.cpp CalculateLinear):

- rows with a missing value (NaN) in ANY of the leaf's features are
  excluded from the normal equations and predict through the leaf's
  CONSTANT output (``leaf_value``) — zeros stay numeric;
- a leaf degrades LOUDLY to its constant output when a categorical split
  sits on its path, when it has no numerical path features, when fewer
  (included, non-missing) rows than coefficients remain, or when the
  Cholesky factorization is not finite (ill-conditioned Gram) — the
  degraded leaf serializes with an empty feature list, never silently
  wrong coefficients;
- shrinkage scales the intercept and every coefficient exactly like the
  constant leaf value (Tree::Shrinkage).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..analysis.contracts.registry import trace_entry
from .histogram import table_lookup


def linear_chunk_rows(chunk_rows: int, cap: int = 8192) -> int:
    """Row-chunk length of the moment accumulation: the largest divisor of
    the histogram chunk that is <= ``cap``, so every padded row count the
    wave loop accepts (a chunk multiple) also divides the linear pass.
    The [R, K, F] one-hot gather intermediate scales with the chunk, so
    the linear leg runs smaller chunks than the histogram matmul."""
    c = min(chunk_rows, cap)
    while chunk_rows % c:
        c -= 1
    return max(c, 1)


def leaf_path_features(tree, is_cat: jnp.ndarray, max_features: int,
                       max_steps: int
                       ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Per-leaf path features from the device TreeArrays.

    Walks each leaf toward the root (``max_steps`` bounds the depth),
    collecting the first ``max_features`` DISTINCT numerical split
    features in leaf-to-root order — the nearest splits are the most
    leaf-relevant, matching the reference's path-feature collection.

    Returns ``(leaf_feat [L+1, K] i32, -1-padded; has_cat [L+1] bool;
    nfeat [L+1] i32)``. A categorical split anywhere on the path flags
    ``has_cat`` — the solve degrades that leaf to its constant output.
    """
    L1 = tree.leaf_value.shape[0]                    # L + 1 (scratch row)
    M1 = tree.left_child.shape[0]                    # M + 1
    K = max_features
    iota_k = jnp.arange(K, dtype=jnp.int32)[None, :]

    # parent of each internal node, by scattering the child links
    # (leaf_parent only covers leaves); children < 0 encode leaves ~c
    node_iota = jnp.arange(M1, dtype=jnp.int32)
    node_parent = jnp.full(M1, -1, jnp.int32)
    lc, rc = tree.left_child, tree.right_child
    node_parent = node_parent.at[
        jnp.where(lc >= 0, lc, M1)].set(node_iota, mode="drop")
    node_parent = node_parent.at[
        jnp.where(rc >= 0, rc, M1)].set(node_iota, mode="drop")

    sf = tree.split_feature
    node_is_cat = tree.is_cat | is_cat[jnp.clip(sf, 0, is_cat.shape[0] - 1)]

    def body(_i, carry):
        node, feats, nfeat, has_cat = carry
        valid = node >= 0
        nid = jnp.maximum(node, 0)
        f = sf[nid]
        c = node_is_cat[nid]
        has_cat = has_cat | (valid & c)
        seen = jnp.any(feats == f[:, None], axis=1)
        add = valid & ~c & ~seen & (nfeat < K)
        feats = jnp.where(add[:, None] & (iota_k == nfeat[:, None]),
                          f[:, None], feats)
        nfeat = nfeat + add.astype(jnp.int32)
        node = jnp.where(valid, node_parent[nid], -1)
        return node, feats, nfeat, has_cat

    node0 = tree.leaf_parent[:L1]
    feats0 = jnp.full((L1, K), -1, jnp.int32)
    nfeat0 = jnp.zeros(L1, jnp.int32)
    has_cat0 = jnp.zeros(L1, bool)
    _, feats, nfeat, has_cat = jax.lax.fori_loop(
        0, max_steps, body, (node0, feats0, nfeat0, has_cat0))
    return feats, has_cat, nfeat


def _gather_leaf_values(Xraw: jnp.ndarray, Xmiss: jnp.ndarray,
                        feats: jnp.ndarray
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Raw value + missing flag of each row's K leaf features.

    ``feats`` is [R, K] (-1 = unused slot). The gather is the grower's
    one-hot multiply-sum idiom over the F lanes (_route_rows) — a fused
    VPU stream, no per-row table gather; ``Xraw`` is NaN-sanitized at
    placement (boosting/gbdt.py) so 0 * sanitized value never poisons
    the sum, and missingness rides the separate ``Xmiss`` plane. Unused
    slots (-1) match no lane: value 0, not missing.
    """
    iota_f = jnp.arange(Xraw.shape[1], dtype=jnp.int32)[None, None, :]
    onehot = (feats[:, :, None] == iota_f)                    # [R, K, F]
    vals = jnp.sum(jnp.where(onehot, Xraw[:, None, :], 0.0), axis=2)
    miss = jnp.any(onehot & Xmiss[:, None, :], axis=2)
    return vals, miss


@trace_entry("linear.moments")
def accumulate_leaf_moments(Xraw, Xmiss, leaf_id, leaf_feat, g, h, included,
                            chunk_rows: int):
    """Per-leaf normal-equation moments, chunked like the histogram build.

    Returns ``(XTHX [L+1, K+1, K+1], XTg [L+1, K+1], cnt [L+1])`` where
    the design row is ``z = [1, x_f1 .. x_fK]`` and rows with a missing
    value in any leaf feature (or excluded by the bagging/padding mask)
    contribute nothing. One ``[R, L+1] x [R, C]`` one-hot contraction per
    chunk — the same segmented-reduction shape as ops/histogram.py — at
    Precision.HIGHEST (exact products; the one-hot side is 0/1).
    """
    N = Xraw.shape[0]
    L1, K = leaf_feat.shape
    K1 = K + 1
    assert N % chunk_rows == 0, (N, chunk_rows)
    n_chunks = N // chunk_rows
    leaf_iota = jnp.arange(L1, dtype=jnp.int32)[None, :]

    def chunk_part(i):
        sl = jax.lax.dynamic_slice_in_dim
        lo = i * chunk_rows
        lid = sl(leaf_id, lo, chunk_rows)
        xr = sl(Xraw, lo, chunk_rows)
        xm = sl(Xmiss, lo, chunk_rows)
        gc = sl(g, lo, chunk_rows)
        hc = sl(h, lo, chunk_rows)
        mc = sl(included, lo, chunk_rows)
        feats = table_lookup(lid, leaf_feat)                   # [R, K]
        vals, miss = _gather_leaf_values(xr, xm, feats)        # [R, K]
        w = mc * (~jnp.any(miss, axis=1)).astype(jnp.float32)  # [R]
        z = jnp.concatenate(
            [jnp.ones((chunk_rows, 1), jnp.float32), vals], axis=1)
        outer = (z[:, :, None] * z[:, None, :]).reshape(chunk_rows, K1 * K1)
        ch = jnp.concatenate(
            [outer * (hc * w)[:, None], z * (gc * w)[:, None], w[:, None]],
            axis=1)                                            # [R, C]
        onehot = (lid[:, None] == leaf_iota).astype(jnp.float32)
        return jax.lax.dot_general(
            onehot, ch, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)               # [L+1, C]

    acc0 = jnp.zeros((L1, K1 * K1 + K1 + 1), jnp.float32)
    acc, _ = jax.lax.scan(lambda a, i: (a + chunk_part(i), ()), acc0,
                          jnp.arange(n_chunks))
    XTHX = acc[:, : K1 * K1].reshape(L1, K1, K1)
    XTg = acc[:, K1 * K1: K1 * K1 + K1]
    cnt = acc[:, -1]
    return XTHX, XTg, cnt


def solve_leaf_models(XTHX, XTg, leaf_feat, nfeat, has_cat, cnt,
                      linear_lambda: float):
    """Batched ridge solve: ``(XTHX + lambda I) beta = -XTg`` for every
    leaf at once via one vmapped Cholesky (bit-reproducible — no pivoting,
    fixed operation order).

    Unused design dims (slot padding beyond ``nfeat``) carry an identity
    diagonal so the factorization stays well-posed; the ridge term applies
    to coefficient dims only, never the intercept. A leaf is LINEAR iff it
    has >= 1 numerical path feature, no categorical split on the path, at
    least ``nfeat + 2`` fitted rows, and a finite solve — everything else
    degrades to the constant leaf (empty feature list, zero coefficients).

    Returns ``(leaf_const [L+1] f32, leaf_coeff [L+1, K] f32,
    leaf_feat' [L+1, K] i32, n_degraded i32)``.
    """
    L1, K1, _ = XTHX.shape
    K = K1 - 1
    iota1 = jnp.arange(K1, dtype=jnp.int32)[None, :]
    # dim 0 = intercept; dims 1..K real iff slot < nfeat
    dim_real = iota1 <= nfeat[:, None]                        # [L+1, K1]
    diag_add = jnp.where(
        dim_real, jnp.where(iota1 > 0, jnp.float32(linear_lambda), 0.0),
        1.0)
    A = XTHX + jax.vmap(jnp.diag)(diag_add)
    # zero any stray mass in padded rows/cols (identity block must be pure)
    pad2 = (~dim_real)[:, :, None] | (~dim_real)[:, None, :]
    A = jnp.where(pad2 & ~jax.vmap(jnp.diag)(jnp.ones((L1, K1), bool)),
                  0.0, A)
    b = -XTg * dim_real.astype(jnp.float32)
    chol = jnp.linalg.cholesky(A)
    y = jax.lax.linalg.triangular_solve(
        chol, b[:, :, None], left_side=True, lower=True)
    beta = jax.lax.linalg.triangular_solve(
        chol, y, left_side=True, lower=True, transpose_a=True)[:, :, 0]
    solvable = jnp.all(jnp.isfinite(beta), axis=1) \
        & jnp.all(jnp.isfinite(chol[:, jnp.arange(K1), jnp.arange(K1)]),
                  axis=1)
    fittable = (nfeat > 0) & ~has_cat
    ok = fittable & solvable & (cnt >= (nfeat + 2).astype(jnp.float32))
    leaf_const = jnp.where(ok, beta[:, 0], 0.0).astype(jnp.float32)
    leaf_coeff = jnp.where(ok[:, None] & dim_real[:, 1:], beta[:, 1:],
                           0.0).astype(jnp.float32)
    leaf_feat_out = jnp.where(ok[:, None], leaf_feat, -1)
    n_degraded = jnp.sum((fittable & ~ok).astype(jnp.int32))
    return leaf_const, leaf_coeff, leaf_feat_out, n_degraded


@trace_entry("linear.fit_leg")
def fit_linear_leaves(tree, Xraw, Xmiss, leaf_id, g, h, included, is_cat,
                      *, max_features: int, linear_lambda: float,
                      chunk_rows: int, max_steps: int):
    """The whole fit: path features -> chunked moments -> batched Cholesky.

    Traced inside the training step right after ``grow_tree`` (before
    shrinkage, so the coefficients scale with the constant exactly like
    the reference's Tree::Shrinkage). Returns the tree with
    ``leaf_feat``/``leaf_coeff``/``leaf_const`` populated; degraded
    leaves keep an empty feature list and serve their constant output.
    """
    leaf_feat, has_cat, nfeat = leaf_path_features(
        tree, is_cat, max_features, max_steps)
    lin_chunk = linear_chunk_rows(chunk_rows)
    XTHX, XTg, cnt = accumulate_leaf_moments(
        Xraw, Xmiss, leaf_id, leaf_feat, g, h, included, lin_chunk)
    leaf_const, leaf_coeff, leaf_feat, _n_deg = solve_leaf_models(
        XTHX, XTg, leaf_feat, nfeat, has_cat, cnt, linear_lambda)
    # scratch row (leaf L) stays inert: table_lookup reads every table row
    # with weight 0 and 0 * garbage must stay 0
    L = tree.leaf_value.shape[0] - 1
    leaf_const = leaf_const.at[L].set(0.0)
    leaf_coeff = leaf_coeff.at[L].set(0.0)
    leaf_feat = leaf_feat.at[L].set(-1)
    return tree._replace(leaf_feat=leaf_feat, leaf_coeff=leaf_coeff,
                         leaf_const=leaf_const)


def linear_leaf_scores(tree, leaf_id, Xraw, Xmiss) -> jnp.ndarray:
    """Per-row leaf OUTPUT of a linear tree (f32, device) — the score-update
    epilogue shared by the train rows and every valid set: rows in a linear
    leaf with all features present get ``const + sum_k coeff_k * x_k``,
    everything else (constant leaf, degraded leaf, missing feature) the
    constant ``leaf_value`` — the reference's NaN fallback.
    """
    K = tree.leaf_feat.shape[1]
    packed = table_lookup(
        leaf_id,
        jnp.concatenate([tree.leaf_value[:, None], tree.leaf_const[:, None],
                         tree.leaf_coeff], axis=1))            # [N, 2+K]
    feats = table_lookup(leaf_id, tree.leaf_feat)              # [N, K]
    vals, miss = _gather_leaf_values(Xraw, Xmiss, feats)
    lin = (feats[:, 0] >= 0) & ~jnp.any(miss, axis=1)
    acc = packed[:, 1] + jnp.sum(packed[:, 2:] * vals, axis=1)
    return jnp.where(lin, acc, packed[:, 0])


def linear_cost_report(n_rows: int, num_features: int, num_leaves: int,
                       max_features: int, chunk_rows: int,
                       site: Optional[str] = None) -> dict:
    """Compile-time cost probe of the standalone linear-fit leg at one
    shape class (the twin of histogram.histogram_cost_report): lower +
    compile a jitted moment-accumulation + solve on zero inputs and
    publish FLOPs/bytes/HBM as ``cost.<site>.*`` — the solve leg's entry
    in the cost-capture site list so the drift gate covers it. In
    production the fit is fused into the train step; its isolated cost is
    only observable here. Explicit call = intent (ignores the
    ``costs.enabled()`` gate)."""
    from ..observability import costs as obs_costs
    lin_chunk = linear_chunk_rows(chunk_rows)
    n_rows = ((n_rows + lin_chunk - 1) // lin_chunk) * lin_chunk
    L1 = num_leaves + 1
    Xraw = jnp.zeros((n_rows, num_features), jnp.float32)
    Xmiss = jnp.zeros((n_rows, num_features), bool)
    lid = jnp.zeros(n_rows, jnp.int32)
    leaf_feat = jnp.full((L1, max_features), -1, jnp.int32)
    zf = jnp.zeros(n_rows, jnp.float32)
    nfeat = jnp.zeros(L1, jnp.int32)
    has_cat = jnp.zeros(L1, bool)

    def run(Xraw, Xmiss, lid, leaf_feat, g, h, inc, nfeat, has_cat):
        XTHX, XTg, cnt = accumulate_leaf_moments(
            Xraw, Xmiss, lid, leaf_feat, g, h, inc, lin_chunk)
        return solve_leaf_models(XTHX, XTg, leaf_feat, nfeat, has_cat, cnt,
                                 0.0)[:3]

    site = site or f"linear.fit.k{max_features}"
    dims = dict(rows=int(n_rows), features=int(num_features),
                num_leaves=int(num_leaves),
                max_features=int(max_features), chunk_rows=int(lin_chunk))
    try:
        compiled = jax.jit(run).lower(Xraw, Xmiss, lid, leaf_feat, zf, zf,
                                      zf, nfeat, has_cat).compile()
        rep = obs_costs.report_from_compiled(compiled, site, dims)
    except Exception as e:                                   # noqa: BLE001
        rep = dict(dims, site=site, error=f"{type(e).__name__}: {e}"[:300])
    obs_costs.publish(rep)
    return rep
