"""Device-side dataset ingest: on-device binning, in-trace code packing,
double-buffered H2D chunk feeding (``tpu_ingest=device|auto``).

Host dataset construction binned every column serially through
``BinMapper.value_to_bin`` (binning.py) and materialized the full
``X_binned`` matrix before a single tree trained — at the 10.5M-row HIGGS
scale that is a fixed multi-second tax invisible to every training bench.
This module moves the bin application onto the accelerator, following the
quantile-sketch + feature-packing design of "XGBoost: Scalable GPU
Accelerated Learning" (arXiv 1806.11248) and the overlapped out-of-core
ingest discipline of "Out-of-Core GPU Gradient Boosting" (arXiv
2005.09148): raw f32 row chunks stream H2D under the previous chunk's
bin+pack compute, and the packed code layout lands directly in the device
residency buffers — host ``X_binned`` is never built.

Bit-exactness contract (pinned in tests/test_ingest.py): the device path
reproduces ``BinMapper.value_to_bin`` EXACTLY, not approximately.

- Numerical. The host oracle computes, over f64 bounds ``ub``,
  ``bin = searchsorted(ub[:r+1], v, side="left")`` capped at ``r``
  (``r = num_bin-1``, minus one more under MISSING_NAN), i.e.
  ``bin = sum_k [ub_k < v]`` over the first ``r`` bounds (the cap is
  redundant: the trailing bound never compares below a finite value).
  The device works in f32 (R003: no f64 on device) over per-feature
  threshold rows ``t_k`` = the LARGEST f32 <= ``ub_k`` (round-to-nearest
  then a conditional ``nextafter`` step down). For any f32 value ``v``:
  ``t_k < v  =>  v >= nextafter(t_k, +inf) > ub_k``  and
  ``ub_k < v  =>  t_k <= ub_k < v`` — so ``[t_k < v] == [ub_k < v]``
  exactly, and ``bin = sum_k [v > t_k]`` matches the host bin for every
  f32 input, including ±inf, -0.0 and exact-tie values. The kernel
  computes that count with a BRANCHLESS POWER-OF-TWO lower bound (Shar's
  search: threshold rows are padded with +inf to ``Tp = 2^k``; each of
  the k unrolled steps gathers one pivot and conditionally advances the
  base by ``Tp >> step``) — ``O(log B)`` per value like the host's
  ``searchsorted``, fully vectorized over the chunk, and bit-equal to
  the naive compare-sum on sorted input including duplicate collapsed
  thresholds (the advance condition is strict ``<``). NaN searches as
  0.0 (the host's ``search_vals``) and is redirected to the last bin only
  under ``has_nan_bin``. Inputs must be losslessly f32-representable —
  :func:`device_ingest_blocker` gates engagement on exactly that.
- Categorical. The host truncates to int64 and dict-maps, negatives and
  unseen categories to the last bin. The device clamps to
  ``[-1, max_cat+1]`` BEFORE the f32->i32 truncating cast (same
  round-toward-zero as numpy ``astype``; the clamp keeps huge raw values
  out of int overflow — anything above the largest seen category clamps
  to an unseen value), then one-hot matches against a padded per-feature
  category table. Engagement requires every category < 2^24 (f32-exact
  integers) and a bounded per-feature category count.

Padding contract: the residency layout pads rows AND feature columns with
literal zero codes (``np.pad`` in boosting/gbdt.py), NOT with the default
bin — the jitted kernel masks rows past ``n_rows`` to 0 (the row offset is
a traced scalar, so every chunk shares ONE compiled executable per shape
class — RecompileGuard-pinned) and padded feature columns carry all-+inf
threshold rows, which bin every value to 0.

Overlap: :class:`ChunkFeeder` is the raw-chunk twin of
``ops/stream.ShardPrefetcher`` — same stall accounting (a ``get`` that
finds nothing prefetched is a counted, timed stall), same honesty knob
(``LGBM_TPU_INGEST_NO_PREFETCH=1`` forces every transfer into a measured
stall — ``bench.py --ingest``'s overlap-vs-no-overlap arm). Metrics:
``ingest.rows``, ``ingest.chunks``, ``ingest.bytes_h2d``,
``ingest.prefetch_hits``, ``ingest.stalls``, ``ingest.stall_seconds``
(histogram), under an ``ingest`` span (docs/Observability.md).

Module-level imports stay numpy-only: the eligibility helpers run inside
``dataset.construct_dataset`` before jax is ever needed; jax loads lazily
when a kernel is actually built.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..binning import BIN_CATEGORICAL, BIN_NUMERICAL, MISSING_NAN, BinMapper
from ..utils.log import Log

# f32 represents every integer in [-2^24, 2^24] exactly — categories at or
# beyond this would alias under the f32 raw-value transport
_CAT_EXACT_LIMIT = 1 << 24
# one-hot category matching is O(rows * categories) per feature; past this
# width the host dict map is the better tool
_CAT_TABLE_LIMIT = 1024
# auto-sized chunks target ~4 MiB of raw f32 per H2D transfer: big enough
# to amortize per-chunk dispatch, small enough that several chunks overlap
_CHUNK_BUDGET_BYTES = 4 << 20
_CHUNK_MIN, _CHUNK_MAX = 4096, 131072


# ------------------------------------------------------------- eligibility

def f32_lossless(data: np.ndarray, probe_stride: int = 257) -> bool:
    """True when every value survives the f64 -> f32 -> f64 round trip
    (NaN == NaN). The host oracle reads values through f64
    (``value_to_bin``'s ``asarray(..., float64)``), so f64 is the fidelity
    reference; f32 input is lossless by definition. A strided probe
    rejects most non-representable matrices without paying the full
    two-pass check."""
    if data.dtype == np.float32:
        return True
    if data.dtype != np.float64:
        return False

    def _roundtrips(x: np.ndarray) -> bool:
        return bool(np.array_equal(x.astype(np.float32).astype(np.float64),
                                   x, equal_nan=True))

    if data.shape[0] > probe_stride and not _roundtrips(data[::probe_stride]):
        return False
    return _roundtrips(data)


def device_ingest_blocker(data, mappers: Sequence[BinMapper]) -> Optional[str]:
    """Why device ingest cannot serve this input, or None when it can.
    Numpy-only: runs inside dataset construction before jax is touched."""
    if hasattr(data, "tocsc"):
        return "sparse input (device ingest bins dense raw rows)"
    if data.dtype not in (np.float32, np.float64):
        return (f"raw dtype {data.dtype} (device ingest transports raw "
                f"values as f32; pass float32/float64)")
    for m in mappers:
        if m.bin_type != BIN_CATEGORICAL:
            continue
        cats = [c for c in m.categorical_2_bin if c >= 0]
        if len(cats) > _CAT_TABLE_LIMIT:
            return (f"categorical feature with {len(cats)} categories "
                    f"(> {_CAT_TABLE_LIMIT}: one-hot table match would "
                    f"dominate the bin kernel)")
        if cats and max(cats) >= _CAT_EXACT_LIMIT:
            return (f"categorical value {max(cats)} >= 2^24 "
                    f"(not exactly representable in f32)")
    if not f32_lossless(data):
        return ("float64 values not losslessly f32-representable "
                "(device binning compares in f32)")
    return None


# ------------------------------------------------------------- bin tables

@dataclass
class IngestTables:
    """Host-built per-feature tables the jitted bin kernel closes over.
    All rows are padded to common widths; padded FEATURE columns get
    all-+inf thresholds (every value bins to 0 — the residency layout's
    zero column padding)."""
    thresholds: np.ndarray   # [C, T] f32; t_k = largest f32 <= ub_k
    nan_bin: np.ndarray      # [C] i32; num_bin-1 under has_nan_bin else -1
    is_cat: np.ndarray       # [C] bool
    cat_vals: np.ndarray     # [C, K] i32 category values (pad -2: never hit)
    cat_bins: np.ndarray     # [C, K] i32 bin of each category
    cat_last: np.ndarray     # [C] i32 last bin (negative/unseen categories)
    cat_hi: np.ndarray       # [C] f32 clamp ceiling (max category + 1)

    @property
    def has_categorical(self) -> bool:
        return bool(self.is_cat.any())


def f32_floor_thresholds(ub: np.ndarray) -> np.ndarray:
    """Largest f32 <= each f64 bound: round to nearest, then step down one
    ulp wherever rounding went UP (this is what makes the f32 compare-sum
    agree with the f64 searchsorted — module docstring proof)."""
    t = np.asarray(ub, np.float64).astype(np.float32)
    over = t.astype(np.float64) > ub
    if over.any():
        t[over] = np.nextafter(t[over], np.float32(-np.inf))
    return t


def build_ingest_tables(mappers: Sequence[BinMapper],
                        num_cols: int) -> IngestTables:
    """Pack every mapper's boundaries/categories into fixed-width arrays
    covering ``num_cols`` feature columns (>= len(mappers); the excess is
    residency column padding)."""
    C = max(int(num_cols), 1)
    th_rows: List[np.ndarray] = []
    cat_rows: List[Tuple[np.ndarray, np.ndarray]] = []
    nan_bin = np.full(C, -1, np.int32)
    is_cat = np.zeros(C, bool)
    cat_last = np.zeros(C, np.int32)
    cat_hi = np.zeros(C, np.float32)
    for j, m in enumerate(mappers):
        if m.bin_type == BIN_NUMERICAL:
            r = m.num_bin - 1 - (1 if m.missing_type == MISSING_NAN else 0)
            # the host search range is ub[:r+1], whose LAST bound (+inf, or
            # the NaN sentinel) never compares below a value — the first r
            # bounds are the whole decision surface
            th_rows.append(f32_floor_thresholds(m.bin_upper_bound[:r]))
            cat_rows.append((np.zeros(0, np.int32), np.zeros(0, np.int32)))
            if m.has_nan_bin:
                nan_bin[j] = m.num_bin - 1
        else:
            pairs = sorted((c, b) for c, b in m.categorical_2_bin.items()
                           if c >= 0)
            cat_rows.append((
                np.array([c for c, _ in pairs], np.int32),
                np.array([b for _, b in pairs], np.int32)))
            th_rows.append(np.zeros(0, np.float32))
            is_cat[j] = True
            cat_last[j] = m.num_bin - 1
            cat_hi[j] = np.float32((pairs[-1][0] + 1) if pairs else 0)
    T = max([len(r) for r in th_rows], default=0)
    K = max([len(v) for v, _ in cat_rows], default=0)
    T, K = max(T, 1), max(K, 1)
    # pad the threshold axis to a POWER OF TWO: the kernel's branchless
    # lower bound advances by halving strides, and +inf padding never
    # compares below a value, so the count of t_k < v is unchanged
    T = 1 << max(1, (T - 1).bit_length())
    thresholds = np.full((C, T), np.inf, np.float32)
    cat_vals = np.full((C, K), -2, np.int32)
    cat_bins = np.zeros((C, K), np.int32)
    for j, row in enumerate(th_rows):
        thresholds[j, :len(row)] = row
    for j, (v, b) in enumerate(cat_rows):
        cat_vals[j, :len(v)] = v
        cat_bins[j, :len(v)] = b
    return IngestTables(thresholds, nan_bin, is_cat, cat_vals, cat_bins,
                        cat_last, cat_hi)


# ------------------------------------------------------------- bin kernel

class DeviceIngestor:
    """Jit-compiled bin(+pack) over fixed-shape raw chunks.

    One instance = one shape class: ``[chunk_rows, num_cols]`` f32 in,
    ``[chunk_rows, num_cols]`` codes (or the ``code_mode`` packed byte
    layout) out. The row offset is a TRACED scalar, so every chunk of a
    dataset — including the zero-masked tail — reuses the first chunk's
    executable (``compiles`` stays 1; RecompileGuard pin in
    tests/test_ingest.py)."""

    def __init__(self, mappers: Sequence[BinMapper], *, num_cols: int,
                 n_rows: int, out_dtype, code_mode: Optional[str] = None,
                 device=None):
        import jax
        import jax.numpy as jnp
        from .histogram import _pack_codes

        tables = build_ingest_tables(mappers, num_cols)
        self.tables = tables
        self.n_rows = int(n_rows)
        self.out_dtype = np.dtype(out_dtype)
        self.code_mode = code_mode
        put = (lambda a: jax.device_put(a, device)) if device is not None \
            else jnp.asarray
        nan_bin = put(tables.nan_bin)
        has_cat = tables.has_categorical
        if has_cat:
            is_cat = put(tables.is_cat)
            cat_vals = put(tables.cat_vals)
            cat_bins = put(tables.cat_bins)
            cat_last = put(tables.cat_last)
            cat_hi = put(tables.cat_hi)
        jnp_dtype = self.out_dtype
        n_valid = jnp.int32(self.n_rows)

        Tp = int(tables.thresholds.shape[1])       # power of two
        k_steps = Tp.bit_length() - 1
        thf = put(tables.thresholds.ravel())
        col_base = put((np.arange(num_cols, dtype=np.int32) * Tp)[None, :])

        def _bin(chunk, offset):
            # chunk [R, C] f32, offset i32 = global row of chunk[0]
            nanm = jnp.isnan(chunk)
            sv = jnp.where(nanm, jnp.float32(0.0), chunk)
            # branchless power-of-two lower bound (module docstring): after
            # the k unrolled halving steps ``pos`` is the count of
            # thresholds strictly below the value — exactly
            # searchsorted(side="left") over the floored-f32 thresholds;
            # +inf padding never advances the base
            pos = jnp.zeros(chunk.shape, jnp.int32)
            for s in range(k_steps):
                half = Tp >> (s + 1)
                pivot = thf[col_base + pos + (half - 1)]
                pos = pos + jnp.where(pivot < sv, half, 0).astype(jnp.int32)
            bins = pos
            bins = jnp.where(nanm & (nan_bin[None, :] >= 0),
                             nan_bin[None, :], bins)
            if has_cat:
                vi = jnp.where(nanm, jnp.float32(-1.0),
                               jnp.clip(chunk, jnp.float32(-1.0),
                                        cat_hi[None, :]))
                vii = vi.astype(jnp.int32)       # trunc toward zero, like np
                match = vii[:, :, None] == cat_vals[None, :, :]
                cb = jnp.sum(jnp.where(match, cat_bins[None, :, :] + 1, 0),
                             axis=2) - 1          # -1 == unseen
                cb = jnp.where((cb < 0) | (vii < 0), cat_last[None, :], cb)
                bins = jnp.where(is_cat[None, :], cb, bins)
            rows = offset + jnp.arange(chunk.shape[0], dtype=jnp.int32)
            bins = jnp.where((rows < n_valid)[:, None], bins, 0)
            codes = bins.astype(jnp_dtype)
            if code_mode is not None:
                codes = _pack_codes(codes, code_mode)
            return codes

        self._fn = jax.jit(_bin)

    def bin_chunk(self, chunk, offset: int):
        """Codes (or packed bytes) for one device-resident raw chunk."""
        return self._fn(chunk, np.int32(offset))

    @property
    def compiles(self) -> Optional[int]:
        try:
            return int(self._fn._cache_size())
        except Exception:
            return None


# ------------------------------------------------------------ chunk feeder

class ChunkFeeder:
    """Double-buffered H2D feed of raw row chunks — the ingest twin of
    ``ops/stream.ShardPrefetcher`` (same stall accounting, same honesty
    knob). ``prefetch(j)`` is called right after the driver dispatches
    chunk ``i``'s bin+pack, so chunk ``j``'s copy rides under it; a
    ``get`` that finds nothing pending transfers synchronously inside a
    counted, timed stall (``ingest.stalls`` / ``ingest.stall_seconds``).
    ``LGBM_TPU_INGEST_NO_PREFETCH=1`` turns every transfer into a measured
    stall (bench.py --ingest's no-overlap arm). Chunks select the used
    feature columns, cast to f32 (exact under the losslessness gate), and
    zero-fill the tail — the kernel's row mask makes tail content
    irrelevant, zeros keep the bytes deterministic."""

    def __init__(self, raw: np.ndarray, real_indices: np.ndarray, *,
                 chunk_rows: int, n_chunks: int, num_cols: int,
                 device=None, prefetch_enabled: Optional[bool] = None,
                 depth: int = 1):
        self.raw = raw
        self.real_indices = np.asarray(real_indices, np.int64)
        self.chunk_rows = int(chunk_rows)
        self.n_chunks = int(n_chunks)
        self.num_cols = int(num_cols)
        self.device = device
        if prefetch_enabled is None:
            prefetch_enabled = os.environ.get(
                "LGBM_TPU_INGEST_NO_PREFETCH", "") not in ("1", "true")
        self.prefetch_enabled = prefetch_enabled and depth > 0
        self.depth = max(1, int(depth))
        self._pending: Dict[int, object] = {}
        self.stalls = 0
        self.hits = 0
        self.stall_seconds = 0.0
        self.bytes_h2d = 0

    def _obs(self):
        from .. import observability as obs
        return obs

    def _host_chunk(self, i: int) -> np.ndarray:
        R, C = self.chunk_rows, self.num_cols
        a = i * R
        b = min(a + R, self.raw.shape[0])
        block = np.zeros((R, C), np.float32)
        if b > a:
            sel = self.raw[a:b][:, self.real_indices]
            block[: b - a, : sel.shape[1]] = sel
        return block

    def _put(self, i: int):
        import jax
        block = self._host_chunk(i)
        self.bytes_h2d += block.nbytes
        self._obs().inc("ingest.bytes_h2d", block.nbytes)
        if self.device is not None:
            return jax.device_put(block, self.device)
        return jax.device_put(block)

    def prefetch(self, j: int) -> None:
        """Issue chunk ``j``'s H2D copy if not already pending; at most
        ``depth`` transfers stay in flight (depth 1 == double buffering —
        deeper queues pin host+device memory without hiding more
        latency)."""
        if not self.prefetch_enabled or not (0 <= j < self.n_chunks):
            return
        if j not in self._pending:
            if len(self._pending) >= self.depth + 1:   # defensive bound
                self._pending.clear()
            self._pending[j] = self._put(j)

    def get(self, i: int):
        """Device buffer of chunk ``i`` — prefetched if the overlap
        worked, a counted timed stall if not."""
        obs = self._obs()
        arr = self._pending.pop(i, None)
        if arr is not None:
            self.hits += 1
            obs.inc("ingest.prefetch_hits")
            return arr
        self.stalls += 1
        obs.inc("ingest.stalls")
        t0 = obs.clock()
        with obs.span("ingest_stall", chunk=i):
            arr = self._put(i)
            try:
                arr.block_until_ready()
            except AttributeError:
                pass
        dt = obs.clock() - t0
        self.stall_seconds += dt
        obs.get_registry().histogram("ingest.stall_seconds").observe(dt)
        return arr

    def report(self) -> Dict:
        return {"n_chunks": self.n_chunks, "chunk_rows": self.chunk_rows,
                "stalls": self.stalls, "prefetch_hits": self.hits,
                "stall_seconds": round(self.stall_seconds, 6),
                "bytes_h2d": self.bytes_h2d,
                "prefetch_enabled": self.prefetch_enabled}


# ----------------------------------------------------------------- driver

def resolve_chunk_rows(requested: int, n_rows_padded: int,
                       num_cols: int) -> int:
    """Chunk row count: the config value, or auto-sized so one raw f32
    chunk stays near a fixed byte budget. Chunk size never changes the
    produced codes — only compile shape and overlap granularity."""
    if requested > 0:
        R = int(requested)
    else:
        R = _CHUNK_BUDGET_BYTES // max(1, 4 * num_cols)
        R = max(_CHUNK_MIN, min(_CHUNK_MAX, (R // 256) * 256))
    return max(1, min(R, max(n_rows_padded, 1)))


def device_ingest(raw: np.ndarray, mappers: Sequence[BinMapper],
                  real_indices: np.ndarray, *, n_rows: int,
                  n_rows_padded: int, num_cols: int, out_dtype,
                  chunk_rows: int = 0, device=None,
                  prefetch_depth: int = 1,
                  code_mode: Optional[str] = None,
                  ingestor: Optional[DeviceIngestor] = None):
    """Bin + pack ``raw`` on device into the residency layout.

    Returns ``(codes, report)`` where ``codes`` is the
    ``[n_rows_padded, num_cols]`` device array (or the packed byte layout
    under ``code_mode``) bit-identical to host binning + ``np.pad`` +
    ``device_put``, and ``report`` carries the throughput/overlap numbers
    (``bench.py --ingest``, ``--smoke``'s ingest leg). The caller owns any
    further resharding (boosting/gbdt.py ``device_put``s onto the mesh
    row sharding — a device-to-device move)."""
    import jax.numpy as jnp
    from .. import observability as obs

    R = resolve_chunk_rows(chunk_rows, n_rows_padded, num_cols)
    n_chunks = max(1, -(-n_rows_padded // R))
    # a caller-supplied (already-warm) ingestor lets bench.py --ingest time
    # a steady pass without re-paying the jit compile
    ing = ingestor if ingestor is not None else DeviceIngestor(
        mappers, num_cols=num_cols, n_rows=n_rows,
        out_dtype=out_dtype, code_mode=code_mode, device=device)
    feeder = ChunkFeeder(raw, real_indices, chunk_rows=R, n_chunks=n_chunks,
                         num_cols=num_cols, device=device,
                         depth=prefetch_depth)
    t0 = obs.clock()
    with obs.span("ingest", rows=int(n_rows), chunks=int(n_chunks)):
        feeder.prefetch(0)
        outs = []
        for i in range(n_chunks):
            chunk = feeder.get(i)
            out = ing.bin_chunk(chunk, i * R)
            for j in range(i + 1, min(i + 1 + feeder.depth, n_chunks)):
                feeder.prefetch(j)       # copy rides under chunk i's compute
            outs.append(out)
        codes = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)
        if codes.shape[0] != n_rows_padded:
            codes = codes[:n_rows_padded]
        try:
            codes.block_until_ready()
        except AttributeError:
            pass
    seconds = obs.clock() - t0
    obs.inc("ingest.rows", int(n_rows))
    obs.inc("ingest.chunks", int(n_chunks))
    rep = feeder.report()
    rep.update({
        "rows": int(n_rows), "rows_padded": int(n_rows_padded),
        "num_cols": int(num_cols), "seconds": round(seconds, 6),
        "rows_per_s": (float(n_rows) / seconds) if seconds > 0 else None,
        "stall_fraction": (rep["stall_seconds"] / seconds)
        if seconds > 0 else 0.0,
        "compiles": ing.compiles,
    })
    Log.debug("device ingest: %d rows in %d x %d-row chunks (%.3fs, "
              "%d stalls, %.1f MB H2D)", n_rows, n_chunks, R, seconds,
              rep["stalls"], rep["bytes_h2d"] / (1 << 20))
    return codes, rep
