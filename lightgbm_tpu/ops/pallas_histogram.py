"""Pallas TPU histogram kernel — the direct replacement for the reference's
OpenCL histogram kernels (src/treelearner/ocl/histogram256.cl:95-125
local-memory atomic sub-histograms).

Design (vs the XLA one-hot matmul in ops/histogram.py):

- The [S*ch, F*B] f32 accumulator lives in VMEM scratch for the whole pass
  (≈2.3MB at S=16, ch=5, F=28, B=256) — the analog of the OpenCL kernel's
  per-workgroup local-memory sub-histograms, but with NO atomics: one core
  owns the whole accumulator and the grid walks row chunks sequentially.
- Each grid step loads a row chunk's bin codes [R, F] (uint8 -> tiny DMA),
  builds the per-leaf-slot weight columns rhs [R, S*ch] and the per-feature
  one-hot [R, B] IN VMEM (never HBM), and feeds the MXU with
  [S*ch, R] x [R, B] contractions per feature. The one-hot generation (VPU)
  pipelines against the matmul (MXU).
- Row compaction composes as a *chunk-level skip*: rows gathered to a
  pending-prefix order by the caller, and chunks past ceil(n_active/R) skip
  their compute via @pl.when — a skipped chunk costs only its (tiny) DMA,
  so the pass needs no dynamic trip count and no scatter.
- Under EFB the compacted pass's slot layout is BUNDLE-space native: the
  caller hands bundled columns with `num_bins_padded` = the bundle-bin pad
  (grower `hist_bins`), so the VMEM accumulator is [S*ch, G*Bb] — smaller
  than feature space by the bundling win ratio — and the packed row bytes
  carry bundle codes. The kernel never sees original-feature space; the
  bundle-space split scan (ops/split_finder.per_feature_best_bundled)
  consumes its output as-is, so no unpack sits between kernel and scan.

Precision matches ops/histogram.py: bf16 hi+lo gradient/hessian channels
accumulated in f32 (~f32-exact; the reference GPU path used plain f32 and
accepted small deltas, docs/GPU-Performance.rst:131-133). Counts are exact
(bf16 1.0 * onehot accumulated in f32).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

# Importing pallas' TPU backend registers MLIR lowerings for platform "tpu",
# which jax rejects when only the CPU plugin is present (the interpret-mode
# test bed). Registering the identity alias first makes "tpu" a known
# platform without initializing any backend.
from jax._src import xla_bridge as _xb
if not _xb.is_known_platform("tpu"):
    _xb._platform_aliases["tpu"] = "tpu"

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .histogram import (NUM_CHANNELS, NUM_CHANNELS_FAST, code_bytes,
                        combine_channels, pack_rows, slot_from_position,
                        slot_position_base, table_lookup, unpack_weights)

_INTERPRET = False   # flipped by tests on CPU


def _hist_kernel(n_active_ref,        # SMEM scalar prefetch: [1] i32
                 x_ref,               # [R, F*cb] u8 bin-code bytes (chunk)
                 slot_ref,            # [R, 1] i32 slot per row (-1 = masked)
                 w_ref,               # [R, ch] bf16 weight channels (chunk)
                 out_ref,             # [SC, F*B] f32 — doubles as the VMEM
                                      # accumulator (constant index_map keeps
                                      # the block resident across grid steps)
                 *, chunk_rows: int, num_bins: int, num_features: int,
                 num_slots: int, cb: int):
    i = pl.program_id(0)
    acc_ref = out_ref

    @pl.when(i == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # chunk-level skip: all rows of this chunk are past the active prefix
    @pl.when(i * chunk_rows < n_active_ref[0])
    def _compute():
        # slot-weight columns built IN VMEM (never round-tripped via HBM):
        # rhs[r, s*ch+c] = (slot[r]==s) * w[r, c]. The accumulator's row
        # count is SC padded up to the f32 sublane tile (8) — Mosaic
        # rejects a [125, ...] block (S=25 x ch=5, the default-slot
        # config) outright; padded columns map to slot id >= num_slots,
        # which no row carries, so they stay zero and the caller slices
        # them off.
        ch = w_ref.shape[1]
        sc_pad = acc_ref.shape[0]
        slot = slot_ref[:]                                 # [R, 1]
        iota_s = jax.lax.broadcasted_iota(
            jnp.int32, (chunk_rows, sc_pad), 1) // ch
        w_rep = jnp.tile(w_ref[:], (1, -(-sc_pad // ch)))[:, :sc_pad]
        rhs = (slot == iota_s).astype(jnp.bfloat16) * w_rep   # [R, SC_pad]

        # One feature per step: the one-hot is a BROADCAST compare of the
        # feature column [R, 1] against a bin iota [R, B] — one VPU op per
        # one-hot element. The earlier f-blocked form first materialized
        # [R, fb*B] i32 via jnp.repeat and compared against iota%B, i.e.
        # 3-4 VPU passes over the same elements; the one-hot build is the
        # measured VPU bottleneck of this kernel (exp/RESULTS.md round-3
        # cost model), so the extra passes were the pass-level gap vs the
        # MXU floor. Per-feature [R, B] contractions keep the MXU busy at
        # B >= 128 (2 lane tiles at B=256).
        iota_b = jax.lax.broadcasted_iota(
            jnp.int32, (chunk_rows, num_bins), 1)
        for f in range(num_features):
            if cb == 1:
                xs = x_ref[:, f:f + 1].astype(jnp.int32)      # [R, 1]
            else:
                # little-endian byte pair, two contiguous 1-column slices
                # (a stride-2 lane slice is lowered as a gather Mosaic
                # fails to shape-check — round-5 on-chip gate log)
                xs = (x_ref[:, 2 * f:2 * f + 1].astype(jnp.int32)
                      | (x_ref[:, 2 * f + 1:2 * f + 2].astype(jnp.int32)
                         << 8))                               # [R, 1]
            onehot = (xs == iota_b).astype(jnp.bfloat16)      # [R, B]
            part = jax.lax.dot_general(
                rhs, onehot,
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)           # [SC_pad, B]
            sl = slice(f * num_bins, (f + 1) * num_bins)
            acc_ref[:, sl] += part


def hist_pallas(
    Xb8: jnp.ndarray,          # [N, F*cb] u8 bin-code bytes
    slot: jnp.ndarray,         # [N] i32 histogram slot per row, -1 = skip
    w: jnp.ndarray,            # [N, ch] bf16 weight channels
    num_slots: int,
    num_bins: int,
    num_features: int,
    cb: int,                   # bytes per code (1 = uint8, 2 = uint16)
    chunk_rows: int = 512,
    n_active: Optional[jnp.ndarray] = None,   # i32: rows [0, n_active) matter
) -> jnp.ndarray:
    """Returns hist [S, F, B, 3] f32 (sum_g, sum_h, count).

    The caller may pre-gather rows into a pending prefix and pass
    ``n_active`` — chunks fully past it skip compute (cheap DMA only).
    """
    N, ncb = Xb8.shape
    ch = w.shape[1]
    hilo = ch == NUM_CHANNELS
    SC = num_slots * ch
    # f32 sublane-tile alignment for the accumulator block (see the
    # kernel's rhs comment): 125 -> 128 at the default S=25 x ch=5
    SC_pad = -(-SC // 8) * 8
    assert N % chunk_rows == 0, (N, chunk_rows)
    if n_active is None:
        n_active = jnp.asarray(N, jnp.int32)

    n_chunks = N // chunk_rows

    kernel = functools.partial(
        _hist_kernel, chunk_rows=chunk_rows, num_bins=num_bins,
        num_features=num_features, num_slots=num_slots, cb=cb)

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_chunks,),
            in_specs=[
                pl.BlockSpec((chunk_rows, ncb), lambda i, n: (i, 0)),
                pl.BlockSpec((chunk_rows, 1), lambda i, n: (i, 0)),
                pl.BlockSpec((chunk_rows, ch), lambda i, n: (i, 0)),
            ],
            out_specs=pl.BlockSpec(
                (SC_pad, num_features * num_bins), lambda i, n: (0, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct(
            (SC_pad, num_features * num_bins), jnp.float32),
        interpret=_INTERPRET,
    )(n_active.reshape(1), Xb8, slot.reshape(N, 1), w)

    acc = out[:SC].reshape(num_slots, ch, num_features, num_bins)
    acc = jnp.transpose(acc, (0, 2, 3, 1))                        # [S, F, B, ch]
    return combine_channels(acc, hilo)                            # [S, F, B, 3]


def build_histograms_pallas(
    X: jnp.ndarray,
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    included: jnp.ndarray,
    leaf_id: jnp.ndarray,
    slot_of_leaf: jnp.ndarray,
    num_slots: int,
    num_bins_padded: int,
    chunk_rows: int,
    row_idx: jnp.ndarray = None,
    n_active: jnp.ndarray = None,
    hilo: bool = True,
    slot_counts: jnp.ndarray = None,   # [S] i32: row_idx is slot-grouped —
                                       # slots derive from position (no
                                       # leaf_id/slot_of_leaf row gathers)
    slot_starts: jnp.ndarray = None,   # [S] i32: row_idx is a LEAF-CONTIGUOUS
                                       # permutation (grower incremental
                                       # partition) — positions remap through
                                       # slot_position_base before the gather
    packed: jnp.ndarray = None,        # pre-built pack_rows output (amortize
                                       # the O(N) pack across a tree's waves)
    max_rows: int = 0,                 # STATIC cap on n_active (0 = N). The
                                       # grower's adaptive cond guarantees
                                       # n_active < N/4 on this path, so the
                                       # kernel grid and gather buffers can
                                       # shrink 4x — skipped grid steps are
                                       # not free at a 10.5M-row full grid.
) -> jnp.ndarray:
    """Drop-in replacement for ops.histogram.build_histograms backed by the
    Pallas kernel (same signature/semantics — the GPU_DEBUG_COMPARE analog
    lives in tests/test_pallas_hist.py).

    With ``max_rows`` set, active rows beyond it are silently dropped — the
    caller must guarantee n_active <= max_rows."""
    N, F = X.shape
    cb = code_bytes(X.dtype)
    ch = NUM_CHANNELS if hilo else NUM_CHANNELS_FAST
    if packed is None:
        packed, _ = pack_rows(X, grad, hess, included, hilo)  # [N, ncb+2ch] u8
    ncb = F * cb
    if row_idx is not None:
        # pending-prefix gather, bounded to active chunks only — ONE random
        # row gather from the packed array per active row (vs four separate
        # X/g/h/inc gathers; a random HBM row access costs the same ~30 ns
        # regardless of row width). Gather granularity (32k rows) is
        # independent of the kernel grid step (512 rows). Rg must divide
        # the buffer length or the tail rows would silently never be
        # gathered.
        cap = N if max_rows in (0, None) else min(max_rows, N)
        R = min(chunk_rows, cap)
        cap = ((cap + R - 1) // R) * R
        Rg = min(32768, cap)
        while Rg > 1 and cap % Rg:
            Rg //= 2
        n_chunks_active = jnp.minimum((n_active + Rg - 1) // Rg, cap // Rg)
        iota_r = jnp.arange(Rg, dtype=jnp.int32)
        slot_cum = (jnp.cumsum(slot_counts) if slot_counts is not None
                    else None)

        def gather_chunk(c, bufs):
            pb, sb = bufs
            sl = c * Rg
            pos = sl + iota_r
            if slot_cum is not None:
                raw = slot_from_position(pos, slot_cum)
                if slot_starts is not None:
                    # leaf-contiguous permutation (incremental partition):
                    # positions translate into the pending segments
                    src = pos + slot_position_base(raw, slot_cum, slot_starts)
                    idx = jnp.take(row_idx,
                                   jnp.clip(src, 0, row_idx.shape[0] - 1))
                else:
                    idx = jax.lax.dynamic_slice_in_dim(row_idx, sl, Rg)
            else:
                idx = jax.lax.dynamic_slice_in_dim(row_idx, sl, Rg)
                raw = table_lookup(jnp.take(leaf_id, idx), slot_of_leaf)
            chunk_slot = jnp.where(pos < n_active, raw, -1)
            upd = jax.lax.dynamic_update_slice_in_dim
            return (upd(pb, jnp.take(packed, idx, axis=0), sl, 0),
                    upd(sb, chunk_slot, sl, 0))

        bufs = (jnp.zeros((cap, packed.shape[1]), packed.dtype),
                jnp.full(cap, -1, jnp.int32))
        _, bufs = jax.lax.while_loop(
            lambda c: c[0] < n_chunks_active,
            lambda c: (c[0] + 1, gather_chunk(c[0], c[1])),
            (jnp.asarray(0, jnp.int32), bufs))
        packed, slot = bufs
        n_rows = cap
    else:
        slot = table_lookup(leaf_id, slot_of_leaf)
        n_active = None
        n_rows = N
    Xb8 = packed[:, :ncb]
    w = unpack_weights(packed[:, ncb:], ch)
    return hist_pallas(Xb8, slot, w, num_slots, num_bins_padded,
                       num_features=F, cb=cb,
                       chunk_rows=min(chunk_rows, n_rows),
                       n_active=n_active)
