"""Vectorized tree traversal over binned data (device).

Replaces the reference's per-row pointer-chasing Tree::GetLeaf
(include/LightGBM/tree.h:434-487) with a data-parallel frontier walk: every
row holds its current node id; one step gathers (feature, threshold, children)
for all rows at once and advances; a `while_loop` runs until all rows sit in
leaves (bounded by tree depth). Used for validation-score updates during
training — training rows never traverse (their leaf ids are maintained
incrementally by the grower).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..grower import TreeArrays, decode_bundled_bin


def leaves_from_binned(
    tree: TreeArrays,
    Xb: jnp.ndarray,            # [N, F] bin codes ([N, G] bundled under EFB)
    num_bins: jnp.ndarray,      # [F] i32
    missing_code: jnp.ndarray,  # [F] i32
    default_bin: jnp.ndarray,   # [F] i32
    bundle=None,                # grower.BundleDecode when Xb is EFB-bundled
) -> jnp.ndarray:
    """Leaf index [N] for each row."""
    N = Xb.shape[0]
    max_steps = tree.leaf_value.shape[0]  # depth <= num_leaves

    # cur >= 0: internal node id; cur < 0: settled in leaf ~cur
    cur0 = jnp.where(tree.num_leaves > 1,
                     jnp.zeros(N, jnp.int32),
                     jnp.full(N, -1, jnp.int32))

    def cond(carry):
        cur, steps = carry
        return jnp.any(cur >= 0) & (steps < max_steps)

    def body(carry):
        cur, steps = carry
        at_node = cur >= 0
        nid = jnp.maximum(cur, 0)
        f = tree.split_feature[nid]
        thr = tree.threshold_bin[nid]
        dl = tree.default_left[nid]
        if bundle is None:
            b = jnp.take_along_axis(Xb, f[:, None], axis=1)[:, 0].astype(jnp.int32)
        else:
            b = decode_bundled_bin(Xb, f, bundle, default_bin)
        mcode = missing_code[f]
        nbin = num_bins[f]
        dbin = default_bin[f]
        is_missing = ((mcode == 2) & (b == nbin - 1)) | ((mcode == 1) & (b == dbin))
        go_left = jnp.where(is_missing, dl, b <= thr)
        # categorical: bin-in-left-set lookup (reference tree.h:257-284)
        go_left_cat = jnp.take_along_axis(tree.cat_mask[nid], b[:, None],
                                          axis=1)[:, 0]
        go_left = jnp.where(tree.is_cat[nid], go_left_cat, go_left)
        child = jnp.where(go_left, tree.left_child[nid], tree.right_child[nid])
        cur = jnp.where(at_node, child, cur)
        return cur, steps + 1

    cur, _ = jax.lax.while_loop(cond, body, (cur0, jnp.asarray(0, jnp.int32)))
    return -cur - 1  # ~cur


def add_tree_scores(score: jnp.ndarray, tree: TreeArrays, leaf_ids: jnp.ndarray
                    ) -> jnp.ndarray:
    """score += leaf_value[leaf] — the reference's leaf-partition fast path
    (ScoreUpdater::AddScore with tree_learner, score_updater.hpp:49-56)."""
    return score + tree.leaf_value[leaf_ids]
