"""Vectorized tree traversal over binned data (device).

Replaces the reference's per-row pointer-chasing Tree::GetLeaf
(include/LightGBM/tree.h:434-487) with a data-parallel frontier walk: every
row holds its current node id; one step gathers (feature, threshold, children)
for all rows at once and advances; a `while_loop` runs until all rows sit in
leaves (bounded by tree depth). Used for validation-score updates during
training — training rows never traverse (their leaf ids are maintained
incrementally by the grower).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..analysis.contracts.registry import trace_entry
from ..grower import TreeArrays, decode_bundled_bin
from .histogram import table_lookup


def leaves_from_binned(
    tree: TreeArrays,
    Xb: jnp.ndarray,            # [N, F] bin codes ([N, G] bundled under EFB)
    num_bins: jnp.ndarray,      # [F] i32
    missing_code: jnp.ndarray,  # [F] i32
    default_bin: jnp.ndarray,   # [F] i32
    bundle=None,                # grower.BundleDecode when Xb is EFB-bundled
    use_categorical: bool = True,  # False skips the [N, B] cat-mask gather
) -> jnp.ndarray:
    """Leaf index [N] for each row."""
    N = Xb.shape[0]
    max_steps = tree.leaf_value.shape[0]  # depth <= num_leaves

    # One packed [M+1, 7] per-node decision table, resolved per row by
    # table_lookup's one-hot contraction — the old per-field node gathers
    # cost ~15-25 ms each at 2M rows (see grower step 7 for the same
    # pattern). Missing semantics fold into a per-node missing bin
    # (reference NumericalDecision, tree.h:218-243).
    sf = tree.split_feature
    mc, nb, db = missing_code[sf], num_bins[sf], default_bin[sf]
    miss_bin = jnp.where(mc == 2, nb - 1, jnp.where(mc == 1, db, -1))
    node_tab = jnp.stack(
        [sf.astype(jnp.int32), tree.threshold_bin.astype(jnp.int32),
         miss_bin.astype(jnp.int32), tree.left_child.astype(jnp.int32),
         tree.right_child.astype(jnp.int32),
         tree.default_left.astype(jnp.int32), tree.is_cat.astype(jnp.int32)],
        axis=-1)                                                 # [M+1, 7]
    iota_f = jnp.arange(Xb.shape[1], dtype=jnp.int32)[None, :]

    # cur >= 0: internal node id; cur < 0: settled in leaf ~cur
    cur0 = jnp.where(tree.num_leaves > 1,
                     jnp.zeros(N, jnp.int32),
                     jnp.full(N, -1, jnp.int32))

    def cond(carry):
        cur, steps = carry
        return jnp.any(cur >= 0) & (steps < max_steps)

    def body(carry):
        cur, steps = carry
        at_node = cur >= 0
        nid = jnp.maximum(cur, 0)
        pk = table_lookup(nid, node_tab)                         # [N, 7]
        f, thr, miss = pk[:, 0], pk[:, 1], pk[:, 2]
        if bundle is None:
            # bin of the node's split feature as a one-hot multiply-sum
            # over the F lanes (fused VPU stream, no per-row gather)
            b = jnp.sum(Xb.astype(jnp.int32) * (f[:, None] == iota_f), axis=1)
        else:
            b = decode_bundled_bin(Xb, f, bundle, default_bin)
        go_left = jnp.where(b == miss, pk[:, 5] != 0, b <= thr)
        if use_categorical:
            # categorical: bin-in-left-set lookup (reference tree.h:257-284)
            go_left_cat = jnp.take_along_axis(tree.cat_mask[nid], b[:, None],
                                              axis=1)[:, 0]
            go_left = jnp.where(pk[:, 6] != 0, go_left_cat, go_left)
        child = jnp.where(go_left, pk[:, 3], pk[:, 4])
        cur = jnp.where(at_node, child, cur)
        return cur, steps + 1

    cur, _ = jax.lax.while_loop(cond, body, (cur0, jnp.asarray(0, jnp.int32)))
    return -cur - 1  # ~cur


def add_tree_scores(score: jnp.ndarray, tree: TreeArrays, leaf_ids: jnp.ndarray
                    ) -> jnp.ndarray:
    """score += leaf_value[leaf] — the reference's leaf-partition fast path
    (ScoreUpdater::AddScore with tree_learner, score_updater.hpp:49-56)."""
    return score + tree.leaf_value[leaf_ids]


# ---------------------------------------------------------------------------
# Batch forest prediction (the reference's OMP row-parallel Predictor,
# src/application/predictor.hpp:25-241, re-designed for the TPU):
#
# Float thresholds are rank-encoded on the host: per feature, the sorted
# unique thresholds appearing anywhere in the forest form a tiny "threshold
# grid"; each raw value maps to its rank via float64 searchsorted (exact),
# and every node stores its threshold's rank. The device then walks all
# trees with pure integer compares — bit-exact traversal with no float64 on
# the accelerator. Missing semantics (NumericalDecision, tree.h:218-243)
# are precomputed as per-(row, feature) NaN/zero masks folded into the rank
# code's sign bits.
# ---------------------------------------------------------------------------

import numpy as np

# one-time process-wide warning flag for the categorical host fallback in
# forest_predict_raw (a serving loop over a categorical model must not log
# per dispatch)
_CATEGORICAL_FALLBACK = {"warned": False}


class StackedForest:
    """Host-built stacked arrays for a list of model-space Trees."""

    def __init__(self, trees, num_features: int):
        T = len(trees)
        M = max([t.num_internal for t in trees] + [1])
        L = max([t.num_leaves for t in trees] + [1])
        self.num_trees = T
        self.max_leaves = L
        self.has_categorical = any(
            (np.asarray(t.decision_type) & 1).any() for t in trees)
        # piecewise-linear leaves (linear_tree models): stacked -1-padded
        # (feature, coefficient) tables for the leaf-local dot-product
        # epilogue of forest_walk_linear; None when every leaf is constant
        self.has_linear = any(t.is_linear for t in trees)
        self.max_leaf_features = 0
        self.leaf_const32 = self.leaf_coeff32 = self.leaf_feat = None
        if self.has_linear:
            Kf = max(max((len(f) for f in t.leaf_features), default=0)
                     for t in trees if t.leaf_features is not None)
            self.max_leaf_features = Kf = max(Kf, 1)
            self.leaf_const32 = np.zeros((T, L), np.float32)
            self.leaf_coeff32 = np.zeros((T, L, Kf), np.float32)
            self.leaf_feat = np.full((T, L, Kf), -1, np.int32)
            for i, t in enumerate(trees):
                if t.leaf_features is None:
                    continue
                self.leaf_const32[i, : len(t.leaf_const)] = t.leaf_const
                for li, feats in enumerate(t.leaf_features):
                    k = len(feats)
                    if k:
                        self.leaf_feat[i, li, :k] = feats
                        self.leaf_coeff32[i, li, :k] = t.leaf_coeff[li]

        split_feature = np.zeros((T, M), np.int32)
        thr_rank = np.zeros((T, M), np.int32)
        decision = np.zeros((T, M), np.uint8)
        left = np.full((T, M), -1, np.int32)
        right = np.full((T, M), -1, np.int32)
        leaf_value = np.zeros((T, L), np.float32)
        root_is_leaf = np.zeros(T, bool)

        # per-feature threshold grid over the whole forest
        grids = [[] for _ in range(num_features)]
        for t in trees:
            for n in range(t.num_internal):
                if not (t.decision_type[n] & 1):
                    grids[int(t.split_feature[n])].append(float(t.threshold[n]))
        self.grids = [np.array(sorted(set(g)), np.float64) for g in grids]

        for i, t in enumerate(trees):
            m = t.num_internal
            if m == 0 or t.num_leaves <= 1:
                root_is_leaf[i] = True
                leaf_value[i, 0] = t.leaf_value[0] if len(t.leaf_value) else 0.0
                continue
            split_feature[i, :m] = t.split_feature[:m]
            decision[i, :m] = t.decision_type[:m]
            left[i, :m] = t.left_child[:m]
            right[i, :m] = t.right_child[:m]
            leaf_value[i, : t.num_leaves] = t.leaf_value[: t.num_leaves]
            for n in range(m):
                f = int(t.split_feature[n])
                if not (t.decision_type[n] & 1):
                    # node rank = index of its threshold in the grid; with
                    # value codes c(v) = #{g < v} (side='left'),
                    # v <= thr  <=>  c(v) <= rank(thr) including ties
                    thr_rank[i, n] = np.searchsorted(
                        self.grids[f], float(t.threshold[n]), side="left")

        self.split_feature = split_feature
        self.thr_rank = thr_rank
        self.decision = decision
        self.left = left
        self.right = right
        self.leaf_value = leaf_value
        # the f64 leaf twin (serving path) builds lazily from the retained
        # tree list — training-side forests (Booster.predict device route,
        # bench) never pay its memory/fill cost
        self._trees = trees
        self._leaf_value64 = None
        self.root_is_leaf = root_is_leaf
        # rank of literal 0.0 per feature — what a NaN becomes when the node's
        # missing_type is not nan (tree.h:224-227 NaN->0 conversion)
        self.zero_rank = np.array(
            [np.searchsorted(g, 0.0, side="left") for g in self.grids]
            or [0], np.int32)
        # concatenated offset grid for the one-searchsorted vectorized
        # encode: grid entries keyed (feature, threshold) as complex128
        # (real=feature index, imag=threshold) — numpy's complex sort order
        # is lexicographic with exact float compares on each component, so
        # ONE searchsorted over the concatenation reproduces every
        # per-feature searchsorted bit-for-bit (ties and ±inf included; NaN
        # keys sort to the GLOBAL end under the complex total order and are
        # patched from the nan mask to the per-feature len(grid) the loop
        # would produce)
        self.grid_sizes = np.array([len(g) for g in self.grids], np.int64)
        self.grid_offsets = np.concatenate(
            ([0], np.cumsum(self.grid_sizes))).astype(np.int64)
        total = int(self.grid_offsets[-1]) if len(self.grid_sizes) else 0
        self._grid_keys = np.empty(total, np.complex128)
        if total:
            self._grid_keys.real = np.repeat(
                np.arange(len(self.grids)), self.grid_sizes)
            self._grid_keys.imag = np.concatenate(
                [g for g in self.grids if len(g)])
        self._feat_iota = np.arange(num_features, dtype=np.float64)

    @property
    def leaf_value64(self) -> np.ndarray:
        """f64 twin of ``leaf_value`` for the serving path's host-side
        accumulation (lightgbm_tpu/serving): the device walk returns leaf
        INDICES and the engine sums f64 leaf values in tree order —
        bit-identical to the host predictor's sequential accumulation.
        Built on first access (serving engines only), cached after."""
        if self._leaf_value64 is None:
            lv = np.zeros((self.num_trees, self.leaf_value.shape[1]),
                          np.float64)
            for i, t in enumerate(self._trees):
                if self.root_is_leaf[i]:
                    lv[i, 0] = t.leaf_value[0] if len(t.leaf_value) else 0.0
                else:
                    lv[i, : t.num_leaves] = t.leaf_value[: t.num_leaves]
            self._leaf_value64 = lv
        return self._leaf_value64

    # elements (rows*features) below which the vectorized encode wins: one
    # complex searchsorted beats F Python-level calls up to ~8k elements
    # (measured: 5.8x at [1, 28], 2.4x at [64, 28], 45x at [1, 137]); past
    # the crossover the per-feature loop's cheaper float compares win
    # (~2x at [4096, 28]) and large training-side batches keep it
    VEC_ENCODE_MAX_ELEMS = 8192

    def encode_rows(self, X: np.ndarray):
        """Raw [N, F] float64 -> (rank codes i32, nan mask, zero mask).

        c(v) = #{grid thresholds < v} (side='left', f64 on host), so the
        device's integer compare c(v) <= rank(thr) reproduces the float64
        v <= thr exactly, ties included. Small batches (the serving
        critical path — many concurrent micro-batches) take the one-
        searchsorted concatenated-grid path; large ones the per-feature
        loop (see VEC_ENCODE_MAX_ELEMS). Both are parity-pinned against
        each other in tests/test_serving.py."""
        N, F = X.shape
        from ..binning import K_ZERO_RANGE
        is_nan = np.isnan(X)
        # missing_type zero treats NaN as 0 first (tree.h:224-227)
        is_zero = is_nan | (np.abs(np.where(is_nan, 0.0, X)) <= K_ZERO_RANGE)
        if N * F <= self.VEC_ENCODE_MAX_ELEMS and self._grid_keys.size:
            codes = self._encode_vectorized(X, is_nan)
        else:
            codes = self._encode_loop(X)
        return codes, is_nan, is_zero

    def _encode_loop(self, X: np.ndarray) -> np.ndarray:
        """Per-feature searchsorted — the reference implementation the
        vectorized path is pinned against, and the large-batch winner."""
        N, F = X.shape
        codes = np.zeros((N, F), np.int32)
        for f, grid in enumerate(self.grids):
            if len(grid):
                codes[:, f] = np.searchsorted(grid, X[:, f], side="left")
        return codes

    def _encode_vectorized(self, X: np.ndarray, is_nan: np.ndarray
                           ) -> np.ndarray:
        """One searchsorted over the concatenated (feature, threshold)
        offset grid; exact by construction (complex lexicographic compare =
        feature segment select + float64 threshold compare)."""
        keys = np.empty(X.shape, np.complex128)
        keys.real = self._feat_iota[None, :]
        keys.imag = X
        flat = np.searchsorted(self._grid_keys, keys.ravel(), side="left")
        codes = (flat.reshape(X.shape)
                 - self.grid_offsets[:-1][None, :]).astype(np.int32)
        if is_nan.any():
            # complex keys with a NaN component sort past every segment;
            # restore the loop's per-feature searchsorted(grid, nan) ==
            # len(grid) so the parity pin holds (the value is semantically
            # dead — the walk replaces it via zero_rank / the default path)
            codes[is_nan] = np.broadcast_to(
                self.grid_sizes[None, :].astype(np.int32), X.shape)[is_nan]
        return codes


@trace_entry("predict.forest_walk")
def forest_walk_leaves(split_feature, thr_rank, decision, left, right,
                       root_is_leaf, zero_rank, codes, is_nan, is_zero):
    """Leaf index [N, T] for every (row, tree); integer-exact traversal.

    All T trees advance together: the frontier is [N, T] (trees in the lane
    dimension), so one step is a handful of vectorized gathers instead of a
    per-tree Python/scan loop — the whole forest finishes in max-tree-depth
    steps. The serving engine jits THIS variant per batch-size bucket and
    accumulates f64 leaf values on the host (bit-identical to the host
    predictor); the training-side ``_forest_walk`` folds the f32 leaf sum
    on device."""
    T, M = split_feature.shape
    N = codes.shape[0]
    max_steps = M + 1                                    # depth <= internals
    t_iota = jnp.arange(T, dtype=jnp.int32)[None, :]               # [1, T]

    cur0 = jnp.where(root_is_leaf[None, :], -1, 0).astype(jnp.int32)
    cur0 = jnp.broadcast_to(cur0, (N, T))

    def cond(c):
        cur, steps = c
        return jnp.any(cur >= 0) & (steps < max_steps)

    def body(c):
        cur, steps = c
        nid = jnp.maximum(cur, 0)                                  # [N, T]
        f = split_feature[t_iota, nid]                             # [N, T]
        node_dt = decision[t_iota, nid]
        v_rank = jnp.take_along_axis(codes, f, axis=1)             # [N, T]
        v_nan = jnp.take_along_axis(is_nan, f, axis=1)
        v_zero = jnp.take_along_axis(is_zero, f, axis=1)
        missing_type = (node_dt >> 2) & 3
        default_left = (node_dt & 2) != 0
        # NaN converts to 0 unless missing_type==nan (tree.h:224-227) —
        # in rank space, 0.0 is the feature's zero_rank
        v_rank_eff = jnp.where(v_nan & (missing_type != 2),
                               zero_rank[f], v_rank)
        is_default = jnp.where(missing_type == 1, v_zero,
                               jnp.where(missing_type == 2, v_nan, False))
        go_left = jnp.where(is_default, default_left,
                            v_rank_eff <= thr_rank[t_iota, nid])
        child = jnp.where(go_left, left[t_iota, nid], right[t_iota, nid])
        cur = jnp.where(cur >= 0, child, cur)
        return cur, steps + 1

    cur, _ = jax.lax.while_loop(cond, body, (cur0, jnp.asarray(0, jnp.int32)))
    return -cur - 1                                                # [N, T]


def forest_walk_linear(split_feature, thr_rank, decision, left, right,
                       leaf_value, leaf_const, leaf_coeff, leaf_feat,
                       root_is_leaf, zero_rank, codes, is_nan, is_zero,
                       raw, raw_nan):
    """Per-(row, tree) leaf OUTPUT [N, T] f32 for a linear-leaf forest:
    the integer-exact ``forest_walk_leaves`` traversal plus a leaf-local
    dot-product epilogue over the device-resident raw-feature slice
    (``raw`` NaN-sanitized f32 [N, F]; ``raw_nan`` its missing plane).
    Rows missing any leaf feature take the constant ``leaf_value`` —
    exactly the host predictor's fallback semantics."""
    T = split_feature.shape[0]
    N = codes.shape[0]
    Kf = leaf_feat.shape[2]
    t_iota = jnp.arange(T, dtype=jnp.int32)[None, :]
    leaves = forest_walk_leaves(split_feature, thr_rank, decision, left,
                                right, root_is_leaf, zero_rank, codes,
                                is_nan, is_zero)               # [N, T]
    feats = leaf_feat[t_iota, leaves]                          # [N, T, Kf]
    coeff = leaf_coeff[t_iota, leaves]
    const = leaf_const[t_iota, leaves]
    base = leaf_value[t_iota, leaves]
    # raw value + missing flag per (row, tree, k): a flat per-row gather
    # over the F axis (feats are -1 for unused slots -> clipped index 0,
    # masked out below)
    idx = jnp.maximum(feats, 0).reshape(N, T * Kf)
    vals = jnp.take_along_axis(raw, idx, axis=1).reshape(N, T, Kf)
    miss = jnp.take_along_axis(raw_nan, idx, axis=1).reshape(N, T, Kf)
    used = feats >= 0
    vals = jnp.where(used, vals, 0.0)
    miss = miss & used
    lin = used[..., 0] & ~jnp.any(miss, axis=2)                # [N, T]
    acc = const + jnp.sum(coeff * vals, axis=2)
    return jnp.where(lin, acc, base)                           # [N, T]


@jax.jit
def _forest_walk_linear_sum(split_feature, thr_rank, decision, left, right,
                            leaf_value, leaf_const, leaf_coeff, leaf_feat,
                            root_is_leaf, zero_rank, codes, is_nan, is_zero,
                            raw, raw_nan):
    """f32 device sum over trees of ``forest_walk_linear`` — the linear
    twin of ``_forest_walk`` for the training-side batch-predict entry."""
    return jnp.sum(forest_walk_linear(
        split_feature, thr_rank, decision, left, right, leaf_value,
        leaf_const, leaf_coeff, leaf_feat, root_is_leaf, zero_rank,
        codes, is_nan, is_zero, raw, raw_nan), axis=1)


@jax.jit
def _forest_walk(split_feature, thr_rank, decision, left, right, leaf_value,
                 root_is_leaf, zero_rank, codes, is_nan, is_zero):
    """Leaf-value sum [N] over all trees (f32 accumulation on device) —
    the training-side batch-predict entry; traversal is
    ``forest_walk_leaves``."""
    T = split_feature.shape[0]
    t_iota = jnp.arange(T, dtype=jnp.int32)[None, :]               # [1, T]
    leaves = forest_walk_leaves(split_feature, thr_rank, decision, left,
                                right, root_is_leaf, zero_rank, codes,
                                is_nan, is_zero)
    return jnp.sum(leaf_value[t_iota, leaves], axis=1)             # [N]


def forest_predict_raw(trees, X: np.ndarray, num_features: int,
                       chunk_rows: int = 1 << 16,
                       forest: "StackedForest" = None) -> np.ndarray:
    """Raw-score batch prediction for a forest on device.

    Returns f64 [N]; traversal is bit-exact vs the host path (integer rank
    compares), leaf-value accumulation is f32 on device. Categorical
    forests fall back to the host predictor (one-time warning). Pass a
    prebuilt ``forest`` to amortize the stacking across calls (serving
    loops)."""
    if forest is None:
        forest = StackedForest(trees, num_features)
    if forest.has_categorical:
        # the rank-encoded device walk covers numerical splits only —
        # categorical forests fall back to the (vectorized-numpy) host
        # predictor so every model serves through one entry point
        # (lightgbm_tpu/serving relies on this); warn ONCE per process
        if not _CATEGORICAL_FALLBACK["warned"]:
            _CATEGORICAL_FALLBACK["warned"] = True
            from ..utils.log import Log
            Log.warning(
                "forest holds categorical splits: device batch predict "
                "covers numerical splits only — routing through the host "
                "predictor (one-time warning; throughput is the host "
                "path's)")
        Xh = np.asarray(X, np.float64)
        out = np.zeros(Xh.shape[0], np.float64)
        for t in trees:
            out += t.predict(Xh)
        return out
    out = np.zeros(X.shape[0], np.float64)
    linear = forest.has_linear
    if linear:
        dev = [jnp.asarray(a) for a in
               (forest.split_feature, forest.thr_rank, forest.decision,
                forest.left, forest.right, forest.leaf_value,
                forest.leaf_const32, forest.leaf_coeff32, forest.leaf_feat,
                forest.root_is_leaf, forest.zero_rank)]
        walk = _forest_walk_linear_sum
    else:
        dev = [jnp.asarray(a) for a in
               (forest.split_feature, forest.thr_rank, forest.decision,
                forest.left, forest.right, forest.leaf_value,
                forest.root_is_leaf, forest.zero_rank)]
        walk = _forest_walk
    for lo in range(0, X.shape[0], chunk_rows):
        chunk = np.asarray(X[lo:lo + chunk_rows], np.float64)
        codes, is_nan, is_zero = forest.encode_rows(chunk)
        args = (*dev, jnp.asarray(codes), jnp.asarray(is_nan),
                jnp.asarray(is_zero))
        if linear:
            # the leaf-local dot-product epilogue reads raw f32 values —
            # sanitized (NaN -> 0) with the missing plane alongside, so the
            # 0-weight lanes of the gather can never poison the sum
            raw32 = chunk.astype(np.float32)
            raw_nan = np.isnan(raw32)
            np.nan_to_num(raw32, copy=False, nan=0.0)
            args = args + (jnp.asarray(raw32), jnp.asarray(raw_nan))
        if lo == 0:
            # cost-report leg of the predict dispatch (observability/costs):
            # compile-time capture of the first chunk's signature, once
            from ..observability import costs as obs_costs
            if obs_costs.enabled():
                # the walk is ONE module-level jit serving every forest:
                # the fingerprint makes a different forest/batch shape
                # re-capture instead of serving the first model's numbers
                obs_costs.capture_jit(
                    "predict.forest_walk" + (".linear" if linear else ""),
                    walk, args,
                    dims=dict(rows=int(codes.shape[0]),
                              trees=int(forest.num_trees)),
                    fingerprint=(int(codes.shape[0]), codes.shape[1],
                                 int(forest.num_trees),
                                 int(forest.max_leaves), linear))
        # host boundary: predict RETURNS numpy — the sync is the contract
        out[lo:lo + chunk_rows] = np.asarray(  # tpu-lint: disable=R002
            walk(*args))
    return out
