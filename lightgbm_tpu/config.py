"""Parameter/config system.

Mirrors the reference's string-map driven config pipeline so that LightGBM
parameter dicts and `train.conf` files work unchanged:

- full parameter surface of LightGBM v2.0.10 with identical defaults
  (reference: include/LightGBM/config.h:94-300),
- alias resolution with the same priority rule — longest name wins, ties
  alphabetical (reference: include/LightGBM/config.h:358-514),
- conf-file parsing `key = value` with `#` comments
  (reference: src/application/application.cpp:48-81),
- conflict checks (reference: src/io/config.cpp OverallConfig::CheckParamConflict).

TPU additions: ``device=tpu`` (the default) joins ``cpu``/``gpu``;
``tree_learner`` gains no new values — serial/feature/data/voting map onto a
`jax.sharding.Mesh` instead of sockets/MPI.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .utils.log import Log

# Alias -> canonical parameter name (reference: config.h:360-445).
PARAMETER_ALIASES: Dict[str, str] = {
    "config": "config_file",
    "nthread": "num_threads",
    "random_seed": "seed",
    "num_thread": "num_threads",
    "boosting": "boosting_type",
    "boost": "boosting_type",
    "application": "objective",
    "app": "objective",
    "train_data": "data",
    "train": "data",
    "model_output": "output_model",
    "model_out": "output_model",
    "model_input": "input_model",
    "model_in": "input_model",
    "predict_result": "output_result",
    "prediction_result": "output_result",
    "valid": "valid_data",
    "test_data": "valid_data",
    "test": "valid_data",
    "is_sparse": "is_enable_sparse",
    "enable_sparse": "is_enable_sparse",
    "pre_partition": "is_pre_partition",
    "training_metric": "is_training_metric",
    "train_metric": "is_training_metric",
    "ndcg_at": "ndcg_eval_at",
    "eval_at": "ndcg_eval_at",
    "min_data_per_leaf": "min_data_in_leaf",
    "min_data": "min_data_in_leaf",
    "min_child_samples": "min_data_in_leaf",
    "min_sum_hessian_per_leaf": "min_sum_hessian_in_leaf",
    "min_sum_hessian": "min_sum_hessian_in_leaf",
    "min_hessian": "min_sum_hessian_in_leaf",
    "min_child_weight": "min_sum_hessian_in_leaf",
    "num_leaf": "num_leaves",
    "sub_feature": "feature_fraction",
    "colsample_bytree": "feature_fraction",
    "num_iteration": "num_iterations",
    "num_tree": "num_iterations",
    "num_round": "num_iterations",
    "num_trees": "num_iterations",
    "num_rounds": "num_iterations",
    "num_boost_round": "num_iterations",
    "sub_row": "bagging_fraction",
    "subsample": "bagging_fraction",
    "subsample_freq": "bagging_freq",
    "shrinkage_rate": "learning_rate",
    "tree": "tree_learner",
    "num_machine": "num_machines",
    "local_port": "local_listen_port",
    "two_round_loading": "use_two_round_loading",
    "two_round": "use_two_round_loading",
    "mlist": "machine_list_file",
    "is_save_binary": "is_save_binary_file",
    "save_binary": "is_save_binary_file",
    "early_stopping_rounds": "early_stopping_round",
    "early_stopping": "early_stopping_round",
    "verbosity": "verbose",
    "header": "has_header",
    "label": "label_column",
    "weight": "weight_column",
    "group": "group_column",
    "query": "group_column",
    "query_column": "group_column",
    "ignore_feature": "ignore_column",
    "blacklist": "ignore_column",
    "categorical_feature": "categorical_column",
    "cat_column": "categorical_column",
    "cat_feature": "categorical_column",
    "predict_raw_score": "is_predict_raw_score",
    "predict_leaf_index": "is_predict_leaf_index",
    "raw_score": "is_predict_raw_score",
    "leaf_index": "is_predict_leaf_index",
    "contrib": "is_predict_contrib",
    "predict_contrib": "is_predict_contrib",
    "min_split_gain": "min_gain_to_split",
    "topk": "top_k",
    "reg_alpha": "lambda_l1",
    "reg_lambda": "lambda_l2",
    "num_classes": "num_class",
    "unbalanced_sets": "is_unbalance",
    "bagging_fraction_seed": "bagging_seed",
    "workers": "machines",
    "nodes": "machines",
}

# Historical misspelling kept by the reference (config.h:466 "poission_...").
PARAMETER_ALIASES["poission_max_delta_step"] = "poisson_max_delta_step"
# Reference accepts both spellings of the machine list file param.
PARAMETER_ALIASES["machine_list_filename"] = "machine_list_file"
PARAMETER_ALIASES["data_filename"] = "data"
PARAMETER_ALIASES["valid_data_filenames"] = "valid_data"


def _parse_bool(value: Any, name: str) -> bool:
    if isinstance(value, bool):
        return value
    v = str(value).lower()
    if v in ("false", "-", "0"):
        return False
    if v in ("true", "+", "1"):
        return True
    Log.fatal('Parameter %s should be "true"/"+" or "false"/"-", got "%s"', name, value)


def _parse_int_list(value: Any) -> List[int]:
    if isinstance(value, (list, tuple)):
        return [int(v) for v in value]
    return [int(v) for v in str(value).split(",") if v != ""]


def _parse_float_list(value: Any) -> List[float]:
    if isinstance(value, (list, tuple)):
        return [float(v) for v in value]
    return [float(v) for v in str(value).split(",") if v != ""]


def _parse_str_list(value: Any) -> List[str]:
    if isinstance(value, (list, tuple)):
        return [str(v) for v in value]
    return [v for v in str(value).split(",") if v != ""]


@dataclass
class Config:
    """Flat config holding the whole reference parameter surface.

    Defaults match include/LightGBM/config.h:94-300 exactly; the grouping into
    IO/Objective/Metric/Tree/Boosting/Network structs is collapsed — every
    consumer reads the fields it needs (the reference nests copies of e.g.
    num_class into four structs; one field here).
    """

    # --- task / device -----------------------------------------------------
    task: str = "train"                       # train | predict | convert_model | refit
    device: str = "tpu"                       # tpu (native) | cpu | gpu (aliases for tpu)
    seed: int = 0
    num_threads: int = 0
    verbose: int = 1

    # --- IO (config.h:94-160) ---------------------------------------------
    max_bin: int = 255
    num_class: int = 1
    data_random_seed: int = 1
    data: str = ""
    valid_data: List[str] = field(default_factory=list)
    init_score_file: str = ""
    valid_init_score_file: List[str] = field(default_factory=list)
    snapshot_freq: int = -1
    output_model: str = "LightGBM_model.txt"
    output_result: str = "LightGBM_predict_result.txt"
    convert_model: str = "gbdt_prediction.cpp"
    convert_model_language: str = ""
    input_model: str = ""
    model_format: str = "text"                # text | proto (fork addition: proto/model.proto)
    num_iteration_predict: int = -1
    is_pre_partition: bool = False
    is_enable_sparse: bool = True
    sparse_threshold: float = 0.8
    use_two_round_loading: bool = False
    is_save_binary_file: bool = False
    enable_load_from_binary_file: bool = True
    bin_construct_sample_cnt: int = 200000
    is_predict_leaf_index: bool = False
    is_predict_contrib: bool = False
    is_predict_raw_score: bool = False
    min_data_in_bin: int = 3
    max_conflict_rate: float = 0.0
    # EFB (exclusive feature bundling, efb.py): "auto" (the default)
    # resolves per shape class — bundle iff the plan actually shrinks the
    # histogram work (the BundlePlan win ratio, boosting/gbdt.py), the way
    # tpu_hist_kernel=auto resolves per shape class; "true" bundles
    # whenever any plan exists; "false" disables. Since the bundle-space
    # split-finding redesign the scan, the collectives, and row routing all
    # run on bundled bins natively (ops/split_finder.py
    # per_feature_best_bundled) — the round-5 "EFB hurts on TPU" regression
    # this knob used to warn about is gone on the default arm.
    enable_bundle: str = "auto"
    has_header: bool = False
    label_column: str = ""
    weight_column: str = ""
    group_column: str = ""
    ignore_column: str = ""
    categorical_column: str = ""
    pred_early_stop: bool = False
    pred_early_stop_freq: int = 10
    pred_early_stop_margin: float = 10.0
    zero_as_missing: bool = False
    use_missing: bool = True

    # --- objective (config.h:163-184) --------------------------------------
    objective: str = "regression"
    sigmoid: float = 1.0
    huber_delta: float = 1.0
    fair_c: float = 1.0
    gaussian_eta: float = 1.0
    poisson_max_delta_step: float = 0.7
    label_gain: List[float] = field(default_factory=list)
    max_position: int = 20
    is_unbalance: bool = False
    scale_pos_weight: float = 1.0

    # --- metric (config.h:187-196) ------------------------------------------
    metric: List[str] = field(default_factory=list)
    metric_freq: int = 1
    is_training_metric: bool = False
    ndcg_eval_at: List[int] = field(default_factory=lambda: [1, 2, 3, 4, 5])

    # --- tree (config.h:200-233) --------------------------------------------
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0
    num_leaves: int = 31
    feature_fraction_seed: int = 2
    feature_fraction: float = 1.0
    histogram_pool_size: float = -1.0
    max_depth: int = -1
    top_k: int = 20
    gpu_platform_id: int = -1
    gpu_device_id: int = -1
    gpu_use_dp: bool = False
    min_data_per_group: int = 100
    max_cat_threshold: int = 32
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    max_cat_to_onehot: int = 4
    # --- piecewise-linear leaves (ops/linear.py, docs/Linear-Trees.md) ------
    # fit a linear model per leaf over the leaf's path features instead of a
    # constant (arXiv 1802.05640; later-LightGBM linear_tree). The per-leaf
    # ridge solves run INSIDE the training step as one batched Cholesky —
    # zero extra dispatches. Changes the model: fingerprinted for
    # checkpoint/resume, like linear_lambda / linear_max_features.
    linear_tree: bool = False
    # ridge term added to the coefficient diagonal of every leaf's normal
    # equations (never the intercept); 0 = plain least squares with loud
    # degradation to constant leaves on singular systems
    linear_lambda: float = 0.0
    # cap on distinct numerical path features per leaf (leaf-to-root order:
    # the nearest splits enter first)
    linear_max_features: int = 8
    # warn (once per train()) when leaves degrade to constant output
    # (categorical path / too few rows / ill-conditioned solve) — loudness
    # knob only, never the math: VOLATILE_CONFIG_FIELDS
    tpu_linear_warn_fallback: bool = True

    # --- boosting (config.h:236-260) ----------------------------------------
    boosting_type: str = "gbdt"               # gbdt | dart | goss | rf
    output_freq: int = 1
    num_iterations: int = 100
    learning_rate: float = 0.1
    bagging_fraction: float = 1.0
    bagging_seed: int = 3
    bagging_freq: int = 0
    early_stopping_round: int = 0
    drop_rate: float = 0.1
    max_drop: int = 50
    skip_drop: float = 0.5
    xgboost_dart_mode: bool = False
    uniform_drop: bool = False
    drop_seed: int = 4
    top_rate: float = 0.2
    other_rate: float = 0.1
    boost_from_average: bool = True
    # serial | feature | data | voting, plus the TPU addition "auto":
    # resolve the strategy (and hence which dataset dimension the device
    # mesh shards — rows vs features) from the training matrix's shape
    # class per the reference's Parallel-Learning-Guide table
    # (parallel/comm.py choose_tree_learner); tpu_mesh_axis overrides the
    # axis side of that choice
    tree_learner: str = "serial"

    # --- network (config.h:264-272) — mapped onto jax.distributed -----------
    num_machines: int = 1
    local_listen_port: int = 12400
    time_out: int = 120
    machine_list_file: str = ""
    machines: str = ""

    # --- TPU-specific knobs (no reference equivalent) -----------------------
    # mesh-axis override for tree_learner=auto: "rows" constrains the
    # resolution to the row-sharded strategies (data/voting), "features"
    # forces feature-parallel, "auto" lets the shape class decide. Ignored
    # (with a warning when inconsistent) when tree_learner is explicit.
    tpu_mesh_axis: str = "auto"
    # resume a checkpoint written on a DIFFERENT device count: off (the
    # default) rejects loudly at restore time — sharded state does not
    # silently re-layout; true re-shards the global training state onto
    # this booster's mesh deliberately (single-process only; pre-partitioned
    # snapshots never re-shard). See docs/Fault-Tolerance.md.
    tpu_reshard_on_resume: bool = False
    # leaf splits applied per device-side wave; 0 = auto (frontier-wide,
    # leaf-wise order preserved near the leaf budget), 1 = exact LightGBM
    # one-leaf-at-a-time growth.
    tpu_wave_size: int = 0
    # row-chunk length for the histogram one-hot matmul pass
    tpu_hist_chunk: int = 32768
    # accumulate g/h as bf16 hi+lo pairs (~f32 precision) vs plain bf16
    tpu_hist_hilo: bool = True
    # High-precision histogram accumulation: full-f32 weight columns
    # contracted at Precision.HIGHEST (exact products) + Kahan-compensated
    # chunk carry — the role of the reference's double HistogramBinEntry
    # (bin.h:29-31). Measured ~30x tighter bin sums vs the bf16 hi/lo
    # default (tests/test_hist_packing.py::test_hist_f64_precision). The
    # split SCAN still runs in f32, so near-tie node flips vs the reference
    # (test_tree_parity.py) are narrowed, not guaranteed closed. Forces the
    # xla kernel.
    tpu_hist_f64: bool = False
    # number of leaf slots whose histograms are built in one pass
    tpu_hist_slots: int = 0                   # 0 = auto
    # row compaction: each wave histograms only rows in pending leaves via a
    # prefix-compacted index gather (the analog of the reference's
    # smaller-leaf histogramming, serial_tree_learner.cpp:354-362)
    tpu_row_compact: bool = True
    tpu_compact_frac: float = 0.25            # compact passes below this
                                              # active-row fraction
    # incremental leaf partition (grower.py GrowState.perm — the reference's
    # DataPartition, data_partition.hpp:94): the slot-grouped row permutation
    # is maintained ACROSS waves by a cumsum-based stable counting-sort over
    # the split leaves' segments, so the wave body carries no full-N stable
    # argsort / [N,S] count reduction / slot table_lookup. false = the
    # legacy per-wave argsort rebuild (bit-identical — the A/B + parity pin,
    # tests/test_incremental_partition.py)
    tpu_incremental_partition: bool = True
    # LEGACY EFB scan arm: unpack bundle-space histograms into full
    # [T, F, B, 3] feature space before split finding and route rows
    # through the per-row bundle-decode gather — the pre-redesign layout
    # that measured 3.5x SLOWER on the round-5 Bosch-shaped sparse bench
    # (1.1 vs 3.8 Mrow-tree/s; docs/TPU-Performance.md). Kept as the A/B +
    # parity arm for the native bundle-space scan
    # (tests/test_efb_bundlespace.py); requires enable_bundle != false.
    tpu_efb_unpack: bool = False
    # --- out-of-core streaming (ops/stream.py, docs/TPU-Performance.md) ----
    # where the binned code matrix LIVES during training:
    #   device — fully HBM-resident (the historical behavior)
    #   stream — host-resident packed row shards, double-buffered H2D
    #            through the wave loop; gradients/scores/partition state
    #            stay on device. Bit-identical to device residency (which
    #            it forces tpu_row_compact=false to match), unlocks
    #            datasets far beyond HBM.
    #   auto   — stream iff the analytic HBM pre-flight estimate exceeds
    #            the per-device budget (tpu_hbm_budget_bytes or the
    #            reported device capacity), else device.
    tpu_residency: str = "auto"
    # rows per host shard PER DEVICE (rounded to a divisor of the padded
    # per-device row count that is a multiple of tpu_hist_chunk — shard
    # size never changes the math, so any value resumes any checkpoint);
    # 0 = auto (~8 shards)
    tpu_stream_shard_rows: int = 0
    # --- device-side ingest (ops/ingest.py, docs/TPU-Performance.md) -------
    # where raw float rows are BINNED into the packed code matrix:
    #   host   — the classical path: BinMapper.value_to_bin column loop on
    #            host, then one bulk H2D placement
    #   device — defer binning: raw f32 chunks stream H2D double-buffered
    #            and a jit kernel bins + packs in-trace, writing straight
    #            into the sharded residency buffers. BIT-identical to host
    #            binning (tests/test_ingest.py pins it) or it falls back
    #            with a logged reason (f32-lossy f64 input, sparse,
    #            oversized categoricals, stream residency, multi-process)
    #   auto   — device iff eligible AND num_data is large enough for the
    #            deferral to pay (dataset._AUTO_DEFER_MIN_ROWS)
    # checkpoint-VOLATILE: it changes WHERE binning runs, never the codes
    tpu_ingest: str = "auto"
    # raw rows per ingest chunk; 0 = auto (~64 MiB of f32 chunk + threshold
    # working set, clamped to [4096, 131072], rounded to a multiple of 256)
    tpu_ingest_chunk_rows: int = 0
    # ingest H2D prefetch depth (chunks in flight ahead of the bin kernel);
    # 0 disables overlap — the stall-accounting A/B arm of bench --ingest
    tpu_ingest_prefetch: int = 1
    # artificial per-device HBM budget in bytes for the residency auto-
    # decision and the engine.train budget line; 0 = use the capacity the
    # backend reports (env LGBM_TPU_HBM_BUDGET overrides both)
    tpu_hbm_budget_bytes: int = 0
    # histogram kernel: "auto" resolves to "mixed" (XLA streaming passes +
    # pallas-512 compacted passes — the round-5 pass-level measured best,
    # 18.0 vs 22.1 ms at 25% active) on a real TPU whose on-chip gate has
    # validated this kernel shape class, and to "xla" everywhere else; see
    # boosting/gbdt.py kernel-resolution block. "xla" one-hot matmul |
    # "pallas" fused VMEM-accumulator kernel (ops/pallas_histogram.py, the
    # OpenCL histogram256.cl analog) | "mixed" (pallas for compacted passes
    # only). Explicit pallas/mixed on a never-gated shape class runs with a
    # warning (exp/pallas_onchip_check.py records the trust markers)
    tpu_hist_kernel: str = "auto"
    # per-phase wall-clock accumulators (reference TIMETAG) printed after
    # training; tpu_profile_dir wraps training in a jax.profiler trace
    tpu_time_tag: bool = False
    tpu_profile_dir: str = ""
    # jax.profiler capture WINDOW "start:stop" over boosting iterations
    # (batch-boundary aligned under tree_batch) — the deep-profiling leg of
    # the telemetry contract; output under tpu_profile_dir, or
    # <telemetry_dir>/xprof when only telemetry_dir is set. See
    # docs/Observability.md.
    tpu_profile_iters: str = ""

    # --- observability (lightgbm_tpu/observability, docs/Observability.md) --
    # telemetry output directory: JSONL event stream (events_<pid>.jsonl) +
    # Perfetto-loadable Chrome trace (trace_<pid>.json). Also settable via
    # env LGBM_TPU_TELEMETRY_DIR; empty + no env = span recording disabled
    # (the metrics registry is always live)
    telemetry_dir: str = ""
    # compile-time cost capture (observability/costs.py): lower+compile each
    # dispatch site once with the live arguments and publish
    # cost_analysis()/memory_analysis() — FLOPs, bytes accessed, argument/
    # temp HBM — as cost.<site>.* gauges, into snapshot(), and as Perfetto
    # trace metadata. Off by default (it duplicates trace work and, without
    # the persistent compile cache, the XLA compile); env
    # LGBM_TPU_COST_ANALYSIS=1 also enables. bench.py --smoke runs with it
    # on and pins the fused step's FLOPs/bytes to golden values.
    tpu_cost_analysis: bool = False
    # write observability.snapshot() (counters/gauges/histograms + cost and
    # memory reports) to this JSON file at train end; "" = off — but with
    # telemetry_dir set a snapshot_<pid>.json always lands there. CLI:
    # --dump-snapshot[=FILE].
    dump_snapshot: str = ""
    # boosting iterations fused into ONE jit dispatch via lax.scan (built-in
    # objectives only): score updates, tree growth, and leaf application for
    # K trees never leave HBM, and the host loop pays dispatch + sync cost
    # once per K trees instead of per tree. Metric eval, callbacks, and
    # checkpoints land on batch boundaries; dart/goss and custom objectives
    # fall back to 1 (loudly). See docs/TPU-Performance.md.
    tree_batch: int = 1

    # --- serving (lightgbm_tpu/serving, docs/Serving.md) --------------------
    # largest rows-per-dispatch the serving engine compiles for; also the
    # micro-batcher's coalescing budget and the top of the auto bucket
    # ladder. Requests beyond it are chunked.
    serve_max_batch_rows: int = 4096
    # micro-batcher coalescing window: a queued request waits at most this
    # long past its arrival for companions before dispatching
    serve_max_wait_ms: float = 2.0
    # batch-size bucket ladder (comma list, strictly ascending) the engine
    # AOT-compiles and pads requests into; "" = powers of two
    # 1,2,4,...,serve_max_batch_rows (padding never exceeds 2x)
    serve_buckets: str = ""
    # --- serving resilience (serving/resilience.py, docs/Serving.md) --------
    # admission bound: rows the micro-batcher queue may hold; a request
    # that would overflow it is SHED with ServerOverloadedError instead of
    # queued (0 = unbounded — the pre-resilience behavior)
    serve_max_queue_rows: int = 32768
    # default per-request deadline: past it a queued request is dropped at
    # dequeue (never dispatched) and a waiting caller unblocks, both with
    # DeadlineExceededError; 0 = no deadline. Per-call deadline_ms wins.
    serve_deadline_ms: float = 0.0
    # circuit breaker: this many device-dispatch failures inside
    # serve_breaker_window_s trip the engine to `degraded` (host-predictor
    # fallback, bit-identical answers) until the device probe succeeds;
    # 0 disables the breaker
    serve_breaker_failures: int = 5
    serve_breaker_window_s: float = 30.0
    # seconds between background device re-warm probes while degraded
    serve_probe_interval_s: float = 1.0

    # --- fault tolerance (robustness/, docs/Fault-Tolerance.md) -------------
    # directory of atomic booster snapshots (ckpt_<id>.pkl); empty = off
    checkpoint_dir: str = ""
    # save a snapshot every N iterations during train() (0 = only on demand)
    checkpoint_interval: int = 0
    # snapshots retained after each save (0 = keep everything)
    checkpoint_keep_last_n: int = 3
    # checkpoint file/dir to resume from; "auto" = latest in checkpoint_dir
    # if any exist, else start fresh (the preemption-restart idiom: rerun
    # the identical command line)
    resume_from: str = ""
    # non-finite gradient/hessian/leaf-output guard compiled into the
    # training step: none (off) | raise | skip_iter | clip
    nan_policy: str = "none"
    # --- self-healing (robustness/watchdog.py, robustness/supervisor.py) ----
    # hang watchdog: fire when no dispatch boundary is seen for
    # max(hang_timeout_s, hang_median_factor * trailing-median iteration
    # time). 0 = watchdog off (the default).
    hang_timeout_s: float = 0.0
    # adaptive multiple of the trailing median iteration time (0 = fixed
    # hang_timeout_s only)
    hang_median_factor: float = 8.0
    # on firing: "dump" writes the diagnostic snapshot (thread stacks +
    # observability.snapshot()) and keeps waiting; "abort" additionally
    # exits 142 so a supervisor restarts from the last checkpoint
    hang_action: str = "dump"
    # verify each host code shard's CRC32 before its H2D transfer under
    # tpu_residency=stream (ops/stream.py); detected corruption raises
    # ShardCorruptionError (CLI exit 144) instead of training on rot
    tpu_stream_verify: bool = True
    # --- distributed fault tolerance (robustness/distributed.py) ------------
    # seconds between per-rank heartbeat-lease writes to the coordination-
    # service KV store (beaten at the same dispatch boundaries the hang
    # watchdog uses); also rate-limits the pre-wave liveness probe
    gang_heartbeat_interval_s: float = 2.0
    # a peer whose lease has not advanced for this long (by the OBSERVER's
    # monotonic clock — cross-host clock skew is irrelevant) is declared
    # lost: typed PeerLostError naming the rank, exit 145 at top level.
    # 0 = peer failure detection off.
    gang_lease_timeout_s: float = 30.0
    # permit resume on a DIFFERENT world size than the gang checkpoint
    # manifest records (the fleet supervisor's shrink path; pair with
    # tpu_reshard_on_resume for the device re-layout). Off = loud refusal.
    elastic: bool = False

    def __post_init__(self):
        self._check()

    # -- construction --------------------------------------------------------

    @classmethod
    def from_params(cls, params: Optional[Dict[str, Any]] = None, **kwargs) -> "Config":
        """Build a Config from a LightGBM-style parameter dict (aliases ok)."""
        merged = dict(params or {})
        merged.update(kwargs)
        resolved = resolve_aliases(merged)
        return cls(**_coerce_fields(resolved))

    @classmethod
    def from_conf_file(cls, path: str, overrides: Optional[Dict[str, Any]] = None) -> "Config":
        """Parse a reference-style `train.conf` (application.cpp:48-81)."""
        params = parse_conf_file(path)
        params.update(overrides or {})
        return cls.from_params(params)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def replace(self, **kwargs) -> "Config":
        resolved = resolve_aliases(kwargs)
        return dataclasses.replace(self, **_coerce_fields(resolved))

    # -- validation ----------------------------------------------------------

    def _check(self) -> None:
        """Parameter conflict checks (reference: OverallConfig::CheckParamConflict)."""
        if self.num_leaves < 2:
            Log.fatal("num_leaves must be >= 2, got %d", self.num_leaves)
        if self.max_bin < 2:
            Log.fatal("max_bin must be >= 2, got %d", self.max_bin)
        if not 0.0 < self.feature_fraction <= 1.0:
            Log.fatal("feature_fraction must be in (0, 1], got %g", self.feature_fraction)
        if not 0.0 < self.bagging_fraction <= 1.0:
            Log.fatal("bagging_fraction must be in (0, 1], got %g", self.bagging_fraction)
        if self.boosting_type not in ("gbdt", "gbrt", "dart", "goss", "rf", "random_forest"):
            Log.fatal("Unknown boosting type %s", self.boosting_type)
        if self.tree_learner not in ("serial", "feature", "data", "voting",
                                     "auto"):
            Log.fatal("Unknown tree learner type %s", self.tree_learner)
        if self.tpu_mesh_axis not in ("auto", "rows", "features"):
            Log.fatal("Unknown tpu_mesh_axis %s (auto|rows|features)",
                      self.tpu_mesh_axis)
        if self.tpu_mesh_axis != "auto" and self.tree_learner not in \
                ("auto", "serial"):
            expected = "features" if self.tree_learner == "feature" else "rows"
            if self.tpu_mesh_axis != expected:
                Log.warning("tpu_mesh_axis=%s is ignored: tree_learner=%s "
                            "shards the %s axis by definition (the knob only "
                            "constrains tree_learner=auto)",
                            self.tpu_mesh_axis, self.tree_learner, expected)
        # enable_bundle is a tri-state: bools and their string spellings
        # normalize onto "true"/"false", everything else must be "auto"
        eb = str(self.enable_bundle).lower()
        if eb in ("true", "+", "1"):
            eb = "true"
        elif eb in ("false", "-", "0"):
            eb = "false"
        if eb not in ("auto", "true", "false"):
            Log.fatal('Parameter enable_bundle should be "auto", "true" or '
                      '"false", got "%s"', self.enable_bundle)
        self.enable_bundle = eb
        if not 0.0 <= self.max_conflict_rate < 1.0:
            # the conflict budget is a row FRACTION (reference
            # max_conflict_rate, dataset.cpp:152): 1.0+ would admit bundles
            # whose members collide on every sampled row, and negative
            # values silently disable bundling through an int() truncation
            Log.fatal("max_conflict_rate must be in [0, 1), got %g",
                      self.max_conflict_rate)
        if self.tpu_efb_unpack and self.enable_bundle == "false":
            # reject loudly instead of silently ignoring the knob: the
            # legacy unpack arm only exists as the A/B + parity arm OF
            # bundling — asking for it with bundling off is a contradiction
            Log.fatal("tpu_efb_unpack=true requires enable_bundle=auto|true "
                      "(the unpack arm is the legacy layout OF bundling; "
                      "with enable_bundle=false there is nothing to unpack)")
        if self.tpu_hist_kernel not in ("auto", "xla", "pallas", "mixed"):
            Log.fatal("Unknown tpu_hist_kernel %s (auto|xla|pallas|mixed)",
                      self.tpu_hist_kernel)
        if self.tpu_residency not in ("auto", "device", "stream"):
            Log.fatal("Unknown tpu_residency %s (auto|device|stream)",
                      self.tpu_residency)
        if self.tpu_stream_shard_rows < 0:
            Log.fatal("tpu_stream_shard_rows must be >= 0 (0 = auto), got %d",
                      self.tpu_stream_shard_rows)
        if self.tpu_ingest not in ("auto", "host", "device"):
            Log.fatal("Unknown tpu_ingest %s (auto|host|device)",
                      self.tpu_ingest)
        if self.tpu_ingest_chunk_rows < 0:
            Log.fatal("tpu_ingest_chunk_rows must be >= 0 (0 = auto), got %d",
                      self.tpu_ingest_chunk_rows)
        if self.tpu_ingest_prefetch < 0:
            Log.fatal("tpu_ingest_prefetch must be >= 0 (0 = no overlap), "
                      "got %d", self.tpu_ingest_prefetch)
        if self.tpu_hbm_budget_bytes < 0:
            Log.fatal("tpu_hbm_budget_bytes must be >= 0 (0 = device "
                      "capacity), got %d", self.tpu_hbm_budget_bytes)
        if not 0.0 < self.tpu_compact_frac <= 1.0:
            # <=0 silently disables compaction; >1 forces the argsort+gather
            # path on every pass (n_active < frac*N is always true)
            Log.fatal("tpu_compact_frac must be in (0, 1], got %g — values "
                      "<= 0 disable row compaction entirely and values > 1 "
                      "force the compacted argsort+gather path on every "
                      "histogram pass", self.tpu_compact_frac)
        if self.tree_batch < 1:
            Log.fatal("tree_batch must be >= 1, got %d", self.tree_batch)
        if self.boosting_type in ("rf", "random_forest"):
            # reference: rf.hpp:18-29 — bagging is mandatory for random forest
            if not (self.bagging_freq > 0 and self.bagging_fraction < 1.0):
                Log.fatal("Random forest needs bagging_freq > 0 and bagging_fraction < 1.0")
        if self.objective in ("multiclass", "multiclassova", "softmax", "ova") and self.num_class <= 1:
            Log.fatal("Number of classes should be > 1 for multiclass training")
        if self.top_rate + self.other_rate > 1.0:
            Log.fatal("top_rate + other_rate cannot be larger than 1.0 for GOSS")
        if self.serve_max_batch_rows < 1:
            Log.fatal("serve_max_batch_rows must be >= 1, got %d",
                      self.serve_max_batch_rows)
        if self.serve_max_wait_ms < 0:
            Log.fatal("serve_max_wait_ms must be >= 0, got %g",
                      self.serve_max_wait_ms)
        if self.serve_buckets:
            try:
                ladder = [int(v) for v in
                          str(self.serve_buckets).split(",") if v]
            except ValueError:
                ladder = []
            if not ladder or any(b < 1 for b in ladder) or \
                    any(b >= c for b, c in zip(ladder, ladder[1:])):
                Log.fatal("serve_buckets must be a comma list of strictly "
                          "ascending positive ints, got %r",
                          self.serve_buckets)
            elif ladder[-1] > self.serve_max_batch_rows:
                Log.fatal("serve_buckets top entry %d exceeds "
                          "serve_max_batch_rows=%d (the largest "
                          "rows-per-dispatch the engine compiles for)",
                          ladder[-1], self.serve_max_batch_rows)
        if self.serve_max_queue_rows < 0:
            Log.fatal("serve_max_queue_rows must be >= 0 (0 = unbounded), "
                      "got %d", self.serve_max_queue_rows)
        if self.serve_deadline_ms < 0:
            Log.fatal("serve_deadline_ms must be >= 0 (0 = no deadline), "
                      "got %g", self.serve_deadline_ms)
        if self.serve_breaker_failures < 0:
            Log.fatal("serve_breaker_failures must be >= 0 (0 = breaker "
                      "off), got %d", self.serve_breaker_failures)
        if self.serve_breaker_window_s <= 0:
            Log.fatal("serve_breaker_window_s must be > 0, got %g",
                      self.serve_breaker_window_s)
        if self.serve_probe_interval_s <= 0:
            Log.fatal("serve_probe_interval_s must be > 0, got %g",
                      self.serve_probe_interval_s)
        if self.nan_policy not in ("none", "raise", "skip_iter", "clip"):
            Log.fatal("Unknown nan_policy %s (none|raise|skip_iter|clip)",
                      self.nan_policy)
        if self.checkpoint_interval < 0:
            Log.fatal("checkpoint_interval must be >= 0, got %d",
                      self.checkpoint_interval)
        if self.checkpoint_keep_last_n < 0:
            Log.fatal("checkpoint_keep_last_n must be >= 0, got %d",
                      self.checkpoint_keep_last_n)
        if self.checkpoint_interval > 0 and not self.checkpoint_dir:
            Log.fatal("checkpoint_interval=%d needs checkpoint_dir to be set",
                      self.checkpoint_interval)
        if self.hang_timeout_s < 0:
            Log.fatal("hang_timeout_s must be >= 0 (0 = watchdog off), "
                      "got %g", self.hang_timeout_s)
        if self.hang_median_factor < 0:
            Log.fatal("hang_median_factor must be >= 0 (0 = fixed timeout "
                      "only), got %g", self.hang_median_factor)
        if self.hang_action not in ("dump", "abort"):
            Log.fatal("Unknown hang_action %s (dump|abort)", self.hang_action)
        if self.gang_heartbeat_interval_s < 0:
            Log.fatal("gang_heartbeat_interval_s must be >= 0, got %g",
                      self.gang_heartbeat_interval_s)
        if self.gang_lease_timeout_s < 0:
            Log.fatal("gang_lease_timeout_s must be >= 0 (0 = peer failure "
                      "detection off), got %g", self.gang_lease_timeout_s)
        if 0 < self.gang_lease_timeout_s <= self.gang_heartbeat_interval_s:
            # a lease shorter than the beat cadence declares every healthy
            # peer dead between two writes
            Log.fatal("gang_lease_timeout_s (%g) must exceed "
                      "gang_heartbeat_interval_s (%g)",
                      self.gang_lease_timeout_s,
                      self.gang_heartbeat_interval_s)
        if self.tpu_profile_iters:
            from .observability.profiler import parse_profile_iters
            try:
                parse_profile_iters(self.tpu_profile_iters)
            except ValueError as e:
                Log.fatal("%s", e)
        if self.linear_lambda < 0:
            Log.fatal("linear_lambda must be >= 0, got %g", self.linear_lambda)
        if self.linear_max_features < 1:
            Log.fatal("linear_max_features must be >= 1, got %d",
                      self.linear_max_features)
        if self.linear_tree and self.boosting_normalized in ("dart", "rf"):
            # dart replays/subtracts dropped trees through the constant-leaf
            # table path and rf transforms leaf outputs through the
            # objective — neither composes with per-leaf linear models;
            # reject at config time, never train silently-wrong coefficients
            Log.fatal("linear_tree=true is not supported with boosting=%s "
                      "(use gbdt or goss)", self.boosting_type)
        if self.linear_tree and self.tpu_residency == "stream":
            Log.fatal("linear_tree=true needs the raw feature slice "
                      "device-resident and is not supported with "
                      "tpu_residency=stream (use device)")
        if self.boosting_normalized == "dart" and (self.checkpoint_dir
                                                   or self.resume_from):
            # reject at config time, not at the first save: otherwise the
            # interval/SIGTERM checkpoint machinery kills a dart run mid-
            # flight instead of protecting it (host-side drop state is not
            # captured by checkpoints)
            Log.fatal("checkpoint/resume (checkpoint_dir/resume_from) is "
                      "not supported with boosting=dart")

    # -- derived -------------------------------------------------------------

    @property
    def max_leaves_by_depth(self) -> int:
        """max_depth caps leaves at 2**max_depth (config.h:216-219)."""
        if self.max_depth > 0:
            return min(self.num_leaves, 2 ** self.max_depth)
        return self.num_leaves

    @property
    def boosting_normalized(self) -> str:
        return {"gbrt": "gbdt", "random_forest": "rf"}.get(self.boosting_type, self.boosting_type)


_FIELD_TYPES = {f.name: f.type for f in dataclasses.fields(Config)}
_LIST_INT_FIELDS = {"ndcg_eval_at"}
_LIST_FLOAT_FIELDS = {"label_gain"}
_LIST_STR_FIELDS = {"valid_data", "valid_init_score_file", "metric"}
_KNOWN_DROPPED = {"config_file", "machine_list_filename"}  # handled out-of-band


def resolve_aliases(params: Dict[str, Any]) -> Dict[str, Any]:
    """Apply the alias table with the reference's priority rule.

    When multiple aliases of one parameter appear, the one with the longest
    name wins; ties break alphabetically (config.h:479-513). A canonical name
    always beats its aliases.
    """
    out: Dict[str, Any] = {}
    alias_source: Dict[str, str] = {}
    canonical_names = set(_FIELD_TYPES)
    for key, value in params.items():
        canon = PARAMETER_ALIASES.get(key)
        if canon is None:
            if key in canonical_names:
                out[key] = value
            elif key in _KNOWN_DROPPED:
                continue
            else:
                Log.warning("Unknown parameter: %s", key)
            continue
        prev = alias_source.get(canon)
        if prev is None or (len(key), key) > (len(prev), prev):
            alias_source[canon] = key
            if canon not in params:  # canonical name in input always wins
                out[canon] = value
        if prev is not None:
            Log.warning("%s is set by aliases %s and %s; using %s", canon, prev, key,
                        alias_source[canon])
    return out


def _coerce_fields(params: Dict[str, Any]) -> Dict[str, Any]:
    """Coerce string values (from conf files / CLI) to field types."""
    out: Dict[str, Any] = {}
    for name, value in params.items():
        if name in _LIST_INT_FIELDS:
            out[name] = _parse_int_list(value)
        elif name in _LIST_FLOAT_FIELDS:
            out[name] = _parse_float_list(value)
        elif name in _LIST_STR_FIELDS:
            out[name] = _parse_str_list(value)
        else:
            ftype = str(_FIELD_TYPES.get(name, "str"))
            if "bool" in ftype:
                out[name] = _parse_bool(value, name)
            elif "int" in ftype:
                out[name] = int(float(value)) if not isinstance(value, int) else value
            elif "float" in ftype:
                out[name] = float(value)
            else:
                out[name] = str(value)
    return out


def parse_conf_file(path: str) -> Dict[str, str]:
    """Parse `key = value` lines, `#` comments (application.cpp:60-77)."""
    params: Dict[str, str] = {}
    with open(path, "r") as fh:
        for line in fh:
            line = line.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            key, value = line.split("=", 1)
            params[key.strip()] = value.strip()
    return params
