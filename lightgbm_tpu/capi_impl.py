"""Python side of the C API (reference: src/c_api.cpp, 1,448 LoC).

The native shim (capi/lgbm_capi.c) exposes the reference's ``LGBM_*``
symbols and proxies every call here. The split keeps the C layer to
argument forwarding: buffers cross the boundary as raw addresses
(int64) + dtype codes, and this module views them with numpy/ctypes —
zero-copy in, explicit memcpy out. Handles given to C are small integers
into a registry (no PyObject lifetime crosses the boundary).

Matches c_api.h semantics: C_API_DTYPE_* codes (c_api.h:22-25),
C_API_PREDICT_* (c_api.h:27-30), 0/-1 return codes with
LGBM_GetLastError() carrying the message.
"""
from __future__ import annotations

import ctypes
import json
from typing import Dict, Optional

import numpy as np

from .basic import Booster, Dataset
from .config import resolve_aliases

# ---- handle registry -------------------------------------------------------

_objects: Dict[int, object] = {}
_next_handle = [1]


def _register(obj) -> int:
    h = _next_handle[0]
    _next_handle[0] += 1
    _objects[h] = obj
    return h


def _get(h: int):
    return _objects[int(h)]


def free_handle(h: int) -> None:
    _objects.pop(int(h), None)


# ---- raw-memory views ------------------------------------------------------

_DTYPES = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64}


def _view(ptr: int, dtype_code: int, count: int) -> np.ndarray:
    ct = {0: ctypes.c_float, 1: ctypes.c_double,
          2: ctypes.c_int32, 3: ctypes.c_int64}[int(dtype_code)]
    buf = (ct * int(count)).from_address(int(ptr))
    return np.ctypeslib.as_array(buf)


def _write_doubles(ptr: int, values: np.ndarray) -> int:
    arr = np.ascontiguousarray(values, dtype=np.float64)
    ctypes.memmove(int(ptr), arr.ctypes.data, arr.nbytes)
    return arr.size


def _write_string(ptr: int, text: str, buffer_len: int) -> int:
    """Reference out_len contract (c_api.cpp SaveModelToString): report
    len+1 (including NUL) and copy ONLY when the whole string fits, so the
    two-call size-then-fetch protocol never truncates silently."""
    raw = text.encode("utf-8") + b"\0"
    if len(raw) <= int(buffer_len):
        ctypes.memmove(int(ptr), raw, len(raw))
    return len(raw)


def _write_string_array(ptrs_addr: int, strings, each_len: int = 255) -> int:
    """Fill a char** (preallocated buffers, reference basic.py convention)."""
    arr = (ctypes.c_void_p * len(strings)).from_address(int(ptrs_addr))
    for i, s in enumerate(strings):
        raw = s.encode("utf-8")[: each_len - 1] + b"\0"
        ctypes.memmove(arr[i], raw, len(raw))
    return len(strings)


def _params(parameters: Optional[str]) -> dict:
    out = {}
    for tok in (parameters or "").replace("\n", " ").split():
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k] = v
    return resolve_aliases(out)


# ---- dataset ---------------------------------------------------------------

def dataset_create_from_file(filename: str, parameters: str,
                             reference: int) -> int:
    params = _params(parameters)
    ref = _get(reference) if reference else None
    ds = Dataset(filename, params=params, reference=ref)
    ds.construct()
    return _register(ds)


def dataset_create_from_mat(data_ptr: int, data_type: int, nrow: int,
                            ncol: int, is_row_major: int, parameters: str,
                            reference: int) -> int:
    flat = _view(data_ptr, data_type, nrow * ncol)
    mat = flat.reshape(nrow, ncol) if is_row_major else \
        flat.reshape(ncol, nrow).T
    params = _params(parameters)
    ref = _get(reference) if reference else None
    ds = Dataset(np.array(mat, dtype=np.float64), params=params, reference=ref)
    return _register(ds)


def dataset_create_from_csr(indptr_ptr: int, indptr_type: int,
                            indices_ptr: int, data_ptr: int, data_type: int,
                            nindptr: int, nelem: int, num_col: int,
                            parameters: str, reference: int) -> int:
    import scipy.sparse as sp
    indptr = _view(indptr_ptr, indptr_type, nindptr).astype(np.int64)
    indices = _view(indices_ptr, 2, nelem)
    data = _view(data_ptr, data_type, nelem)
    csr = sp.csr_matrix((np.array(data, np.float64), np.array(indices),
                         np.array(indptr)), shape=(nindptr - 1, num_col))
    ref = _get(reference) if reference else None
    ds = Dataset(csr, params=_params(parameters), reference=ref)
    return _register(ds)


def dataset_create_from_csc(colptr_ptr: int, colptr_type: int,
                            indices_ptr: int, data_ptr: int, data_type: int,
                            ncolptr: int, nelem: int, num_row: int,
                            parameters: str, reference: int) -> int:
    import scipy.sparse as sp
    colptr = _view(colptr_ptr, colptr_type, ncolptr).astype(np.int64)
    indices = _view(indices_ptr, 2, nelem)
    data = _view(data_ptr, data_type, nelem)
    csc = sp.csc_matrix((np.array(data, np.float64), np.array(indices),
                         np.array(colptr)), shape=(num_row, ncolptr - 1))
    ds = Dataset(csc, params=_params(parameters),
                 reference=_get(reference) if reference else None)
    return _register(ds)


def dataset_get_subset(handle: int, indices_ptr: int, num_indices: int,
                       parameters: str) -> int:
    ds: Dataset = _get(handle)
    idx = np.array(_view(indices_ptr, 2, num_indices))
    return _register(ds.subset(idx, params=_params(parameters)))


def dataset_set_feature_names(handle: int, names) -> None:
    _get(handle).feature_name = list(names)


def dataset_get_feature_names(handle: int, ptrs_addr: int) -> int:
    ds: Dataset = _get(handle)
    names = ds.feature_name if isinstance(ds.feature_name, list) else \
        [f"Column_{i}" for i in range(ds.num_feature())]
    return _write_string_array(ptrs_addr, names)


def dataset_save_binary(handle: int, filename: str) -> None:
    ds: Dataset = _get(handle)
    ds.construct()
    ds._constructed.save_binary(filename)


def dataset_set_field(handle: int, field: str, ptr: int, n: int,
                      dtype_code: int) -> None:
    ds: Dataset = _get(handle)
    arr = np.array(_view(ptr, dtype_code, n))
    if field == "label":
        ds.set_label(arr.astype(np.float32))
    elif field == "weight":
        ds.set_weight(arr.astype(np.float32))
    elif field in ("group", "query"):
        ds.set_group(arr.astype(np.int32))
    elif field == "init_score":
        ds.set_init_score(arr.astype(np.float64))
    else:
        raise ValueError(f"unknown field {field}")


def dataset_get_field(handle: int, field: str, out_ptr_addr: int,
                      out_type_addr: int) -> int:
    """Returns length; writes the array pointer + dtype code like
    LGBM_DatasetGetField (c_api.cpp). The array is kept alive on the
    dataset object."""
    ds: Dataset = _get(handle)
    val = ds.get_field(field)
    if val is None:
        return 0
    if field in ("group", "query"):
        arr = np.ascontiguousarray(val, dtype=np.int32)
        code = 2
    else:
        arr = np.ascontiguousarray(val, dtype=np.float32)
        code = 0
    if not hasattr(ds, "_capi_field_refs"):
        ds._capi_field_refs = {}
    ds._capi_field_refs[field] = arr            # keep buffer alive
    ctypes.c_void_p.from_address(int(out_ptr_addr)).value = arr.ctypes.data
    ctypes.c_int32.from_address(int(out_type_addr)).value = code
    return arr.size


def dataset_get_num_data(handle: int) -> int:
    return int(_get(handle).num_data())


def dataset_get_num_feature(handle: int) -> int:
    return int(_get(handle).num_feature())


# ---- booster ---------------------------------------------------------------

def booster_create(train_handle: int, parameters: str) -> int:
    bst = Booster(params=_params(parameters), train_set=_get(train_handle))
    return _register(bst)


def booster_create_from_modelfile(filename: str) -> int:
    return _register(Booster(model_file=filename))


def booster_load_from_string(model_str: str) -> int:
    return _register(Booster(model_str=model_str))


def booster_add_valid_data(handle: int, valid_handle: int) -> None:
    bst: Booster = _get(handle)
    vs: Dataset = _get(valid_handle)
    if vs.reference is None:
        vs.reference = bst.train_dataset
    bst.add_valid(vs, f"valid_{len(getattr(bst._gbdt, 'valid_sets', []))}")


def booster_reset_training_data(handle: int, train_handle: int) -> None:
    bst: Booster = _get(handle)
    # update(train_set=...) swaps the data AND trains one iteration;
    # rollback_one_iter fully reverts that extra iteration (trees + score),
    # matching LGBM_BoosterResetTrainingData's swap-only contract
    bst.update(train_set=_get(train_handle))
    bst.rollback_one_iter()


def booster_reset_parameter(handle: int, parameters: str) -> None:
    _get(handle).reset_parameter(_params(parameters))


def booster_get_num_classes(handle: int) -> int:
    return max(int(_get(handle).params.get("num_class", 1)), 1)


def booster_update_one_iter(handle: int) -> int:
    bst: Booster = _get(handle)
    before = bst._gbdt.iter_
    bst.update()
    return 1 if bst._gbdt.iter_ == before else 0   # is_finished


def dataset_get_num_data_of_booster(handle: int) -> int:
    """Gradient length for LGBM_BoosterUpdateOneIterCustom: num_data *
    num_models (class-major, reference c_api.cpp UpdateOneIterCustom)."""
    bst: Booster = _get(handle)
    return int(bst.train_dataset.num_data()
               * max(bst.num_model_per_iteration, 1))


def booster_update_one_iter_custom(handle: int, grad_ptr: int, hess_ptr: int,
                                   n: int) -> int:
    bst: Booster = _get(handle)
    g = np.array(_view(grad_ptr, 0, n), np.float64)
    h = np.array(_view(hess_ptr, 0, n), np.float64)
    bst.update(fobj=lambda preds, ds: (g, h))
    return 0


def booster_rollback_one_iter(handle: int) -> None:
    _get(handle).rollback_one_iter()


def _sync(bst: Booster) -> Booster:
    """Materialize host trees from device state — the C API drives raw
    update() calls, so predict/save/dump must see the current forest
    (engine.train does this once at the end; here it's lazy per call)."""
    gbdt = bst._gbdt
    if gbdt is not None:
        K = max(bst.num_model_per_iteration, 1)
        expected = len(getattr(bst, "_prev_trees", [])) + gbdt.iter_ * K
        if len(bst.trees) != expected:
            bst._finalize()
    return bst


def booster_get_current_iteration(handle: int) -> int:
    bst: Booster = _get(handle)
    if bst._gbdt is not None:
        return int(bst._gbdt.iter_)
    return int(bst.current_iteration())


def _metric_names(bst: Booster):
    """Per-dataset metric names — the c_api contract counts METRICS, not
    (dataset, metric) pairs (c_api.h GetEvalCounts/GetEvalNames)."""
    gbdt = bst._gbdt
    if gbdt is None:
        return []
    metrics = gbdt.valid_sets[0].metrics if gbdt.valid_sets else \
        getattr(gbdt, "train_metrics", [])
    return [m.name for m in metrics]


def booster_get_eval_counts(handle: int) -> int:
    return len(_metric_names(_get(handle)))


def booster_get_eval_names(handle: int, ptrs_addr: int) -> int:
    return _write_string_array(ptrs_addr, _metric_names(_get(handle)))


def booster_get_eval(handle: int, data_idx: int, out_ptr: int) -> int:
    """data_idx 0 = training, i+1 = i-th valid set (c_api.h:474)."""
    bst: Booster = _get(handle)
    gbdt = bst._gbdt
    rows = gbdt.eval_all()
    names = {0: "training"}
    for i, vs in enumerate(gbdt.valid_sets):
        names[i + 1] = vs.name
    want = names.get(int(data_idx))
    vals = [v for (d, _m, v, _h) in rows if d == want]
    return _write_doubles(out_ptr, np.array(vals, np.float64))


def booster_get_feature_names(handle: int, ptrs_addr: int) -> int:
    return _write_string_array(ptrs_addr, _get(handle).feature_name())


def booster_get_num_feature(handle: int) -> int:
    return int(_get(handle).num_total_features)


def booster_calc_num_predict(handle: int, num_row: int, predict_type: int,
                             num_iteration: int) -> int:
    bst: Booster = _sync(_get(handle))
    K = max(bst.num_model_per_iteration, 1)
    n_iter = bst.current_iteration() if num_iteration <= 0 else \
        min(num_iteration, bst.current_iteration())
    if predict_type == 2:       # leaf index
        return num_row * K * n_iter
    if predict_type == 3:       # contrib
        return num_row * K * (bst.num_total_features + 1)
    return num_row * K


def _predict(bst: Booster, X, predict_type: int, num_iteration: int,
             parameter: str, out_ptr: int) -> int:
    _sync(bst)
    kw = {}
    p = _params(parameter)
    if "pred_early_stop" in p:
        kw["pred_early_stop"] = p["pred_early_stop"] in ("1", "true")
    preds = bst.predict(
        X, num_iteration=num_iteration if num_iteration > 0 else None,
        raw_score=predict_type == 1, pred_leaf=predict_type == 2,
        pred_contrib=predict_type == 3, **kw)
    return _write_doubles(out_ptr, np.asarray(preds, np.float64))


def booster_predict_for_mat(handle: int, data_ptr: int, data_type: int,
                            nrow: int, ncol: int, is_row_major: int,
                            predict_type: int, num_iteration: int,
                            parameter: str, out_ptr: int) -> int:
    flat = _view(data_ptr, data_type, nrow * ncol)
    X = flat.reshape(nrow, ncol) if is_row_major else flat.reshape(ncol, nrow).T
    return _predict(_get(handle), np.array(X, np.float64), predict_type,
                    num_iteration, parameter, out_ptr)


def booster_predict_for_csr(handle: int, indptr_ptr: int, indptr_type: int,
                            indices_ptr: int, data_ptr: int, data_type: int,
                            nindptr: int, nelem: int, num_col: int,
                            predict_type: int, num_iteration: int,
                            parameter: str, out_ptr: int) -> int:
    import scipy.sparse as sp
    indptr = _view(indptr_ptr, indptr_type, nindptr).astype(np.int64)
    indices = _view(indices_ptr, 2, nelem)
    data = _view(data_ptr, data_type, nelem)
    csr = sp.csr_matrix((np.array(data, np.float64), np.array(indices),
                         np.array(indptr)), shape=(nindptr - 1, num_col))
    return _predict(_get(handle), csr, predict_type, num_iteration,
                    parameter, out_ptr)


def booster_predict_for_csc(handle: int, colptr_ptr: int, colptr_type: int,
                            indices_ptr: int, data_ptr: int, data_type: int,
                            ncolptr: int, nelem: int, num_row: int,
                            predict_type: int, num_iteration: int,
                            parameter: str, out_ptr: int) -> int:
    import scipy.sparse as sp
    colptr = _view(colptr_ptr, colptr_type, ncolptr).astype(np.int64)
    indices = _view(indices_ptr, 2, nelem)
    data = _view(data_ptr, data_type, nelem)
    csc = sp.csc_matrix((np.array(data, np.float64), np.array(indices),
                         np.array(colptr)), shape=(num_row, ncolptr - 1))
    return _predict(_get(handle), csc.tocsr(), predict_type, num_iteration,
                    parameter, out_ptr)


def booster_predict_for_file(handle: int, data_filename: str,
                             data_has_header: int, predict_type: int,
                             num_iteration: int, parameter: str,
                             result_filename: str) -> None:
    from .io.file_io import load_data_file
    p = _params(parameter)
    if data_has_header:
        p["has_header"] = "true"
    X, _, _ = load_data_file(data_filename, p)
    bst: Booster = _sync(_get(handle))
    preds = bst.predict(
        X, num_iteration=num_iteration if num_iteration > 0 else None,
        raw_score=predict_type == 1, pred_leaf=predict_type == 2,
        pred_contrib=predict_type == 3)
    preds = np.atleast_2d(preds.T).T if preds.ndim == 1 else preds
    with open(result_filename, "w") as fh:
        for row in (preds if preds.ndim == 2 else preds[:, None]):
            fh.write("\t".join(f"{v:.18g}" for v in np.atleast_1d(row)) + "\n")


def booster_save_model(handle: int, num_iteration: int, filename: str) -> None:
    _sync(_get(handle)).save_model(filename,
                            num_iteration if num_iteration > 0 else None)


def booster_save_model_to_string(handle: int, num_iteration: int,
                                 buffer_len: int, out_ptr: int) -> int:
    text = _sync(_get(handle)).model_to_string(
        num_iteration if num_iteration > 0 else None)
    return _write_string(out_ptr, text, buffer_len)


def booster_dump_model(handle: int, num_iteration: int, buffer_len: int,
                       out_ptr: int) -> int:
    d = _sync(_get(handle)).dump_model(num_iteration if num_iteration > 0 else None)
    return _write_string(out_ptr, json.dumps(d), buffer_len)


def booster_get_leaf_value(handle: int, tree_idx: int, leaf_idx: int) -> float:
    return float(_sync(_get(handle)).trees[int(tree_idx)].leaf_value[int(leaf_idx)])


def booster_set_leaf_value(handle: int, tree_idx: int, leaf_idx: int,
                           val: float) -> None:
    bst: Booster = _sync(_get(handle))
    bst.trees[int(tree_idx)].leaf_value[int(leaf_idx)] = val
    bst._stacked_cache = None        # device predict caches copy leaf values


def booster_feature_importance(handle: int, num_iteration: int,
                               importance_type: int, out_ptr: int) -> int:
    imp = _sync(_get(handle)).feature_importance(
        "split" if importance_type == 0 else "gain")
    return _write_doubles(out_ptr, np.asarray(imp, np.float64))


def network_init(machines: str, local_listen_port: int, listen_time_out: int,
                 num_machines: int) -> None:
    from .config import Config
    from .parallel.comm import init_distributed
    cfg = Config.from_params({
        "machines": machines, "local_listen_port": local_listen_port,
        "time_out": max(listen_time_out, 1), "num_machines": num_machines})
    init_distributed(cfg)


def network_free() -> None:
    pass        # the jax.distributed service lives for the process
