"""Python side of the C API (reference: src/c_api.cpp, 1,448 LoC).

The native shim (capi/lgbm_capi.c) exposes the reference's ``LGBM_*``
symbols and proxies every call here. The split keeps the C layer to
argument forwarding: buffers cross the boundary as raw addresses
(int64) + dtype codes, and this module views them with numpy/ctypes —
zero-copy in, explicit memcpy out. Handles given to C are small integers
into a registry (no PyObject lifetime crosses the boundary).

Matches c_api.h semantics: C_API_DTYPE_* codes (c_api.h:22-25),
C_API_PREDICT_* (c_api.h:27-30), 0/-1 return codes with
LGBM_GetLastError() carrying the message.
"""
from __future__ import annotations

import ctypes
import functools
import json
import threading
from typing import Dict, Optional

import numpy as np

from .basic import Booster, Dataset
from .config import resolve_aliases

# ---- handle registry -------------------------------------------------------
# The registry itself and each handle's object are mutex-guarded like the
# reference (c_api.cpp:29 Booster lock, :67 handle lifetime): the embedded-C
# hosting mode may call in from multiple native threads, and jax/numpy
# release the GIL mid-operation.

_objects: Dict[int, object] = {}
_next_handle = [1]
_registry_lock = threading.RLock()
_handle_locks: Dict[int, threading.RLock] = {}


def _register(obj) -> int:
    with _registry_lock:
        h = _next_handle[0]
        _next_handle[0] += 1
        _objects[h] = obj
        _handle_locks[h] = threading.RLock()
        return h


def _get(h: int):
    with _registry_lock:
        return _objects[int(h)]


def _lock_of(h: int) -> threading.RLock:
    with _registry_lock:
        return _handle_locks.setdefault(int(h), threading.RLock())


def _with_handle_lock(fn):
    """Serialize operations on one handle (first argument)."""
    @functools.wraps(fn)
    def wrapper(handle, *args, **kwargs):
        with _lock_of(handle):
            return fn(handle, *args, **kwargs)
    return wrapper


def free_handle(h: int) -> None:
    with _registry_lock:
        _objects.pop(int(h), None)
        _handle_locks.pop(int(h), None)


# ---- raw-memory views ------------------------------------------------------

_DTYPES = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64}


def _view(ptr: int, dtype_code: int, count: int) -> np.ndarray:
    ct = {0: ctypes.c_float, 1: ctypes.c_double,
          2: ctypes.c_int32, 3: ctypes.c_int64}[int(dtype_code)]
    buf = (ct * int(count)).from_address(int(ptr))
    return np.ctypeslib.as_array(buf)


def _write_doubles(ptr: int, values: np.ndarray) -> int:
    arr = np.ascontiguousarray(values, dtype=np.float64)
    ctypes.memmove(int(ptr), arr.ctypes.data, arr.nbytes)
    return arr.size


def _write_string(ptr: int, text: str, buffer_len: int) -> int:
    """Reference out_len contract (c_api.cpp SaveModelToString): report
    len+1 (including NUL) and copy ONLY when the whole string fits, so the
    two-call size-then-fetch protocol never truncates silently."""
    raw = text.encode("utf-8") + b"\0"
    if len(raw) <= int(buffer_len):
        ctypes.memmove(int(ptr), raw, len(raw))
    return len(raw)


def _write_string_array(ptrs_addr: int, strings, each_len: int = 255) -> int:
    """Fill a char** (preallocated buffers, reference basic.py convention)."""
    arr = (ctypes.c_void_p * len(strings)).from_address(int(ptrs_addr))
    for i, s in enumerate(strings):
        raw = s.encode("utf-8")[: each_len - 1] + b"\0"
        ctypes.memmove(arr[i], raw, len(raw))
    return len(strings)


def _params(parameters: Optional[str]) -> dict:
    out = {}
    for tok in (parameters or "").replace("\n", " ").split():
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k] = v
    return resolve_aliases(out)


# ---- dataset ---------------------------------------------------------------

def dataset_create_from_file(filename: str, parameters: str,
                             reference: int) -> int:
    params = _params(parameters)
    ref = _get(reference) if reference else None
    ds = Dataset(filename, params=params, reference=ref)
    ds.construct()
    return _register(ds)


def dataset_create_from_mat(data_ptr: int, data_type: int, nrow: int,
                            ncol: int, is_row_major: int, parameters: str,
                            reference: int) -> int:
    flat = _view(data_ptr, data_type, nrow * ncol)
    mat = flat.reshape(nrow, ncol) if is_row_major else \
        flat.reshape(ncol, nrow).T
    params = _params(parameters)
    ref = _get(reference) if reference else None
    ds = Dataset(np.array(mat, dtype=np.float64), params=params, reference=ref)
    return _register(ds)


def dataset_create_from_csr(indptr_ptr: int, indptr_type: int,
                            indices_ptr: int, data_ptr: int, data_type: int,
                            nindptr: int, nelem: int, num_col: int,
                            parameters: str, reference: int) -> int:
    import scipy.sparse as sp
    indptr = _view(indptr_ptr, indptr_type, nindptr).astype(np.int64)
    indices = _view(indices_ptr, 2, nelem)
    data = _view(data_ptr, data_type, nelem)
    csr = sp.csr_matrix((np.array(data, np.float64), np.array(indices),
                         np.array(indptr)), shape=(nindptr - 1, num_col))
    ref = _get(reference) if reference else None
    ds = Dataset(csr, params=_params(parameters), reference=ref)
    return _register(ds)


def dataset_create_from_csc(colptr_ptr: int, colptr_type: int,
                            indices_ptr: int, data_ptr: int, data_type: int,
                            ncolptr: int, nelem: int, num_row: int,
                            parameters: str, reference: int) -> int:
    import scipy.sparse as sp
    colptr = _view(colptr_ptr, colptr_type, ncolptr).astype(np.int64)
    indices = _view(indices_ptr, 2, nelem)
    data = _view(data_ptr, data_type, nelem)
    csc = sp.csc_matrix((np.array(data, np.float64), np.array(indices),
                         np.array(colptr)), shape=(num_row, ncolptr - 1))
    ds = Dataset(csc, params=_params(parameters),
                 reference=_get(reference) if reference else None)
    return _register(ds)


class _StreamingDataset:
    """Chunk-streamed dataset creation (reference c_api.h:67-127:
    LGBM_DatasetCreateFromSampledColumn / CreateByReference + PushRows[ByCSR]).

    TPU-first inversion of the reference's push path: BinMappers are built
    up-front (from the provided column sample, or borrowed from the reference
    dataset), and every pushed chunk is binned to uint8/16 codes immediately —
    the float matrix never materializes, so ingestion is genuinely
    out-of-core like the reference's PushData → FinishLoad flow."""

    def __init__(self, features, num_total_features, feature_names, config,
                 params, num_total_row: int, ref_basic: Optional[Dataset]):
        self.features = features                    # List[FeatureInfo]
        self.num_total_features = num_total_features
        self.feature_names = feature_names
        self.config = config
        self.params = params
        self.num_total_row = int(num_total_row)
        self.ref_basic = ref_basic
        dtype = np.uint8 if all(f.mapper.num_bin <= 256 for f in features) \
            else np.uint16
        self.X_binned = np.zeros((self.num_total_row, max(len(features), 1)),
                                 dtype=dtype)
        self.fields: Dict[str, np.ndarray] = {}

    @classmethod
    def from_reference(cls, ref_basic: Dataset, num_total_row: int,
                       params: dict) -> "_StreamingDataset":
        from .dataset import FeatureInfo
        ref_basic.construct()
        cd = ref_basic._constructed
        if cd is None:
            raise ValueError("reference dataset has no constructed bin "
                             "mappers (is it itself an aligned valid set?)")
        features = [FeatureInfo(int(r), m)
                    for r, m in zip(cd.real_feature_idx, cd.mappers)]
        return cls(features, cd.num_total_features, cd.feature_names,
                   cd.config, params, num_total_row, ref_basic)

    @classmethod
    def from_samples(cls, samples, num_sample_row: int, num_total_row: int,
                     params: dict) -> "_StreamingDataset":
        """``samples[j]``: sampled NON-ZERO values of column j (zeros implied
        by num_sample_row — the BinMapper::FindBin contract, bin.cpp:232)."""
        from .binning import BIN_CATEGORICAL, BIN_NUMERICAL, BinMapper
        from .config import Config
        from .dataset import FeatureInfo, _parse_column_spec
        config = Config.from_params(params)
        ncol = len(samples)
        feature_names = [f"Column_{i}" for i in range(ncol)]
        cat_set = set(_parse_column_spec(config.categorical_column,
                                         feature_names))
        filter_cnt = int(config.min_data_in_leaf * num_sample_row
                         / max(num_total_row, 1))
        features = []
        for j in range(ncol):
            mapper = BinMapper()
            mapper.find_bin(
                np.asarray(samples[j], dtype=np.float64), num_sample_row,
                config.max_bin, config.min_data_in_bin, filter_cnt,
                BIN_CATEGORICAL if j in cat_set else BIN_NUMERICAL,
                config.use_missing, config.zero_as_missing)
            if not mapper.is_trivial:
                features.append(FeatureInfo(j, mapper))
        return cls(features, ncol, feature_names, config, params,
                   num_total_row, None)

    def push_dense(self, chunk: np.ndarray, start_row: int) -> bool:
        n = chunk.shape[0]
        if start_row + n > self.num_total_row:
            raise ValueError(f"push beyond num_total_row: {start_row}+{n} > "
                             f"{self.num_total_row}")
        dt = self.X_binned.dtype
        for inner, f in enumerate(self.features):
            self.X_binned[start_row:start_row + n, inner] = \
                f.mapper.value_to_bin(chunk[:, f.real_index]).astype(dt)
        # reference: FinishLoad when nrow + start_row == num_total_row
        return start_row + n == self.num_total_row

    # buffered metadata: the reference allows SetField before FinishLoad
    def set_label(self, v):
        self.fields["label"] = v

    def set_weight(self, v):
        self.fields["weight"] = v

    def set_group(self, v):
        self.fields["group"] = v

    def set_init_score(self, v):
        self.fields["init_score"] = v

    def num_data(self) -> int:
        return self.num_total_row

    def num_feature(self) -> int:
        return self.num_total_features

    def finish(self) -> Dataset:
        """Materialize the real Dataset; the caller swaps it into the
        registry under the same handle (the C side's pointer is unchanged)."""
        from .dataset import ConstructedDataset, Metadata
        meta = Metadata(self.num_total_row)
        if "label" in self.fields:
            meta.set_label(self.fields["label"])
        if "weight" in self.fields:
            meta.set_weight(self.fields["weight"])
        if "group" in self.fields:
            meta.set_group(self.fields["group"])
        if "init_score" in self.fields:
            meta.set_init_score(self.fields["init_score"])
        cd = ConstructedDataset(self.X_binned, self.features,
                                self.num_total_features, meta,
                                self.feature_names, self.config)
        d = Dataset(np.zeros((0, 1)), params=dict(self.params))
        d._constructed = cd
        # mirror buffered fields onto the Dataset attributes too, so
        # LGBM_DatasetGetField sees what was SetField'd before the last push
        d.label = meta.label
        d.weight = self.fields.get("weight")
        d.group = self.fields.get("group")
        d.init_score = self.fields.get("init_score")
        if self.ref_basic is not None:
            # usable as an aligned valid set too (Booster.add_valid contract)
            d.reference = self.ref_basic
            d._binned_aligned = self.X_binned
            d._metadata = meta
        return d


def dataset_create_by_reference(reference: int, num_total_row: int) -> int:
    with _lock_of(reference):            # from_reference constructs the ref
        stream = _StreamingDataset.from_reference(_get(reference),
                                                  int(num_total_row), {})
    return _register(stream)


def dataset_create_from_sampled_column(col_ptrs_addr: int, ind_ptrs_addr: int,
                                       ncol: int, num_per_col_ptr: int,
                                       num_sample_row: int,
                                       num_total_row: int,
                                       parameters: str) -> int:
    npc = np.array(_view(num_per_col_ptr, 2, ncol))
    col_ptrs = (ctypes.c_void_p * int(ncol)).from_address(int(col_ptrs_addr))
    samples = [np.array(_view(col_ptrs[j], 1, int(npc[j])))
               if npc[j] else np.zeros(0) for j in range(int(ncol))]
    stream = _StreamingDataset.from_samples(samples, int(num_sample_row),
                                            int(num_total_row),
                                            _params(parameters))
    return _register(stream)


def _finish_stream(handle: int, stream: _StreamingDataset) -> None:
    with _registry_lock:
        _objects[int(handle)] = stream.finish()


@_with_handle_lock
def dataset_push_rows(handle: int, data_ptr: int, data_type: int, nrow: int,
                      ncol: int, start_row: int) -> None:
    stream: _StreamingDataset = _get(handle)
    chunk = np.array(_view(data_ptr, data_type, nrow * ncol),
                     dtype=np.float64).reshape(nrow, ncol)
    if stream.push_dense(chunk, int(start_row)):
        _finish_stream(handle, stream)


@_with_handle_lock
def dataset_push_rows_by_csr(handle: int, indptr_ptr: int, indptr_type: int,
                             indices_ptr: int, data_ptr: int, data_type: int,
                             nindptr: int, nelem: int, num_col: int,
                             start_row: int) -> None:
    import scipy.sparse as sp
    stream: _StreamingDataset = _get(handle)
    indptr = np.array(_view(indptr_ptr, indptr_type, nindptr), dtype=np.int64)
    indices = np.array(_view(indices_ptr, 2, nelem))
    data = np.array(_view(data_ptr, data_type, nelem), dtype=np.float64)
    chunk = sp.csr_matrix((data, indices, indptr),
                          shape=(int(nindptr) - 1, int(num_col))).toarray()
    if stream.push_dense(chunk, int(start_row)):
        _finish_stream(handle, stream)


def dataset_get_subset(handle: int, indices_ptr: int, num_indices: int,
                       parameters: str) -> int:
    ds: Dataset = _get(handle)
    idx = np.array(_view(indices_ptr, 2, num_indices))
    return _register(ds.subset(idx, params=_params(parameters)))


def dataset_set_feature_names(handle: int, names) -> None:
    _get(handle).feature_name = list(names)


def dataset_get_feature_names(handle: int, ptrs_addr: int) -> int:
    ds: Dataset = _get(handle)
    names = ds.feature_name if isinstance(ds.feature_name, list) else \
        [f"Column_{i}" for i in range(ds.num_feature())]
    return _write_string_array(ptrs_addr, names)


@_with_handle_lock
def dataset_save_binary(handle: int, filename: str) -> None:
    ds: Dataset = _get(handle)
    ds.construct()
    ds._constructed.save_binary(filename)


@_with_handle_lock
def dataset_set_field(handle: int, field: str, ptr: int, n: int,
                      dtype_code: int) -> None:
    ds: Dataset = _get(handle)
    arr = np.array(_view(ptr, dtype_code, n))
    if field == "label":
        ds.set_label(arr.astype(np.float32))
    elif field == "weight":
        ds.set_weight(arr.astype(np.float32))
    elif field in ("group", "query"):
        ds.set_group(arr.astype(np.int32))
    elif field == "init_score":
        ds.set_init_score(arr.astype(np.float64))
    else:
        raise ValueError(f"unknown field {field}")


@_with_handle_lock
def dataset_get_field(handle: int, field: str, out_ptr_addr: int,
                      out_type_addr: int) -> int:
    """Returns length; writes the array pointer + dtype code like
    LGBM_DatasetGetField (c_api.cpp). The array is kept alive on the
    dataset object."""
    ds: Dataset = _get(handle)
    val = ds.get_field(field)
    if val is None:
        return 0
    if field in ("group", "query"):
        arr = np.ascontiguousarray(val, dtype=np.int32)
        code = 2
    else:
        arr = np.ascontiguousarray(val, dtype=np.float32)
        code = 0
    if not hasattr(ds, "_capi_field_refs"):
        ds._capi_field_refs = {}
    ds._capi_field_refs[field] = arr            # keep buffer alive
    ctypes.c_void_p.from_address(int(out_ptr_addr)).value = arr.ctypes.data
    ctypes.c_int32.from_address(int(out_type_addr)).value = code
    return arr.size


def dataset_get_num_data(handle: int) -> int:
    return int(_get(handle).num_data())


def dataset_get_num_feature(handle: int) -> int:
    return int(_get(handle).num_feature())


# ---- booster ---------------------------------------------------------------

def booster_create(train_handle: int, parameters: str) -> int:
    with _lock_of(train_handle):         # construction mutates the dataset
        bst = Booster(params=_params(parameters),
                      train_set=_get(train_handle))
    return _register(bst)


def booster_create_from_modelfile(filename: str) -> int:
    return _register(Booster(model_file=filename))


def booster_load_from_string(model_str: str) -> int:
    return _register(Booster(model_str=model_str))


def booster_add_valid_data(handle: int, valid_handle: int) -> None:
    # two locks in handle order (same protocol as booster_merge): add_valid
    # constructs/aligns the valid dataset, which mutates it
    h1, h2 = sorted((int(handle), int(valid_handle)))
    with _lock_of(h1), _lock_of(h2):
        bst: Booster = _get(handle)
        vs: Dataset = _get(valid_handle)
        if vs.reference is None:
            vs.reference = bst.train_dataset
        bst.add_valid(vs, f"valid_{len(getattr(bst._gbdt, 'valid_sets', []))}")


@_with_handle_lock
def booster_reset_training_data(handle: int, train_handle: int) -> None:
    bst: Booster = _get(handle)
    # update(train_set=...) swaps the data AND trains one iteration;
    # rollback_one_iter fully reverts that extra iteration (trees + score),
    # matching LGBM_BoosterResetTrainingData's swap-only contract
    bst.update(train_set=_get(train_handle))
    bst.rollback_one_iter()


@_with_handle_lock
def booster_reset_parameter(handle: int, parameters: str) -> None:
    _get(handle).reset_parameter(_params(parameters))


def booster_get_num_classes(handle: int) -> int:
    return max(int(_get(handle).params.get("num_class", 1)), 1)


@_with_handle_lock
def booster_update_one_iter(handle: int) -> int:
    bst: Booster = _get(handle)
    before = bst._gbdt.iter_
    bst.update()
    return 1 if bst._gbdt.iter_ == before else 0   # is_finished


def dataset_get_num_data_of_booster(handle: int) -> int:
    """Gradient length for LGBM_BoosterUpdateOneIterCustom: num_data *
    num_models (class-major, reference c_api.cpp UpdateOneIterCustom)."""
    bst: Booster = _get(handle)
    return int(bst.train_dataset.num_data()
               * max(bst.num_model_per_iteration, 1))


@_with_handle_lock
def booster_update_one_iter_custom(handle: int, grad_ptr: int, hess_ptr: int,
                                   n: int) -> int:
    bst: Booster = _get(handle)
    g = np.array(_view(grad_ptr, 0, n), np.float64)
    h = np.array(_view(hess_ptr, 0, n), np.float64)
    bst.update(fobj=lambda preds, ds: (g, h))
    return 0


@_with_handle_lock
def booster_rollback_one_iter(handle: int) -> None:
    _get(handle).rollback_one_iter()


def booster_merge(handle: int, other_handle: int) -> None:
    """LGBM_BoosterMerge (c_api.h:361): append other's trees to handle's
    forest. Device training state of the target is released (resume by
    passing a train_set to the next update, the continued-training path);
    the merged model predicts/saves immediately — the reference's
    worker-train-then-merge usage."""
    import copy
    h1, h2 = sorted((int(handle), int(other_handle)))
    with _lock_of(h1), _lock_of(h2):
        bst: Booster = _sync(_get(handle))
        other: Booster = _sync(_get(other_handle))
        if max(bst.num_model_per_iteration, 1) != \
                max(other.num_model_per_iteration, 1):
            raise ValueError("cannot merge boosters with different "
                             "models-per-iteration")
        if bst._gbdt is not None:
            bst.free_dataset()
        bst.trees = list(bst.trees) + [copy.deepcopy(t) for t in other.trees]
        bst._forest_rev = getattr(bst, "_forest_rev", 0) + 1
        bst._stacked_cache = None


@_with_handle_lock
def booster_get_num_predict(handle: int, data_idx: int) -> int:
    """LGBM_BoosterGetNumPredict (c_api.h:488): score length for the
    training data (0) or i-th valid set (i+1)."""
    gbdt = _get(handle)._gbdt
    if gbdt is None:
        raise ValueError("booster has no training data attached")
    if int(data_idx) == 0:
        n = gbdt.num_data
    else:
        n = gbdt.valid_sets[int(data_idx) - 1].num_data
    return int(n) * max(gbdt.num_models, 1)


@_with_handle_lock
def booster_get_predict(handle: int, data_idx: int, out_ptr: int) -> int:
    """LGBM_BoosterGetPredict (c_api.h:502): current objective-transformed
    scores of train/valid rows, class-major like GBDT::GetPredictAt
    (gbdt.cpp:683-708)."""
    gbdt = _get(handle)._gbdt
    if gbdt is None:
        raise ValueError("booster has no training data attached")
    if int(data_idx) == 0:
        # _real_rows, not [:num_data]: under is_pre_partition the padded
        # device layout interleaves per-process block padding (gbdt.py:750
        # uses the same selector for metrics)
        scores = gbdt._fetch(gbdt._convert(gbdt.score))[:, gbdt._real_rows()]
    else:
        vs = gbdt.valid_sets[int(data_idx) - 1]
        scores = gbdt._fetch(gbdt._convert(vs.score))[:, : vs.num_data]
    return _write_doubles(out_ptr, np.asarray(scores, np.float64).reshape(-1))


def _sync(bst: Booster) -> Booster:
    """Materialize host trees from device state — the C API drives raw
    update() calls, so predict/save/dump must see the current forest
    (engine.train does this once at the end; here it's lazy per call)."""
    bst._ensure_finalized()
    return bst


def booster_get_current_iteration(handle: int) -> int:
    bst: Booster = _get(handle)
    if bst._gbdt is not None:
        return int(bst._gbdt.iter_)
    return int(bst.current_iteration())


def _metric_names(bst: Booster):
    """Per-dataset metric names — the c_api contract counts METRICS, not
    (dataset, metric) pairs (c_api.h GetEvalCounts/GetEvalNames)."""
    gbdt = bst._gbdt
    if gbdt is None:
        return []
    metrics = gbdt.valid_sets[0].metrics if gbdt.valid_sets else \
        getattr(gbdt, "train_metrics", [])
    return [m.name for m in metrics]


def booster_get_eval_counts(handle: int) -> int:
    return len(_metric_names(_get(handle)))


def booster_get_eval_names(handle: int, ptrs_addr: int) -> int:
    return _write_string_array(ptrs_addr, _metric_names(_get(handle)))


@_with_handle_lock
def booster_get_eval(handle: int, data_idx: int, out_ptr: int) -> int:
    """data_idx 0 = training, i+1 = i-th valid set (c_api.h:474)."""
    bst: Booster = _get(handle)
    gbdt = bst._gbdt
    rows = gbdt.eval_all()
    names = {0: "training"}
    for i, vs in enumerate(gbdt.valid_sets):
        names[i + 1] = vs.name
    want = names.get(int(data_idx))
    vals = [v for (d, _m, v, _h) in rows if d == want]
    return _write_doubles(out_ptr, np.array(vals, np.float64))


def booster_get_feature_names(handle: int, ptrs_addr: int) -> int:
    return _write_string_array(ptrs_addr, _get(handle).feature_name())


def booster_get_num_feature(handle: int) -> int:
    return int(_get(handle).num_total_features)


@_with_handle_lock
def booster_calc_num_predict(handle: int, num_row: int, predict_type: int,
                             num_iteration: int) -> int:
    bst: Booster = _sync(_get(handle))
    K = max(bst.num_model_per_iteration, 1)
    n_iter = bst.current_iteration() if num_iteration <= 0 else \
        min(num_iteration, bst.current_iteration())
    if predict_type == 2:       # leaf index
        return num_row * K * n_iter
    if predict_type == 3:       # contrib
        return num_row * K * (bst.num_total_features + 1)
    return num_row * K


def _predict(bst: Booster, X, predict_type: int, num_iteration: int,
             parameter: str, out_ptr: int) -> int:
    _sync(bst)
    kw = {}
    p = _params(parameter)
    if "pred_early_stop" in p:
        kw["pred_early_stop"] = p["pred_early_stop"] in ("1", "true")
    preds = bst.predict(
        X, num_iteration=num_iteration if num_iteration > 0 else None,
        raw_score=predict_type == 1, pred_leaf=predict_type == 2,
        pred_contrib=predict_type == 3, **kw)
    return _write_doubles(out_ptr, np.asarray(preds, np.float64))


@_with_handle_lock
def booster_predict_for_mat(handle: int, data_ptr: int, data_type: int,
                            nrow: int, ncol: int, is_row_major: int,
                            predict_type: int, num_iteration: int,
                            parameter: str, out_ptr: int) -> int:
    flat = _view(data_ptr, data_type, nrow * ncol)
    X = flat.reshape(nrow, ncol) if is_row_major else flat.reshape(ncol, nrow).T
    return _predict(_get(handle), np.array(X, np.float64), predict_type,
                    num_iteration, parameter, out_ptr)


@_with_handle_lock
def booster_predict_for_csr(handle: int, indptr_ptr: int, indptr_type: int,
                            indices_ptr: int, data_ptr: int, data_type: int,
                            nindptr: int, nelem: int, num_col: int,
                            predict_type: int, num_iteration: int,
                            parameter: str, out_ptr: int) -> int:
    import scipy.sparse as sp
    indptr = _view(indptr_ptr, indptr_type, nindptr).astype(np.int64)
    indices = _view(indices_ptr, 2, nelem)
    data = _view(data_ptr, data_type, nelem)
    csr = sp.csr_matrix((np.array(data, np.float64), np.array(indices),
                         np.array(indptr)), shape=(nindptr - 1, num_col))
    return _predict(_get(handle), csr, predict_type, num_iteration,
                    parameter, out_ptr)


@_with_handle_lock
def booster_predict_for_csc(handle: int, colptr_ptr: int, colptr_type: int,
                            indices_ptr: int, data_ptr: int, data_type: int,
                            ncolptr: int, nelem: int, num_row: int,
                            predict_type: int, num_iteration: int,
                            parameter: str, out_ptr: int) -> int:
    import scipy.sparse as sp
    colptr = _view(colptr_ptr, colptr_type, ncolptr).astype(np.int64)
    indices = _view(indices_ptr, 2, nelem)
    data = _view(data_ptr, data_type, nelem)
    csc = sp.csc_matrix((np.array(data, np.float64), np.array(indices),
                         np.array(colptr)), shape=(num_row, ncolptr - 1))
    return _predict(_get(handle), csc.tocsr(), predict_type, num_iteration,
                    parameter, out_ptr)


@_with_handle_lock
def booster_predict_for_file(handle: int, data_filename: str,
                             data_has_header: int, predict_type: int,
                             num_iteration: int, parameter: str,
                             result_filename: str) -> None:
    from .io.file_io import load_data_file
    p = _params(parameter)
    if data_has_header:
        p["has_header"] = "true"
    X, _, _ = load_data_file(data_filename, p)
    bst: Booster = _sync(_get(handle))
    preds = bst.predict(
        X, num_iteration=num_iteration if num_iteration > 0 else None,
        raw_score=predict_type == 1, pred_leaf=predict_type == 2,
        pred_contrib=predict_type == 3)
    preds = np.atleast_2d(preds.T).T if preds.ndim == 1 else preds
    with open(result_filename, "w") as fh:
        for row in (preds if preds.ndim == 2 else preds[:, None]):
            fh.write("\t".join(f"{v:.18g}" for v in np.atleast_1d(row)) + "\n")


@_with_handle_lock
def booster_save_model(handle: int, num_iteration: int, filename: str) -> None:
    _sync(_get(handle)).save_model(filename,
                            num_iteration if num_iteration > 0 else None)


@_with_handle_lock
def booster_save_model_to_string(handle: int, num_iteration: int,
                                 buffer_len: int, out_ptr: int) -> int:
    text = _sync(_get(handle)).model_to_string(
        num_iteration if num_iteration > 0 else None)
    return _write_string(out_ptr, text, buffer_len)


@_with_handle_lock
def booster_dump_model(handle: int, num_iteration: int, buffer_len: int,
                       out_ptr: int) -> int:
    d = _sync(_get(handle)).dump_model(num_iteration if num_iteration > 0 else None)
    return _write_string(out_ptr, json.dumps(d), buffer_len)


@_with_handle_lock
def booster_get_leaf_value(handle: int, tree_idx: int, leaf_idx: int) -> float:
    return float(_sync(_get(handle)).trees[int(tree_idx)].leaf_value[int(leaf_idx)])


@_with_handle_lock
def booster_set_leaf_value(handle: int, tree_idx: int, leaf_idx: int,
                           val: float) -> None:
    bst: Booster = _sync(_get(handle))
    bst.trees[int(tree_idx)].leaf_value[int(leaf_idx)] = val
    bst._stacked_cache = None        # device predict caches copy leaf values


@_with_handle_lock
def booster_feature_importance(handle: int, num_iteration: int,
                               importance_type: int, out_ptr: int) -> int:
    imp = _sync(_get(handle)).feature_importance(
        "split" if importance_type == 0 else "gain")
    return _write_doubles(out_ptr, np.asarray(imp, np.float64))


def network_init(machines: str, local_listen_port: int, listen_time_out: int,
                 num_machines: int) -> None:
    from .config import Config
    from .parallel.comm import init_distributed
    cfg = Config.from_params({
        "machines": machines, "local_listen_port": local_listen_port,
        "time_out": max(listen_time_out, 1), "num_machines": num_machines})
    init_distributed(cfg)


def network_free() -> None:
    pass        # the jax.distributed service lives for the process
