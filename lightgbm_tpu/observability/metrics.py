"""Process-wide metrics registry: counters, gauges, histograms, summaries.

One registry per process (``observability.get_registry()``) absorbs every
runtime signal the training stack used to scatter across ad-hoc consumers:
``RecompileGuard.report()`` recompile/host-sync counts (analysis/guards.py
publishes them on guard exit), comm retry/timeout events
(robustness/retry.py, parallel/comm.py), ``nan_policy`` events
(boosting/gbdt.py), checkpoint writes (robustness/checkpoint.py), per-booster
kernel choice, waves per tree, rows routed, and the serving subsystem's
per-request traffic (``serve.*`` counters plus the quantile-capable
``Summary`` latency metrics — docs/Serving.md). ``bench.py`` reads the same
registry for its ``telemetry`` summary block instead of keeping parallel
bookkeeping.

Deliberately jax-free and dependency-free: the lint CLI
(``lightgbm_tpu.analysis``) must stay importable in jax-free environments,
and guards.py publishes here. All mutation happens under one lock — counters
are incremented at host-side dispatch/retry/flush boundaries (a handful of
times per iteration at most), never per row, so the lock is nowhere near any
hot path.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Dict, Optional


class Counter:
    """Monotonic event count (e.g. ``comm.retries``)."""
    __slots__ = ("name", "_lock", "value")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self.value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += int(n)


class Gauge:
    """Last-written value (e.g. ``booster.tree_batch``)."""
    __slots__ = ("name", "_lock", "value")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self.value: Optional[float] = None

    def set(self, v) -> None:
        with self._lock:
            self.value = float(v)


class Histogram:
    """Streaming summary (count/sum/min/max) of an observed distribution
    (e.g. ``tree.waves``). No buckets: the consumers here want the shape of
    a per-run distribution in a snapshot, not a full HDR histogram."""
    __slots__ = ("name", "_lock", "count", "sum", "min", "max")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, v) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)


class Summary:
    """Windowed quantile summary: lifetime count/sum/min/max plus a bounded
    ring of the most recent ``window`` observations from which ``snapshot``
    computes p50/p90/p99 (nearest-rank over the window). The serving
    subsystem's per-request latency metrics (``serve.latency_ms``,
    ``serve.dispatch_ms``) are the consumers — a plain Histogram's
    count/sum/min/max cannot answer the p99 question a latency SLO asks.
    The window bounds memory (one float per slot) and biases the quantiles
    toward RECENT traffic, which is what a live probe wants."""
    __slots__ = ("name", "_lock", "count", "sum", "min", "max",
                 "window", "_ring", "_next")

    def __init__(self, name: str, lock: threading.Lock, window: int = 8192):
        self.name = name
        self._lock = lock
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.window = int(window)
        self._ring: list = []
        self._next = 0

    def observe(self, v) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            if len(self._ring) < self.window:
                self._ring.append(v)
            else:
                self._ring[self._next] = v
            self._next = (self._next + 1) % self.window

    @staticmethod
    def _quantiles_of(data: list, qs=(0.5, 0.9, 0.99)
                      ) -> Dict[str, Optional[float]]:
        """Nearest-rank quantiles of an already-sorted sample (caller holds
        whatever lock protects the sample)."""
        out: Dict[str, Optional[float]] = {}
        n = len(data)
        for q in qs:
            key = f"p{int(q * 100)}"
            out[key] = None if n == 0 else \
                data[min(n - 1, max(0, math.ceil(q * n) - 1))]
        return out

    def quantiles(self, qs=(0.5, 0.9, 0.99)) -> Dict[str, Optional[float]]:
        with self._lock:
            data = sorted(self._ring)
        return self._quantiles_of(data, qs)


class MetricsRegistry:
    """Named metric store; metrics are created on first use so producers
    never need registration order coordination."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._summaries: Dict[str, Summary] = {}

    # ------------------------------------------------------------- accessors

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name, self._lock))
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(name, self._lock))
        return g

    def histogram(self, name: str) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(name,
                                                Histogram(name, self._lock))
        return h

    def summary(self, name: str, window: int = 8192) -> Summary:
        s = self._summaries.get(name)
        if s is None:
            with self._lock:
                s = self._summaries.setdefault(
                    name, Summary(name, self._lock, window=window))
        return s

    def inc(self, name: str, n: int = 1) -> None:
        """Convenience: ``registry.inc("comm.retries")``."""
        self.counter(name).inc(n)

    # -------------------------------------------------------------- snapshot

    def snapshot(self) -> Dict:
        """Point-in-time view of every metric — the serving-side API
        (docs/Observability.md): cheap, lock-consistent, JSON-serializable."""
        with self._lock:
            counters = {k: c.value for k, c in sorted(self._counters.items())}
            gauges = {k: g.value for k, g in sorted(self._gauges.items())}
            hists = {}
            for k, h in sorted(self._histograms.items()):
                hists[k] = {
                    "count": h.count, "sum": round(h.sum, 6),
                    "min": h.min, "max": h.max,
                    "mean": round(h.sum / h.count, 6) if h.count else None,
                }
            sums = {}
            for k, s in sorted(self._summaries.items()):
                q = Summary._quantiles_of(sorted(s._ring))
                sums[k] = {
                    "count": s.count, "min": s.min, "max": s.max,
                    "mean": round(s.sum / s.count, 6) if s.count else None,
                    "p50": q["p50"], "p90": q["p90"], "p99": q["p99"],
                    "window": len(s._ring),
                }
        out = {"time_unix": round(time.time(), 3), "counters": counters,
               "gauges": gauges, "histograms": hists}
        if sums:
            # additive key: older snapshot consumers (bench telemetry block,
            # JSONL counters records) ignore it; serving probes read p50/p99
            out["summaries"] = sums
        return out

    def reset(self) -> None:
        """Drop every metric (tests; a fresh serving epoch)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._summaries.clear()
