"""Process-wide metrics registry: counters, gauges, histograms.

One registry per process (``observability.get_registry()``) absorbs every
runtime signal the training stack used to scatter across ad-hoc consumers:
``RecompileGuard.report()`` recompile/host-sync counts (analysis/guards.py
publishes them on guard exit), comm retry/timeout events
(robustness/retry.py, parallel/comm.py), ``nan_policy`` events
(boosting/gbdt.py), checkpoint writes (robustness/checkpoint.py), per-booster
kernel choice, waves per tree, and rows routed. ``bench.py`` reads the same
registry for its ``telemetry`` summary block instead of keeping parallel
bookkeeping.

Deliberately jax-free and dependency-free: the lint CLI
(``lightgbm_tpu.analysis``) must stay importable in jax-free environments,
and guards.py publishes here. All mutation happens under one lock — counters
are incremented at host-side dispatch/retry/flush boundaries (a handful of
times per iteration at most), never per row, so the lock is nowhere near any
hot path.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional


class Counter:
    """Monotonic event count (e.g. ``comm.retries``)."""
    __slots__ = ("name", "_lock", "value")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self.value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += int(n)


class Gauge:
    """Last-written value (e.g. ``booster.tree_batch``)."""
    __slots__ = ("name", "_lock", "value")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self.value: Optional[float] = None

    def set(self, v) -> None:
        with self._lock:
            self.value = float(v)


class Histogram:
    """Streaming summary (count/sum/min/max) of an observed distribution
    (e.g. ``tree.waves``). No buckets: the consumers here want the shape of
    a per-run distribution in a snapshot, not a full HDR histogram."""
    __slots__ = ("name", "_lock", "count", "sum", "min", "max")

    def __init__(self, name: str, lock: threading.Lock):
        self.name = name
        self._lock = lock
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, v) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)


class MetricsRegistry:
    """Named metric store; metrics are created on first use so producers
    never need registration order coordination."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    # ------------------------------------------------------------- accessors

    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(name, Counter(name, self._lock))
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(name, Gauge(name, self._lock))
        return g

    def histogram(self, name: str) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            with self._lock:
                h = self._histograms.setdefault(name,
                                                Histogram(name, self._lock))
        return h

    def inc(self, name: str, n: int = 1) -> None:
        """Convenience: ``registry.inc("comm.retries")``."""
        self.counter(name).inc(n)

    # -------------------------------------------------------------- snapshot

    def snapshot(self) -> Dict:
        """Point-in-time view of every metric — the serving-side API
        (docs/Observability.md): cheap, lock-consistent, JSON-serializable."""
        with self._lock:
            counters = {k: c.value for k, c in sorted(self._counters.items())}
            gauges = {k: g.value for k, g in sorted(self._gauges.items())}
            hists = {}
            for k, h in sorted(self._histograms.items()):
                hists[k] = {
                    "count": h.count, "sum": round(h.sum, 6),
                    "min": h.min, "max": h.max,
                    "mean": round(h.sum / h.count, 6) if h.count else None,
                }
        return {"time_unix": round(time.time(), 3), "counters": counters,
                "gauges": gauges, "histograms": hists}

    def reset(self) -> None:
        """Drop every metric (tests; a fresh serving epoch)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
