"""Compile-time cost introspection: XLA cost/memory reports per dispatch site.

The runtime half of the observability stack (spans, registry, Perfetto —
PR 5) says *when* the compiled step ran; this module says *what it costs*:
FLOPs, bytes accessed, and the compiled executable's argument/temp/output
HBM, captured from ``lowered.compile().cost_analysis()`` /
``memory_analysis()`` at every major dispatch site (the fused train step in
boosting/gbdt.py, the histogram kernels via ops/histogram.py
``histogram_cost_report``, batch predict in ops/predict.py) plus analytic
per-collective byte estimates from parallel/comm.py ``collective_bytes``.

Capture contract (the same one the span tracer honors):

- **compile/trace time only** — ``capture_jit`` lowers + compiles the SAME
  jitted callable with the live dispatch arguments ONCE per callable and
  never again, so the steady-state loop stays recompile-free and
  host-sync-free (``bench.py --smoke`` A/Bs the fused step with capture on).
  With the persistent compile cache enabled the duplicate XLA compile is a
  cache hit (the AOT compile and the first dispatch lower to identical HLO);
  the capture does NOT populate the jit fastpath cache, so RecompileGuard
  ``_cache_size()`` deltas are untouched.
- **off by default** — compiling everything twice would tax every tiny test
  training; enable via ``costs.configure(enabled=True)``, config
  ``tpu_cost_analysis=true`` (engine.train), or env
  ``LGBM_TPU_COST_ANALYSIS=1``. ``bench.py --smoke`` runs with it on and
  pins the fused step's FLOPs/bytes to golden values (``drift`` below) so a
  silent cost regression fails tier-1.
- **graceful fallback** — a backend returning ``None`` (or raising
  ``Unimplemented``) from either analysis yields a report with ``None``
  fields, never an exception; capture failures are recorded in the report's
  ``error`` field and never take training down.

Reports land in three places: the in-module report table (``reports()``,
folded into ``observability.snapshot()`` — the serving probe sees them),
the metrics registry as ``cost.<site>.<field>`` gauges, and the Perfetto
trace's ``otherData.cost_reports`` metadata at flush time.

jax is imported lazily: the module stays importable in jax-free
environments (the lint CLI path) like the rest of the subsystem.
"""
from __future__ import annotations

import os
import re
import threading
from typing import Dict, Optional, Tuple

ENV_COST_ANALYSIS = "LGBM_TPU_COST_ANALYSIS"

# numeric report fields mirrored into the registry as cost.<site>.<field>
_GAUGE_FIELDS = ("flops", "bytes_accessed", "transcendentals",
                 "argument_bytes", "output_bytes", "temp_bytes",
                 "generated_code_bytes", "peak_hbm_bytes")

_lock = threading.Lock()
_state: Dict = {"enabled": None}          # None = consult the env once
_reports: Dict[str, Dict] = {}            # site -> normalized report
# site -> (jitted callable, fingerprint) whose report is current. Holding
# the callable itself (a STRONG reference) is load-bearing: an id()-keyed
# set would let CPython reuse a garbage-collected step's address for a new
# booster's step and silently skip its capture, leaving a stale report
# under the site. A different callable — or the same shared callable with a
# different caller-supplied fingerprint (predict's module-level walk serves
# every forest) — re-captures; an unchanged pair never re-lowers.
_captured: Dict[str, tuple] = {}


# ------------------------------------------------------------- configuration

def enabled() -> bool:
    if _state["enabled"] is None:
        _state["enabled"] = os.environ.get(ENV_COST_ANALYSIS, "").lower() \
            not in ("", "0", "false", "off")
    return bool(_state["enabled"])


def configure(enabled: Optional[bool] = None) -> None:
    """Force cost capture on/off (explicit calls beat the env knob)."""
    if enabled is not None:
        _state["enabled"] = bool(enabled)


def reset_for_tests() -> None:
    with _lock:
        _state["enabled"] = None
        _reports.clear()
        _captured.clear()


# ------------------------------------------------------------ normalization

def _first_cost_dict(ca):
    """``cost_analysis()`` returns a list of per-executable dicts on some
    jax versions and a flat dict on others; normalize to one dict or None."""
    if ca is None:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    return ca if isinstance(ca, dict) else None


def report_from_compiled(compiled, site: str, dims: Optional[Dict] = None
                         ) -> Dict:
    """Normalize one compiled executable's cost/memory analyses into the
    report schema. Every field degrades to ``None`` when the backend
    returns nothing (the graceful-fallback contract) — the report itself
    always exists."""
    out: Dict = {"site": site}
    if dims:
        out.update(dims)
    out.update({"flops": None, "bytes_accessed": None,
                "transcendentals": None})
    try:
        ca = _first_cost_dict(compiled.cost_analysis())
    except Exception:                                        # noqa: BLE001
        ca = None
    if ca:
        for field, key in (("flops", "flops"),
                           ("bytes_accessed", "bytes accessed"),
                           ("transcendentals", "transcendentals")):
            v = ca.get(key)
            if v is not None:
                out[field] = float(v)
    out.update({"argument_bytes": None, "output_bytes": None,
                "temp_bytes": None, "generated_code_bytes": None,
                "peak_hbm_bytes": None})
    try:
        ma = compiled.memory_analysis()
    except Exception:                                        # noqa: BLE001
        ma = None
    if ma is not None:
        for field, attr in (("argument_bytes", "argument_size_in_bytes"),
                            ("output_bytes", "output_size_in_bytes"),
                            ("temp_bytes", "temp_size_in_bytes"),
                            ("generated_code_bytes",
                             "generated_code_size_in_bytes")):
            v = getattr(ma, attr, None)
            if v is not None:
                out[field] = int(v)
        # XLA's peak device residency for one execution: arguments stay
        # live, temps are the while-carry + intermediates, outputs are
        # written before arguments die (donation aliases some of this —
        # the estimate is the safe upper bound)
        parts = [out[f] for f in ("argument_bytes", "output_bytes",
                                  "temp_bytes", "generated_code_bytes")]
        if any(p is not None for p in parts):
            out["peak_hbm_bytes"] = int(sum(p or 0 for p in parts))
    # measured collectives: scan the optimized HLO once (compile-time
    # only) — gated on the CALLER's mesh size (``dims["n_devices"]``, the
    # booster's own device count): a single-device program cannot contain
    # collectives, and materializing the full HLO text of a bench-scale
    # fused step just to parse an empty dict is real memory — a serial
    # booster on a multi-device host must not pay it either
    if (dims or {}).get("n_devices", 0) > 1:
        try:
            text = compiled.as_text()
        except Exception:                                    # noqa: BLE001
            text = None
        if text:
            coll = hlo_collectives(text)
            if coll:
                out["collectives"] = coll
    return out


# --------------------------------------------------- measured collectives

# one optimized-HLO instruction: `%name = <shape> <op>(...)` where <op> is
# a cross-device collective. Async pairs lower as `-start`/`-done`; only the
# `-start` (or the sync form) carries the transfer, so `-done` is excluded
# (after the op name only `-start(` or `(` may follow). The tuple branch is
# GREEDY (`\(.*\)`): TPU layouts carry parens inside the shape —
# `(f32[1024]{0:T(1024)}, ...)` — so a lazy/negated match would stop at the
# first `)` and silently drop every async TPU collective.
_HLO_COLLECTIVE_RE = re.compile(
    r"=\s*(?P<shape>\(.*\)|\S+)\s+"
    r"(?P<op>all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(?P<start>-start)?\(")
_HLO_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_HLO_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
                    "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "f32": 4,
                    "s64": 8, "u64": 8, "f64": 8}


def hlo_collectives(hlo_text: str) -> Dict[str, Dict[str, int]]:
    """MEASURED cross-device traffic of a compiled executable: scan the
    optimized HLO for collective instructions and sum their output-shape
    bytes per op kind -> ``{op: {"instances": n, "output_bytes": b}}``.

    This is the ground truth the analytic ``parallel/comm.py
    collective_bytes`` estimates are validated against (``bench.py
    --multichip`` reports both and their ratio): an in-loop collective
    appears once in the HLO and executes once per wave, exactly the
    per-wave unit the analytic estimates use."""
    out: Dict[str, Dict[str, int]] = {}
    for m in _HLO_COLLECTIVE_RE.finditer(hlo_text):
        shapes = _HLO_SHAPE_RE.findall(m.group("shape"))
        if m.group("start") and m.group("shape").startswith("("):
            # async form: the tuple is (aliased operands..., results...,
            # context scalars...) — counting everything would double-count
            # the transfer (2x for all-reduce-start, (D+1)/D for
            # all-gather-start). Drop collective-permute's u32[] context
            # scalars first, then keep the result half only.
            shapes = [s for s in shapes
                      if not (s[1] == "" and s[0] in ("u32", "s32"))]
            shapes = shapes[len(shapes) // 2:]
        nbytes = 0
        for dtype, dims in shapes:
            size = _HLO_DTYPE_BYTES.get(dtype)
            if size is None:          # token/opaque tuple elements
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            nbytes += n * size
        slot = out.setdefault(m.group("op"),
                              {"instances": 0, "output_bytes": 0})
        slot["instances"] += 1
        slot["output_bytes"] += nbytes
    return out


def collective_wire_bytes(collectives: Dict[str, Dict[str, int]],
                          n_devices: int) -> Dict[str, float]:
    """Per-op-kind bytes actually moved over the interconnect per device,
    from the HLO output shapes under the standard ring-collective cost
    model: all-reduce ~ 2(D-1)/D x payload, all-gather ~ (D-1)/D x gathered
    output, reduce-scatter ~ (D-1) x scattered output (the output is 1/D of
    the reduced payload), permute/all-to-all ~ the moved shape itself."""
    D = max(int(n_devices), 1)
    factor = {"all-reduce": 2.0 * (D - 1) / D,
              "all-gather": (D - 1) / D,
              "reduce-scatter": float(D - 1),
              "collective-permute": 1.0,
              "all-to-all": 1.0}
    out = {op: round(rec["output_bytes"] * factor.get(op, 1.0), 1)
           for op, rec in collectives.items()}
    out["total"] = round(sum(out.values()), 1)
    return out


# ----------------------------------------------------------------- capture

def publish(report: Dict) -> None:
    """Record a report: the site table (-> ``snapshot()``/Perfetto
    metadata), ``cost.<site>.*`` gauges, and one instant trace event."""
    site = report["site"]
    with _lock:
        _reports[site] = dict(report)
    from . import event, get_registry
    reg = get_registry()
    for field in _GAUGE_FIELDS:
        v = report.get(field)
        if v is not None:
            reg.gauge(f"cost.{site}.{field}").set(v)
    ev = {k: v for k, v in report.items() if v is not None and k != "site"}
    event("cost_report", site=site, **ev)


def capture_jit(site: str, fn, args: Tuple = (), kwargs: Optional[Dict] = None,
                dims: Optional[Dict] = None,
                fingerprint=None) -> Optional[Dict]:
    """Capture the cost/memory report of ``fn`` (a jitted callable) for the
    given dispatch arguments — once per (callable, fingerprint): a NEW
    callable at a known site re-captures and replaces the report, and a
    SHARED callable (one module-level jit serving many shapes, like the
    predict walk) re-captures whenever the caller's ``fingerprint``
    (hashable shape summary) changes. Compile-time only, never raising into
    the caller. Returns the report (or the previously captured one),
    ``None`` when capture is disabled."""
    if not enabled():
        return None
    with _lock:
        prev = _captured.get(site)
        if prev is not None and prev[0] is fn and prev[1] == fingerprint:
            return _reports.get(site)
        _captured[site] = (fn, fingerprint)
    try:
        lowered = fn.lower(*args, **(kwargs or {}))
        compiled = lowered.compile()
        report = report_from_compiled(compiled, site, dims)
    except Exception as e:                                   # noqa: BLE001
        # capture must never take a training run down: record the failure
        # as the site's report so the absence is visible, not silent
        report = dict(dims or {}, site=site,
                      error=f"{type(e).__name__}: {e}"[:300])
    try:
        publish(report)
    except Exception as e:                                   # noqa: BLE001
        # never-raises contract — but a failed publish is logged (R010),
        # not silently dropped: the report still returns to the caller
        from ..utils.log import Log
        Log.debug("cost report publish failed for %s: %s: %s",
                  site, type(e).__name__, e)
    return report


# ------------------------------------------------------------------ access

def reports() -> Dict[str, Dict]:
    """Copy of every captured report, keyed by site (sorted)."""
    with _lock:
        return {k: dict(v) for k, v in sorted(_reports.items())}


def report(site: str) -> Optional[Dict]:
    with _lock:
        r = _reports.get(site)
        return dict(r) if r else None


# ------------------------------------------------------------- golden pins

def drift(report: Dict, golden: Dict, fields=("flops", "bytes_accessed"),
          rel_tol: float = 0.35) -> Dict[str, Dict]:
    """Compare a report against golden values; returns the out-of-band
    fields as ``{field: {value, golden, ratio}}`` (empty = within band).

    The band is relative (default +/-35%): XLA version bumps move absolute
    FLOP/byte counts a little, while the regressions this pin exists to
    catch (an accidental extra full-N pass, a dtype widening, a lost
    donation) move them 2x. A ``None`` value against a numeric golden IS
    drift — losing the measurement entirely must not pass the pin."""
    tol = float(golden.get("rel_tol", rel_tol))
    out = {}
    for f in fields:
        g = golden.get(f)
        if g is None:
            continue
        v = report.get(f)
        if v is None:
            out[f] = {"value": None, "golden": g, "ratio": None}
            continue
        ratio = float(v) / float(g) if g else float("inf")
        if not (1.0 - tol) <= ratio <= (1.0 + tol):
            out[f] = {"value": float(v), "golden": float(g),
                      "ratio": round(ratio, 4)}
    return out
