"""Perf regression ledger: normalized BENCH/MULTICHIP history + compare.

The repo's measured trajectory lives in checked-in ``BENCH_r<N>.json`` /
``MULTICHIP_r<N>.json`` files whose schemas grew organically (round 1 is a
raw harness wrapper with ``parsed: null``, round 2 a bare payload, round 5
a full phase report). This module normalizes that history into ONE
machine-readable ledger (``PERF_LEDGER.json``) and answers the question no
PR could answer before: *did this change regress a number we already
banked?*

- ``build_ledger()`` — rebuild the ledger from the checked-in files; the
  one-shot ``python -m lightgbm_tpu.observability.ledger --rebuild`` keeps
  the committed ledger from ever drifting from history (``--check`` fails
  when it has).
- ``compare(candidate, entries)`` — flag regressions of a fresh bench
  payload against best-known values: throughput (per platform/rows/kernel
  comparability key; serving entries additionally key on the ``|serve=``
  load shape), post-warm-up recompiles, headline host syncs, peak HBM,
  serving p99 latency, and compiled cost-model drift (FLOPs / bytes
  accessed, when both sides carry cost reports). ``bench.py --compare``
  wraps this and exits nonzero on any flag; ``make bench-diff`` wires it
  into ``make verify``.

Deliberately dependency-free (stdlib + the jax-free sibling
``costs.drift`` for the one shared band check) and deterministic (no
timestamps): rebuilding from the same files yields byte-identical output,
so the committed ledger is diffable and the ``--check`` mode is a plain
equality.
"""
from __future__ import annotations

import glob
import json
import os
import re
from typing import Dict, List, Optional, Tuple

LEDGER_FILE = "PERF_LEDGER.json"
_ROUND_RE = re.compile(r"_r(\d+)\.json$")

# relative tolerances for compare(): generous enough to absorb run-to-run
# noise on a shared tunnel, tight enough that a real regression (the 2x
# cost of an extra full-N pass; a 20%+ throughput loss) always trips
DEFAULT_TOLERANCES = {
    "throughput": 0.15,       # value may sit up to 15% below best-known
    "hbm": 0.15,              # peak HBM may grow up to 15%
    "cost": 0.35,             # flops/bytes drift band vs recorded reports
    # serving p99 latency may sit up to this far ABOVE the best-known
    # floor: tail latency on a shared CI box is far noisier than
    # throughput, so the band is wide — a real regression (an extra
    # dispatch, a recompile in the loop) moves p99 by integer factors
    "p99": 0.75,
    # serve-chaos shed-rate ceiling: under the SAME offered overload the
    # shed fraction may sit this far (relative) above best-known plus a
    # 0.05 absolute allowance — shedding much more at equal load means
    # serving capacity regressed even if measured rows/s held
    "shed": 0.5,
    # chaos-dist recovery bands: fleet MTTR and peer-loss detection
    # latency are wall-clock of process relaunch + jit compile on a shared
    # CI box, so the bands are very wide (100% relative) — they exist to
    # catch order-of-magnitude regressions (a lost heartbeat probe turning
    # detection from ms into the full lease timeout; a resume path that
    # silently retrains from scratch), not run-to-run noise
    "mttr": 1.0,
    "detect": 1.0,
}


def _round_of(path: str) -> Optional[int]:
    m = _ROUND_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def payload_of(path: str) -> Optional[Dict]:
    """Extract the result payload from a history file: either a bare bench
    JSON or the driver wrapper holding it under ``parsed``."""
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict):
        return None
    if "parsed" in doc and "metric" not in doc:
        return doc["parsed"] if isinstance(doc["parsed"], dict) else None
    return doc


# ------------------------------------------------------------- normalization

def normalize_bench(payload: Optional[Dict], source: str,
                    round_: Optional[int]) -> Dict:
    """One BENCH payload -> the normalized ledger entry schema. Missing
    fields stay ``None`` — old rounds simply carry less signal."""
    e: Dict = {"source": source, "round": round_, "kind": "bench",
               "value": None, "unit": None, "vs_baseline": None,
               "platform": None, "rows": None, "kernel": None,
               "n_devices": None, "residency": None, "tree_batch": None,
               "auc": None, "serve": None, "serve_chaos": None,
               "chaos_dist": None, "bundle": None, "linear": None,
               "shed_rate": None, "p99_ms": None,
               "fleet_mttr_s": None, "detect_p50_ms": None,
               "detect_p99_ms": None, "shed_epochs": None,
               "recompiles_post_warmup": None, "host_syncs": None,
               "steady_s_per_iter": None, "hbm_peak_gb": None,
               "ingest": None, "identical_to_host": None,
               "cost": None, "error": None}
    if not payload:
        e["error"] = "unparseable history file"
        return e
    for k in ("value", "unit", "vs_baseline", "platform", "rows", "kernel",
              "n_devices", "residency", "tree_batch", "auc", "serve",
              "serve_chaos", "chaos_dist", "bundle", "linear", "shed_rate",
              "p99_ms", "fleet_mttr_s", "detect_p50_ms", "detect_p99_ms",
              "shed_epochs", "recompiles_post_warmup", "hbm_peak_gb",
              "ingest", "identical_to_host", "error"):
        if payload.get(k) is not None:
            e[k] = payload[k]
    head = (payload.get("phase_timings") or {}).get("headline") or {}
    if head.get("host_syncs") is not None:
        e["host_syncs"] = head["host_syncs"]
    if head.get("steady_s_per_iter") is not None:
        e["steady_s_per_iter"] = head["steady_s_per_iter"]
    cost = (payload.get("telemetry") or {}).get("cost_reports") \
        or payload.get("cost_reports")
    if cost:
        # keep only the drift-comparable numerics per site
        e["cost"] = {site: {f: r.get(f) for f in
                            ("flops", "bytes_accessed", "peak_hbm_bytes")
                            if r.get(f) is not None}
                     for site, r in cost.items() if isinstance(r, dict)}
    return e


def normalize_multichip(payload: Optional[Dict], source: str,
                        round_: Optional[int]) -> Dict:
    """Two generations of MULTICHIP files: rounds 1-5 are dry-run wrappers
    (``{n_devices, rc, ok, tail}`` — a train step compiled, nothing
    measured), round 6+ are ``bench.py --multichip`` scaling reports whose
    headline is Mrow-tree/s PER CHIP at the max device count plus weak/
    strong scaling efficiency. Both normalize here; only measured entries
    carry a ``value`` and participate in the regression gate."""
    e = {"source": source, "round": round_, "kind": "multichip",
         "ok": None, "n_devices": None, "rc": None,
         "value": None, "unit": None, "platform": None,
         "rows_per_device": None, "tree_learner": None,
         "weak_efficiency": None, "strong_efficiency": None,
         "simulated": None, "error": None}
    if payload:
        for k in ("ok", "n_devices", "rc"):
            if payload.get(k) is not None:
                e[k] = payload[k]
        if payload.get("metric") == "multichip_scaling":
            e["value"] = payload.get("per_chip_mrow_tree_per_s")
            e["unit"] = "Mrow-tree/s/chip"
            for k in ("platform", "rows_per_device", "tree_learner",
                      "weak_efficiency", "strong_efficiency", "simulated",
                      "error"):
                if payload.get(k) is not None:
                    e[k] = payload[k]
    return e


def load_history(root: str) -> List[Dict]:
    """Normalized entries from every checked-in BENCH/MULTICHIP file,
    round order."""
    entries: List[Dict] = []
    # STREAM_r*.json (bench.py --stream) and SERVE_r*.json (bench.py
    # --serve) share the bench schema; the residency=stream / serve=shape
    # fields key each into its own comparability class
    for pat, norm in (("BENCH_r*.json", normalize_bench),
                      ("STREAM_r*.json", normalize_bench),
                      ("SERVE_r*.json", normalize_bench),
                      ("SERVE_CHAOS_r*.json", normalize_bench),
                      ("CHAOS_DIST_r*.json", normalize_bench),
                      ("SPARSE_r*.json", normalize_bench),
                      ("LINEAR_r*.json", normalize_bench),
                      ("INGEST_r*.json", normalize_bench),
                      ("MULTICHIP_r*.json", normalize_multichip)):
        for path in sorted(glob.glob(os.path.join(root, pat))):
            entries.append(norm(payload_of(path), os.path.basename(path),
                                _round_of(path)))
    entries.sort(key=lambda e: (e.get("round") or 0, e["source"]))
    return entries


# ------------------------------------------------------------------ ledger

def _clean(e: Dict) -> bool:
    """A bench entry with a real measurement (nonzero value, no error)."""
    return (e.get("kind") == "bench" and not e.get("error")
            and isinstance(e.get("value"), (int, float)) and e["value"] > 0)


def comparability_key(e: Dict) -> str:
    """Entries are only compared within the same platform, scale, kernel,
    device count, and residency — a 2.1M-row quick pre-bank must never be
    judged against the 10.5M headline, a CPU fallback against a TPU
    number, a deliberate ``LGBM_TPU_BENCH_KERNEL`` A/B arm against a
    different kernel's best, a single-chip headline against an 8-chip
    mesh run, or a host-streamed out-of-core run
    (``tpu_residency=stream``, which pays H2D per wave by design) against
    a fully device-resident one. Serving results (``bench.py --serve``)
    additionally key on the load shape (``serve="closed|b512xc2"``) — a
    1-row-latency arm must never be judged against a 512-row-throughput
    arm, and training benches (serve=None) never mix with serving ones.
    Serve-chaos results (``bench.py --serve-chaos``) key on their
    fault-injection shape (``serve_chaos="open|b4|overload"``): numbers
    measured UNDER injected overload and faults are a comparability class
    of their own. Distributed-chaos results (``bench.py --chaos-dist``,
    CHAOS_DIST_r*.json) key the same way on their gang/fault matrix shape
    (``chaos_dist="gang2|kill9+flap+lease+manifest+shrink"``): fleet MTTR
    and detection latency only compare against runs of the SAME chaos
    matrix. Sparse-bench results (``bench.py --sparse``,
    SPARSE_r*.json) additionally key on the EFB representation
    (``bundle="bundlespace"``): the bundle-space, legacy-unpack, and
    no-EFB arms deliberately trade throughput against memory layout, so a
    sparse arm is never judged cross-representation. Linear-leaf results
    (``bench.py --linear``, LINEAR_r*.json) key on the leaf model
    (``linear="linear"``): a per-leaf ridge-solve workload pays the fit
    leg by design and must never be judged against constant-leaf
    throughput. Ingest results (``bench.py --ingest``, INGEST_r*.json)
    key on the ingest arm (``ingest="device"``): a raw-rows-to-codes
    rows/s number measures the binning pipeline, not training, and never
    mixes with train/serve throughput. Fields absent on older history are
    None — those entries keep comparing among themselves."""
    return (f"platform={e.get('platform')}|rows={e.get('rows')}"
            f"|kernel={e.get('kernel')}|n_devices={e.get('n_devices')}"
            f"|residency={e.get('residency')}|serve={e.get('serve')}"
            f"|serve_chaos={e.get('serve_chaos')}"
            f"|chaos_dist={e.get('chaos_dist')}|bundle={e.get('bundle')}"
            f"|linear={e.get('linear')}|ingest={e.get('ingest')}")


def multichip_key(e: Dict) -> str:
    """Comparability key for measured multichip entries: per-chip numbers
    only compare at the same platform, per-device scale, device count, and
    strategy."""
    return (f"multichip|platform={e.get('platform')}"
            f"|rows_per_device={e.get('rows_per_device')}"
            f"|n_devices={e.get('n_devices')}"
            f"|learner={e.get('tree_learner')}")


def _clean_multichip(e: Dict) -> bool:
    return (e.get("kind") == "multichip" and not e.get("error")
            and isinstance(e.get("value"), (int, float)) and e["value"] > 0)


def best_known_multichip(entries: List[Dict],
                         exclude_source: Optional[str] = None
                         ) -> Dict[str, Dict]:
    """Best measured multichip entry per key (highest per-chip value)."""
    best: Dict[str, Dict] = {}
    for e in entries:
        if not _clean_multichip(e) or e.get("source") == exclude_source:
            continue
        key = multichip_key(e)
        cur = best.get(key)
        if cur is None or e["value"] > cur["value"]:
            best[key] = e
    return best


def best_known(entries: List[Dict],
               exclude_source: Optional[str] = None) -> Dict[str, Dict]:
    """Best clean bench entry per comparability key (highest value; the
    recompile/host-sync/HBM floors are the minima over clean entries of
    the key, carried next to it)."""
    best: Dict[str, Dict] = {}
    for e in entries:
        if not _clean(e) or e.get("source") == exclude_source:
            continue
        key = comparability_key(e)
        cur = best.get(key)
        if cur is None or e["value"] > cur["entry"]["value"]:
            best[key] = {"entry": e}
    for key, slot in best.items():
        group = [e for e in entries if _clean(e)
                 and e.get("source") != exclude_source
                 and comparability_key(e) == key]
        for field in ("recompiles_post_warmup", "host_syncs", "hbm_peak_gb",
                      "p99_ms", "shed_rate", "fleet_mttr_s",
                      "detect_p50_ms", "detect_p99_ms", "shed_epochs"):
            vals = [e[field] for e in group if e.get(field) is not None]
            slot[f"min_{field}"] = min(vals) if vals else None
    return best


def build_ledger(root: str) -> Dict:
    entries = load_history(root)
    best = {k: {"source": v["entry"]["source"],
                "round": v["entry"]["round"],
                "value": v["entry"]["value"],
                "kernel": v["entry"].get("kernel"),
                "min_recompiles_post_warmup":
                    v.get("min_recompiles_post_warmup"),
                "min_host_syncs": v.get("min_host_syncs"),
                "min_hbm_peak_gb": v.get("min_hbm_peak_gb"),
                "min_p99_ms": v.get("min_p99_ms"),
                "min_shed_rate": v.get("min_shed_rate"),
                "min_fleet_mttr_s": v.get("min_fleet_mttr_s"),
                "min_detect_p50_ms": v.get("min_detect_p50_ms"),
                "min_detect_p99_ms": v.get("min_detect_p99_ms"),
                "min_shed_epochs": v.get("min_shed_epochs")}
            for k, v in sorted(best_known(entries).items())}
    best_mc = {k: {"source": v["source"], "round": v["round"],
                   "value": v["value"],
                   "weak_efficiency": v.get("weak_efficiency"),
                   "strong_efficiency": v.get("strong_efficiency")}
               for k, v in sorted(best_known_multichip(entries).items())}
    return {"version": 1,
            "baseline_mrow_tree_per_s": 22.0,
            "entries": entries,
            "best": best,
            "best_multichip": best_mc}


def write_ledger(root: str, out_path: Optional[str] = None,
                 doc: Optional[Dict] = None) -> str:
    from .export import atomic_write_json
    out_path = out_path or os.path.join(root, LEDGER_FILE)
    doc = doc if doc is not None else build_ledger(root)
    return atomic_write_json(out_path, doc, indent=1, sort_keys=True,
                             trailing_newline=True)


def check_ledger(root: str, path: Optional[str] = None) -> bool:
    """True iff the committed ledger matches a fresh rebuild (no drift)."""
    path = path or os.path.join(root, LEDGER_FILE)
    try:
        with open(path) as fh:
            committed = json.load(fh)
    except (OSError, ValueError):
        return False
    return committed == build_ledger(root)


# ----------------------------------------------------------------- compare

def compare(candidate: Dict, entries: List[Dict],
            exclude_source: Optional[str] = None,
            tolerances: Optional[Dict[str, float]] = None
            ) -> Tuple[List[str], List[str]]:
    """Flag regressions of ``candidate`` (a bench payload or normalized
    entry) against the history. Returns (problems, notes): any problem
    means regression — ``bench.py --compare`` exits nonzero on it."""
    tol = dict(DEFAULT_TOLERANCES, **(tolerances or {}))
    problems: List[str] = []
    notes: List[str] = []
    if (candidate.get("kind") == "multichip"
            or candidate.get("metric") == "multichip_scaling"):
        return compare_multichip(candidate, entries,
                                 exclude_source=exclude_source,
                                 tolerances=tolerances)
    c = candidate if candidate.get("kind") == "bench" else \
        normalize_bench(candidate, candidate.get("source", "<candidate>"),
                        candidate.get("round"))
    if not _clean(c):
        problems.append(
            f"candidate has no clean measurement (value={c.get('value')!r}, "
            f"error={c.get('error')!r})")
        return problems, notes
    if c.get("ingest") is not None and c.get("identical_to_host") is False:
        # bit-identity is the ingest contract, not a tolerance band: a
        # faster device binning that changes even one code is a bug
        problems.append(
            "ingest bit-identity violation: device-binned codes differ "
            "from the host oracle (identical_to_host=false)")
    best = best_known(entries, exclude_source=exclude_source)
    key = comparability_key(c)
    slot = best.get(key)
    if slot is None:
        notes.append(f"no comparable history for {key} — nothing to regress "
                     f"against")
    else:
        b = slot["entry"]
        floor = b["value"] * (1.0 - tol["throughput"])
        if c["value"] < floor:
            problems.append(
                f"throughput regression: {c['value']} {c.get('unit') or ''} "
                f"vs best-known {b['value']} ({b['source']}, kernel="
                f"{b.get('kernel')}) — below the {tol['throughput']:.0%} "
                f"band floor {floor:.3g}")
        else:
            notes.append(f"throughput ok: {c['value']} vs best {b['value']} "
                         f"({b['source']})")
        min_rec = slot.get("min_recompiles_post_warmup")
        if (c.get("recompiles_post_warmup") or 0) > 0 and min_rec == 0:
            problems.append(
                f"recompile regression: {c['recompiles_post_warmup']} "
                f"post-warm-up cache miss(es) where history has 0")
        min_sync = slot.get("min_host_syncs")
        if (min_sync is not None and c.get("host_syncs") is not None
                and c["host_syncs"] > min_sync):
            problems.append(
                f"host-sync regression: headline host_syncs "
                f"{c['host_syncs']} vs best-known {min_sync}")
        min_hbm = slot.get("min_hbm_peak_gb")
        if (min_hbm is not None and c.get("hbm_peak_gb") is not None
                and c["hbm_peak_gb"] > min_hbm * (1.0 + tol["hbm"])):
            problems.append(
                f"peak-HBM regression: {c['hbm_peak_gb']} GB vs best-known "
                f"{min_hbm} GB (+{tol['hbm']:.0%} band)")
        min_p99 = slot.get("min_p99_ms")
        if (min_p99 is not None and c.get("p99_ms") is not None
                and c["p99_ms"] > min_p99 * (1.0 + tol["p99"])):
            problems.append(
                f"p99 latency regression: {c['p99_ms']} ms vs best-known "
                f"{min_p99} ms (+{tol['p99']:.0%} band)")
        min_shed = slot.get("min_shed_rate")
        if (min_shed is not None and c.get("shed_rate") is not None
                and c["shed_rate"] > min_shed * (1.0 + tol["shed"]) + 0.05):
            problems.append(
                f"shed-rate regression: {c['shed_rate']} of offered load "
                f"shed vs best-known {min_shed} — shedding more at the "
                f"same offered overload means serving capacity regressed "
                f"(+{tol['shed']:.0%} relative +0.05 absolute band)")
        # chaos-dist recovery gates (bench.py --chaos-dist): wide relative
        # bands plus small absolute allowances, because both numbers ride
        # process relaunch + jit compile wall-clock on a shared box
        min_mttr = slot.get("min_fleet_mttr_s")
        if (min_mttr is not None and c.get("fleet_mttr_s") is not None
                and c["fleet_mttr_s"] > min_mttr * (1.0 + tol["mttr"]) + 5.0):
            problems.append(
                f"fleet-MTTR regression: {c['fleet_mttr_s']} s from gang "
                f"failure to a newer recovery point vs best-known "
                f"{min_mttr} s (+{tol['mttr']:.0%} relative +5s absolute "
                f"band)")
        min_det = slot.get("min_detect_p99_ms")
        if (min_det is not None and c.get("detect_p99_ms") is not None
                and c["detect_p99_ms"]
                > min_det * (1.0 + tol["detect"]) + 200.0):
            problems.append(
                f"peer-loss detection regression: p99 {c['detect_p99_ms']} "
                f"ms to a typed PeerLostError vs best-known {min_det} ms "
                f"(+{tol['detect']:.0%} relative +200ms absolute band)")
        min_se = slot.get("min_shed_epochs")
        if (min_se is not None and c.get("shed_epochs") is not None
                and c["shed_epochs"] > min_se + 1):
            problems.append(
                f"shed-epochs regression: the gang fell back "
                f"{c['shed_epochs']} epoch(s) to agree on a resume point "
                f"vs best-known {min_se} (+1 allowance) — losing more "
                f"banked epochs under the same chaos matrix means the "
                f"manifest commit protocol regressed")
        problems.extend(_cost_drift(c, b, tol["cost"]))
    return problems, notes


def compare_multichip(candidate: Dict, entries: List[Dict],
                      exclude_source: Optional[str] = None,
                      tolerances: Optional[Dict[str, float]] = None
                      ) -> Tuple[List[str], List[str]]:
    """Flag regressions of a ``multichip_scaling`` payload against the
    measured multichip history: per-chip throughput below the tolerance
    band, or scaling efficiency collapsing below best-known minus the band
    — the gate the satellite 'per-chip throughput regressions fail make
    bench-diff' names."""
    tol = dict(DEFAULT_TOLERANCES, **(tolerances or {}))
    problems: List[str] = []
    notes: List[str] = []
    c = candidate if candidate.get("kind") == "multichip" else \
        normalize_multichip(candidate,
                            candidate.get("source", "<candidate>"),
                            candidate.get("round"))
    if not _clean_multichip(c):
        problems.append(
            f"multichip candidate has no clean per-chip measurement "
            f"(value={c.get('value')!r}, error={c.get('error')!r})")
        return problems, notes
    best = best_known_multichip(entries, exclude_source=exclude_source)
    b = best.get(multichip_key(c))
    if b is None:
        notes.append(f"no comparable multichip history for "
                     f"{multichip_key(c)} — nothing to regress against")
        return problems, notes
    floor = b["value"] * (1.0 - tol["throughput"])
    if c["value"] < floor:
        problems.append(
            f"per-chip throughput regression: {c['value']} "
            f"{c.get('unit') or ''} vs best-known {b['value']} "
            f"({b['source']}) — below the {tol['throughput']:.0%} band "
            f"floor {floor:.3g}")
    else:
        notes.append(f"per-chip throughput ok: {c['value']} vs best "
                     f"{b['value']} ({b['source']})")
    for field in ("weak_efficiency", "strong_efficiency"):
        bv, cv = b.get(field), c.get(field)
        # multiplicative band like the throughput check — an absolute
        # delta would never fire for efficiencies below the tolerance
        if bv is not None and cv is not None \
                and cv < bv * (1.0 - tol["throughput"]):
            problems.append(
                f"scaling-efficiency regression: {field} {cv} vs "
                f"best-known {bv} ({b['source']})")
    return problems, notes


def _cost_drift(cand: Dict, base: Dict, rel_tol: float) -> List[str]:
    """Compiled cost-model drift between two entries' shared sites — the
    band logic IS ``costs.drift`` (one implementation; the golden pin and
    the ledger gate cannot disagree on semantics, including 'losing the
    measurement against a recorded number is drift')."""
    from . import costs as _costs
    out: List[str] = []
    cc, bc = cand.get("cost") or {}, base.get("cost") or {}
    for site in sorted(set(cc) & set(bc)):
        bad = _costs.drift(cc[site], bc[site],
                           fields=("flops", "bytes_accessed"),
                           rel_tol=rel_tol)
        for field, info in sorted(bad.items()):
            out.append(
                f"cost drift: {site}.{field} {info['value']} vs recorded "
                f"{info['golden']} ({base['source']}) — ratio "
                f"{info['ratio']} outside +/-{rel_tol:.0%}")
    return out


# --------------------------------------------------------------------- CLI

def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.observability.ledger",
        description="Rebuild/inspect the perf regression ledger "
                    f"({LEDGER_FILE}) from checked-in BENCH_*/MULTICHIP_* "
                    "history")
    ap.add_argument("--root", default=".",
                    help="repo root holding the history files")
    ap.add_argument("--rebuild", action="store_true",
                    help=f"rewrite {LEDGER_FILE} from the history files")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 when the committed ledger does not match a "
                         "fresh rebuild (drift)")
    args = ap.parse_args(argv)
    root = os.path.abspath(args.root)
    if args.rebuild:
        doc = build_ledger(root)
        path = write_ledger(root, doc=doc)
        print(f"ledger: wrote {path} ({len(doc['entries'])} entries, "
              f"{len(doc['best'])} best-known keys)")
    if args.check:
        if not check_ledger(root):
            print(f"ledger: {LEDGER_FILE} does NOT match the checked-in "
                  f"history — run --rebuild and commit the result")
            return 1
        print("ledger: up to date with history")
    if not args.rebuild and not args.check:
        print(json.dumps(build_ledger(root), indent=1, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
