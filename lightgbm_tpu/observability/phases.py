"""Attributable per-phase device timing (``PhaseBreakdown``).

Moved here from ``utils/timer.py`` so the bench's ``phase_timings`` are a
CONSUMER of the observability subsystem instead of a parallel
implementation: ``to_dict()`` output is byte-compatible with the historical
BENCH json schema (the BENCH_r* trajectory scripts parse it), and every
breakdown also lands in the process-wide metrics registry as
``phase.<name>.*`` gauges so a live snapshot sees the same numbers the
bench prints. ``utils.timer.PhaseBreakdown`` remains as a re-export for
existing imports.

    pb = PhaseBreakdown("headline")
    with pb.compile_window():      # warm-up: compiles allowed
        ...
    with pb.steady_window(iters=12):
        ...
    pb.attach_guard(guard.report())
    json["phase_timings"]["headline"] = pb.to_dict()

Recompile/host-sync counts come from a ``RecompileGuard.report()``
(analysis/guards.py) — the guard itself publishes its totals to the
registry on exit, so ``attach_guard`` only carries them into this phase's
dict and gauges (no double counting of registry counters).
"""
from __future__ import annotations

import contextlib
import time
from typing import Dict


class PhaseBreakdown:
    """Compile/warm-up wall-clock vs steady-state wall-clock vs host-sync +
    recompile counts for one named bench phase (docs/TPU-Performance.md)."""

    def __init__(self, name: str):
        self.name = name
        self.compile_s = 0.0
        self.steady_s = 0.0
        self.steady_iters = 0
        self.guard_report: Dict = {}

    @contextlib.contextmanager
    def compile_window(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.compile_s += time.perf_counter() - t0

    @contextlib.contextmanager
    def steady_window(self, iters: int = 0):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.steady_s += time.perf_counter() - t0
            self.steady_iters += iters

    def attach_guard(self, report: Dict) -> None:
        """Fold in a RecompileGuard report (host_syncs / cache misses)."""
        self.guard_report = report or {}

    def to_dict(self) -> Dict:
        out = {"compile_s": round(self.compile_s, 3),
               "steady_s": round(self.steady_s, 3),
               "steady_iters": self.steady_iters}
        if self.steady_iters and self.steady_s:
            out["steady_s_per_iter"] = round(
                self.steady_s / self.steady_iters, 4)
        if self.guard_report:
            out["host_syncs"] = self.guard_report.get("host_syncs")
            out["post_warmup_cache_misses"] = self.guard_report.get(
                "post_warmup_cache_misses")
        self._publish(out)
        return out

    def _publish(self, d: Dict) -> None:
        """Mirror this phase into the registry (gauges keyed by phase name —
        idempotent, so repeated to_dict() calls don't skew anything)."""
        from . import get_registry
        reg = get_registry()
        for key in ("compile_s", "steady_s", "steady_iters",
                    "steady_s_per_iter"):
            if d.get(key) is not None:
                reg.gauge(f"phase.{self.name}.{key}").set(d[key])
