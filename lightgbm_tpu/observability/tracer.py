"""Host-side span tracer: nested wall-clock spans at dispatch boundaries.

The span model mirrors the training stack's host-visible structure:

    train                       engine.train (one per call)
      tree_batch                one jit dispatch (K fused iterations)
        iteration               per boosting iteration; DERIVED slices of
                                the tree_batch span when K > 1 (the fused
                                scan is opaque to the host by design)
          wave                  DERIVED from the finished tree's leaf count
                                (grower.waves_for_tree) at telemetry-publish
                                time — the while_loop runs device-side
      eval | comm | checkpoint  real host-side operations

Spans are recorded ONLY at host dispatch boundaries: entering/leaving a span
costs two ``time.perf_counter()`` calls and one dict append — no device
array is ever touched, so the fused ``tree_batch`` path stays recompile-free
and host-sync-free with telemetry on (asserted by ``bench.py --smoke``).
Device-internal phases (histogram / split / partition) have no host
boundary; their true timing comes from the optional ``jax.profiler`` window
(``tpu_profile_iters``, observability/profiler.py) — the derived iteration/
wave spans are explicitly labeled ``"derived": true`` in their args.

When disabled (the default), ``span()`` returns a shared no-op context
manager: the hot loop pays one attribute check per dispatch and nothing
else.

Events use the Chrome trace-event schema directly (``ph: "X"`` complete
events, microsecond timestamps) so the JSONL stream and the Perfetto
export are the same records (export.py).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional


class _NullSpan:
    """Shared no-op context manager for the disabled tracer."""
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "name", "args", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str, args: Dict):
        self._tracer = tracer
        self.name = name
        self.args = args

    def __enter__(self):
        self._t0 = self._tracer._now_us()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._tracer._finish(self, self._t0, exc_type)
        return False


class SpanTracer:
    """Bounded in-memory recorder of finished spans and instant events."""

    def __init__(self, max_events: int = 200_000):
        self.enabled = False
        self.max_events = max_events
        self.dropped = 0
        self._events: List[Dict] = []
        self._lock = threading.Lock()
        self._epoch = time.perf_counter()
        self._epoch_unix = time.time()

    # --------------------------------------------------------------- recording

    def _now_us(self) -> int:
        return int((time.perf_counter() - self._epoch) * 1e6)

    def span(self, name: str, **args):
        """Context manager recording one complete ("X") span on exit."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args)

    def _finish(self, span: _Span, t0: int, exc_type) -> None:
        args = span.args
        if exc_type is not None:
            args = dict(args, error=exc_type.__name__)
        self._record({"name": span.name, "ph": "X", "ts": t0,
                      "dur": max(self._now_us() - t0, 0),
                      "pid": os.getpid(), "tid": threading.get_ident(),
                      "cat": "lightgbm_tpu", "args": args})

    def event(self, name: str, **args) -> None:
        """Instant ("i") event — e.g. a nan_policy trip, a booster init."""
        if not self.enabled:
            return
        self._record({"name": name, "ph": "i", "ts": self._now_us(), "s": "p",
                      "pid": os.getpid(), "tid": threading.get_ident(),
                      "cat": "lightgbm_tpu", "args": args})

    def _record(self, ev: Dict) -> None:
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return
            self._events.append(ev)

    # ------------------------------------------------------- derived children

    def subdivide_last(self, parent_name: str, child_name: str, n: int,
                       base_iteration: int = 0) -> None:
        """Slice the most recent ``parent_name`` span into ``n`` equal
        ``child_name`` children (the fused-batch iteration attribution: the
        scan body is one dispatch, so per-iteration timing inside it is an
        even split by construction — marked ``derived``)."""
        if not self.enabled or n <= 0:
            return
        with self._lock:
            parent = next((e for e in reversed(self._events)
                           if e["name"] == parent_name and e["ph"] == "X"),
                          None)
        if parent is None:
            return
        self._slice(parent, child_name, n,
                    [{"iteration": base_iteration + i} for i in range(n)])

    def derive_children(self, parent_name: str, child_name: str,
                        counts: List[int]) -> None:
        """Attach ``counts[i]`` derived children to the LAST ``len(counts)``
        not-yet-derived ``parent_name`` spans, in order (telemetry publish:
        wave spans from per-tree leaf counts — the publishing run's
        iteration spans are the most recently recorded, so tail alignment
        pairs each count with its own iteration even when earlier
        direct-loop spans exist). Parents are marked so repeated publishes
        (multiple train() calls per process) never double-derive."""
        if not self.enabled or not counts:
            return
        with self._lock:
            parents = [e for e in self._events
                       if e["name"] == parent_name and e["ph"] == "X"
                       and not e["args"].get(f"{child_name}s_derived")]
        # tail-align both sides: a resumed booster's counts include restored
        # iterations that never recorded a span in this process
        n = min(len(parents), len(counts))
        parents, counts = parents[-n:], list(counts)[-n:]
        for parent, cnt in zip(parents, counts):
            parent["args"][f"{child_name}s_derived"] = True
            if cnt > 0:
                self._slice(parent, child_name, int(cnt),
                            [{child_name: i} for i in range(int(cnt))])

    def _slice(self, parent: Dict, child_name: str, n: int,
               args_list: List[Dict]) -> None:
        dur = parent["dur"] / n
        for i in range(n):
            args = dict(args_list[i], derived=True)
            self._record({"name": child_name, "ph": "X",
                          "ts": int(parent["ts"] + i * dur),
                          "dur": max(int(dur), 1),
                          "pid": parent["pid"], "tid": parent["tid"],
                          "cat": "lightgbm_tpu.derived", "args": args})

    # ----------------------------------------------------------------- export

    def events(self) -> List[Dict]:
        """Copy of every recorded event (chronological by record order)."""
        with self._lock:
            return list(self._events)

    def events_since(self, cursor: int):
        """(new_events, new_cursor) — incremental drain for the JSONL sink."""
        with self._lock:
            return list(self._events[cursor:]), len(self._events)

    def epoch_unix(self) -> float:
        """Wall-clock time of ``ts == 0`` (for correlating JSONL streams)."""
        return self._epoch_unix

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0
            self._epoch = time.perf_counter()
            self._epoch_unix = time.time()
