"""HBM accounting: device memory stats + analytic pre-flight budget.

Two halves:

- ``device_memory()`` — the one home of the ``device.memory_stats()`` read
  (bench.py used to inline it twice), with backend fallbacks: TPU runtimes
  report ``bytes_in_use``/``peak_bytes_in_use``/``bytes_limit``, the CPU
  backend returns ``None``, and a jax-free process gets ``{}`` — callers
  never branch on backend. Folded into ``observability.snapshot()``.

- ``hbm_preflight(gbdt)`` — an analytic model of the wave loop's device
  residency as a function of N/features/bins/slots/wave state: the binned
  code matrix, packed gather rows, scores + gradients, the carried leaf
  partition, the per-leaf histogram cache, and the per-wave matmul
  temporaries. This is the "will it fit?" answer *before* the first
  compile — the prerequisite question for out-of-core training (ROADMAP
  item 3, arXiv 2005.09148: chunk residency planning needs exactly this
  breakdown) and for sizing double-buffered feeding (arXiv 1806.11248).
  ``engine.train`` logs the budget line and warns when the estimate
  exceeds the device capacity ``device_memory()`` reports. The estimate is
  cross-checked against the compiled step's ``memory_analysis()`` in
  tests/test_costs.py (tolerance-banded, two shape classes).

Pure host arithmetic — nothing here touches device state beyond the
(optional) ``memory_stats()`` query.
"""
from __future__ import annotations

import sys
from typing import Dict, Optional

_GB = float(1 << 30)


# ---------------------------------------------------------- device memory

def _backend_initialized() -> bool:
    """True iff some jax backend has ALREADY been instantiated — the single
    probe point for the private registry (same stance as
    parallel.comm.distributed_client). ``jax.local_devices()`` on a
    merely-imported jax would itself initialize the backend, which on a TPU
    host grabs the libtpu runtime exclusively."""
    if "jax" not in sys.modules:
        return False
    try:
        from jax._src import xla_bridge
        return bool(xla_bridge._backends)
    except Exception:                                        # noqa: BLE001
        return False


def device_memory(device=None) -> Dict:
    """Memory stats of one device (default: first local), normalized across
    backends. Keys always present when a device exists: ``platform``;
    ``peak_bytes`` falls back peak_bytes_in_use -> bytes_in_use -> None and
    ``capacity_bytes`` is ``bytes_limit`` or None (CPU backends report
    nothing). Returns ``{}`` in a jax-free / backend-less process — the
    serving ``snapshot()`` path must never force a backend init, so with no
    explicit ``device`` the query runs only when a backend already
    exists."""
    if device is None and not _backend_initialized():
        return {}
    try:
        import jax
        dev = device if device is not None else jax.local_devices()[0]
    except Exception:                                        # noqa: BLE001
        return {}
    out: Dict = {"platform": getattr(dev, "platform", "unknown")}
    try:
        stats = dev.memory_stats() or {}
    except Exception:                                        # noqa: BLE001
        stats = {}
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                "largest_alloc_size"):
        if stats.get(key) is not None:
            out[key] = int(stats[key])
    out["peak_bytes"] = out.get("peak_bytes_in_use",
                                out.get("bytes_in_use"))
    out["capacity_bytes"] = out.get("bytes_limit")
    return out


# ------------------------------------------------------ analytic pre-flight

def hbm_budget_bytes(config=None) -> Optional[int]:
    """The per-device HBM budget the residency decision judges against:
    env ``LGBM_TPU_HBM_BUDGET`` > config ``tpu_hbm_budget_bytes`` > the
    capacity the backend reports (None when nothing is known — CPU
    backends report no limit). The artificial knobs exist so out-of-core
    behavior is testable on any host (bench.py --stream trains a dataset
    >= 4x a configured budget on CPU)."""
    import os
    env = os.environ.get("LGBM_TPU_HBM_BUDGET", "")
    if env:
        try:
            return int(float(env))
        except ValueError:
            from ..utils.log import Log
            Log.warning("LGBM_TPU_HBM_BUDGET=%r is not a byte count — "
                        "ignoring it (use plain bytes, e.g. 17179869184)",
                        env)
    if config is not None and getattr(config, "tpu_hbm_budget_bytes", 0) > 0:
        return int(config.tpu_hbm_budget_bytes)
    cap = device_memory().get("capacity_bytes")
    return int(cap) if cap else None

def estimate_wave_residency(*, rows: int, cols: int, code_itemsize: int,
                            num_models: int, num_leaves: int,
                            hist_cols: int, hist_bins: int,
                            cache_cols: int, cache_bins: int,
                            num_bins_padded: int, slots: int,
                            chunk_rows: int, channels: int,
                            channel_bytes: int, packed_row_bytes: int = 0,
                            row_compact: bool = True,
                            incremental: bool = True, bagging: bool = False,
                            has_weight: bool = False, tree_batch: int = 1,
                            compensated: bool = False,
                            valid_bytes: int = 0,
                            stream_shard_bytes: int = 0,
                            linear_max_features: int = 0) -> Dict:
    """Per-device HBM residency of one training step, by component (bytes).

    ``rows``/``cols`` are the PADDED per-device dims the step actually
    dispatches ([Npad(/D), cols_pad]); the model mirrors the buffers the
    grower documents (GrowState carry + the jit-level donated carry):

    - codes:      the binned (possibly bundled) code matrix — or, with
                  ``stream_shard_bytes`` set (tpu_residency=stream), the
                  TWO ping-pong shard buffers of the prefetcher: per-shard
                  instead of full-N residency is the whole point of the
                  out-of-core mode
    - metadata:   label/pad_mask(/bag_mask/weight) row vectors, f32
    - scores:     the [K, N] carried score (donation keeps ONE copy live)
    - gradients:  g and h, [K, N] f32 each
    - partition:  leaf_id (+ the carried permutation and segment tables
                  under the incremental partition)
    - packed:     the per-tree packed gather rows (code bytes + weight
                  channel bytes per row)
    - hist_cache: the [L+1, F_cache, B_cache, 3] f32 per-leaf cache
    - wave_temps: the per-chunk one-hot operand, the [chunk, S*ch] rhs, and
                  the [F, B, S*ch] f32 accumulator (x2 Kahan-compensated)
    - trees:      stacked per-batch tree outputs (small)
    - valid:      attached validation sets (codes + scores), if any
    - linear:     linear_tree=true only (``linear_max_features`` > 0): the
                  device-resident raw f32 slice + missing plane
                  ([N, F] x 5 B), the per-leaf moment buffers
                  ([L+1, K+1, K+1] + [L+1, K+1] f32), and the chunked
                  one-hot gather intermediate of the fit leg
    """
    f32 = 4
    comp = {}
    comp["codes"] = (2 * stream_shard_bytes if stream_shard_bytes
                     else rows * cols * code_itemsize)
    comp["metadata"] = rows * f32 * (2 + int(bagging) + int(has_weight))
    comp["scores"] = num_models * rows * f32
    comp["gradients"] = 2 * num_models * rows * f32
    comp["partition"] = rows * f32 * (2 if incremental else 1) \
        + (2 * (num_leaves + 1) * f32 if incremental else 0)
    comp["packed"] = rows * packed_row_bytes if row_compact else 0
    comp["hist_cache"] = (num_leaves + 1) * cache_cols * cache_bins * 3 * f32
    acc = hist_cols * hist_bins * slots * channels * f32
    comp["wave_temps"] = (acc * (2 if compensated else 1)
                          + chunk_rows * hist_cols * hist_bins * channel_bytes
                          + chunk_rows * slots * channels * channel_bytes)
    per_tree = ((num_leaves) * num_bins_padded          # cat_mask, bool
                + 13 * (num_leaves + 1) * f32)          # node/leaf arrays
    comp["trees"] = max(1, tree_batch) * num_models * per_tree
    comp["valid"] = valid_bytes
    comp["linear"] = 0
    if linear_max_features > 0:
        K1 = linear_max_features + 1
        lin_chunk = min(chunk_rows, 8192)
        comp["linear"] = (
            rows * cols * (f32 + 1)                    # raw slice + missing
            + (num_leaves + 1) * (K1 * K1 + K1 + 1) * f32   # moments
            + lin_chunk * linear_max_features * cols * f32  # one-hot gather
            + lin_chunk * (K1 * K1 + K1 + 1) * f32)         # channel matrix
    total = int(sum(comp.values()))
    return {"components": {k: int(v) for k, v in comp.items()},
            "total_bytes": total,
            "total_gb": round(total / _GB, 3)}


def hbm_preflight(gbdt) -> Dict:
    """Analytic pre-flight for a constructed booster: reads the spec and
    array shapes the step will dispatch (no device traffic) and returns the
    ``estimate_wave_residency`` breakdown plus the dims it used. Results
    land in the registry as ``memory.preflight.*`` gauges."""
    import numpy as np

    spec = gbdt.spec
    pctx = gbdt.pctx
    # per-device rows under row-sharded strategies; feature-parallel
    # replicates rows but slices columns
    n_dev = max(1, pctx.num_devices)
    rows = gbdt.num_data_padded
    residency = getattr(gbdt, "residency", "device")
    stream_store = getattr(gbdt, "_stream_store", None)
    if stream_store is not None:
        # out-of-core: the code matrix never materializes on device — only
        # the prefetcher's two shard buffers count (per-shard residency)
        cols = int(stream_store.num_cols)
        code_itemsize = int(np.dtype(stream_store.dtype).itemsize)
        stream_shard_bytes = int(stream_store.shard_bytes) // n_dev \
            if pctx.mesh is not None and pctx.strategy in ("data", "voting") \
            else int(stream_store.shard_bytes)
    else:
        cols = int(gbdt.Xb.shape[1])
        code_itemsize = int(np.dtype(gbdt.Xb.dtype).itemsize)
        stream_shard_bytes = 0
    if pctx.mesh is not None and pctx.strategy in ("data", "voting"):
        rows = rows // n_dev
    hist_cols = cols
    if pctx.mesh is not None and pctx.strategy == "feature":
        hist_cols = max(1, cols // n_dev)
    B = spec.num_bins_padded
    B_hist = spec.hist_bins or B
    cache_cols = hist_cols
    try:
        cache_cols = int(gbdt.comm.reduced_hist_features(hist_cols))
    except Exception as e:                                   # noqa: BLE001
        from ..utils.log import Log
        Log.debug("hbm_preflight: reduced_hist_features unavailable "
                  "(using %d): %s: %s", cache_cols, type(e).__name__, e)
    if spec.hist_f64:
        channels, channel_bytes = 3, 4
    elif spec.hist_hilo:
        channels, channel_bytes = 5, 2
    else:
        channels, channel_bytes = 3, 2
    packed_row_bytes = 0
    if spec.row_compact:
        from ..ops.histogram import code_bytes_total, default_code_mode
        mode = spec.code_mode or default_code_mode(gbdt.Xb.dtype)
        packed_row_bytes = (code_bytes_total(hist_cols, mode)
                            + channels * channel_bytes)
    valid_bytes = 0
    for vs in getattr(gbdt, "valid_sets", ()):
        valid_bytes += int(vs.Xb.shape[0]) * (
            int(vs.Xb.shape[1]) * int(np.dtype(vs.Xb.dtype).itemsize)
            + gbdt.num_models * 4)
        if getattr(vs, "Xraw", None) is not None:
            # linear_tree: the valid raw slice (f32) + missing plane (bool)
            valid_bytes += int(vs.Xraw.shape[0]) * int(vs.Xraw.shape[1]) * 5
    dims = dict(rows=rows, cols=cols, code_itemsize=code_itemsize,
                num_models=gbdt.num_models, num_leaves=spec.num_leaves,
                hist_cols=hist_cols, hist_bins=B_hist,
                cache_cols=cache_cols, cache_bins=B_hist,
                num_bins_padded=B, slots=spec.hist_slots,
                chunk_rows=spec.chunk_rows, channels=channels,
                channel_bytes=channel_bytes,
                packed_row_bytes=packed_row_bytes,
                row_compact=spec.row_compact,
                incremental=spec.row_compact and spec.incremental_partition,
                bagging=bool(getattr(gbdt, "bagging_on", False)),
                has_weight=gbdt.weight is not None,
                tree_batch=int(getattr(gbdt, "tree_batch", 1)),
                compensated=spec.hist_f64, valid_bytes=valid_bytes,
                stream_shard_bytes=stream_shard_bytes,
                linear_max_features=(
                    int(getattr(gbdt.config, "linear_max_features", 0))
                    if getattr(gbdt, "linear_tree", False) else 0))
    est = estimate_wave_residency(**dims)
    est["dims"] = dims
    est["residency"] = residency
    if stream_store is not None:
        est["stream"] = stream_store.describe()
    from . import get_registry
    reg = get_registry()
    reg.gauge("memory.preflight.total_bytes").set(est["total_bytes"])
    for k, v in est["components"].items():
        reg.gauge(f"memory.preflight.{k}_bytes").set(v)
    return est


def log_budget(estimate: Dict, devmem: Optional[Dict] = None,
               budget: Optional[int] = None) -> bool:
    """The engine.train budget line: one INFO line with the breakdown, and
    a WARNING when the estimate exceeds the budget (``tpu_hbm_budget_bytes``
    / env / reported device capacity). Returns True when the estimate fits
    (or no budget is known).

    Residency-aware: under ``tpu_residency=stream`` the estimate already
    counts only the two ping-pong shard buffers, the line says so, and the
    warning fires only when even the STREAMED state does not fit. Under
    forced device residency the warning points at ``tpu_residency=stream``
    as the remedy (auto-selection would already have taken it)."""
    from ..utils.log import Log

    comp = estimate["components"]
    top = sorted(comp.items(), key=lambda kv: -kv[1])[:4]
    detail = ", ".join(f"{k} {v / _GB:.2f}" for k, v in top if v)
    devmem = devmem if devmem is not None else device_memory()
    cap = budget if budget is not None else devmem.get("capacity_bytes")
    cap_s = f" / {cap / _GB:.2f} GB budget" if cap else ""
    residency = estimate.get("residency", "device")
    stream = estimate.get("stream")
    stream_s = ""
    if residency == "stream" and stream:
        stream_s = (f" [tpu_residency=stream: codes in {stream['n_shards']} "
                    f"host shards x {stream['shard_bytes'] / _GB:.3f} GB, "
                    f"{stream['code_mode']} packed]")
    Log.info("HBM pre-flight: %.2f GB estimated per device (%s)%s%s",
             estimate["total_bytes"] / _GB, detail, cap_s, stream_s)
    if cap and estimate["total_bytes"] > cap:
        if residency == "stream":
            Log.warning(
                "HBM pre-flight: even the STREAMED training state (%.2f "
                "GB — gradients/scores/partition + two shard buffers) "
                "exceeds the %.2f GB budget (platform=%s): shrink "
                "tpu_stream_shard_rows, shard rows across chips "
                "(tree_learner=data), or lower tree_batch",
                estimate["total_bytes"] / _GB, cap / _GB,
                devmem.get("platform"))
        else:
            Log.warning(
                "HBM pre-flight: estimated residency %.2f GB EXCEEDS the "
                "%.2f GB budget (platform=%s) — expect an OOM at first "
                "dispatch; set tpu_residency=stream (host-resident code "
                "shards, docs/TPU-Performance.md) or shard the rows "
                "across chips (tree_learner=data)",
                estimate["total_bytes"] / _GB, cap / _GB,
                devmem.get("platform"))
        return False
    return True
