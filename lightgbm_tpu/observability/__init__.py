"""Unified training telemetry (docs/Observability.md).

One subsystem for every runtime signal the boosting stack produces:

- ``SpanTracer`` (tracer.py)      — nested host-side spans
  (train -> tree_batch -> iteration -> wave, plus eval/comm/checkpoint),
  recorded at dispatch boundaries only so the fused step and the
  recompile-free steady state are preserved.
- ``MetricsRegistry`` (metrics.py) — process-wide counters/gauges/
  histograms/quantile summaries absorbing ``RecompileGuard.report()``,
  ``PhaseBreakdown``, comm retries/timeouts, ``nan_policy`` events,
  checkpoint writes, per-booster kernel choice, waves per tree, rows
  routed, and the serving subsystem's per-request latency p50/p99
  (``serve.*``, docs/Serving.md).
- exporters (export.py)           — JSONL event stream + Chrome trace-event
  JSON (Perfetto-loadable) under ``LGBM_TPU_TELEMETRY_DIR`` / config
  ``telemetry_dir``; ``snapshot()`` is the point-in-time serving API.
- ``ProfileWindow`` (profiler.py) — optional ``jax.profiler`` capture of an
  iteration range (``tpu_profile_iters=start:stop``).
- cost reports (costs.py)         — compile-time ``cost_analysis()`` /
  ``memory_analysis()`` capture per dispatch site (opt-in:
  ``tpu_cost_analysis`` / ``LGBM_TPU_COST_ANALYSIS``), published as
  ``cost.<site>.*`` gauges, into ``snapshot()``, and as Perfetto metadata.
- HBM accounting (memory.py)      — ``device_memory()`` stats helper and
  the analytic pre-flight residency estimate ``engine.train`` budgets
  against before the first compile.
- perf ledger (ledger.py)         — normalized BENCH/MULTICHIP history +
  regression compare (``bench.py --compare`` / ``make bench-diff``).

The module singletons are process-wide on purpose: a training run, the
bench harness, and a serving probe all read the same registry. Everything
here is jax-free at import time (the lint CLI and guards publish through
it in jax-free environments).

Overhead contract: with no telemetry directory configured the tracer is
disabled — ``span()`` returns a shared no-op and the registry costs one
dict lookup + int add per event, at host boundaries only. ``bench.py
--smoke`` enforces that telemetry-on adds zero steady-state recompiles and
zero new host syncs inside the fused step.
"""
from __future__ import annotations

import os
import time
from typing import Dict, Optional

from .metrics import MetricsRegistry
from .phases import PhaseBreakdown  # noqa: F401  (public: bench phase timing)
from .tracer import SpanTracer

ENV_TELEMETRY_DIR = "LGBM_TPU_TELEMETRY_DIR"


def clock() -> float:
    """Monotonic wall-clock for package modules whose measurements FEED the
    registry/trace (the streaming prefetcher's stall accounting,
    ops/stream.py). tpu-lint R008 keeps raw ``time.perf_counter()`` out of
    package code so no timing lives outside observability; this is the one
    sanctioned source for code that reports its numbers here."""
    return time.perf_counter()

_registry = MetricsRegistry()
_tracer = SpanTracer()
_state: Dict = {"dir": None, "jsonl_cursor": 0, "env_checked": False}


# ------------------------------------------------------------- configuration

def get_registry() -> MetricsRegistry:
    return _registry


def get_tracer() -> SpanTracer:
    return _tracer


def enabled() -> bool:
    """True when spans are being recorded (a telemetry dir is configured or
    the tracer was force-enabled)."""
    return _tracer.enabled


def telemetry_dir() -> Optional[str]:
    return _state["dir"]


def configure(telemetry_dir: Optional[str] = None,
              enabled: Optional[bool] = None) -> None:
    """Point the exporters at ``telemetry_dir`` (created if missing) and/or
    force the tracer on/off. Setting a directory enables the tracer unless
    ``enabled=False`` is passed explicitly."""
    if telemetry_dir:
        os.makedirs(telemetry_dir, exist_ok=True)
        _state["dir"] = telemetry_dir
        if enabled is None:
            enabled = True
    if enabled is not None:
        _tracer.enabled = bool(enabled)


def maybe_configure_from_env() -> None:
    """Honor ``LGBM_TPU_TELEMETRY_DIR`` once per process (called from every
    training entry point; explicit ``configure()`` calls always win)."""
    if _state["env_checked"]:
        return
    _state["env_checked"] = True
    env = os.environ.get(ENV_TELEMETRY_DIR)
    if env and _state["dir"] is None:
        configure(telemetry_dir=env)


# ----------------------------------------------------------------- recording

def span(name: str, **args):
    """``with observability.span("tree_batch", k=4): ...`` — no-op when
    telemetry is disabled."""
    return _tracer.span(name, **args)


def event(name: str, **args) -> None:
    _tracer.event(name, **args)


def inc(name: str, n: int = 1) -> None:
    _registry.inc(name, n)


# ------------------------------------------------------------------- export

def trace_path() -> Optional[str]:
    d = _state["dir"]
    return os.path.join(d, f"trace_{os.getpid()}.json") if d else None


def jsonl_path() -> Optional[str]:
    d = _state["dir"]
    return os.path.join(d, f"events_{os.getpid()}.jsonl") if d else None


def snapshot() -> Dict:
    """Point-in-time metrics snapshot (the serving API): registry contents
    plus tracer bookkeeping, the captured compile-time cost reports
    (costs.py), and the device memory stats (memory.py — ``{}``-safe in a
    jax-free process, so this stays callable from anywhere)."""
    snap = _registry.snapshot()
    snap["spans_recorded"] = len(_tracer.events())
    snap["spans_dropped"] = _tracer.dropped
    from . import costs as _costs
    cost_reports = _costs.reports()
    if cost_reports:
        snap["cost_reports"] = cost_reports
    from .memory import device_memory
    dm = device_memory()
    if dm:
        snap["device_memory"] = dm
    return snap


def write_snapshot(path: str) -> str:
    """Write ``snapshot()`` to ``path`` as JSON (atomic) — the
    ``--dump-snapshot`` / train-end artifact harvest windows collect."""
    from .export import atomic_write_json
    return atomic_write_json(path, snapshot(), indent=1, sort_keys=True,
                             trailing_newline=True)


def flush() -> Optional[str]:
    """Write pending telemetry to disk: append new events + a counters
    record to the JSONL stream, rewrite the Chrome trace. Returns the trace
    path (None when no directory is configured). Called at training exit
    (engine.train) and bench boundaries — never inside the hot loop."""
    d = _state["dir"]
    if not d:
        return None
    from .export import JsonlWriter, write_chrome_trace
    new, _state["jsonl_cursor"] = _tracer.events_since(_state["jsonl_cursor"])
    records = [dict(ev, type="span" if ev.get("ph") == "X" else "event")
               for ev in new]
    records.append(dict(snapshot(), type="counters"))
    JsonlWriter(jsonl_path()).append(records)
    from . import costs as _costs
    metadata = {"epoch_unix": _tracer.epoch_unix()}
    cost_reports = _costs.reports()
    if cost_reports:
        # compile-time cost reports ride as trace metadata so the Perfetto
        # artifact is self-describing about what the traced step costs
        metadata["cost_reports"] = cost_reports
    return write_chrome_trace(_tracer.events(), trace_path(),
                              metadata=metadata)


def reset_for_tests() -> None:
    """Full reset of the process-wide singletons (test isolation)."""
    from . import costs as _costs
    _registry.reset()
    _tracer.reset()
    _tracer.enabled = False
    _state["dir"] = None
    _state["jsonl_cursor"] = 0
    _state["env_checked"] = False
    _costs.reset_for_tests()
