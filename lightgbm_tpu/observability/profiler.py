"""Optional ``jax.profiler`` capture window over a boosting-iteration range.

``tpu_profile_iters=start:stop`` captures a device-level profile (XProf /
TensorBoard / Perfetto) of exactly the iterations ``[start, stop)`` instead
of the whole run (``tpu_profile_dir`` alone wraps the full training loop in
one trace — utils/timer.maybe_xla_trace). The window is the deep-profiling
leg of the telemetry contract: host-side spans (tracer.py) attribute
dispatch boundaries; the profiler window attributes the device program
(histogram / split / partition) for the chosen iterations only, keeping
profile volume bounded at bench scale.

Window edges land on DISPATCH boundaries: under ``tree_batch=K`` the trace
starts at the first batch whose iterations overlap the window and stops at
the first batch boundary at-or-past ``stop`` — a fused batch is never split
(that would change the compiled program, violating the zero-recompile
contract).

jax is imported lazily at the start edge so this module stays importable in
jax-free environments (the lint CLI imports the observability package).
"""
from __future__ import annotations

from typing import Optional, Tuple

from ..utils.log import Log


def parse_profile_iters(spec: str) -> Optional[Tuple[int, int]]:
    """``"start:stop"`` -> (start, stop); None for empty. Raises ValueError
    on malformed input (config validation surfaces it as Log.fatal)."""
    if not spec:
        return None
    parts = spec.split(":")
    if len(parts) != 2:
        raise ValueError(
            f"tpu_profile_iters must be 'start:stop', got {spec!r}")
    try:
        start, stop = int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(
            f"tpu_profile_iters must be two integers 'start:stop', "
            f"got {spec!r}") from None
    if start < 0 or stop <= start:
        raise ValueError(
            f"tpu_profile_iters needs 0 <= start < stop, got {spec!r}")
    return start, stop


class ProfileWindow:
    """Start/stop a ``jax.profiler`` trace when the training loop crosses
    the configured iteration window (engine.train calls ``before_step`` /
    ``after_step`` at batch boundaries and ``close`` on exit)."""

    def __init__(self, spec: str, out_dir: str):
        window = parse_profile_iters(spec)
        if window and not out_dir:
            Log.warning("tpu_profile_iters=%s has no output directory "
                        "(set tpu_profile_dir or telemetry_dir) — "
                        "profiling window disabled", spec)
            window = None
        self.start_iter, self.stop_iter = window or (0, 0)
        self.enabled = window is not None
        self.out_dir = out_dir
        self.active = False
        self._done = False

    def before_step(self, it: int, batch: int = 1) -> None:
        """Called with the first iteration of the batch about to dispatch
        and the batch's iteration count. The trace starts at the first
        batch that OVERLAPS the window ([it, it+batch) ∩ [start, stop) is
        non-empty) — a window that begins mid-batch, or sits entirely
        inside one fused batch, still captures that batch instead of being
        clipped or silently skipped."""
        if not self.enabled or self.active or self._done:
            return
        if it >= self.stop_iter:        # resumed run already past the window
            self._done = True
            return
        if it + max(batch, 1) > self.start_iter:
            import jax
            jax.profiler.start_trace(self.out_dir)
            self.active = True
            Log.info("tpu_profile_iters: jax.profiler trace started at "
                     "iteration %d (window %d:%d) -> %s", it,
                     self.start_iter, self.stop_iter, self.out_dir)
            from . import get_tracer
            get_tracer().event("profiler_window_start", iteration=it,
                               out_dir=self.out_dir)

    def after_step(self, it_end: int) -> None:
        """Called with the first iteration AFTER the batch that finished."""
        if self.active and it_end >= self.stop_iter:
            self._stop(it_end)

    def close(self) -> None:
        """Stop an in-flight trace at training exit (early stop, errors)."""
        if self.active:
            self._stop(-1)

    def _stop(self, it_end: int) -> None:
        import jax
        try:
            jax.profiler.stop_trace()
        finally:
            self.active = False
            self._done = True
        Log.info("tpu_profile_iters: jax.profiler trace stopped (%s) -> %s",
                 f"iteration {it_end}" if it_end >= 0 else "training exit",
                 self.out_dir)
        from . import get_tracer
        get_tracer().event("profiler_window_stop", iteration=it_end,
                           out_dir=self.out_dir)
