"""Telemetry exporters: JSONL event stream + Chrome trace-event JSON.

Two on-disk products per process under the telemetry directory
(``LGBM_TPU_TELEMETRY_DIR`` / config ``telemetry_dir``):

- ``events_<pid>.jsonl`` — append-only stream of span/instant events plus
  periodic metric-registry snapshots (``{"type": "counters", ...}``), one
  JSON object per line. Meant for log shippers and the chaos-test
  assertions (tests/test_chaos.py).
- ``trace_<pid>.json``   — Chrome trace-event JSON (``{"traceEvents":
  [...]}``) loadable directly in Perfetto (https://ui.perfetto.dev) or
  ``chrome://tracing``. Rewritten atomically on every flush so a reader
  never sees a torn file.

Writes happen only at flush sites (end of ``engine.train``, bench
boundaries) — never inside the training loop.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List


def atomic_write_json(path: str, doc, *, indent=None,
                      sort_keys: bool = False,
                      trailing_newline: bool = False) -> str:
    """The one tmp+``os.replace`` atomic JSON write (pid-suffixed temp so
    concurrent writers in one checkout never clobber each other's
    in-flight file): a crash or race mid-write can never leave a truncated
    'valid' artifact behind. Used by the trace/snapshot exporters here and
    the perf ledger."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=indent, sort_keys=sort_keys)
        if trailing_newline:
            fh.write("\n")
    os.replace(tmp, path)
    return path


def write_chrome_trace(events: List[Dict], path: str,
                       metadata: Dict = None) -> str:
    """Write ``events`` (already in trace-event schema, tracer.py) as a
    Perfetto-loadable JSON object, atomically."""
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": dict(metadata or {}, producer="lightgbm_tpu"),
    }
    return atomic_write_json(path, doc)


class JsonlWriter:
    """Append-only JSONL sink; one record per line, flushed per batch."""

    def __init__(self, path: str):
        self.path = path

    def append(self, records: List[Dict]) -> None:
        if not records:
            return
        with open(self.path, "a") as fh:
            for rec in records:
                fh.write(json.dumps(rec) + "\n")


def read_jsonl(path: str) -> List[Dict]:
    """Parse a JSONL stream, skipping torn trailing lines (a reader racing
    the writer must not crash on the in-flight record)."""
    out = []
    if not os.path.exists(path):
        return out
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out
