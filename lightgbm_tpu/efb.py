"""Exclusive Feature Bundling (EFB) — host-side preprocessing.

Reference counterpart: Dataset::Construct's FindGroups / FastFeatureBundling
(src/io/dataset.cpp:66-210, :212-295) and the FeatureGroup bundled-bin
encoding (include/LightGBM/feature_group.h:30-52).

TPU framing: the binned training matrix is one dense ``[N, F]`` array whose
histogram cost is ``F × B_pad`` one-hot matmul columns per pass — every
near-always-default (sparse) feature still burns a full B_pad-wide column.
EFB packs mutually-(almost-)exclusive features into one bundled column whose
codes concatenate the member features' non-default bin ranges, cutting the
histogram build from F to G columns. It is exactly the "densifier" role the
reference gives EFB for its sparse formats, re-targeted at MXU column count.

Encoding (mirrors FeatureGroup::PushData semantics):
- bundle code 0 == every member feature at its default bin;
- member j with original bins ``0..nb_j-1`` and default bin d_j occupies the
  code range ``[lo_j, hi_j)`` where codes map back as
  ``orig_bin = code - off_j``; the default bin has no code (rows at default
  push nothing) and is reconstructed downstream by subtraction from leaf
  totals — the reference's FixHistogram (dataset.cpp:750-769), which the
  serial learner applies to every feature anyway.
- on a conflict row (two members non-default) the later member in group
  order wins; the loser's mass lands in its default bin. Bounded by
  ``max_conflict_rate`` exactly as in the reference.

Everything here is NumPy on host — bundling is O(sample × F) preprocessing,
not device work.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import numpy as np


_MAX_SEARCH_GROUPS = 100          # reference max_search_group (dataset.cpp:75)
_SAMPLE_ROWS = 100_000


@dataclass
class BundlePlan:
    """Result of planning + materializing bundles for one dataset.
    ``X_bundled`` is None when planned without a bin matrix (deferred
    device ingest plans from a row sample); ``materialize_bundles`` fills
    it if the plan wins."""
    X_bundled: Optional[np.ndarray]  # [N, G] uint8/uint16 bundled codes
    groups: List[List[int]]        # group -> member (inner) feature indices
    group_total_bins: np.ndarray   # [G] i64 bins per bundled column (incl. 0)
    # per ORIGINAL (inner) feature arrays [F]:
    col: np.ndarray                # bundled column holding feature f
    lo: np.ndarray                 # first bundle code of f's non-default range
    hi: np.ndarray                 # one-past-last bundle code
    off: np.ndarray                # orig_bin = code - off for code in [lo, hi)
    unpack_bin: np.ndarray         # [F, B] bundle-bin for (f, orig_bin); -1 =
                                   # default/invalid (reconstructed by FixHistogram)

    @property
    def num_groups(self) -> int:
        return len(self.groups)

    @property
    def max_bundle_bins(self) -> int:
        return int(self.group_total_bins.max()) if len(self.group_total_bins) else 1


def _find_groups(masks: np.ndarray, counts: np.ndarray, order: np.ndarray,
                 nbins_eff: np.ndarray, max_error_cnt: int, filter_cnt: float,
                 num_data: int, max_group_bins: int) -> List[List[int]]:
    """Greedy conflict-bounded grouping (reference FindGroups,
    dataset.cpp:66-137). ``masks[:, f]`` is the sampled non-default mask."""
    S = masks.shape[0]
    feats: List[List[int]] = []
    marks: List[np.ndarray] = []
    conflict: List[int] = []
    bins: List[int] = []
    for f in order:
        f = int(f)
        placed = False
        avail = [g for g in range(len(feats))
                 if bins[g] + nbins_eff[f] <= max_group_bins]
        # reference searches the newest group + a random subset capped at 100;
        # newest-first over a deterministic cap keeps the same O(1) behavior
        for g in reversed(avail[-_MAX_SEARCH_GROUPS:]):
            rest = max_error_cnt - conflict[g]
            if rest < 0:
                continue
            cnt = int(np.count_nonzero(marks[g] & masks[:, f]))
            if cnt <= rest:
                rest_nonzero = (counts[f] - cnt) * num_data / max(S, 1)
                if rest_nonzero < filter_cnt:
                    continue
                feats[g].append(f)
                conflict[g] += cnt
                marks[g] |= masks[:, f]
                bins[g] += int(nbins_eff[f])
                placed = True
                break
        if not placed:
            feats.append([f])
            marks.append(masks[:, f].copy())
            conflict.append(0)
            bins.append(1 + int(nbins_eff[f]))
    return feats


def build_code_feat(plan: "BundlePlan", cols_pad: int, bins_pad: int,
                    default_bin: np.ndarray) -> np.ndarray:
    """[cols_pad, bins_pad] i32 inverse code map: the member feature owning
    each bundle code, -1 for unowned positions.

    The native bundle-space split scan (ops/split_finder.py
    per_feature_best_bundled) is driven by this table: code 0 (all members
    at default), bin padding, and the default-bin hole at
    ``off[f] + default_bin[f]`` are unowned — the default bin's mass is
    never stored (reference FeatureGroup encoding, feature_group.h:30-52)
    and is reconstructed by subtraction at scan time. For shift-1 members
    (default bin 0) the hole position ``lo - 1`` falls OUTSIDE the member's
    range and must not clobber the neighbouring member's last code, hence
    the in-range test."""
    F = plan.col.shape[0]
    cf = np.full((cols_pad, bins_pad), -1, np.int32)
    for f in range(F):
        g, lo, hi, off = (int(plan.col[f]), int(plan.lo[f]),
                          int(plan.hi[f]), int(plan.off[f]))
        if hi > lo:
            cf[g, lo:hi] = f
            hole = off + int(default_bin[f])
            if lo <= hole < hi:
                cf[g, hole] = -1
    return cf


def sample_row_indices(num_data: int, max_rows: int = _SAMPLE_ROWS,
                       rng_seed: int = 1) -> np.ndarray:
    """The sorted row indices :func:`sample_rows` would draw — exposed so
    a DEFERRED dataset (tpu_ingest, dataset.DeferredBinning) can bin
    exactly this sample through the host oracle and plan from it: the
    plan is a pure function of the sample, so planning from
    ``bin_rows(sample_row_indices(N))`` is bit-identical to planning from
    the materialized matrix."""
    if num_data <= max_rows:
        return np.arange(num_data)
    rng = np.random.RandomState(rng_seed)
    return np.sort(rng.choice(num_data, max_rows, replace=False))


def sample_rows(X_binned: np.ndarray, max_rows: int = _SAMPLE_ROWS,
                rng_seed: int = 1) -> np.ndarray:
    """Deterministic row sample for conflict estimation. Exposed so the
    pre-partitioned path can sample each LOCAL shard, allgather the samples,
    and hand every rank the identical concatenation (the reference plans
    bundles from the same distributed sample it bins from,
    dataset_loader.cpp:820-899)."""
    N = X_binned.shape[0]
    if N <= max_rows:
        return np.asarray(X_binned)
    return X_binned[sample_row_indices(N, max_rows, rng_seed)]


def plan_bundles(X_binned: Optional[np.ndarray], num_bins: np.ndarray,
                 default_bin: np.ndarray, config,
                 max_group_bins: int = 256,
                 rng_seed: int = 1,
                 sample: Optional[np.ndarray] = None,
                 num_data: Optional[int] = None) -> Optional[BundlePlan]:
    """Plan and materialize EFB bundles; None when bundling cannot help.

    Mirrors FastFeatureBundling (dataset.cpp:141-215): try both original and
    by-nonzero-count order, keep the grouping with fewer groups. The
    small-sparse-group breakup (:186-203) is intentionally absent: there is
    no sparse bin storage here — dense bundled columns are always the win.

    ``sample``/``num_data`` override the local sample and global row count
    for the pre-partitioned case: the plan must be a pure function of the
    (identical) sample so every rank derives the same bundling, while the
    materialized codes come from the LOCAL ``X_binned`` shard.

    ``X_binned=None`` (deferred device ingest) plans WITHOUT a bin matrix
    — ``sample`` and ``num_data`` are then required, and the returned
    plan's ``X_bundled`` is None until :func:`materialize_bundles` fills
    it (only a winning plan pays that host materialization).
    """
    if X_binned is None:
        assert sample is not None and num_data is not None
        N, F = int(num_data), sample.shape[1]
    else:
        N, F = X_binned.shape
    if F < 2:
        return None
    # conflict estimation on a row sample (the reference uses its
    # bin-construction sample; we sample the materialized bin matrix)
    if sample is None:
        sample = sample_rows(X_binned, rng_seed=rng_seed)
    if num_data is None:
        num_data = N
    S = sample.shape[0]

    masks = sample != default_bin[None, :]                   # non-default mask
    counts = np.count_nonzero(masks, axis=0)
    nbins_eff = num_bins - (default_bin == 0).astype(np.int64)

    max_error_cnt = int(S * getattr(config, "max_conflict_rate", 0.0))
    filter_cnt = (0.95 * getattr(config, "min_data_in_leaf", 20)
                  / max(num_data, 1) * S)

    order1 = np.arange(F)
    order2 = np.argsort(-counts, kind="stable")
    g1 = _find_groups(masks, counts, order1, nbins_eff, max_error_cnt,
                      filter_cnt, num_data, max_group_bins)
    g2 = _find_groups(masks, counts, order2, nbins_eff, max_error_cnt,
                      filter_cnt, num_data, max_group_bins)
    groups = g2 if len(g2) < len(g1) else g1
    if len(groups) >= F:
        return None                                           # nothing bundled

    G = len(groups)
    B = int(num_bins.max())
    col = np.zeros(F, np.int32)
    lo = np.zeros(F, np.int32)
    hi = np.zeros(F, np.int32)
    off = np.zeros(F, np.int32)
    unpack_bin = np.full((F, B), -1, np.int32)
    group_total_bins = np.zeros(G, np.int64)

    for g, members in enumerate(groups):
        if len(members) == 1:
            # singleton: keep original codes (no re-encoding); default bin is
            # still reconstructed by subtraction like every other feature
            f = members[0]
            col[f] = g
            lo[f], hi[f], off[f] = 0, int(num_bins[f]), 0
            b = np.arange(int(num_bins[f]))
            unpack_bin[f, b] = b
            unpack_bin[f, int(default_bin[f])] = -1
            group_total_bins[g] = int(num_bins[f])
            continue
        total = 1                                             # code 0 = all-default
        for f in members:
            shift = 1 if default_bin[f] == 0 else 0
            nb = int(num_bins[f])
            col[f] = g
            lo[f] = total
            hi[f] = total + nb - shift
            off[f] = total - shift
            b = np.arange(nb)
            codes = b + off[f]
            valid = (b != default_bin[f]) & (codes >= lo[f]) & (codes < hi[f])
            unpack_bin[f, b[valid]] = codes[valid]
            total += nb - shift
        group_total_bins[g] = total

    plan = BundlePlan(None, groups, group_total_bins, col, lo, hi, off,
                      unpack_bin)
    if X_binned is not None:
        plan.X_bundled = materialize_bundles(plan, X_binned, default_bin)
    return plan


def materialize_bundles(plan: BundlePlan, X_binned: np.ndarray,
                        default_bin: np.ndarray) -> np.ndarray:
    """[N, G] bundled codes for an existing plan (FeatureGroup::PushData
    semantics: later member wins on conflict rows). Split from planning so
    a deferred dataset only materializes its host bin matrix when the
    plan actually WINS the engagement ratio (boosting/gbdt.py)."""
    N = X_binned.shape[0]
    G = len(plan.groups)
    dtype = np.uint8 if plan.group_total_bins.max() <= 255 else np.uint16
    Xb = np.zeros((N, G), dtype=dtype)
    for g, members in enumerate(plan.groups):
        if len(members) == 1:
            Xb[:, g] = X_binned[:, members[0]].astype(dtype)
            continue
        for f in members:                                     # later member wins
            codes = X_binned[:, f].astype(np.int64)
            nz = codes != default_bin[f]
            Xb[nz, g] = (codes[nz] + plan.off[f]).astype(dtype)
    return Xb
