"""Command-line application driver.

Reference counterpart: src/application/application.cpp + src/main.cpp — the
`task=train|predict|convert_model` dispatcher driven by `key=value` argv
pairs and a `config=<file>` conf file (`key = value` lines, `#` comments),
compatible with the reference's example configs
(examples/*/train.conf, predict.conf).

Usage:  python -m lightgbm_tpu config=train.conf [key=value ...]
"""
from __future__ import annotations

import os
import sys
from typing import Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset
from .config import Config
from .engine import train as train_fn
from .io.file_io import load_data_file
from .utils.log import Log


# every task value main() dispatches on (bare-subcommand whitelist derives
# from this so the two can't drift)
TASK_TOKENS = ("train", "predict", "prediction", "test",
               "convert_model", "convert", "serve_bench")


def parse_args(argv: List[str]) -> Dict[str, str]:
    """argv `key=value` pairs + conf file merge; argv wins on conflict
    (reference Application::LoadParameters, application.cpp:48-81)."""
    cli: Dict[str, str] = {}
    for tok in argv:
        tok = tok.strip()
        if not tok or tok.startswith("#"):
            continue
        if tok.startswith("--"):
            # GNU-style convenience form: `--telemetry-dir=/x` ==
            # `telemetry_dir=/x` (the reference CLI is strictly key=value).
            # Only the KEY normalizes dashes to underscores — the value must
            # pass through untouched (`--data=/path/my-file.csv`)
            tok = tok[2:]
            if "=" in tok:
                k, v = tok.split("=", 1)
                tok = k.replace("-", "_") + "=" + v
            else:
                tok = tok.replace("-", "_")
        if "=" not in tok:
            if tok == "dump_snapshot":
                # bare `--dump-snapshot`: write observability.snapshot() to
                # the default file at train end (an explicit
                # `--dump-snapshot=FILE` names the destination instead)
                cli.setdefault("dump_snapshot", "observability_snapshot.json")
                continue
            # convenience subcommand form: `cli train config=...` ==
            # `cli task=train config=...` (the reference CLI is strictly
            # key=value, application.cpp:48-81; the bare form costs
            # nothing). Must cover exactly main()'s dispatch set incl.
            # aliases — see TASK_TOKENS.
            if tok in TASK_TOKENS:
                if cli.setdefault("task", tok) != tok:
                    Log.warning("task already set to %s; ignoring bare "
                                "subcommand %s", cli["task"], tok)
            else:
                Log.warning("Unknown argument %s (expected key=value)", tok)
            continue
        k, v = tok.split("=", 1)
        cli[k.strip()] = v.strip().strip('"')

    params: Dict[str, str] = {}
    conf_path = cli.get("config", cli.get("config_file", ""))
    if conf_path:
        with open(conf_path) as fh:
            for line in fh:
                line = line.split("#", 1)[0].strip()
                if not line or "=" not in line:
                    continue
                k, v = line.split("=", 1)
                params[k.strip()] = v.strip().strip('"')
    params.update(cli)                  # argv has higher priority (:76-80)
    params.pop("config", None)
    params.pop("config_file", None)
    return params


def _load_dataset(path: str, params: Dict, config: Config,
                  reference: Optional[Dataset] = None) -> Dataset:
    X, label, side = load_data_file(path, params)
    if reference is not None:
        ds = reference.create_valid(X, label=label)
    else:
        ds = Dataset(X, label=label, feature_name=side.get("feature_names"))
    if side.get("weight") is not None:
        ds.set_weight(side["weight"])
    if side.get("group") is not None:
        ds.set_group(side["group"].astype(np.int64))
    if side.get("init_score") is not None:
        ds.set_init_score(side["init_score"])
    return ds


def run_train(params: Dict) -> None:
    config = Config.from_params(params)
    # reference verbosity semantics (utils/log.py): <0 fatal-only,
    # 0 warnings, 1 info, >1 debug
    Log.set_level(config.verbose)
    if config.telemetry_dir:
        # telemetry_dir=... / --telemetry-dir=...: JSONL + Perfetto trace
        # under this directory (docs/Observability.md); engine.train flushes
        from . import observability as obs
        obs.configure(telemetry_dir=config.telemetry_dir)
    if not config.data:
        Log.fatal("No training data specified (data=...)")
    train_set = _load_dataset(config.data, params, config)
    valid_sets, valid_names = [], []
    for i, vf in enumerate(config.valid_data):
        valid_sets.append(_load_dataset(vf, params, config, reference=train_set))
        valid_names.append(f"valid_{i + 1}" if len(config.valid_data) > 1 else "valid_1")
    callbacks = []
    saved_handlers = []
    if config.checkpoint_dir:
        # preemption-friendly runs (docs/Fault-Tolerance.md): SIGTERM/SIGINT
        # request an on-demand atomic checkpoint at the next iteration
        # boundary, then exit 143 — restarting the identical command with
        # resume_from=auto continues bit-identically. A SECOND signal
        # escalates (KeyboardInterrupt) so a hung iteration — where the
        # boundary never arrives — stays interruptible without SIGKILL.
        import signal

        stop_signals: List[int] = []

        def _on_signal(signum, frame):
            stop_signals.append(signum)
            if len(stop_signals) > 1:
                Log.warning("signal %d received again before an iteration "
                            "boundary: aborting without a checkpoint", signum)
                raise KeyboardInterrupt
            Log.warning("signal %d received: writing a checkpoint at the "
                        "next iteration boundary, then exiting", signum)

        for _sig in (signal.SIGTERM, signal.SIGINT):
            try:
                saved_handlers.append((_sig, signal.signal(_sig, _on_signal)))
            except ValueError:       # non-main thread (embedded use)
                pass

        def _signal_checkpoint(env):
            if stop_signals:
                path = env.model.save_checkpoint()
                Log.warning("checkpoint %s written on signal %d; exiting",
                            path, stop_signals[0])
                raise SystemExit(143)
        _signal_checkpoint.order = 50
        callbacks.append(_signal_checkpoint)
    if config.snapshot_freq > 0:
        # reference: model.snapshot_iter_N every snapshot_freq iterations
        # during training (gbdt.cpp:349-353, config.h:103)
        def _snapshot(env):
            it = env.iteration + 1
            if it % config.snapshot_freq == 0:
                env.model._finalize()
                env.model.save_model(f"{config.output_model}.snapshot_iter_{it}")
        _snapshot.order = 30
        callbacks.append(_snapshot)
    try:
        try:
            booster = train_fn(params, train_set,
                               num_boost_round=config.num_iterations,
                               valid_sets=valid_sets, valid_names=valid_names,
                               init_model=config.input_model or None,
                               early_stopping_rounds=(
                                   config.early_stopping_round or None),
                               callbacks=callbacks)
        except Exception as e:
            # stream-shard corruption is a RESTARTABLE fault: the host
            # shard store is rebuilt from the dataset at construction, so
            # exit with the typed status the supervisor recognizes
            # (docs/Fault-Tolerance.md) instead of a generic traceback
            from .ops.stream import ShardCorruptionError
            if isinstance(e, ShardCorruptionError):
                from .robustness.supervisor import EXIT_SHARD_CORRUPT
                Log.warning("stream-shard corruption detected: %s — "
                            "exiting %d (a supervisor relaunch with "
                            "resume_from=auto self-heals)", e,
                            EXIT_SHARD_CORRUPT)
                raise SystemExit(EXIT_SHARD_CORRUPT) from e
            # comm loss — a peer rank died or stopped answering
            # (PeerLostError names the rank; its base CommTimeoutError
            # covers the generic collective deadline). Typed exit 145 so
            # the fleet supervisor attributes the gang failure to a peer,
            # not to this rank (docs/Fault-Tolerance.md exit-code table)
            from .robustness.retry import CommTimeoutError, PeerLostError
            if isinstance(e, CommTimeoutError):
                from .robustness.watchdog import EXIT_COMM_LOST
                Log.warning("comm loss: %s — exiting %d (%s; the fleet "
                            "supervisor relaunches the gang from the "
                            "newest consistent manifest)", e, EXIT_COMM_LOST,
                            f"lost peer rank {e.rank}"
                            if isinstance(e, PeerLostError)
                            else "collective deadline expired")
                import jax
                if jax.process_count() > 1:
                    # under a live gang sys.exit never reaches the shell:
                    # jax's atexit shutdown blocks on its shutdown barrier
                    # waiting for the DEAD peer and the coordination
                    # service aborts the process (-6) — which the fleet
                    # supervisor would misread as this rank being the
                    # crash culprit
                    import sys as _sys
                    _sys.stdout.flush()
                    _sys.stderr.flush()
                    os._exit(EXIT_COMM_LOST)
                raise SystemExit(EXIT_COMM_LOST) from e
            raise
    finally:
        if saved_handlers:
            # past the training loop nothing checks stop_signals — restore
            # the previous handlers so model save/predict stay interruptible
            import signal
            for _sig, _old in saved_handlers:
                signal.signal(_sig, _old)
    booster.save_model(config.output_model)
    Log.info("Finished training, model saved to %s", config.output_model)


def run_predict(params: Dict) -> None:
    config = Config.from_params(params)
    Log.set_level(config.verbose)
    if not config.input_model:
        Log.fatal("No input model specified for prediction (input_model=...)")
    if not config.data:
        Log.fatal("No prediction data specified (data=...)")
    booster = Booster(params=params, model_file=config.input_model)
    X, _, _ = load_data_file(config.data, params)
    niter = config.num_iteration_predict if config.num_iteration_predict > 0 else None
    preds = booster.predict(
        X, num_iteration=niter,
        raw_score=config.is_predict_raw_score,
        pred_leaf=config.is_predict_leaf_index,
        pred_contrib=config.is_predict_contrib)
    preds = np.atleast_2d(preds.T).T if preds.ndim == 1 else preds
    with open(config.output_result, "w") as fh:
        for row in (preds if preds.ndim == 2 else preds[:, None]):
            fh.write("\t".join(f"{v:.18g}" for v in np.atleast_1d(row)) + "\n")
    Log.info("Finished prediction, results saved to %s", config.output_result)


def run_serve_bench(params: Dict) -> None:
    """task=serve_bench: load a model (text/proto/JSON) into the serving
    engine, replay closed-loop load from `data=` at a few concurrency x
    batch-size shapes, and print one JSON report with p50/p99 latency and
    rows/s per shape (docs/Serving.md). The hermetic full-harness version
    — Poisson open loop, recompile pinning, ledger banking — is
    ``python bench.py --serve``; this task is the operator's quick probe
    against a real model artifact."""
    import json

    config = Config.from_params(params)
    Log.set_level(config.verbose)
    if not config.input_model:
        Log.fatal("No input model specified for serve_bench (input_model=...)")
    if not config.data:
        Log.fatal("No request data specified for serve_bench (data=...)")
    from .serving import ServingEngine
    from .serving.loadgen import run_closed_loop
    engine = ServingEngine(config.input_model, params=params)
    X, _, _ = load_data_file(config.data, params)
    X = np.asarray(X, np.float64)
    shapes = [(1, 1), (8, 4), (64, 4)]
    shapes = [(b, c) for b, c in shapes if b <= X.shape[0]] or [(X.shape[0], 1)]
    report = {"task": "serve_bench", "model": config.input_model,
              "engine": engine.describe(), "shapes": {}}
    for batch, conc in shapes:
        r = run_closed_loop(engine.predict, X, batch, conc,
                            requests_per_worker=max(200 // conc, 20))
        report["shapes"][f"b{batch}xc{conc}"] = r
    print(json.dumps(report))
    if config.dump_snapshot:
        from . import observability as obs
        obs.write_snapshot(config.dump_snapshot)
        Log.info("serving snapshot written to %s", config.dump_snapshot)


def run_convert_model(params: Dict) -> None:
    config = Config.from_params(params)
    Log.set_level(config.verbose)
    if not config.input_model:
        Log.fatal("No input model specified (input_model=...)")
    booster = Booster(params=params, model_file=config.input_model)
    from .io.codegen import model_to_cpp
    with open(config.convert_model, "w") as fh:
        fh.write(model_to_cpp(booster))
    Log.info("Model converted to C++ at %s", config.convert_model)


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    params = parse_args(argv)
    task = params.get("task", "train")
    if task == "train":
        run_train(params)
    elif task in ("predict", "prediction", "test"):
        run_predict(params)
    elif task in ("convert_model", "convert"):
        run_convert_model(params)
    elif task == "serve_bench":
        run_serve_bench(params)
    else:
        Log.fatal("Unknown task %s", task)
    return 0


if __name__ == "__main__":
    sys.exit(main())
