"""`python -m lightgbm_tpu.analysis` — run tpu-lint."""
from .tpu_lint import main

if __name__ == "__main__":
    raise SystemExit(main())
