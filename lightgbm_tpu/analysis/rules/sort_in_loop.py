"""R007: full-array argsort/sort inside a lax.while_loop body.

A sort inside the device-side wave loop is a per-iteration fixed cost the
whole loop pays on every trip — the exact failure class the incremental
leaf partition removed from the grower (a full-N stable argsort per wave at
the 10.5M-row bench; grower.py GrowState.perm replaces it with cumsum
counting-sort maintenance). New sorts must not creep back into loop bodies:
slot grouping derives from carried per-leaf segment tables, compaction from
prefix sums + monotonic scatters (ops/histogram.py compact_rows /
slot_position_base).

Detection is a reachability walk over the whole-package call graph
(``common.PackageIndex``): functions passed to ``lax.while_loop`` (by name
or inline lambda) anywhere in the lint run are roots; any function they
reference — called directly, through an imported module object, via a
``self.`` method, or passed onward to e.g. ``lax.cond`` — is reachable,
across module boundaries; a ``jnp.argsort``/``jnp.sort``/``jnp.lexsort``/
``lax.sort``/``lax.sort_key_val`` call in reachable code fires. Linting a
single file degrades to the historical same-file walk. Audited intentional
sites — the grower's LEGACY compact path (the bit-identity pin for
``tpu_incremental_partition=false``) — live in the committed baseline;
deliberate small-axis sorts (categorical bin ordering, voting gain ranks)
carry inline waivers at the call site.
"""
from __future__ import annotations

import ast

from .common import dotted_name, reachable_loop_code

RULE_ID = "R007"

_WHILE_LOOP = frozenset({"jax.lax.while_loop", "lax.while_loop"})
_SORT_CALLS = {
    "jnp.argsort", "jnp.sort", "jnp.lexsort",
    "jax.numpy.argsort", "jax.numpy.sort", "jax.numpy.lexsort",
    "jax.lax.sort", "lax.sort",
    "jax.lax.sort_key_val", "lax.sort_key_val",
}


class SortInLoopRule:
    rule_id = RULE_ID
    cross_module = True   # findings depend on the whole-package call graph
    summary = ("argsort/sort reachable from a lax.while_loop body — a "
               "per-iteration fixed cost; use the carried incremental "
               "partition / prefix-sum compaction instead")

    def check(self, ctx):
        reported = set()
        for fn in reachable_loop_code(ctx, _WHILE_LOOP):
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) \
                        and dotted_name(node.func) in _SORT_CALLS \
                        and id(node) not in reported:
                    reported.add(id(node))
                    where = getattr(fn, "name", "<lambda>")
                    yield ctx.finding(
                        self.rule_id, node,
                        f"`{dotted_name(node.func)}` reachable from a "
                        f"lax.while_loop body (via `{where}`) — sorts are "
                        f"per-iteration fixed costs; derive grouping from "
                        f"carried state (incremental partition) or "
                        f"prefix-sum compaction")
