"""R007: full-array argsort/sort inside a lax.while_loop body.

A sort inside the device-side wave loop is a per-iteration fixed cost the
whole loop pays on every trip — the exact failure class the incremental
leaf partition removed from the grower (a full-N stable argsort per wave at
the 10.5M-row bench; grower.py GrowState.perm replaces it with cumsum
counting-sort maintenance). New sorts must not creep back into loop bodies:
slot grouping derives from carried per-leaf segment tables, compaction from
prefix sums + monotonic scatters (ops/histogram.py compact_rows /
slot_position_base).

Detection is an intra-module reachability walk: functions passed to
``lax.while_loop`` (by name or inline lambda) are roots; any same-file
function they reference — called directly, or passed onward to e.g.
``lax.cond`` — is reachable; a ``jnp.argsort``/``jnp.sort``/``jnp.lexsort``/
``lax.sort``/``lax.sort_key_val`` call in reachable code fires. Cross-module
calls are invisible to the AST pass (documented limitation); the audited
intentional site — the grower's LEGACY compact path, kept as the
bit-identity pin for ``tpu_incremental_partition=false`` — lives in the
committed baseline.
"""
from __future__ import annotations

import ast

from .common import dotted_name

RULE_ID = "R007"

_WHILE_LOOP = {"jax.lax.while_loop", "lax.while_loop"}
_SORT_CALLS = {
    "jnp.argsort", "jnp.sort", "jnp.lexsort",
    "jax.numpy.argsort", "jax.numpy.sort", "jax.numpy.lexsort",
    "jax.lax.sort", "lax.sort",
    "jax.lax.sort_key_val", "lax.sort_key_val",
}


def _local_defs(tree):
    """Every function def in the module (nested included), by name.

    Name collisions keep the FIRST def — conservative for a lint heuristic;
    the reachability walk only follows names, never instances."""
    defs = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)
    return defs


def _referenced_names(fn):
    """Names a function loads anywhere in its body — covers direct calls
    AND functions passed as arguments (``lax.cond(pred, compact_pass, ...)``
    reaches ``compact_pass`` without a Call node naming it)."""
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            out.add(node.id)
    return out


class SortInLoopRule:
    rule_id = RULE_ID
    summary = ("argsort/sort reachable from a lax.while_loop body — a "
               "per-iteration fixed cost; use the carried incremental "
               "partition / prefix-sum compaction instead")

    def check(self, ctx):
        defs = _local_defs(ctx.tree)

        # roots: callables handed to while_loop (positional or cond=/body=)
        roots = []          # FunctionDef or Lambda nodes
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted_name(node.func) not in _WHILE_LOOP:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Lambda):
                    roots.append(arg)
                else:
                    name = dotted_name(arg)
                    if name in defs:
                        roots.append(defs[name])
        if not roots:
            return

        # reachability over same-file defs via loaded names
        reachable, frontier = [], list(roots)
        seen = set()
        while frontier:
            fn = frontier.pop()
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            reachable.append(fn)
            for name in _referenced_names(fn):
                target = defs.get(name)
                if target is not None and id(target) not in seen:
                    frontier.append(target)

        reported = set()
        for fn in reachable:
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) \
                        and dotted_name(node.func) in _SORT_CALLS \
                        and id(node) not in reported:
                    reported.add(id(node))
                    where = getattr(fn, "name", "<lambda>")
                    yield ctx.finding(
                        self.rule_id, node,
                        f"`{dotted_name(node.func)}` reachable from a "
                        f"lax.while_loop body (via `{where}`) — sorts are "
                        f"per-iteration fixed costs; derive grouping from "
                        f"carried state (incremental partition) or "
                        f"prefix-sum compaction")
