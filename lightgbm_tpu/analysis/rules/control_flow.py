"""R001: Python control flow on traced values inside jit-entered functions.

``if``/``while``/``assert`` on a traced value calls ``bool()`` on a tracer:
at best a ConcretizationTypeError at trace time, at worst (with
``static_argnums`` misuse or accidental concretization) a silent per-value
recompile. Inside ``@jit``-decorated functions, ``jax.jit(f)``-wrapped
defs, and callables handed to jax.lax control-flow primitives, branch on
``jnp.where`` / ``lax.cond`` / ``lax.while_loop`` instead.
"""
from __future__ import annotations

import ast

from .common import expr_is_traced, infer_traced_names, traced_entry_functions

RULE_ID = "R001"


class ControlFlowRule:
    rule_id = RULE_ID
    summary = ("Python if/while/assert on a traced value inside a "
               "jit-entered function (use jnp.where/lax.cond)")

    def check(self, ctx):
        for fn, static_params in traced_entry_functions(ctx.tree):
            traced = infer_traced_names(fn, params_traced=True,
                                        static_params=static_params)
            if not traced:
                continue
            for node in ast.walk(fn):
                if isinstance(node, (ast.If, ast.While)):
                    test = node.test
                    kind = "if" if isinstance(node, ast.If) else "while"
                elif isinstance(node, ast.Assert):
                    test = node.test
                    kind = "assert"
                else:
                    continue
                if expr_is_traced(test, traced):
                    yield ctx.finding(
                        self.rule_id, node,
                        f"Python `{kind}` on a traced value in jit-entered "
                        f"function `{fn.name}` — use jnp.where/jax.lax.cond "
                        f"(or mark the argument static)")
