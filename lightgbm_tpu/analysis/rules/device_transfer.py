"""R009: jax.device_put/device_get reachable from wave-loop or scan bodies.

A host->device (or device->host) transfer issued from inside a traced
``lax.while_loop``/``lax.scan`` body is either a trace-time constant
capture (silently baking one shard of data into the executable) or — in
host-driven loops — an unmanaged per-iteration copy that bypasses the
double-buffered prefetcher. The out-of-core streaming mode
(tpu_residency=stream) exists precisely so mid-loop H2D traffic has ONE
home with stall accounting, overlap, and byte counters:
``ops/stream.py``'s ShardPrefetcher, fed by ``dataset.py``'s residency
cache. Those two files are exempt; a ``device_put`` reachable from a loop
body anywhere else is a finding.

Detection reuses R007's whole-package reachability walk
(``common.PackageIndex``): callables handed to ``lax.while_loop`` OR
``lax.scan`` anywhere in the lint run are roots; any function they
reference — same-file or across an import — is reachable; a
``jax.device_put``/``jax.device_get`` (or ``device_put``/``device_get``
imported from jax) call in reachable code fires. ``from jax import``
aliases are resolved per the module the reachable code lives in, so an
aliased transfer two imports away from the loop still fires. Intentional
sites belong in ``tpu_lint_baseline.json``.
"""
from __future__ import annotations

import ast

from .common import dotted_name, reachable_loop_code

RULE_ID = "R009"

_LOOP_CALLS = frozenset({"jax.lax.while_loop", "lax.while_loop",
                         "jax.lax.scan", "lax.scan"})
_TRANSFER_DOTTED = {"jax.device_put", "jax.device_get"}
_TRANSFER_FROM = {"device_put", "device_get"}

# the sanctioned homes of managed transfers (module doc)
_EXEMPT_MARKERS = ("ops/stream.py", "lightgbm_tpu/dataset.py")


def _exempt(rel: str) -> bool:
    rel = rel.replace("\\", "/")
    return any(rel.endswith(m) or m in rel for m in _EXEMPT_MARKERS)


def _from_jax_aliases(tree) -> set:
    """Local names bound by ``from jax import device_put[ as x]``."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            for alias in node.names:
                if alias.name in _TRANSFER_FROM:
                    out.add(alias.asname or alias.name)
    return out


class DeviceTransferRule:
    rule_id = RULE_ID
    cross_module = True   # findings depend on the whole-package call graph
    summary = ("jax.device_put/device_get reachable from a lax.while_loop "
               "or lax.scan body outside ops/stream.py / dataset.py — "
               "mid-loop transfers belong to the streaming prefetcher")

    def check(self, ctx):
        if _exempt(ctx.rel):
            return
        aliases = _from_jax_aliases(ctx.tree)

        reported = set()
        for fn in reachable_loop_code(ctx, _LOOP_CALLS):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func) or ""
                hit = name in _TRANSFER_DOTTED or \
                    (name in aliases and "." not in name)
                if hit and id(node) not in reported:
                    reported.add(id(node))
                    where = getattr(fn, "name", "<lambda>")
                    yield ctx.finding(
                        self.rule_id, node,
                        f"`{name}()` reachable from a while_loop/scan body "
                        f"(via `{where}`) — a transfer inside a traced "
                        f"loop bakes data into the executable or bypasses "
                        f"the streaming prefetcher; route it through "
                        f"ops/stream.py's ShardPrefetcher (or the "
                        f"dataset.py residency cache) instead")
