"""Shared AST helpers for the tpu-lint rules.

Traced-value inference is deliberately conservative: a name is "traced"
only when it demonstrably flows from a jnp./jax. array expression (or is a
parameter of a function the tracer provably enters — jit-decorated,
jit-wrapped, or passed to a jax.lax control-flow primitive). The goal is a
near-zero false-positive rate on idiomatic host-side code; the baseline
file absorbs the audited remainder.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

# dotted prefixes whose call results are jax arrays (tracer-carrying)
TRACED_CALL_PREFIXES = (
    "jnp.", "jax.numpy.", "jax.lax.", "jax.random.", "jax.nn.",
    "lax.", "pl.", "pltpu.",
)
# jit entry wrappers
JIT_NAMES = {"jax.jit", "jit", "jax.pmap", "pmap", "pjit", "jax.pjit"}
PARTIAL_NAMES = {"partial", "functools.partial"}
# jax.lax primitives taking traced-callable arguments
LAX_HOF = {
    "jax.lax.while_loop", "lax.while_loop",
    "jax.lax.cond", "lax.cond",
    "jax.lax.scan", "lax.scan",
    "jax.lax.fori_loop", "lax.fori_loop",
    "jax.lax.map", "lax.map",
    "jax.lax.switch", "lax.switch",
    "jax.lax.associative_scan", "lax.associative_scan",
}


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted_name(node.func)


def param_names(fn: ast.FunctionDef) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return [n for n in names if n not in ("self", "cls")]


def _const_int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """(1, 2) / [1, 2] / 3 as a tuple of ints when fully static, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int) \
                    and not isinstance(e.value, bool):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    return None


def jit_static_params(fn: ast.FunctionDef, jit_call: Optional[ast.Call]
                      ) -> Set[str]:
    """Parameter names marked static at a jit site (best effort)."""
    if jit_call is None:
        return set()
    names = param_names(fn)
    static: Set[str] = set()
    for kw in jit_call.keywords:
        if kw.arg == "static_argnums":
            idxs = _const_int_tuple(kw.value) or ()
            for i in idxs:
                if 0 <= i < len(names):
                    static.add(names[i])
        elif kw.arg == "static_argnames":
            if isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                static.add(kw.value.value)
            elif isinstance(kw.value, (ast.Tuple, ast.List)):
                for e in kw.value.elts:
                    if isinstance(e, ast.Constant) \
                            and isinstance(e.value, str):
                        static.add(e.value)
    return static


def jit_decorator_call(fn: ast.FunctionDef) -> Tuple[bool, Optional[ast.Call]]:
    """(is_jit_decorated, the jit Call node carrying kwargs or None)."""
    for dec in fn.decorator_list:
        name = dotted_name(dec)
        if name in JIT_NAMES:
            return True, None
        if isinstance(dec, ast.Call):
            cname = dotted_name(dec.func)
            if cname in JIT_NAMES:
                return True, dec
            if cname in PARTIAL_NAMES and dec.args:
                if dotted_name(dec.args[0]) in JIT_NAMES:
                    return True, dec
    return False, None


def iter_functions(tree: ast.Module) -> Iterable[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def traced_entry_functions(tree: ast.Module
                           ) -> List[Tuple[ast.FunctionDef, Set[str]]]:
    """Functions the tracer provably enters, with their static-param names.

    Detected forms:
    - ``@jax.jit`` / ``@partial(jax.jit, ...)`` decorators
    - ``g = jax.jit(f, ...)`` / ``return jax.jit(f, ...)`` wrapping a
      same-module ``def f``
    - ``def body(...)`` passed by name to a jax.lax control-flow primitive
      (while_loop/cond/scan/fori_loop/map/switch)
    """
    by_name = {}
    for fn in iter_functions(tree):
        by_name.setdefault(fn.name, fn)

    out = []
    seen = set()

    def add(fn: ast.FunctionDef, jit_call: Optional[ast.Call]):
        if id(fn) in seen:
            return
        seen.add(id(fn))
        out.append((fn, jit_static_params(fn, jit_call)))

    for fn in iter_functions(tree):
        is_jit, jcall = jit_decorator_call(fn)
        if is_jit:
            add(fn, jcall)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        cname = dotted_name(node.func)
        if cname in JIT_NAMES and node.args:
            target = dotted_name(node.args[0])
            if target in by_name:
                add(by_name[target], node)
        elif cname in LAX_HOF:
            for arg in node.args:
                target = dotted_name(arg)
                if target in by_name:
                    add(by_name[target], None)
    return out


def expr_is_traced(expr: ast.AST, traced: Set[str]) -> bool:
    """Does this expression reference a traced name or a jnp./jax. call?"""
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id in traced:
            return True
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name and (name.startswith(TRACED_CALL_PREFIXES)
                         or name in JIT_NAMES):
                return True
    return False


def _assign_targets(stmt: ast.AST) -> List[str]:
    names = []

    def collect(t):
        if isinstance(t, ast.Name):
            names.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                collect(e)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            collect(t)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        collect(stmt.target)
    return names


def infer_traced_names(fn: ast.FunctionDef, params_traced: bool,
                       static_params: Set[str] = frozenset()) -> Set[str]:
    """Fixpoint dataflow: names holding (expressions derived from) jax
    arrays inside ``fn``. Walks nested functions too — their assignments
    only ever *add* traced names, which is the conservative direction."""
    traced: Set[str] = set()
    if params_traced:
        traced |= set(param_names(fn)) - set(static_params)

    assigns = [s for s in ast.walk(fn)
               if isinstance(s, (ast.Assign, ast.AugAssign, ast.AnnAssign))]
    changed = True
    while changed:
        changed = False
        for stmt in assigns:
            value = stmt.value
            if value is None:
                continue
            if expr_is_traced(value, traced):
                for name in _assign_targets(stmt):
                    if name not in traced:
                        traced.add(name)
                        changed = True
    return traced
