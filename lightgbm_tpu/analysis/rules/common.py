"""Shared AST helpers for the tpu-lint rules.

Traced-value inference is deliberately conservative: a name is "traced"
only when it demonstrably flows from a jnp./jax. array expression (or is a
parameter of a function the tracer provably enters — jit-decorated,
jit-wrapped, or passed to a jax.lax control-flow primitive). The goal is a
near-zero false-positive rate on idiomatic host-side code; the baseline
file absorbs the audited remainder.

The second half of this module is the **whole-package call graph**
(:class:`PackageIndex`): module-level import resolution (absolute,
``as``-aliased, and relative forms) plus attribute-call binding
(``mod.fn(...)`` through an imported module object, ``self.m(...)`` to a
method of the enclosing class), built once per lint run over every file in
the invocation and cached. The reachability rules (R007/R009/R012) walk it
instead of the old same-file-only map, so a sort hidden behind
``from .ops import histogram`` is no longer invisible.
"""
from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

# dotted prefixes whose call results are jax arrays (tracer-carrying)
TRACED_CALL_PREFIXES = (
    "jnp.", "jax.numpy.", "jax.lax.", "jax.random.", "jax.nn.",
    "lax.", "pl.", "pltpu.",
)
# jit entry wrappers
JIT_NAMES = {"jax.jit", "jit", "jax.pmap", "pmap", "pjit", "jax.pjit"}
PARTIAL_NAMES = {"partial", "functools.partial"}
# jax.lax primitives taking traced-callable arguments
LAX_HOF = {
    "jax.lax.while_loop", "lax.while_loop",
    "jax.lax.cond", "lax.cond",
    "jax.lax.scan", "lax.scan",
    "jax.lax.fori_loop", "lax.fori_loop",
    "jax.lax.map", "lax.map",
    "jax.lax.switch", "lax.switch",
    "jax.lax.associative_scan", "lax.associative_scan",
}


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted_name(node.func)


def param_names(fn: ast.FunctionDef) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return [n for n in names if n not in ("self", "cls")]


def _const_int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """(1, 2) / [1, 2] / 3 as a tuple of ints when fully static, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int) \
                    and not isinstance(e.value, bool):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    return None


def jit_static_params(fn: ast.FunctionDef, jit_call: Optional[ast.Call]
                      ) -> Set[str]:
    """Parameter names marked static at a jit site (best effort)."""
    if jit_call is None:
        return set()
    names = param_names(fn)
    static: Set[str] = set()
    for kw in jit_call.keywords:
        if kw.arg == "static_argnums":
            idxs = _const_int_tuple(kw.value) or ()
            for i in idxs:
                if 0 <= i < len(names):
                    static.add(names[i])
        elif kw.arg == "static_argnames":
            if isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                static.add(kw.value.value)
            elif isinstance(kw.value, (ast.Tuple, ast.List)):
                for e in kw.value.elts:
                    if isinstance(e, ast.Constant) \
                            and isinstance(e.value, str):
                        static.add(e.value)
    return static


def jit_decorator_call(fn: ast.FunctionDef) -> Tuple[bool, Optional[ast.Call]]:
    """(is_jit_decorated, the jit Call node carrying kwargs or None)."""
    for dec in fn.decorator_list:
        name = dotted_name(dec)
        if name in JIT_NAMES:
            return True, None
        if isinstance(dec, ast.Call):
            cname = dotted_name(dec.func)
            if cname in JIT_NAMES:
                return True, dec
            if cname in PARTIAL_NAMES and dec.args:
                if dotted_name(dec.args[0]) in JIT_NAMES:
                    return True, dec
    return False, None


def iter_functions(tree: ast.Module) -> Iterable[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def traced_entry_functions(tree: ast.Module
                           ) -> List[Tuple[ast.FunctionDef, Set[str]]]:
    """Functions the tracer provably enters, with their static-param names.

    Detected forms:
    - ``@jax.jit`` / ``@partial(jax.jit, ...)`` decorators
    - ``g = jax.jit(f, ...)`` / ``return jax.jit(f, ...)`` wrapping a
      same-module ``def f``
    - ``def body(...)`` passed by name to a jax.lax control-flow primitive
      (while_loop/cond/scan/fori_loop/map/switch)
    """
    by_name = {}
    for fn in iter_functions(tree):
        by_name.setdefault(fn.name, fn)

    out = []
    seen = set()

    def add(fn: ast.FunctionDef, jit_call: Optional[ast.Call]):
        if id(fn) in seen:
            return
        seen.add(id(fn))
        out.append((fn, jit_static_params(fn, jit_call)))

    for fn in iter_functions(tree):
        is_jit, jcall = jit_decorator_call(fn)
        if is_jit:
            add(fn, jcall)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        cname = dotted_name(node.func)
        if cname in JIT_NAMES and node.args:
            target = dotted_name(node.args[0])
            if target in by_name:
                add(by_name[target], node)
        elif cname in LAX_HOF:
            for arg in node.args:
                target = dotted_name(arg)
                if target in by_name:
                    add(by_name[target], None)
    return out


def expr_is_traced(expr: ast.AST, traced: Set[str]) -> bool:
    """Does this expression reference a traced name or a jnp./jax. call?"""
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id in traced:
            return True
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name and (name.startswith(TRACED_CALL_PREFIXES)
                         or name in JIT_NAMES):
                return True
    return False


def _assign_targets(stmt: ast.AST) -> List[str]:
    names = []

    def collect(t):
        if isinstance(t, ast.Name):
            names.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                collect(e)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            collect(t)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        collect(stmt.target)
    return names


def referenced_callables(fn: ast.FunctionDef) -> Set[str]:
    """Names (bare and dotted) this function's body may call or forward.

    Collects every Name load and every Name-rooted Attribute chain — both
    ``helper(x)`` and ``histmod.compact(x)`` forms, plus bare references
    passed onward as callables (``lax.while_loop(cond, body, ...)``).
    """
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute):
            name = dotted_name(node)
            if name:
                out.add(name)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            out.add(node.id)
    return out


class ModuleInfo:
    """Per-file slice of the package call graph: top-of-tree defs, class
    methods, and the two import maps (module aliases, from-imports)."""

    def __init__(self, path: str, rel: str, tree: ast.Module):
        self.path = path
        self.rel = rel
        self.tree = tree
        self.modname = _modname_for_rel(rel)
        self.defs: Dict[str, ast.FunctionDef] = {}
        self.classes: Dict[str, Dict[str, ast.FunctionDef]] = {}
        # alias -> dotted module it denotes (``import a.b as c`` => c: a.b;
        # ``import a.b`` => a: a, attribute chains re-join the tail)
        self.import_modules: Dict[str, str] = {}
        # alias -> (resolved source module, attribute name)
        self.import_names: Dict[str, Tuple[str, str]] = {}
        # id(fn) -> enclosing ClassDef name (innermost), for self.m() binding
        self.owner_class: Dict[int, str] = {}
        self._index_defs()
        self._index_imports()

    def _index_defs(self) -> None:
        def visit(node: ast.AST, cls: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if cls is None:
                        self.defs.setdefault(child.name, child)
                    else:
                        self.classes.setdefault(cls, {}).setdefault(
                            child.name, child)
                    if cls is not None:
                        self.owner_class[id(child)] = cls
                    visit(child, cls)
                elif isinstance(child, ast.ClassDef):
                    visit(child, child.name)
                else:
                    visit(child, cls)

        visit(self.tree, None)
        # nested defs are callable too (closures handed to lax primitives);
        # record owners but only expose module-level names in ``defs`` —
        # resolution of nested names happens through reachability, not
        # imports, so the name map stays unambiguous.

    def _index_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        self.import_modules[alias.asname] = alias.name
                    else:
                        head = alias.name.split(".")[0]
                        self.import_modules[head] = head
            elif isinstance(node, ast.ImportFrom):
                src = self._resolve_from(node)
                if src is None:
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    bound = alias.asname or alias.name
                    self.import_names[bound] = (src, alias.name)

    def _resolve_from(self, node: ast.ImportFrom) -> Optional[str]:
        if node.level == 0:
            return node.module
        parts = self.modname.split(".")
        # ``from . import x`` in a module drops the module's own leaf; each
        # extra dot climbs one more package
        if len(parts) < node.level:
            return node.module  # fixture linted standalone: best effort
        base = parts[:-node.level] if node.level <= len(parts) else []
        if node.module:
            base = base + node.module.split(".")
        return ".".join(base) if base else node.module


def _modname_for_rel(rel: str) -> str:
    norm = rel.replace(os.sep, "/")
    if norm.endswith(".py"):
        norm = norm[:-3]
    if norm.endswith("/__init__"):
        norm = norm[: -len("/__init__")]
    return norm.replace("/", ".")


class PackageIndex:
    """Whole-package call graph over every file in one lint invocation.

    Modules are matched by dotted-suffix (the linted tree's relative paths
    rarely coincide with installed import paths), imports are resolved
    through both alias maps, and reachability from the jax.lax loop
    primitives is a cross-module BFS cached per root set.
    """

    def __init__(self, modules: List[ModuleInfo]):
        self.modules: Dict[str, ModuleInfo] = {}
        self.by_path: Dict[str, ModuleInfo] = {}
        self._by_tail: Dict[str, List[ModuleInfo]] = {}
        for m in modules:
            self.modules[m.modname] = m
            self.by_path[m.rel] = m
            tail = m.modname.rsplit(".", 1)[-1]
            self._by_tail.setdefault(tail, []).append(m)
        self._reach_cache: Dict[frozenset, Dict[int, Tuple[ModuleInfo,
                                                           ast.FunctionDef]]] \
            = {}
        self._module_cache: Dict[Tuple[str, str], Optional[ModuleInfo]] = {}
        self._defs_cache: Dict[int, Dict[str, ast.FunctionDef]] = {}

    def _local_defs(self, mod: ModuleInfo) -> Dict[str, ast.FunctionDef]:
        cached = self._defs_cache.get(id(mod))
        if cached is None:
            cached = _all_defs(mod.tree)
            self._defs_cache[id(mod)] = cached
        return cached

    @classmethod
    def build(cls, files: Iterable[Tuple[str, str, ast.Module]]
              ) -> "PackageIndex":
        return cls([ModuleInfo(p, r, t) for p, r, t in files])

    # ------------------------------------------------------------- modules

    def find_module(self, dotted: Optional[str],
                    near: Optional[ModuleInfo] = None) -> Optional[ModuleInfo]:
        if not dotted:
            return None
        key = (dotted, near.modname if near else "")
        if key in self._module_cache:
            return self._module_cache[key]
        out = self._find_module(dotted, near)
        self._module_cache[key] = out
        return out

    def _find_module(self, dotted: str,
                     near: Optional[ModuleInfo]) -> Optional[ModuleInfo]:
        if dotted in self.modules:
            return self.modules[dotted]
        parts = dotted.split(".")
        cands = [m for m in self._by_tail.get(parts[-1], ())
                 if m.modname == dotted
                 or m.modname.endswith("." + dotted)
                 or dotted.endswith("." + m.modname)]
        if not cands:
            return None
        if len(cands) == 1 or near is None:
            return cands[0]
        # disambiguate by shared package prefix with the importing module
        def score(m: ModuleInfo) -> int:
            a, b = m.modname.split("."), near.modname.split(".")
            n = 0
            while n < min(len(a), len(b)) and a[n] == b[n]:
                n += 1
            return n
        return max(cands, key=score)

    # ----------------------------------------------------------- resolution

    def resolve(self, mod: ModuleInfo, dotted: str
                ) -> List[Tuple[ModuleInfo, ast.FunctionDef]]:
        """Best-effort binding of a (possibly dotted) callable reference in
        ``mod`` to function defs anywhere in the package."""
        parts = dotted.split(".")
        head, rest = parts[0], parts[1:]
        if not rest:
            if head in mod.defs:
                return [(mod, mod.defs[head])]
            if head in mod.import_names:
                src, attr = mod.import_names[head]
                tm = self.find_module(src, near=mod)
                if tm and attr in tm.defs:
                    return [(tm, tm.defs[attr])]
            return []
        # mod-object attribute call: histmod.compact(...), pkg.sub.fn(...)
        out: List[Tuple[ModuleInfo, ast.FunctionDef]] = []
        if head in mod.import_modules:
            base = mod.import_modules[head]
            tm = self.find_module(".".join([base] + rest[:-1]), near=mod)
            if tm and rest[-1] in tm.defs:
                out.append((tm, tm.defs[rest[-1]]))
        if head in mod.import_names:
            # ``from pkg import sub`` then sub.fn(...): the bound name is a
            # module, not a def
            src, attr = mod.import_names[head]
            tm = self.find_module(
                ".".join([src, attr] + rest[:-1]), near=mod)
            if tm and rest[-1] in tm.defs:
                out.append((tm, tm.defs[rest[-1]]))
        return out

    def resolve_method(self, mod: ModuleInfo, fn: ast.FunctionDef,
                       method: str
                       ) -> List[Tuple[ModuleInfo, ast.FunctionDef]]:
        """``self.method(...)`` inside ``fn`` -> same-class method."""
        cls = mod.owner_class.get(id(fn))
        if cls is None:
            return []
        tgt = mod.classes.get(cls, {}).get(method)
        return [(mod, tgt)] if tgt is not None else []

    # --------------------------------------------------------- reachability

    def loop_roots(self, loop_calls: Iterable[str]
                   ) -> List[Tuple[ModuleInfo, ast.FunctionDef]]:
        """Every function handed (by name) to one of ``loop_calls`` anywhere
        in the package — lambdas count via their enclosing function, which
        the BFS already visits."""
        loop_set = set(loop_calls)
        roots: List[Tuple[ModuleInfo, ast.FunctionDef]] = []
        for mod in self.modules.values():
            local_defs = self._local_defs(mod)
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                if dotted_name(node.func) not in loop_set:
                    continue
                cands = list(node.args) + [kw.value for kw in node.keywords]
                for arg in cands:
                    if isinstance(arg, ast.Lambda):
                        roots.append((mod, arg))
                        continue
                    name = dotted_name(arg)
                    if not name:
                        continue
                    if name in local_defs:
                        roots.append((mod, local_defs[name]))
                    else:
                        roots.extend(self.resolve(mod, name))
        return roots

    def reachable_from_loops(self, loop_calls: frozenset
                             ) -> Dict[int, Tuple[ModuleInfo,
                                                  ast.FunctionDef]]:
        """Transitive closure of functions reachable from jax.lax loop
        bodies, across module boundaries. Keyed by id(fn)."""
        cached = self._reach_cache.get(loop_calls)
        if cached is not None:
            return cached
        seen: Dict[int, Tuple[ModuleInfo, ast.FunctionDef]] = {}
        frontier = list(self.loop_roots(loop_calls))
        while frontier:
            mod, fn = frontier.pop()
            if id(fn) in seen:
                continue
            seen[id(fn)] = (mod, fn)
            local_defs = self._local_defs(mod)
            for name in referenced_callables(fn):
                if "." not in name and name in local_defs:
                    tgt = local_defs[name]
                    if id(tgt) not in seen:
                        frontier.append((mod, tgt))
                    continue
                if name.startswith("self."):
                    for pair in self.resolve_method(
                            mod, fn, name.split(".", 1)[1].split(".")[0]):
                        if id(pair[1]) not in seen:
                            frontier.append(pair)
                    continue
                for pair in self.resolve(mod, name):
                    if id(pair[1]) not in seen:
                        frontier.append(pair)
        self._reach_cache[loop_calls] = seen
        return seen


def _all_defs(tree: ast.Module) -> Dict[str, ast.FunctionDef]:
    """Every def in the file by name, outermost-first on collision — the
    historical same-file map, kept for nested-closure resolution."""
    out: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, node)
    return out


def single_file_index(path: str, rel: str, tree: ast.Module) -> PackageIndex:
    """Degenerate one-module index: standalone ``lint_file`` calls keep the
    historical same-file reachability semantics."""
    return PackageIndex.build([(path, rel, tree)])


def reachable_loop_code(ctx, loop_calls: frozenset) -> List[ast.AST]:
    """Functions and lambdas reachable from jax.lax loop bodies that live in
    ``ctx``'s file — package-wide when the lint run attached a
    :class:`PackageIndex` (``ctx.package``), same-file otherwise."""
    index = getattr(ctx, "package", None)
    if index is None:
        index = single_file_index(ctx.path, ctx.rel, ctx.tree)
    mod = index.by_path.get(ctx.rel)
    if mod is None:
        return []
    reach = index.reachable_from_loops(loop_calls)
    return [fn for (m, fn) in reach.values() if m is mod]


def infer_traced_names(fn: ast.FunctionDef, params_traced: bool,
                       static_params: Set[str] = frozenset()) -> Set[str]:
    """Fixpoint dataflow: names holding (expressions derived from) jax
    arrays inside ``fn``. Walks nested functions too — their assignments
    only ever *add* traced names, which is the conservative direction."""
    traced: Set[str] = set()
    if params_traced:
        traced |= set(param_names(fn)) - set(static_params)

    assigns = [s for s in ast.walk(fn)
               if isinstance(s, (ast.Assign, ast.AugAssign, ast.AnnAssign))]
    changed = True
    while changed:
        changed = False
        for stmt in assigns:
            value = stmt.value
            if value is None:
                continue
            if expr_is_traced(value, traced):
                for name in _assign_targets(stmt):
                    if name not in traced:
                        traced.add(name)
                        changed = True
    return traced
