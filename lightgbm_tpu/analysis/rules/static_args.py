"""R005: non-hashable or array-valued static_argnums.

A ``static_argnums`` parameter is hashed and compared per call: passing a
jax/numpy array there either raises (unhashable) or — worse, for small
hashable proxies like tuples rebuilt per call — recompiles on every
distinct value, which is the recompile-churn failure mode the runtime
guard (guards.py) exists to catch. Flagged statically when:

- the jit site's static parameter is used as an array in the function body
  (passed to jnp./jax. ops, ``.astype``/``.at``/``.dtype`` access), or
- the static parameter carries a mutable (unhashable) default, or
- ``static_argnums``/``static_argnames`` is itself malformed (non-int /
  non-str entries).
"""
from __future__ import annotations

import ast

from .common import (JIT_NAMES, PARTIAL_NAMES, dotted_name, iter_functions,
                     param_names)

RULE_ID = "R005"

_ARRAY_ATTRS = {"astype", "at", "dtype", "reshape", "sum", "mean"}
_JNP_PREFIXES = ("jnp.", "jax.numpy.", "jax.lax.")


def _jit_sites(tree):
    """Yield (jit Call node, target FunctionDef or None)."""
    by_name = {}
    for fn in iter_functions(tree):
        by_name.setdefault(fn.name, fn)
    deco_calls = set()
    for fn in iter_functions(tree):
        for dec in fn.decorator_list:
            if isinstance(dec, ast.Call):
                deco_calls.add(id(dec))
                cname = dotted_name(dec.func)
                if cname in JIT_NAMES:
                    yield dec, fn
                elif cname in PARTIAL_NAMES and dec.args \
                        and dotted_name(dec.args[0]) in JIT_NAMES:
                    yield dec, fn
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and id(node) not in deco_calls \
                and dotted_name(node.func) in JIT_NAMES:
            target = by_name.get(dotted_name(node.args[0])) if node.args else None
            yield node, target


def _static_param_names(call, fn):
    """(names, malformed_entries) for the static args at this jit site."""
    names, bad = [], []
    plist = param_names(fn) if fn is not None else []
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            entries = (kw.value.elts
                       if isinstance(kw.value, (ast.Tuple, ast.List))
                       else [kw.value])
            for e in entries:
                if isinstance(e, ast.Constant) and isinstance(e.value, int) \
                        and not isinstance(e.value, bool):
                    if 0 <= e.value < len(plist):
                        names.append(plist[e.value])
                elif isinstance(e, ast.Constant):
                    bad.append(repr(e.value))
        elif kw.arg == "static_argnames":
            entries = (kw.value.elts
                       if isinstance(kw.value, (ast.Tuple, ast.List))
                       else [kw.value])
            for e in entries:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    names.append(e.value)
                elif isinstance(e, ast.Constant):
                    bad.append(repr(e.value))
    return names, bad


def _used_as_array(fn, pname):
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == pname and node.attr in _ARRAY_ATTRS:
            return f"`.{node.attr}` access"
        if isinstance(node, ast.Call):
            cname = dotted_name(node.func) or ""
            if cname.startswith(_JNP_PREFIXES):
                for a in node.args:
                    if isinstance(a, ast.Name) and a.id == pname:
                        return f"passed to `{cname}`"
    return None


def _mutable_default(fn, pname):
    a = fn.args
    pos = a.posonlyargs + a.args
    defaults = a.defaults
    for p, d in zip(pos[len(pos) - len(defaults):], defaults):
        if p.arg == pname and isinstance(d, (ast.List, ast.Dict, ast.Set)):
            return True
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if p.arg == pname and isinstance(d, (ast.List, ast.Dict, ast.Set)):
            return True
    return False


class StaticArgsRule:
    rule_id = RULE_ID
    summary = ("static_argnums pointing at an array-valued or unhashable "
               "parameter (per-value recompile churn or TypeError)")

    def check(self, ctx):
        for call, fn in _jit_sites(ctx.tree):
            names, bad = _static_param_names(call, fn)
            for b in bad:
                yield ctx.finding(
                    self.rule_id, call,
                    f"malformed static_argnums/static_argnames entry {b} — "
                    f"must be an int index or parameter name")
            if fn is None:
                continue
            for pname in names:
                use = _used_as_array(fn, pname)
                if use:
                    yield ctx.finding(
                        self.rule_id, call,
                        f"static arg `{pname}` of `{fn.name}` is used as an "
                        f"array ({use}) — static args are hashed per call; "
                        f"an array here raises or recompiles per value")
                elif _mutable_default(fn, pname):
                    yield ctx.finding(
                        self.rule_id, call,
                        f"static arg `{pname}` of `{fn.name}` has a mutable "
                        f"(unhashable) default — jit will TypeError when it "
                        f"is used")
