"""R002: implicit host-device sync in hot-path modules.

``np.asarray(x)`` / ``float(x)`` / ``x.item()`` / ``x.tolist()`` on a jax
array blocks on the device and pulls the value to the host — through the
axon tunnel that is ~67 ms per sync (exp/RESULTS.md). One of these inside
the per-iteration training path (``lightgbm_tpu/boosting/``, ``grower.py``,
``ops/``) silently serializes the pipeline every step. Hoist the sync out
of the loop, or keep the value on-device.

Scope: only functions in hot-path modules, and only receivers/arguments
that provably flow from a jnp./jax. expression — host-side numpy code in
the same files is untouched.

Waiver: a function decorated with ``@allowed_host_sync("<reason>")``
(lightgbm_tpu/robustness) is an *audited* sync point — the checkpoint state
fetch, the per-iteration nan_policy flag fetch — and is skipped entirely.
The decorator replaces inline ``# tpu-lint: disable=R002`` suppressions and
records WHY the sync is the contract, next to the code.
"""
from __future__ import annotations

import ast

from .common import (dotted_name, expr_is_traced, infer_traced_names,
                     iter_functions, jit_static_params, traced_entry_functions)

RULE_ID = "R002"

HOT_PATH_MARKERS = ("lightgbm_tpu/boosting/", "lightgbm_tpu/ops/")
HOT_PATH_FILES = ("grower.py", "efb.py")

_SYNC_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
               "float", "int", "bool"}
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}


def _is_hot_path(rel: str) -> bool:
    rel = rel.replace("\\", "/")
    if any(m in rel for m in HOT_PATH_MARKERS):
        return True
    return any(rel.endswith("/" + f) or rel == f for f in HOT_PATH_FILES)


def _has_sync_waiver(fn) -> bool:
    """True when ``fn`` carries the ``allowed_host_sync`` decorator (bare or
    dotted, always called with a reason string)."""
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted_name(target)
        if name and name.split(".")[-1] == "allowed_host_sync":
            return True
    return False


class HostSyncRule:
    rule_id = RULE_ID
    summary = ("implicit host sync (np.asarray/float/.item()/.tolist()) on "
               "a jax array in a hot-path module")

    def check(self, ctx):
        if not _is_hot_path(ctx.rel):
            return
        jit_entries = {id(fn): static
                       for fn, static in traced_entry_functions(ctx.tree)}
        for fn in iter_functions(ctx.tree):
            if _has_sync_waiver(fn):
                continue
            params_traced = id(fn) in jit_entries
            traced = infer_traced_names(
                fn, params_traced=params_traced,
                static_params=jit_entries.get(id(fn), frozenset()))
            if not traced:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if name in _SYNC_CALLS and node.args:
                    if expr_is_traced(node.args[0], traced):
                        yield ctx.finding(
                            self.rule_id, node,
                            f"`{name}()` on a traced/device value in "
                            f"hot-path function `{fn.name}` — implicit "
                            f"host sync; hoist it out of the iteration "
                            f"path or keep the value on-device")
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr in _SYNC_METHODS
                      and node.func.attr != "block_until_ready"
                      and expr_is_traced(node.func.value, traced)):
                    yield ctx.finding(
                        self.rule_id, node,
                        f"`.{node.func.attr}()` on a traced/device value "
                        f"in hot-path function `{fn.name}` — implicit "
                        f"host sync")
