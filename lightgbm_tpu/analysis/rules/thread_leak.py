"""R012: worker threads created without a leak-proof lifecycle.

The serving and robustness layers run real worker threads (micro-batcher
worker, circuit-breaker probe, hang watchdog, chaos killer). A
``threading.Thread`` that is neither ``daemon=True`` nor ``join()``-ed
from a reachable cleanup method outlives its owner: a test leaks it, a
closed server keeps a runner pinned to a dead queue, and interpreter
shutdown blocks on it — exactly the "enqueue into a dead worker and hang
the caller" class the typed serving shutdown exists to prevent.

What fires, for ``threading.Thread(...)`` / ``Thread(...)`` construction
inside ``lightgbm_tpu/``:

- the constructor has no ``daemon=True`` keyword, AND
- no reachable ``join()`` is found for the created thread:
  - ``self.x = Thread(...)`` is cleared by ``self.x.join(...)`` inside a
    cleanup method of the same class (``close`` / ``stop`` / ``shutdown``
    / ``__exit__`` / ``__del__`` / ``join``), or by a cleanup method
    handing ``self.x`` to a helper — same file or across an import, via
    the whole-package call graph — that join()s the parameter;
  - ``t = Thread(...)`` (local) is cleared by ``t.join(...)`` anywhere in
    the same function (the loadgen pattern: start workers, join them);
  - an unassigned ``Thread(...).start()`` has nothing to join and always
    needs ``daemon=True``.

Either discipline is fine — daemon threads die with the process, joined
threads die with their owner. A thread with neither is a leak waiting
for a wedge; fix it or baseline an audited site.
"""
from __future__ import annotations

import ast
from typing import Optional, Set

from .common import dotted_name

RULE_ID = "R012"

_THREAD_CTORS = {"threading.Thread", "Thread"}
_CLEANUP_METHODS = {"close", "stop", "shutdown", "join", "__exit__",
                    "__del__"}
_SCOPE_MARKER = "lightgbm_tpu/"


def _is_daemon_true(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is True:
            return True
    return False


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' for a ``self.x`` attribute node, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _joined_self_attrs(cls: ast.ClassDef) -> Set[str]:
    """Attrs ``x`` with ``self.x.join(...)`` inside a cleanup method."""
    out: Set[str] = set()
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if item.name not in _CLEANUP_METHODS:
            continue
        for node in ast.walk(item):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "join":
                attr = _self_attr(node.func.value)
                if attr:
                    out.add(attr)
    return out


def _raw_params(fn: ast.FunctionDef):
    a = fn.args
    return [p.arg for p in a.posonlyargs + a.args]


def _delegated_join_attrs(ctx, cls: ast.ClassDef) -> Set[str]:
    """Attrs ``x`` whose cleanup method hands ``self.x`` to a helper that
    join()s the corresponding parameter — ``close()`` calling
    ``drain_worker(self._thread)`` where ``drain_worker`` (same file or
    across an import, via the package call graph) does ``t.join()``."""
    index = getattr(ctx, "package", None)
    if index is None:
        return set()
    mod = index.by_path.get(ctx.rel)
    if mod is None:
        return set()
    out: Set[str] = set()
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if item.name not in _CLEANUP_METHODS:
            continue
        for node in ast.walk(item):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if not callee or callee.startswith("self."):
                continue
            passed = [(i, _self_attr(a)) for i, a in enumerate(node.args)
                      if _self_attr(a)]
            if not passed:
                continue
            for _, helper in index.resolve(mod, callee):
                params = _raw_params(helper)
                joined = _joined_locals(helper)
                for i, attr in passed:
                    if i < len(params) and params[i] in joined:
                        out.add(attr)
    return out


def _joined_locals(fn: ast.FunctionDef) -> Set[str]:
    """Local names ``t`` with ``t.join(...)`` anywhere in ``fn``."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "join" and \
                isinstance(node.func.value, ast.Name):
            out.add(node.func.value.id)
    return out


def _contains_thread_ctor(node: ast.AST) -> bool:
    return any(isinstance(n, ast.Call) and
               (dotted_name(n.func) or "") in _THREAD_CTORS
               for n in ast.walk(node))


def _thread_bound_names(fn: ast.FunctionDef) -> Set[str]:
    """Local names that (transitively) hold Thread objects: assigned from
    an expression containing a Thread ctor (``t = Thread(...)``,
    ``ts = [Thread(...) for ...]``), appended into
    (``ts.append(Thread(...))``), or a loop variable over such a name
    (``for t in ts:``) — so a bare ``sep.join(parts)`` on a string never
    counts as joining a worker."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and _contains_thread_ctor(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "append" and \
                isinstance(node.func.value, ast.Name) and \
                any(_contains_thread_ctor(a) for a in node.args):
            out.add(node.func.value.id)
    changed = True
    while changed:                       # for t in ts / for t in (ts + us)
        changed = False
        for node in ast.walk(fn):
            if isinstance(node, (ast.For, ast.AsyncFor)) and \
                    isinstance(node.target, ast.Name) and \
                    node.target.id not in out and \
                    any(isinstance(n, ast.Name) and n.id in out
                        for n in ast.walk(node.iter)):
                out.add(node.target.id)
                changed = True
    return out


class ThreadLeakRule:
    rule_id = RULE_ID
    cross_module = True   # join delegation resolves through the call graph
    summary = ("threading.Thread created without daemon=True or a "
               "reachable join() in a close()/__exit__-style cleanup — "
               "the worker outlives its owner (leak / shutdown hang)")

    def check(self, ctx):
        rel = ctx.rel.replace("\\", "/")
        if _SCOPE_MARKER not in rel:
            return
        yield from self._walk(ctx, ctx.tree, cls=None, fn=None)

    def _walk(self, ctx, node, cls, fn):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from self._walk(ctx, child, cls=child, fn=fn)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._walk(ctx, child, cls=cls, fn=child)
            else:
                for call in ast.walk(child):
                    if isinstance(call, ast.Call) and \
                            (dotted_name(call.func) or "") in _THREAD_CTORS:
                        f = self._judge(ctx, call, child, cls, fn)
                        if f is not None:
                            yield f

    def _judge(self, ctx, call: ast.Call, stmt: ast.AST,
               cls: Optional[ast.ClassDef], fn) -> Optional[object]:
        if _is_daemon_true(call):
            return None
        # where does the thread land? self.<attr>, a local name, a
        # container (comprehension/list literal), or nowhere. The binding
        # Assign may sit anywhere inside the statement (if/try/with), so
        # find the one whose value IS this call rather than requiring a
        # top-level assignment
        targets = []
        for n in ast.walk(stmt):
            if isinstance(n, ast.Assign) and n.value is call:
                targets = n.targets
                break
            if isinstance(n, ast.AnnAssign) and n.value is call:
                targets = [n.target]
                break
        target_attr = target_name = None
        for tgt in targets:
            a = _self_attr(tgt)
            if a:
                target_attr = a
            elif isinstance(tgt, ast.Name):
                target_name = tgt.id
        if target_attr and cls is not None and \
                (target_attr in _joined_self_attrs(cls)
                 or target_attr in _delegated_join_attrs(ctx, cls)):
            return None
        if target_name and fn is not None and \
                target_name in _joined_locals(fn):
            return None
        # container / fire-and-forget pattern: threads collected then
        # joined in the same function ([Thread(...) for ...] with a later
        # `for t in ts: t.join()` loop) — the thread object itself is not
        # name-trackable, so accept a join() on a name that actually
        # holds threads (never, e.g., a str.join on a local)
        if not targets and fn is not None and \
                _joined_locals(fn) & _thread_bound_names(fn):
            return None
        return ctx.finding(
            self.rule_id, call,
            "worker thread is neither daemon=True nor join()-ed from a "
            "cleanup method (close/stop/shutdown/__exit__) — it outlives "
            "its owner and leaks (or wedges interpreter shutdown); mark it "
            "daemon or join it in close()")
