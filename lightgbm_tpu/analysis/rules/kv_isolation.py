"""R013: direct coordination-service KV calls outside the comm layer.

The jax.distributed coordination-service client (``wait_at_barrier``,
``blocking_key_value_get``, ``key_value_set_bytes``, ...) is the one
shared channel every rank of a gang depends on — and every call site on
it carries the full distributed-failure surface: timeouts that must be
attributed to a rank, retries that must reset partial init, chaos
injection that must see the traffic, and the R-isolation needed so the
fault-tolerance tier (heartbeat leases, gang manifests, commit barriers)
can reason about ALL KV traffic in one place.

Scope: ``lightgbm_tpu/`` EXCEPT ``parallel/comm.py`` (the comm layer that
owns the client, its retry policy, and the chaos ``_client_wrapper``
indirection) and ``robustness/`` (the fault-tolerance protocols built on
that layer — distributed.py's manifests/leases, chaos.py's fakes). A
direct client call anywhere else bypasses retry_call's bounded backoff,
the partial-init reset, AND the chaos wrapper — it works until the first
KV flap, then hangs untyped. Route it through ``parallel.comm`` helpers
(``host_allgather``, ``distributed_client`` + ``retry_call``) or the
robustness protocols instead.

Matched on attribute-call NAME (``anything.wait_at_barrier(...)``), so
wrapped clients, ``self._client`` handles, and the raw
``global_state.client`` are all caught without needing type inference.
"""
from __future__ import annotations

import ast

from .common import dotted_name

RULE_ID = "R013"

# the coordination-service client surface (jax._src.distributed client +
# the *_bytes variants comm.py/distributed.py actually use)
_KV_METHODS = {
    "wait_at_barrier",
    "blocking_key_value_get",
    "blocking_key_value_get_bytes",
    "key_value_set",
    "key_value_set_bytes",
    "key_value_delete",
    "key_value_try_get",
    "key_value_dir_get",
    "key_value_dir_get_bytes",
}

_EXEMPT_MARKERS = (
    "lightgbm_tpu/parallel/comm.py",
    "lightgbm_tpu/robustness/",
)


def _in_scope(rel: str) -> bool:
    rel = rel.replace("\\", "/")
    if "lightgbm_tpu/" not in rel and not rel.startswith("lightgbm_tpu"):
        return False
    return not any(m in rel for m in _EXEMPT_MARKERS)


class KVIsolationRule:
    rule_id = RULE_ID
    summary = ("direct coordination-service KV client call (wait_at_barrier/"
               "blocking_key_value_get/...) outside parallel/comm.py and "
               "robustness/ (bypasses retry, partial-init reset, and chaos "
               "injection — route through parallel.comm / the robustness "
               "protocols)")

    def check(self, ctx):
        if not _in_scope(ctx.rel):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            method = node.func.attr
            if method not in _KV_METHODS:
                continue
            target = dotted_name(node.func) or f"<expr>.{method}"
            yield ctx.finding(
                self.rule_id, node,
                f"`{target}(...)` talks to the coordination-service KV "
                f"store directly — outside parallel/comm.py and "
                f"robustness/ this bypasses retry_call's bounded backoff, "
                f"the init partial-state reset, and chaos injection "
                f"(ChaosKVClient), and hides gang traffic from the "
                f"fault-tolerance tier. Use parallel.comm helpers "
                f"(host_allgather, distributed_client + retry_call) or "
                f"the robustness/distributed.py protocols.")
