"""R004: Pallas block shapes must satisfy the TPU sublane/lane tiling.

Mosaic tiles vector memory as (sublane, lane) = (8, 128) for 4-byte types,
(16, 128) for bf16, (32, 128) for 1-byte types. A ``pl.BlockSpec`` block or
``pallas_call`` out_shape whose minor dim is not a multiple of 128, or
whose second-minor dim is not sublane-aligned, is rejected at Mosaic
lowering time — on real hardware only, long after CPU interpret-mode tests
passed. Round 5's "125-row accumulator" (S=25 x ch=5 slot-channel rows)
was exactly this: pad to the tile (``-(-n // 8) * 8``) and mask instead.

Only statically-known integer dims are checked; dims spelled as names or
arithmetic are assumed padded by the caller. The sublane requirement is
checked with dtype-aware strictness for ``ShapeDtypeStruct`` (dtype is in
the call) and with the weakest requirement (8) for ``BlockSpec``.
"""
from __future__ import annotations

import ast

from .common import dotted_name

RULE_ID = "R004"

LANE = 128
_SUBLANE = {"float32": 8, "int32": 8, "uint32": 8,
            "bfloat16": 16, "float16": 16, "int16": 16, "uint16": 16,
            "int8": 32, "uint8": 32, "bool_": 32}


def _static_dims(node):
    """[int or None, ...] for a literal tuple/list shape, else None."""
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    dims = []
    for e in node.elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, int) \
                and not isinstance(e.value, bool):
            dims.append(e.value)
        else:
            dims.append(None)
    return dims


def _sublane_for(dtype_node) -> int:
    name = dotted_name(dtype_node) or ""
    leaf = name.rsplit(".", 1)[-1]
    return _SUBLANE.get(leaf, 8)


def _check_dims(dims, sublane):
    """Yield (what, dim, requirement) misalignment descriptions."""
    if not dims or len(dims) < 2:
        return
    minor, second = dims[-1], dims[-2]
    if minor is not None and minor != 1 and minor % LANE:
        yield ("minor (lane) dim", minor, LANE)
    if second is not None and second != 1 and second % sublane:
        yield ("second-minor (sublane) dim", second, sublane)


class PallasShapeRule:
    rule_id = RULE_ID
    summary = ("pallas_call block / out_shape dims not aligned to the TPU "
               "(sublane, lane) tile — Mosaic rejects them on hardware")

    def check(self, ctx):
        # ShapeDtypeStruct is a general jax utility (eval_shape etc.) — only
        # instances inside a pallas_call(out_shape=...) subtree are
        # tile-constrained. BlockSpec is pallas-specific, checked anywhere.
        in_out_shape = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) \
                    and (dotted_name(node.func) or "").endswith("pallas_call"):
                for kw in node.keywords:
                    if kw.arg == "out_shape":
                        for sub in ast.walk(kw.value):
                            in_out_shape.add(id(sub))
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            leaf = name.rsplit(".", 1)[-1]
            if leaf == "BlockSpec":
                if any(kw.arg == "memory_space" for kw in node.keywords):
                    continue          # SMEM/ANY blocks are not vector-tiled
                shape_node = node.args[0] if node.args else next(
                    (kw.value for kw in node.keywords
                     if kw.arg == "block_shape"), None)
                for what, dim, req in _check_dims(
                        _static_dims(shape_node), sublane=8):
                    yield ctx.finding(
                        self.rule_id, node,
                        f"BlockSpec {what} = {dim} is not a multiple of "
                        f"{req} — Mosaic rejects the block on hardware; "
                        f"pad to the tile and mask")
            elif leaf == "ShapeDtypeStruct" and id(node) in in_out_shape:
                shape_node = node.args[0] if node.args else next(
                    (kw.value for kw in node.keywords
                     if kw.arg == "shape"), None)
                dtype_node = node.args[1] if len(node.args) > 1 else next(
                    (kw.value for kw in node.keywords
                     if kw.arg == "dtype"), None)
                sub = _sublane_for(dtype_node) if dtype_node is not None else 8
                for what, dim, req in _check_dims(_static_dims(shape_node),
                                                  sublane=sub):
                    yield ctx.finding(
                        self.rule_id, node,
                        f"ShapeDtypeStruct {what} = {dim} is not a "
                        f"multiple of {req} for this dtype — pad to the "
                        f"(sublane, lane) tile and slice the result")
