"""R010: swallowed broad exceptions in the package.

``except Exception: pass`` (and the bare-``except``-and-``continue``
variants) silently eats EVERY failure class — including the faults the
self-healing layer (robustness/, docs/Fault-Tolerance.md) exists to
detect: a checkpoint that failed verification, a shard CRC mismatch, a
comm timeout. A fault that is swallowed instead of raised/logged never
reaches the lineage fallback, the watchdog, or the supervisor — the run
keeps going on corrupt state, which is strictly worse than dying.

Flagged: an ``except`` handler that is BROAD (bare ``except:``,
``except Exception``, ``except BaseException``, or a tuple containing one
of those) whose body does NOTHING but ``pass``/``continue``. Narrow
handlers (``except OSError: pass`` around a best-effort unlink) express a
deliberate, bounded decision and stay out of scope, as does any broad
handler that logs, re-raises, counts, or returns a fallback — the rule
targets the silent black hole only.

Intentional sites — best-effort cleanup where even logging can fail —
belong in ``tpu_lint_baseline.json``, recording the audit; any NEW silent
broad catch fails the lint.
"""
from __future__ import annotations

import ast

RULE_ID = "R010"

_BROAD = {"Exception", "BaseException"}


def _in_scope(rel: str) -> bool:
    rel = rel.replace("\\", "/")
    return "lightgbm_tpu/" in rel or rel.startswith("lightgbm_tpu")


def _is_broad(handler_type) -> bool:
    """bare except, Exception/BaseException (dotted or not), or a tuple
    containing one of those."""
    if handler_type is None:
        return True
    if isinstance(handler_type, ast.Tuple):
        return any(_is_broad(el) for el in handler_type.elts)
    if isinstance(handler_type, ast.Name):
        return handler_type.id in _BROAD
    if isinstance(handler_type, ast.Attribute):
        return handler_type.attr in _BROAD
    return False


def _only_passes(body) -> bool:
    return all(isinstance(stmt, (ast.Pass, ast.Continue)) for stmt in body)


class SwallowedExceptionRule:
    rule_id = RULE_ID
    summary = ("broad exception handler that only passes/continues "
               "(`except Exception: pass`, bare except) — swallowed faults "
               "defeat the self-healing layer; log, count, or narrow the "
               "exception type instead")

    def check(self, ctx):
        if not _in_scope(ctx.rel):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _is_broad(node.type) and _only_passes(node.body):
                what = ("bare `except:`" if node.type is None
                        else f"`except {ast.unparse(node.type)}`")
                yield ctx.finding(
                    self.rule_id, node,
                    f"{what} with a body that only "
                    f"passes/continues swallows every failure class — "
                    f"faults the robustness layer needs to see "
                    f"(checkpoint corruption, shard CRC mismatches, comm "
                    f"timeouts) die here silently; log it, count it "
                    f"(observability.inc), narrow the type, or baseline "
                    f"the audited site in tpu_lint_baseline.json")
