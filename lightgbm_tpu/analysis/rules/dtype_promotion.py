"""R003: dtype-promotion hazards around low-precision accumulators.

Two shapes of the round-5 accuracy drift in ops/histogram.py:

- a ``jnp.stack``/``jnp.concatenate`` whose inputs MIX explicit
  ``.astype(...)`` casts with bare names: the bare inputs' dtype is
  whatever upstream happened to produce, and jax's implicit promotion
  silently widens (or narrows) the whole stack — the bf16 hi/lo packing
  changes accuracy without any error. Cast every input explicitly.
- arithmetic combining a name that was explicitly cast to ``bfloat16``
  with a bare Python float literal: numpy scalars/f32 neighbours promote
  the bf16 accumulator to f32, doubling its HBM footprint behind the
  optimizer's back.
"""
from __future__ import annotations

import ast

from .common import dotted_name, iter_functions

RULE_ID = "R003"

_STACK_FNS = {"jnp.stack", "jnp.concatenate", "jax.numpy.stack",
              "jax.numpy.concatenate"}


def _is_astype_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "astype")


def _bf16_cast_names(fn: ast.FunctionDef) -> set:
    """Names assigned from an explicit `.astype(jnp.bfloat16)` cast."""
    out = set()
    for stmt in ast.walk(fn):
        if not isinstance(stmt, ast.Assign) or not _is_astype_call(stmt.value):
            continue
        args = stmt.value.args
        if args and dotted_name(args[0]) in ("jnp.bfloat16",
                                             "jax.numpy.bfloat16"):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


class DtypePromotionRule:
    rule_id = RULE_ID
    summary = ("mixed explicit/implicit dtypes in jnp.stack inputs, or a "
               "bare float literal widening a bfloat16 value")

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) \
                    and dotted_name(node.func) in _STACK_FNS and node.args \
                    and isinstance(node.args[0], (ast.List, ast.Tuple)):
                elts = node.args[0].elts
                cast = [e for e in elts if _is_astype_call(e)]
                bare = [e for e in elts
                        if isinstance(e, (ast.Name, ast.Attribute))]
                if cast and bare:
                    names = ", ".join(sorted(
                        dotted_name(e) or "<expr>" for e in bare))
                    yield ctx.finding(
                        self.rule_id, node,
                        f"`{dotted_name(node.func)}` mixes explicit "
                        f".astype(...) inputs with bare inputs ({names}) — "
                        f"implicit promotion can silently change the "
                        f"accumulator dtype; cast every input explicitly")

        for fn in iter_functions(ctx.tree):
            bf16 = _bf16_cast_names(fn)
            if not bf16:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.BinOp):
                    continue
                sides = (node.left, node.right)
                lit = [s for s in sides
                       if isinstance(s, ast.Constant)
                       and isinstance(s.value, float)]
                name = [s for s in sides
                        if isinstance(s, ast.Name) and s.id in bf16]
                if lit and name:
                    yield ctx.finding(
                        self.rule_id, node,
                        f"bare float literal {lit[0].value!r} in arithmetic "
                        f"with bfloat16-cast `{name[0].id}` — promotion "
                        f"widens the accumulator; use a typed scalar "
                        f"(jnp.bfloat16({lit[0].value!r}))")
