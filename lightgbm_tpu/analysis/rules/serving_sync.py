"""R011: un-sanctioned host syncs in the serving dispatch path.

The serving engine's latency contract is ONE device->host sync per
dispatch: the result fetch. Any other materialization in
``lightgbm_tpu/serving/`` — a ``.block_until_ready()`` "to be safe", an
``np.asarray`` on an intermediate device value, a stray ``.item()`` in
the batcher loop — serializes the pipeline once per request and is
exactly the class of silent p99 regression the micro-batcher exists to
avoid. The one contractual sync (``ServingEngine._dispatch``'s result
fetch) is baseline-exempt (``tpu_lint_baseline.json``); anything new
fails the lint.

What fires, inside ``lightgbm_tpu/serving/`` only:

- ``.block_until_ready()`` / ``.item()`` / ``.tolist()`` method calls and
  ``jax.device_get(...)`` — always (these exist only to sync);
- ``np.asarray(...)`` / ``np.array(...)`` when the argument is a CALL
  result or a name assigned from a non-numpy call in the same function —
  i.e. materializing something just computed (plausibly a device value).
  Plain input normalization (``np.asarray(X)`` on a function parameter)
  stays legal: converting caller data is host work, not a sync.

The runtime twin is the RecompileGuard's transfer counter, which
``bench.py --serve`` runs over the whole load phase.
"""
from __future__ import annotations

import ast

from .common import dotted_name, iter_functions

RULE_ID = "R011"

_NP_MATERIALIZE = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_ALWAYS_CALLS = {"jax.device_get"}
_ALWAYS_METHODS = {"block_until_ready", "item", "tolist"}

_SCOPE_MARKER = "lightgbm_tpu/serving/"


def _device_ish_names(fn) -> set:
    """Names assigned (in ``fn``) from a call whose root is NOT numpy —
    conservatively 'possibly a device value'."""
    out = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign) or \
                not isinstance(node.value, ast.Call):
            continue
        callee = dotted_name(node.value.func) or ""
        if callee.startswith(("np.", "numpy.")):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                out.add(tgt.id)
    return out


class ServingSyncRule:
    rule_id = RULE_ID
    summary = ("un-sanctioned host sync (np.asarray on a computed value / "
               ".block_until_ready / .item / jax.device_get) inside "
               "lightgbm_tpu/serving/ — the dispatch path syncs exactly "
               "once, at the contractual result fetch")

    def check(self, ctx):
        rel = ctx.rel.replace("\\", "/")
        if _SCOPE_MARKER not in rel:
            return
        for fn in iter_functions(ctx.tree):
            device_ish = _device_ish_names(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func) or ""
                if name in _ALWAYS_CALLS:
                    yield ctx.finding(
                        self.rule_id, node,
                        f"`{name}()` in serving code — an explicit "
                        f"device->host sync outside the contractual result "
                        f"fetch; serving dispatch must stay async "
                        f"(baseline an audited site, never add one "
                        f"casually)")
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr in _ALWAYS_METHODS
                      and not (isinstance(node.func.value, ast.Name)
                               and node.func.value.id in ("self",))):
                    yield ctx.finding(
                        self.rule_id, node,
                        f"`.{node.func.attr}()` in serving code — blocks "
                        f"on the device (or materializes a device value) "
                        f"per call; the serving path's one sanctioned sync "
                        f"is the dispatch result fetch")
                elif name in _NP_MATERIALIZE and node.args:
                    arg = node.args[0]
                    is_computed = isinstance(arg, ast.Call) or (
                        isinstance(arg, ast.Name) and arg.id in device_ish)
                    if is_computed:
                        yield ctx.finding(
                            self.rule_id, node,
                            f"`{name}()` on a just-computed value in "
                            f"serving code — if that value lives on "
                            f"device this is a hidden per-request sync; "
                            f"the one contractual result fetch is "
                            f"baseline-exempt, everything else stays "
                            f"device-side or pre-materialized")
