"""R008: ad-hoc wall-clock timing inside the package.

``time.time()`` / ``time.perf_counter()`` sprinkled through
``lightgbm_tpu/`` produce numbers nobody can find again: they print once
(or feed a local variable) and never reach the metrics registry, the
span trace, or the BENCH json. The observability subsystem exists so
every timing lands in ONE place — use ``observability.span(...)`` for
wall-clock sections, ``PhaseBreakdown`` for compile/steady attribution,
or a registry gauge for one-off durations. Worse, a naive ``perf_counter``
pair around a jax dispatch measures *dispatch* time, not device time
(execution is asynchronous) — the exact confusion the span docs call out.

Scope: files under ``lightgbm_tpu/`` EXCEPT ``observability/`` itself
(the subsystem is the one legitimate home of the primitive). Intentional
sites elsewhere — the legacy TIMETAG accumulator in ``utils/timer.py`` —
are baseline-exempt (``tpu_lint_baseline.json``), not rewritten: the
baseline records the audit, and any NEW ad-hoc timer fails the lint.

Both the dotted form (``time.perf_counter()``) and names imported via
``from time import perf_counter`` are caught; ``time.monotonic`` deadline
arithmetic (retry/chaos budgets) is not timing instrumentation and stays
out of scope.
"""
from __future__ import annotations

import ast

from .common import dotted_name

RULE_ID = "R008"

_TIMING_DOTTED = {"time.time", "time.perf_counter", "time.perf_counter_ns"}
_TIMING_FROM = {"time", "perf_counter", "perf_counter_ns"}

_EXEMPT_MARKERS = ("lightgbm_tpu/observability/",)


def _in_scope(rel: str) -> bool:
    rel = rel.replace("\\", "/")
    if "lightgbm_tpu/" not in rel and not rel.startswith("lightgbm_tpu"):
        return False
    return not any(m in rel for m in _EXEMPT_MARKERS)


def _from_time_aliases(tree) -> set:
    """Local names bound by ``from time import time/perf_counter[ as x]``."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name in _TIMING_FROM:
                    out.add(alias.asname or alias.name)
    return out


class AdHocTimingRule:
    rule_id = RULE_ID
    summary = ("ad-hoc time.time()/time.perf_counter() timing in "
               "lightgbm_tpu/ outside observability/ (use spans / "
               "PhaseBreakdown so the number lands in the registry/trace)")

    def check(self, ctx):
        if not _in_scope(ctx.rel):
            return
        aliases = _from_time_aliases(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            if name in _TIMING_DOTTED or (name in aliases and "." not in name):
                yield ctx.finding(
                    self.rule_id, node,
                    f"`{name}()` is ad-hoc wall-clock timing — route it "
                    f"through observability (span()/PhaseBreakdown/a "
                    f"registry gauge) so the measurement is findable in "
                    f"the trace and snapshot; audited legacy sites belong "
                    f"in tpu_lint_baseline.json")
