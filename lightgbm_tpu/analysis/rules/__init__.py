"""Rule registry for tpu-lint. Each rule module exposes a class with
``rule_id``, ``summary`` and ``check(ctx) -> Iterable[Finding]``."""
from .control_flow import ControlFlowRule          # R001
from .host_sync import HostSyncRule                # R002
from .dtype_promotion import DtypePromotionRule    # R003
from .pallas_shapes import PallasShapeRule         # R004
from .static_args import StaticArgsRule            # R005
from .import_exec import ImportExecRule            # R006
from .sort_in_loop import SortInLoopRule           # R007
from .ad_hoc_timing import AdHocTimingRule         # R008
from .device_transfer import DeviceTransferRule    # R009
from .swallowed_exceptions import SwallowedExceptionRule  # R010
from .serving_sync import ServingSyncRule          # R011
from .thread_leak import ThreadLeakRule            # R012
from .kv_isolation import KVIsolationRule          # R013

_RULES = None


def active_rules():
    global _RULES
    if _RULES is None:
        _RULES = [ControlFlowRule(), HostSyncRule(), DtypePromotionRule(),
                  PallasShapeRule(), StaticArgsRule(), ImportExecRule(),
                  SortInLoopRule(), AdHocTimingRule(), DeviceTransferRule(),
                  SwallowedExceptionRule(), ServingSyncRule(),
                  ThreadLeakRule(), KVIsolationRule()]
    return _RULES
