"""R006: jnp./jax. execution at module import time.

A ``jnp.``/``jax.random.``/``jax.lax.`` call at module scope initializes
the backend the moment the module is imported — before the process had a
chance to pick a platform (JAX_PLATFORMS), arm the hermetic-CPU guard
(utils/hermetic.py), or point the compile cache somewhere useful. With the
axon tunnel in the picture, an import-time backend grab from a wedged
tunnel hangs *every* entrypoint, including ones that never touch a TPU.
Constants like ``jnp.inf``/``jnp.float32`` are attribute reads, not calls,
and stay fine; build arrays lazily inside the function that needs them.

``if __name__ == "__main__":`` blocks run at script time, not import, and
are exempt.
"""
from __future__ import annotations

import ast

from .common import dotted_name

RULE_ID = "R006"

_EXEC_PREFIXES = ("jnp.", "jax.numpy.", "jax.random.", "jax.lax.", "jax.nn.")
_EXEC_EXACT = {"jax.device_put", "jax.devices", "jax.local_devices",
               "jax.device_count", "jax.local_device_count",
               "jax.default_backend", "jax.block_until_ready"}


def _walk_skipping_functions(root):
    """ast.walk that never descends into function/lambda bodies — code in
    there runs at call time, not import time."""
    stack = [root]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.Lambda, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _is_main_guard(stmt) -> bool:
    return (isinstance(stmt, ast.If)
            and isinstance(stmt.test, ast.Compare)
            and isinstance(stmt.test.left, ast.Name)
            and stmt.test.left.id == "__name__")


class ImportExecRule:
    rule_id = RULE_ID
    summary = ("jnp./jax. call executed at module import time (forces "
               "backend init before platform/cache setup)")

    def _walk_module_level(self, stmts):
        """Statements executed at import: module body, descending through
        If/Try/With/For/While and ClassDef bodies, but never into function
        or lambda bodies, and skipping `if __name__ == "__main__"`."""
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if _is_main_guard(stmt):
                continue
            yield stmt
            for attr in ("body", "orelse", "finalbody"):
                inner = getattr(stmt, attr, None)
                if inner:
                    yield from self._walk_module_level(inner)
            for h in getattr(stmt, "handlers", ()):
                yield from self._walk_module_level(h.body)

    def check(self, ctx):
        for stmt in self._walk_module_level(ctx.tree.body):
            if isinstance(stmt, (ast.If, ast.Try, ast.With, ast.For,
                                 ast.While, ast.ClassDef)):
                # children were yielded separately; only scan the parts of
                # this statement that are not child statements (tests,
                # with-items, iterables)
                exprs = []
                if isinstance(stmt, (ast.If, ast.While)):
                    exprs = [stmt.test]
                elif isinstance(stmt, ast.With):
                    exprs = [i.context_expr for i in stmt.items]
                elif isinstance(stmt, ast.For):
                    exprs = [stmt.iter]
            else:
                exprs = [stmt]
            for expr in exprs:
                for node in _walk_skipping_functions(expr):
                    if not isinstance(node, ast.Call):
                        continue
                    name = dotted_name(node.func) or ""
                    if name.startswith(_EXEC_PREFIXES) \
                            or name in _EXEC_EXACT:
                        yield ctx.finding(
                            self.rule_id, node,
                            f"`{name}(...)` runs at module import time — "
                            f"it initializes the jax backend before "
                            f"platform/hermetic/cache setup; build the "
                            f"value lazily inside the function that uses it")
