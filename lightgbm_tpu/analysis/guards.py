"""Runtime recompile/transfer guard — the enforced twin of tpu-lint.

The static rules catch recompile *hazards*; this context manager catches
recompiles that actually happened. A steady-state GBDT training loop must
dispatch the SAME compiled executable every iteration: the iteration
counter travels as a device array, the shrinkage scalar is cached
on-device, shapes are fixed. Any post-warm-up jit cache miss means a shape
or static-arg leak sneaked back in — through the axon tunnel one remote
recompile costs minutes, so it fails the run instead of degrading it.

Cache misses are observed as per-entrypoint ``_cache_size()`` deltas on
the registered jitted callables (jax's pjit caches one executable per
distinct (shapes, statics) signature — the cache growing IS the miss).
Host syncs are counted by intercepting the ``jax.Array`` -> host
conversion surface (``__array__``/``item``/``tolist``/``__float__``/...)
for the duration of the context — the runtime analog of lint rule R002.
Caveat: on the CPU backend ``np.asarray`` converts zero-copy through the
buffer protocol and never reaches ``__array__``, so it is invisible here;
on a real TPU (where a sync actually costs something) every conversion
goes through the patched surface and is counted.

Usage (bench.py --smoke, tests/test_guards.py):

    guard = RecompileGuard()
    guard.register(booster._gbdt._step_fn, "train_step")
    with guard:
        guard.mark_warm()
        for _ in range(iters):
            booster.update()
    # raises GuardViolation on any post-warm-up recompile

jax is imported lazily so `lightgbm_tpu.analysis` (the lint CLI) stays
importable in jax-free environments.
"""
from __future__ import annotations

import contextlib
from typing import Callable, Dict, Optional


class GuardViolation(RuntimeError):
    """A guarded invariant (no steady-state recompiles / no implicit host
    transfers) was broken."""


# jax.Array methods whose call implies a device->host sync
_SYNC_METHODS = ("__array__", "__float__", "__int__", "__bool__",
                 "__index__", "item", "tolist")


class RecompileGuard:
    """Counts jit cache misses per registered entrypoint and implicit
    host-sync events; optionally fails on either.

    Parameters
    ----------
    label: tag used in violation messages ("train", "smoke", ...).
    fail: raise GuardViolation on exit when post-warm-up misses > 0.
    disallow_transfers: raise at the call site on any implicit
        device->host sync inside the context (the strict mode used by
        tests that pin down the zero-sync property of the wave loop).
    """

    def __init__(self, label: str = "train", fail: bool = True,
                 disallow_transfers: bool = False):
        self.label = label
        self.fail = fail
        self.disallow_transfers = disallow_transfers
        self._entry: Dict[str, Callable] = {}
        self._warm_sizes: Optional[Dict[str, int]] = None
        self._start_sizes: Dict[str, int] = {}
        self._transfers = 0
        self._saved_methods = None
        self._sync_surface_ok = None     # None until the context is entered
        self._active = False

    # ------------------------------------------------------------- tracking

    def register(self, fn: Callable, name: str = None) -> None:
        """Track a jitted entrypoint (anything exposing ``_cache_size()``)."""
        if fn is None:
            return
        if not hasattr(fn, "_cache_size"):
            raise TypeError(
                f"RecompileGuard.register: {fn!r} has no _cache_size(); "
                f"pass the jax.jit-wrapped callable itself")
        key = name or getattr(fn, "__name__", f"entry{len(self._entry)}")
        self._entry[key] = fn
        self._start_sizes[key] = self._cache_size(fn)
        if self._warm_sizes is not None:
            self._warm_sizes[key] = self._cache_size(fn)

    @staticmethod
    def _cache_size(fn) -> int:
        try:
            return int(fn._cache_size())
        except Exception:
            return 0

    def mark_warm(self) -> None:
        """Snapshot the caches: compiles after this point are violations."""
        self._warm_sizes = {k: self._cache_size(f)
                            for k, f in self._entry.items()}

    def cache_misses_since_warm(self) -> Dict[str, int]:
        base = self._warm_sizes if self._warm_sizes is not None \
            else self._start_sizes
        return {k: self._cache_size(f) - base.get(k, 0)
                for k, f in self._entry.items()}

    @property
    def transfers(self) -> int:
        """Implicit device->host sync events observed inside the context."""
        return self._transfers

    def report(self) -> dict:
        misses = self.cache_misses_since_warm()
        return {"label": self.label,
                "post_warmup_cache_misses": sum(misses.values()),
                "misses_by_entrypoint": misses,
                "host_syncs": self._transfers,
                "transfer_counting": self._sync_surface_ok,
                "warm_marked": self._warm_sizes is not None}

    # ------------------------------------------------------ transfer counting

    def _patch_sync_surface(self):
        # ArrayImpl is private jax API; if a jax upgrade moves it, transfer
        # counting degrades to disabled instead of killing the guarded run
        # (record-only bench guards must survive). Strict transfer mode
        # can't silently not-enforce, so that still raises.
        try:
            from jax._src.array import ArrayImpl
        except ImportError as e:
            self._saved_methods = None
            self._sync_surface_ok = False
            if self.disallow_transfers:
                raise RuntimeError(
                    f"[{self.label}] disallow_transfers requested but the "
                    f"jax.Array sync surface cannot be patched: {e}") from e
            return
        self._sync_surface_ok = True
        guard = self
        saved = {}
        for mname in _SYNC_METHODS:
            orig = ArrayImpl.__dict__.get(mname)
            if orig is None:
                continue

            def make_wrapper(orig_fn, mname=mname):
                def wrapper(self_arr, *a, **kw):
                    guard._transfers += 1
                    if guard.disallow_transfers:
                        raise GuardViolation(
                            f"[{guard.label}] implicit device->host sync "
                            f"via jax.Array.{mname} inside a transfer-"
                            f"guarded region")
                    return orig_fn(self_arr, *a, **kw)
                return wrapper

            saved[mname] = orig
            setattr(ArrayImpl, mname, make_wrapper(orig))
        self._saved_methods = (ArrayImpl, saved)

    def _unpatch_sync_surface(self):
        if not self._saved_methods:
            return
        cls, saved = self._saved_methods
        for mname, orig in saved.items():
            setattr(cls, mname, orig)
        self._saved_methods = None

    # ------------------------------------------------------- context manager

    def __enter__(self) -> "RecompileGuard":
        self._active = True
        self._transfers = 0
        self._patch_sync_surface()
        return self

    def _publish_report(self) -> None:
        """Feed the guard's totals into the process-wide metrics registry
        (lightgbm_tpu/observability) — the single home of recompile /
        host-sync counters; bench.py and serving snapshots read them there.
        Best-effort: the guard must keep working if the registry cannot."""
        try:
            from ..observability import get_registry
        except Exception:                                    # noqa: BLE001
            return
        reg = get_registry()
        misses = sum(self.cache_misses_since_warm().values()) \
            if self._warm_sizes is not None else 0
        if misses > 0:
            reg.counter("recompiles.post_warmup").inc(misses)
        if self._transfers:
            reg.counter("host_syncs").inc(self._transfers)
        reg.counter("guard.windows").inc()

    def __exit__(self, exc_type, exc, tb) -> bool:
        self._unpatch_sync_surface()
        self._active = False
        self._publish_report()
        if exc_type is not None:
            return False
        if self.fail and self._warm_sizes is not None:
            misses = self.cache_misses_since_warm()
            total = sum(misses.values())
            if total > 0:
                detail = ", ".join(f"{k}: +{v}" for k, v in misses.items()
                                   if v)
                raise GuardViolation(
                    f"[{self.label}] {total} jit cache miss(es) after "
                    f"warm-up ({detail}) — the steady-state loop "
                    f"recompiled; a shape, weak-type, or static-arg "
                    f"signature changed between iterations")
        return False


@contextlib.contextmanager
def recompile_guard(entrypoints=(), label: str = "train", fail: bool = True,
                    warm: bool = True, disallow_transfers: bool = False):
    """Functional wrapper: entrypoints pre-registered, warm-marked on entry.

        with recompile_guard([step_fn]) as g:
            for _ in range(n):
                step()
        assert g.transfers == 0
    """
    g = RecompileGuard(label=label, fail=fail,
                       disallow_transfers=disallow_transfers)
    for fn in entrypoints:
        g.register(fn)
    with g:
        if warm:
            g.mark_warm()
        yield g
