"""Program builders + the shipped contract set for the trace tier.

Importing this module pulls in jax and the product modules (which
registers their ``@trace_entry`` hooks), defines one builder per
(entry, shape_class) cell of the matrix, and registers contracts
T001-T010. Builders trace/compile against the SHIPPED callables fetched
through :func:`get_entry` — never a local copy — so a refactor that
breaks an entry point fails here, loudly, instead of silently pinning
dead code.

Shape classes:

- ``serial``        single-device resident growth / fused train step
- ``serial_legacy`` tpu_incremental_partition=false A/B arm (violates)
- ``u4_packed``     u4 packed-row code layout (tpu_code_mode=u4)
- ``data8``         data-parallel over the 8 hermetic CPU devices
- ``stream_shard``/``stream_wave``  StreamedGrower's two device legs
- ``bundled``       native EFB bundle-space routing
- ``bundled_unpack`` tpu_efb_unpack=true legacy decode arm (violates)
- ``linear``        linear_tree=true ridge-fit legs
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

# product-module imports populate ENTRY_POINTS via @trace_entry
import lightgbm_tpu.boosting.gbdt   # noqa: F401
import lightgbm_tpu.grower          # noqa: F401
import lightgbm_tpu.ops.linear      # noqa: F401
import lightgbm_tpu.ops.predict    # noqa: F401

from . import checks as C
from .registry import (Target, TracedProgram, contract, get_entry,
                       program_builder)


# --------------------------------------------------------- grower.wave_body

def _wave_spec(**over):
    from lightgbm_tpu.grower import GrowerSpec
    kw = dict(num_leaves=15, num_features=6, num_bins_padded=16,
              chunk_rows=256, hist_slots=4, wave_size=4, max_depth=0,
              lambda_l1=0.0, lambda_l2=0.0, min_data_in_leaf=5.0,
              min_sum_hessian_in_leaf=1e-3, min_gain_to_split=0.0,
              row_compact=True, incremental_partition=True, compact_frac=1.0)
    kw.update(over)
    return GrowerSpec(**kw)


def _wave_program(shape_class: str, spec, comm=None, comm_bytes=None,
                  N: int = 1024, grow=None) -> TracedProgram:
    F, B = spec.num_features, spec.num_bins_padded
    if grow is None:
        entry = get_entry("grower.wave_body")

        def grow(X, g, h, inc, fok, iscat, nb, mc, db):
            return entry(X, g, h, inc, fok, iscat, nb, mc, db, spec, comm)
    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.randint(0, B, size=(N, F)).astype(np.uint8))
    g = jnp.asarray(rng.randn(N).astype(np.float32))
    ones = jnp.ones(N, jnp.float32)
    nb = jnp.full(F, B, jnp.int32)
    zf = jnp.zeros(F, jnp.int32)
    jx = jax.make_jaxpr(
        lambda Xa, gg, hh, inc: grow(Xa, gg, hh, inc, jnp.ones(F, bool),
                                     jnp.zeros(F, bool), nb, zf, zf))(
        X, g, ones, ones)
    return TracedProgram("grower.wave_body", shape_class, jx, comm=comm_bytes)


@program_builder("grower.wave_body", "serial")
def _wave_serial():
    return _wave_program("serial", _wave_spec())


@program_builder("grower.wave_body", "serial_legacy")
def _wave_serial_legacy():
    # the pre-incremental-partition A/B arm: per-wave argsort compaction
    return _wave_program("serial_legacy",
                         _wave_spec(incremental_partition=False))


@program_builder("grower.wave_body", "u4_packed")
def _wave_u4():
    # u4 packed-row layout: 16 bins fit a nibble, histogram build unpacks
    return _wave_program("u4_packed", _wave_spec(code_mode="u4"))


@program_builder("grower.wave_body", "data8")
def _wave_data8():
    from lightgbm_tpu.parallel.comm import ParallelContext
    devices = jax.devices()
    if len(devices) < 2:
        raise RuntimeError(
            "data8 shape class needs the hermetic multi-device CPU backend "
            "(force_cpu_backend(device_count=8) before jax initializes)")
    pctx = ParallelContext("data", devices)
    D = pctx.num_devices
    F, B, N = 2 * D, 16, 32 * D
    spec = _wave_spec(num_features=F, num_leaves=7, hist_slots=3,
                      wave_size=3, chunk_rows=32)
    comm = pctx.make_comm(F)
    entry = get_entry("grower.wave_body")

    def grow_fn(X, g, h, inc, fok, iscat, nb, mc, db):
        return entry(X, g, h, inc, fok, iscat, nb, mc, db, spec, comm)

    sharded = pctx.shard_grow(grow_fn)
    return _wave_program(
        "data8", spec, N=N, grow=sharded,
        comm_bytes=lambda: comm.collective_bytes(
            spec.hist_slots, B, use_categorical=False))


# ----------------------------------------------------- routing.bundle_space

def _routing_program(shape_class: str, efb_unpack: bool) -> TracedProgram:
    from lightgbm_tpu.grower import BundleDecode
    route = get_entry("routing.bundle_space")
    N, G, F, B, Bb = 64, 3, 8, 8, 16
    spec = _wave_spec(num_leaves=7, num_features=F, num_bins_padded=B,
                      chunk_rows=32, hist_slots=3, wave_size=3, max_depth=-1,
                      min_data_in_leaf=1.0, min_sum_hessian_in_leaf=0.0,
                      efb_unpack=efb_unpack)
    bundle = BundleDecode(
        col=jnp.zeros(F, jnp.int32), lo=jnp.ones(F, jnp.int32),
        hi=jnp.full(F, 2, jnp.int32), off=jnp.zeros(F, jnp.int32),
        unpack_bin=jnp.zeros((F, B), jnp.int32),
        code_feat=jnp.zeros((G, Bb), jnp.int32))
    n_cols = 6 if efb_unpack else 11
    jx = jax.make_jaxpr(
        lambda X, lid, table, db: route(X, lid, table, None, spec,
                                        bundle, db))(
        jnp.zeros((N, G), jnp.uint8), jnp.zeros(N, jnp.int32),
        jnp.zeros((8, n_cols), jnp.int32), jnp.zeros(F, jnp.int32))
    return TracedProgram("routing.bundle_space", shape_class, jx)


@program_builder("routing.bundle_space", "bundled")
def _routing_native():
    return _routing_program("bundled", efb_unpack=False)


@program_builder("routing.bundle_space", "bundled_unpack")
def _routing_unpack():
    # legacy decode arm: per-row take_along_axis through unpack_bin
    return _routing_program("bundled_unpack", efb_unpack=True)


# ----------------------------------------------------- grower.stream_legs

def _stream_grower():
    StreamedGrower = get_entry("grower.stream_legs")
    F, B, N = 6, 16, 128
    spec = _wave_spec(num_features=F, num_leaves=7, hist_slots=3,
                      wave_size=3, chunk_rows=32)
    sg = StreamedGrower(
        spec, None, None, n_rows_padded=N, local_shard_rows=32, n_shards=4,
        num_cols=F, code_mode="u8", num_bins=jnp.full(F, B, jnp.int32),
        missing_code=jnp.zeros(F, jnp.int32),
        default_bin=jnp.zeros(F, jnp.int32), is_cat=jnp.zeros(F, bool))
    return sg, F, N


def _stream_state():
    sg, F, N = _stream_grower()
    g = jnp.ones(N, jnp.float32)
    state, leaf_id, table0, map_mask0 = sg.init_fn(g, g, g)
    acc, comp = sg.zeros_fn()
    slot_of_leaf, leaf_of_slot = sg.slot_fn(state.needs_hist)
    return (sg, F, N, g, state, leaf_id, table0, map_mask0, acc, comp,
            slot_of_leaf, leaf_of_slot)


@program_builder("grower.stream_legs", "stream_shard")
def _stream_shard():
    (sg, F, N, g, _state, leaf_id, table0, map_mask0, acc, comp,
     slot_of_leaf, _los) = _stream_state()
    codes_sh = jnp.zeros((sg.local_shard_rows, F), jnp.uint8)
    jx = jax.make_jaxpr(sg.shard_fn)(
        acc, comp, codes_sh, leaf_id, g, g, g, slot_of_leaf, table0,
        map_mask0, np.int32(0))
    return TracedProgram("grower.stream_legs", "stream_shard", jx)


@program_builder("grower.stream_legs", "stream_wave")
def _stream_wave():
    (sg, F, _N, _g, state, _lid, _t0, _mm0, acc, _comp,
     _sol, leaf_of_slot) = _stream_state()
    jx = jax.make_jaxpr(sg.wave_fn)(state, acc, leaf_of_slot,
                                    jnp.ones(F, bool))
    return TracedProgram("grower.stream_legs", "stream_wave", jx)


# ------------------------------------------------------------- linear legs

@program_builder("linear.moments", "linear")
def _moments_program():
    acc = get_entry("linear.moments")
    N, F, L1, K = 128, 6, 8, 3
    jx = jax.make_jaxpr(
        lambda Xr, Xm, lid, lf, g, h, inc: acc(Xr, Xm, lid, lf, g, h, inc,
                                               64))(
        jnp.zeros((N, F), jnp.float32), jnp.zeros((N, F), bool),
        jnp.zeros(N, jnp.int32), jnp.zeros((L1, K), jnp.int32),
        jnp.zeros(N, jnp.float32), jnp.zeros(N, jnp.float32),
        jnp.ones(N, jnp.float32))
    return TracedProgram("linear.moments", "linear", jx)


@program_builder("linear.fit_leg", "linear")
def _fit_program():
    from lightgbm_tpu.grower import _empty_tree
    fit = get_entry("linear.fit_leg")
    L, B, N, F = 7, 8, 128, 6
    tree = _empty_tree(L, B)
    jx = jax.make_jaxpr(
        lambda t, Xr, Xm, lid, g, h, inc, iscat: fit(
            t, Xr, Xm, lid, g, h, inc, iscat, max_features=3,
            linear_lambda=0.01, chunk_rows=64, max_steps=4))(
        tree, jnp.zeros((N, F), jnp.float32), jnp.zeros((N, F), bool),
        jnp.zeros(N, jnp.int32), jnp.zeros(N, jnp.float32),
        jnp.zeros(N, jnp.float32), jnp.ones(N, jnp.float32),
        jnp.zeros(F, bool))
    return TracedProgram("linear.fit_leg", "linear", jx)


# ------------------------------------------------------ predict.forest_walk

@program_builder("predict.forest_walk", "serial")
def _predict_program():
    walk = get_entry("predict.forest_walk")
    T, N, F = 3, 32, 4
    M = 6
    i32 = jnp.int32
    jx = jax.make_jaxpr(walk)(
        jnp.zeros((T, M), i32), jnp.zeros((T, M), i32),
        jnp.zeros((T, M), i32), jnp.zeros((T, M), i32),
        jnp.zeros((T, M), i32), jnp.zeros(T, bool), jnp.zeros(F, i32),
        jnp.zeros((N, F), i32), jnp.zeros((N, F), bool),
        jnp.zeros((N, F), bool))
    return TracedProgram("predict.forest_walk", "serial", jx)


# ------------------------------------------------------- train_step.fused

def _booster(params=None, N: int = 256, F: int = 6):
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    X = rng.rand(N, F).astype(np.float32)
    y = (X[:, 0] + 0.25 * rng.rand(N) > 0.6).astype(np.float32)
    p = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 5,
         "verbose": -1}
    p.update(params or {})
    return lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=1,
                     keep_training_booster=True)


@program_builder("train_step.fused", "serial")
def _train_step_program():
    get_entry("train_step.fused")      # assert the GBDT hook is registered
    bst = _booster()
    g = bst._gbdt
    # CPU gates donation off in the product path; the contract forces the
    # TPU-style donate set on so the HLO alias header is checkable here
    donate = (2, 3)
    step = g._make_step(donate_override=donate)
    consts, valid_Xb, valid_scores = g._dispatch_prep(
        float(g.config.learning_rate))
    args = (consts, valid_Xb, g.score, valid_scores, g.bag_mask,
            g._rng_key, g._iter_dev, g._shrink_cache[1])
    jx = jax.make_jaxpr(step)(*args)
    expected = len(jax.tree_util.tree_leaves((args[2], args[3])))
    return TracedProgram(
        "train_step.fused", "serial", jx,
        hlo=lambda: step.lower(*args).compile().as_text(),
        donate_argnums=donate, expected_aliases=expected)


# --------------------------------------------------------------- contracts

contract(
    "T001", "no sort in the steady-state wave loop", "grower.wave_body",
    checks=[C.ForbidPrimitives({"sort"})],
    targets=[Target("serial"), Target("u4_packed"),
             Target("serial_legacy", "violates")],
    doc="Incremental partition derives row grouping from carried state; "
        "the legacy arm's per-wave argsort compaction is the A/B pin that "
        "keeps this check sensitive.")

contract(
    "T002", "no gather in bundle-space routing", "routing.bundle_space",
    checks=[C.ForbidPrimitives({"gather"})],
    targets=[Target("bundled"), Target("bundled_unpack", "violates")],
    doc="Native EFB routes on the one-hot table; the legacy unpack arm "
        "keeps the per-row [F, B] decode gather as the sensitivity pin.")

contract(
    "T003", "data-parallel collectives match collective_bytes()",
    "grower.wave_body",
    checks=[C.RequiredCollectives()],
    targets=[Target("data8")],
    doc="Every collective the cost model charges must appear, and none it "
        "does not charge may appear.")

contract(
    "T004", "no silent f64 in the wave loop", "grower.wave_body",
    checks=[C.DtypeDiscipline()],
    targets=[Target("serial"), Target("u4_packed"), Target("data8")],
    doc="f64 belongs to hist_f64 Kahan sums and host accumulation only.")

contract(
    "T005", "train-step donation survives compilation", "train_step.fused",
    checks=[C.DonationEffective()],
    targets=[Target("serial")],
    doc="Donated score carries must alias outputs in the compiled "
        "executable's input_output_alias header.")

contract(
    "T006", "no host round-trips inside the fused step's loops",
    "train_step.fused",
    checks=[C.NoHostTransferInLoops(), C.DtypeDiscipline()],
    targets=[Target("serial")])

contract(
    "T007", "streamed legs stay sort-free and on-device",
    "grower.stream_legs",
    checks=[C.ForbidPrimitives({"sort"}), C.NoHostTransferInLoops(),
            C.DtypeDiscipline()],
    targets=[Target("stream_shard"), Target("stream_wave")])

contract(
    "T008", "linear-leaf moment accumulation is gather-free",
    "linear.moments",
    checks=[C.ForbidPrimitives({"gather"}), C.DtypeDiscipline()],
    targets=[Target("linear")],
    doc="Moments accumulate via the one-hot chunk contraction — a per-row "
        "feature gather here regresses the PR-14 design.")

contract(
    "T009", "one batched Cholesky per linear fit", "linear.fit_leg",
    checks=[C.CountPrimitive("cholesky", 1), C.DtypeDiscipline()],
    targets=[Target("linear")],
    doc="All leaves solve in ONE vmapped factorization; a second cholesky "
        "means the solve leg was duplicated instead of batched.")

contract(
    "T010", "forest walk is sort-free and loop-host-clean",
    "predict.forest_walk",
    checks=[C.ForbidPrimitives({"sort"}), C.NoHostTransferInLoops(),
            C.DtypeDiscipline()],
    targets=[Target("serial")])
