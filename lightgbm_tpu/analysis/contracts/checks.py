"""Predicate checks for trace contracts.

Each check exposes ``run(program) -> list[str]`` where every failure
message starts with a stable kind token (``forbidden-primitive``,
``required-collective``, ``dtype``, ``donation``, ``host-transfer``,
``count``) — the token is the baseline fingerprint component, so message
wording can evolve without rotting baselines.
"""
from __future__ import annotations

from typing import Dict, Iterable, Optional, Tuple

from . import jaxpr_utils as ju
from .registry import TracedProgram

# collective_bytes() key prefix -> jaxpr primitive it lowers to
# (parallel/comm.py DataParallelComm: psum_root_scalars, psum_scatter_hist,
# allgather_splits)
COLLECTIVE_PRIMS: Dict[str, str] = {
    "psum_scatter": "reduce_scatter",
    "allgather": "all_gather",
    "all_gather": "all_gather",
    "psum": "psum",
    "all_reduce": "psum",
}
_KNOWN_COLLECTIVES = {"psum", "reduce_scatter", "all_gather", "all_to_all",
                      "ppermute"}

# host round-trip primitives that must never sit inside a device loop body
_HOST_PRIMS = {"device_put", "pure_callback", "io_callback",
               "debug_callback", "callback", "outside_call",
               "infeed", "outfeed"}


def _prefix_to_prim(key: str) -> Optional[str]:
    best = None
    for prefix, prim in COLLECTIVE_PRIMS.items():
        if key.startswith(prefix) and (best is None
                                       or len(prefix) > len(best[0])):
            best = (prefix, prim)
    return best[1] if best else None


class ForbidPrimitives:
    """Named primitives must not appear — anywhere, or (where="loops")
    only inside while/scan bodies."""

    def __init__(self, names: Iterable[str], where: str = "anywhere"):
        self.names = frozenset(names)
        self.where = where

    def run(self, p: TracedProgram):
        if self.where == "loops":
            present = {e.primitive.name for e in ju.loop_body_eqns(p.jaxpr)}
        else:
            present = ju.primitive_names(p.jaxpr)
        return [f"forbidden-primitive: `{n}` present in the traced program"
                f"{' (inside a loop body)' if self.where == 'loops' else ''}"
                for n in sorted(self.names & present)]


class RequiredCollectives:
    """The collective set the program's comm strategy promises — derived
    from ``comm.collective_bytes()`` key prefixes — must all appear in the
    jaxpr, and no collective outside that set may appear (an undeclared
    collective means ``collective_bytes`` under-reports interconnect
    traffic, breaking the bench's cost model)."""

    def run(self, p: TracedProgram):
        if p.comm is None:
            return ["required-collective: contract target supplies no comm "
                    "object to derive the expected collective set from"]
        # builders hand either the collective_bytes() dict itself (the comm
        # methods take per-spec shape args) or a zero-arg callable
        declared = p.comm() if callable(p.comm) else p.comm
        expected = set()
        for key in declared:
            prim = _prefix_to_prim(str(key))
            if prim is not None:
                expected.add(prim)
        present = ju.primitive_names(p.jaxpr) & _KNOWN_COLLECTIVES
        out = []
        for prim in sorted(expected - present):
            out.append(f"required-collective: `{prim}` promised by "
                       f"collective_bytes() but absent from the program")
        for prim in sorted(present - expected):
            out.append(f"required-collective: undeclared collective "
                       f"`{prim}` in the program — collective_bytes() "
                       f"does not account for it")
        return out


class DtypeDiscipline:
    """No silent f64 upcasts: float64 may only appear when the shape class
    opted in (hist_f64 Kahan accumulation / host-side accumulation —
    neither traces through these entries)."""

    def __init__(self, forbid: Tuple[str, ...] = ("float64", "complex128")):
        self.forbid = tuple(forbid)

    def run(self, p: TracedProgram):
        present = ju.out_dtype_names(p.jaxpr)
        return [f"dtype: `{d}` value materialized in the traced program — "
                f"f64 belongs to hist_f64 Kahan sums and host accumulation "
                f"only" for d in sorted(set(self.forbid) & present)]


class DonationEffective:
    """Donated arguments must actually alias outputs in the compiled
    executable (HloModule ``input_output_alias`` header) — donation that
    XLA silently discards (shape mismatch, CPU gating bug, sharding
    conflict) re-introduces the full-carry copy per step."""

    def run(self, p: TracedProgram):
        if not p.donate_argnums:
            return ["donation: contract target requested no donation — "
                    "nothing to verify (builder bug)"]
        n = ju.hlo_alias_count(p.hlo_text())
        want = max(1, p.expected_aliases)
        if n < want:
            return [f"donation: only {n} input/output alias(es) in the "
                    f"compiled executable, expected >= {want} for "
                    f"donate_argnums={p.donate_argnums} — XLA dropped the "
                    f"donation and the carry copies every step"]
        return []


class NoHostTransferInLoops:
    """No host round-trip primitives (device_put, callbacks, infeed)
    inside while/scan bodies — a per-iteration host sync serializes the
    device loop."""

    def run(self, p: TracedProgram):
        present = {e.primitive.name for e in ju.loop_body_eqns(p.jaxpr)}
        return [f"host-transfer: `{n}` inside a device loop body — a "
                f"per-iteration host round-trip"
                for n in sorted(_HOST_PRIMS & present)]


class CountPrimitive:
    """A primitive must appear exactly ``expect`` times (e.g. ONE batched
    Cholesky in the linear-leaf solve — a second one means the solve leg
    was duplicated instead of batched)."""

    def __init__(self, name: str, expect: int):
        self.name = name
        self.expect = expect

    def run(self, p: TracedProgram):
        n = ju.count_primitive(p.jaxpr, self.name)
        if n != self.expect:
            return [f"count: `{self.name}` appears {n}x, contract pins "
                    f"exactly {self.expect}"]
        return []
