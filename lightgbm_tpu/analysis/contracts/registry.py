"""Trace-contract registry (dependency-free half of the trace tier).

Product modules register their traceable entry points at import time via
the :func:`trace_entry` decorator — the registered object is the SHIPPED
callable (or class), so a contract always traces the exact code the
booster runs, never a test-local copy. Contracts bind an entry to a
shape-class matrix and a list of predicate checks over the traced program.

Everything here is importable without jax (the decorator rides inside
``grower.py``/``ops/``/``gbdt.py``); jax enters only when a contract is
*evaluated* (trace_lint.py / the contract tests), through the builders in
``entries.py``.

A target's ``expect`` field makes sensitivity first-class:

- ``"clean"``   — every check must pass (the shipped configuration);
- ``"violates"``— at least one check must FAIL (a legacy arm kept as the
  A/B pin, e.g. ``tpu_incremental_partition=false``'s per-wave argsort).
  If a violates-target starts passing, the contract has silently lost its
  teeth and lint reports *that* — tests and lint assert the same predicate
  through this one implementation.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

# entry id -> shipped callable/class, populated by product-module import
ENTRY_POINTS: Dict[str, Any] = {}

# (entry id, shape_class) -> builder() -> TracedProgram, populated by
# entries.py (and by --load'ed fixture files)
PROGRAM_BUILDERS: Dict[Tuple[str, str], Callable[[], "TracedProgram"]] = {}

# contract id -> Contract
CONTRACTS: Dict[str, "Contract"] = {}


def trace_entry(name: str):
    """Register the decorated object as traceable entry point ``name``.
    Returns the object unchanged — zero runtime cost in the product path."""
    def deco(obj):
        ENTRY_POINTS[name] = obj
        return obj
    return deco


def get_entry(name: str):
    if name not in ENTRY_POINTS:
        raise KeyError(
            f"trace entry {name!r} is not registered — its product module "
            f"was not imported or its @trace_entry hook was removed "
            f"(registered: {sorted(ENTRY_POINTS)})")
    return ENTRY_POINTS[name]


def program_builder(entry: str, shape_class: str):
    """Register a builder producing the traced program for one
    (entry, shape_class) cell of the matrix."""
    def deco(fn):
        PROGRAM_BUILDERS[(entry, shape_class)] = fn
        return fn
    return deco


@dataclass
class TracedProgram:
    """What a contract's checks see for one (entry, shape_class) cell."""
    entry: str
    shape_class: str
    jaxpr: Any                      # closed jaxpr of the traced entry
    hlo: Optional[Callable[[], str]] = None   # lazy optimized-HLO text
    donate_argnums: Tuple[int, ...] = ()
    expected_aliases: int = 0       # flat donated array leaves
    comm: Any = None                # collective_bytes() dict / 0-arg callable
    notes: str = ""

    _hlo_text: Optional[str] = None

    def hlo_text(self) -> str:
        if self._hlo_text is None:
            if self.hlo is None:
                raise ValueError(
                    f"{self.entry}@{self.shape_class}: contract needs "
                    f"compiled HLO but the builder supplied none")
            self._hlo_text = self.hlo()
        return self._hlo_text


@dataclass(frozen=True)
class Target:
    shape_class: str
    expect: str = "clean"           # "clean" | "violates"


@dataclass
class Contract:
    id: str                         # "T001"
    title: str
    entry: str                      # entry-point id
    checks: tuple                   # checks.py predicate objects
    targets: Tuple[Target, ...]
    severity: str = "error"         # "error" | "warn"
    doc: str = ""


def contract(id: str, title: str, entry: str, checks, targets,
             severity: str = "error", doc: str = "") -> Contract:
    """Define + register a contract. ``targets`` items may be shape-class
    strings (expect clean) or (shape_class, expect) pairs."""
    norm = tuple(t if isinstance(t, Target) else
                 (Target(*t) if isinstance(t, tuple) else Target(t))
                 for t in targets)
    c = Contract(id=id, title=title, entry=entry, checks=tuple(checks),
                 targets=norm, severity=severity, doc=doc)
    CONTRACTS[id] = c
    return c


# (entry, shape_class) -> TracedProgram, memoized across contracts that
# share a cell (tracing + compiling is the expensive half of the tier)
_PROGRAM_CACHE: Dict[Tuple[str, str], TracedProgram] = {}


def build_program(entry: str, shape_class: str) -> TracedProgram:
    key = (entry, shape_class)
    if key not in _PROGRAM_CACHE:
        if key not in PROGRAM_BUILDERS:
            raise KeyError(
                f"no program builder for {entry!r} @ {shape_class!r} — "
                f"entries.py (or a --load'ed fixture) must register one "
                f"(known: {sorted(PROGRAM_BUILDERS)})")
        _PROGRAM_CACHE[key] = PROGRAM_BUILDERS[key]()
    return _PROGRAM_CACHE[key]


def evaluate_target(c: Contract, program: TracedProgram) -> List[str]:
    """Raw check failures for one traced program (empty = all pass)."""
    failures: List[str] = []
    for chk in c.checks:
        failures.extend(chk.run(program))
    return failures


def evaluate(c: Contract, t: Target, program: TracedProgram
             ) -> List[Tuple[str, str]]:
    """(fingerprint, message) findings for one (contract, target) cell,
    folding in the expect semantics: a clean target reports each check
    failure; a violates target reports only when NO check fails (lost
    sensitivity)."""
    failures = evaluate_target(c, program)
    cell = f"{c.entry}@{t.shape_class}"
    if t.expect == "violates":
        if not failures:
            return [(f"{c.id}:{cell}:sensitivity",
                     f"{c.title}: sensitivity lost — the "
                     f"{t.shape_class!r} legacy arm no longer violates "
                     f"this contract, so the check proves nothing")]
        return []
    return [(f"{c.id}:{cell}:{msg.split(':', 1)[0]}",
             f"{c.title}: {msg}") for msg in failures]
