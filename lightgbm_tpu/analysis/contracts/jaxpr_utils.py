"""Structural walks over jaxprs and optimized-HLO text.

This is the ONE implementation of the recursive jaxpr walk the repo used
to carry as per-test helpers (`_jaxpr_has_sort` in
test_incremental_partition, `_jaxpr_has_primitive` in
test_efb_bundlespace) — those are deleted; both the trace-lint tier and
the tests assert through these functions. No jax import: everything here
is duck-typed over ``.eqns`` / ``.jaxpr`` attributes, so the module loads
in the dependency-free AST tier too.
"""
from __future__ import annotations

import re
from typing import Iterable, Iterator, Optional, Set

_LOOP_PRIMS = {"while", "scan"}


def _inner_jaxprs(params: dict) -> Iterator:
    for v in params.values():
        for j in (v if isinstance(v, (list, tuple)) else [v]):
            inner = getattr(j, "jaxpr", None)
            if inner is not None:
                yield inner
            elif hasattr(j, "eqns"):
                yield j


def iter_eqns(jaxpr) -> Iterator:
    """Every equation in ``jaxpr`` including all sub-jaxprs carried in eqn
    params (while/scan/cond bodies, pjit/shard_map calls, custom calls)."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)   # accept ClosedJaxpr
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _inner_jaxprs(eqn.params):
            yield from iter_eqns(sub)


def primitive_names(jaxpr) -> Set[str]:
    return {eqn.primitive.name for eqn in iter_eqns(jaxpr)}


def has_primitive(jaxpr, name: str) -> bool:
    return any(eqn.primitive.name == name for eqn in iter_eqns(jaxpr))


def count_primitive(jaxpr, name: str) -> int:
    return sum(1 for eqn in iter_eqns(jaxpr)
               if eqn.primitive.name == name)


def loop_body_eqns(jaxpr) -> Iterator:
    """Equations living INSIDE while/scan bodies (any nesting depth) —
    the per-iteration cost surface."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in _LOOP_PRIMS:
            for sub in _inner_jaxprs(eqn.params):
                yield from iter_eqns(sub)
        else:
            for sub in _inner_jaxprs(eqn.params):
                yield from loop_body_eqns(sub)


def out_dtype_names(jaxpr) -> Set[str]:
    """dtype names of every equation output var across the program."""
    out: Set[str] = set()
    for eqn in iter_eqns(jaxpr):
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            dt = getattr(aval, "dtype", None)
            if dt is not None:
                out.add(str(dt))
    return out


# content = non-brace runs interleaved with complete one-level brace
# groups ({0}, {}), so the capture spans the whole alias map and stops at
# ITS closing brace, not the first nested one
_ALIAS_HEADER = re.compile(r"input_output_alias=\{((?:[^{}]|\{[^{}]*\})*)\}")
_ALIAS_ENTRY = re.compile(r"\{[\d,\s]*\}:\s*\(")


def hlo_alias_count(hlo_text: str) -> int:
    """Number of input/output alias pairs in an HloModule header —
    ``input_output_alias={ {0}: (0, {}, may-alias), {1}: (2, {}, ...) }``.
    0 when the header is absent (donation requested but discarded)."""
    m = _ALIAS_HEADER.search(hlo_text)
    if not m:
        return 0
    return len(_ALIAS_ENTRY.findall(m.group(1)))
