"""Trace contracts: declarative jaxpr/HLO predicates over the shipped
entry points (docs/Static-Analysis.md, "Trace contracts").

This package front door stays importable WITHOUT jax: product modules
(`grower.py`, `ops/linear.py`, `ops/predict.py`, `boosting/gbdt.py`)
import :func:`trace_entry` from here at import time, and the AST lint
tier imports :mod:`jaxpr_utils` regexes. Only `entries.py` — the program
builders — pulls in jax, and only when the trace tier actually runs
(``python -m lightgbm_tpu.analysis --trace`` / tests).
"""
from .registry import (  # noqa: F401
    CONTRACTS,
    Contract,
    ENTRY_POINTS,
    PROGRAM_BUILDERS,
    Target,
    TracedProgram,
    build_program,
    contract,
    evaluate,
    evaluate_target,
    get_entry,
    program_builder,
    trace_entry,
)
