"""tpu-lint: AST-based JAX/TPU hygiene analyzer (rules R001-R012).

The worst round-5 bugs were statically detectable: a 125-row Pallas
accumulator block Mosaic rejects (sublane misalignment), u16 byte pairs
lowered through a stride-2 lane slice, silent bf16/f32 drift in the
histogram hi-lo packing. Each became a rule here so the next instance is a
lint error on the dev box, not a Mosaic crash on a TPU pod.

Deliberately dependency-free: stdlib ``ast`` only, no jax import, so the
linter runs in any environment (CI sandboxes, pre-commit, the axon driver)
in milliseconds.

Suppression:
- inline, same line:   ``x = float(s)  # tpu-lint: disable=R002``
- whole file:          ``# tpu-lint: disable-file=R006`` on any line
- baseline file:       committed ``tpu_lint_baseline.json`` holding
  fingerprints (file, rule, stripped source line) of pre-existing findings;
  regenerate with ``--write-baseline`` after an audited change.

Exit codes: 0 clean (after suppressions), 1 findings, 2 usage/parse error.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
from collections import Counter
from dataclasses import asdict, dataclass
from typing import Iterable, List, Optional, Tuple

DEFAULT_BASELINE = "tpu_lint_baseline.json"

_PRAGMA = re.compile(r"#\s*tpu-lint:\s*disable=([A-Za-z0-9_,\s]+)")
_PRAGMA_FILE = re.compile(r"#\s*tpu-lint:\s*disable-file=([A-Za-z0-9_,\s]+)")


@dataclass
class Finding:
    rule: str
    path: str          # repo-relative, "/" separators
    line: int          # 1-based
    col: int
    message: str
    snippet: str       # stripped source line (baseline fingerprint)

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"{self.message}\n    {self.snippet}")


class FileContext:
    """One parsed source file handed to every rule."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = None  # ast.Module, set by lint_file

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0) + 1
        return Finding(rule=rule, path=self.rel, line=line, col=col,
                       message=message, snippet=self.snippet(line))


# ---------------------------------------------------------------- suppression

def _inline_disabled(ctx: FileContext, f: Finding) -> bool:
    if not (1 <= f.line <= len(ctx.lines)):
        return False
    m = _PRAGMA.search(ctx.lines[f.line - 1])
    if not m:
        return False
    ids = {s.strip().upper() for s in m.group(1).split(",")}
    return "ALL" in ids or f.rule in ids


def _file_disabled_rules(ctx: FileContext) -> set:
    out = set()
    for line in ctx.lines:
        m = _PRAGMA_FILE.search(line)
        if m:
            out |= {s.strip().upper() for s in m.group(1).split(",")}
    return out


class Baseline:
    """Committed fingerprints of audited pre-existing findings.

    A finding is suppressed when an unconsumed (file, rule, snippet) entry
    matches — line numbers are deliberately NOT part of the fingerprint so
    unrelated edits above a finding don't invalidate the baseline."""

    def __init__(self, entries: Counter = None):
        self.entries = Counter(entries or ())
        self._unused = Counter(self.entries)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path) as fh:
            data = json.load(fh)
        c = Counter()
        for e in data.get("findings", []):
            c[(e["file"], e["rule"], e["snippet"])] += int(e.get("count", 1))
        return cls(c)

    @classmethod
    def from_findings(cls, findings: Iterable[Finding]) -> "Baseline":
        c = Counter((f.path, f.rule, f.snippet) for f in findings)
        return cls(c)

    def suppresses(self, f: Finding) -> bool:
        key = (f.path, f.rule, f.snippet)
        if self._unused.get(key, 0) > 0:
            self._unused[key] -= 1
            return True
        return False

    def dump(self, path: str) -> None:
        findings = [{"file": k[0], "rule": k[1], "snippet": k[2], "count": n}
                    for k, n in sorted(self.entries.items())]
        with open(path, "w") as fh:
            json.dump({"version": 1, "findings": findings}, fh, indent=1)
            fh.write("\n")


# ------------------------------------------------------------------- running

def _iter_py_files(paths: Iterable[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git",
                                              ".jax_cache", ".bench_cache"))
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield os.path.join(root, name)


def lint_file(path: str, rel: str = None, rules=None
              ) -> Tuple[List[Finding], Optional[str]]:
    """Lint one file. Returns (findings, parse_error)."""
    from .rules import active_rules
    import ast

    rules = rules if rules is not None else active_rules()
    rel = rel if rel is not None else os.path.relpath(path)
    try:
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
        ctx = FileContext(path, rel, source)
        ctx.tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError, ValueError) as e:
        return [], f"{rel}: cannot parse: {e}"
    file_off = _file_disabled_rules(ctx)
    findings = []
    for rule in rules:
        if rule.rule_id in file_off or "ALL" in file_off:
            continue
        for f in rule.check(ctx):
            if not _inline_disabled(ctx, f):
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, None


def lint_paths(paths: Iterable[str], rules=None
               ) -> Tuple[List[Finding], List[str]]:
    findings, errors = [], []
    for path in _iter_py_files(paths):
        fs, err = lint_file(path, rules=rules)
        findings.extend(fs)
        if err:
            errors.append(err)
    return findings, errors


# ----------------------------------------------------------------------- CLI

def _resolve_baseline(arg: Optional[str], no_baseline: bool) -> Optional[str]:
    if no_baseline:
        return None
    if arg:
        return arg
    return DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None


def main(argv: Optional[List[str]] = None) -> int:
    from .rules import active_rules

    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.analysis",
        description="tpu-lint: JAX/TPU hygiene analyzer (rules R001-R012)")
    ap.add_argument("paths", nargs="*", default=["lightgbm_tpu"],
                    help="files or directories to lint")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help=f"suppressions baseline (default: {DEFAULT_BASELINE} "
                         "in the current directory, when present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline file")
    ap.add_argument("--write-baseline", nargs="?", const=DEFAULT_BASELINE,
                    default=None, metavar="FILE",
                    help="write current findings as the new baseline and exit 0")
    ap.add_argument("--select", default=None, metavar="R001,R004",
                    help="run only these rule ids")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    rules = active_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.rule_id}  {r.summary}")
        return 0
    if args.select:
        wanted = {s.strip().upper() for s in args.select.split(",")}
        unknown = wanted - {r.rule_id for r in rules}
        if unknown:
            print(f"unknown rule id(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.rule_id in wanted]

    findings, errors = lint_paths(args.paths, rules=rules)
    for err in errors:
        print(f"tpu-lint: {err}", file=sys.stderr)

    if args.write_baseline:
        Baseline.from_findings(findings).dump(args.write_baseline)
        print(f"tpu-lint: wrote {len(findings)} finding(s) to "
              f"{args.write_baseline}")
        return 0

    baseline_path = _resolve_baseline(args.baseline, args.no_baseline)
    if baseline_path:
        try:
            baseline = Baseline.load(baseline_path)
        except (OSError, ValueError, KeyError) as e:
            print(f"tpu-lint: cannot load baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
        findings = [f for f in findings if not baseline.suppresses(f)]

    if args.format == "json":
        print(json.dumps({"findings": [asdict(f) for f in findings],
                          "errors": errors}, indent=1))
    else:
        for f in findings:
            print(f.format())
        n = len(findings)
        suffix = f" (baseline: {baseline_path})" if baseline_path else ""
        print(f"tpu-lint: {n} finding(s){suffix}")
    if errors:
        return 2
    return 1 if findings else 0
