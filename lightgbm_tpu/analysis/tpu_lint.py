"""tpu-lint: AST-based JAX/TPU hygiene analyzer (rules R001-R013).

The worst round-5 bugs were statically detectable: a 125-row Pallas
accumulator block Mosaic rejects (sublane misalignment), u16 byte pairs
lowered through a stride-2 lane slice, silent bf16/f32 drift in the
histogram hi-lo packing. Each became a rule here so the next instance is a
lint error on the dev box, not a Mosaic crash on a TPU pod.

Deliberately dependency-free: stdlib ``ast`` only, no jax import, so the
linter runs in any environment (CI sandboxes, pre-commit, the axon driver)
in milliseconds.

Suppression:
- inline, same line:   ``x = float(s)  # tpu-lint: disable=R002``
- whole file:          ``# tpu-lint: disable-file=R006`` on any line
- baseline file:       committed ``tpu_lint_baseline.json`` holding
  fingerprints (file, rule, stripped source line) of pre-existing findings;
  regenerate with ``--write-baseline`` after an audited change.

Exit codes: 0 clean (after suppressions), 1 findings, 2 usage/parse error.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
from collections import Counter
from dataclasses import asdict, dataclass
from typing import Iterable, List, Optional, Tuple

DEFAULT_BASELINE = "tpu_lint_baseline.json"

_PRAGMA = re.compile(r"#\s*tpu-lint:\s*disable=([A-Za-z0-9_,\s]+)")
_PRAGMA_FILE = re.compile(r"#\s*tpu-lint:\s*disable-file=([A-Za-z0-9_,\s]+)")


@dataclass
class Finding:
    rule: str
    path: str          # repo-relative, "/" separators
    line: int          # 1-based
    col: int
    message: str
    snippet: str       # stripped source line (baseline fingerprint)
    severity: str = "error"   # "error" gates exit code; "warn" reports only

    def format(self) -> str:
        tag = "" if self.severity == "error" else f" [{self.severity}]"
        return (f"{self.path}:{self.line}:{self.col}: {self.rule}{tag} "
                f"{self.message}\n    {self.snippet}")


class FileContext:
    """One parsed source file handed to every rule."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = None  # ast.Module, set by lint_file
        self.package = None  # rules.common.PackageIndex, set by lint_paths

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0) + 1
        return Finding(rule=rule, path=self.rel, line=line, col=col,
                       message=message, snippet=self.snippet(line))


# ---------------------------------------------------------------- suppression

def _inline_disabled(ctx: FileContext, f: Finding) -> bool:
    if not (1 <= f.line <= len(ctx.lines)):
        return False
    m = _PRAGMA.search(ctx.lines[f.line - 1])
    if not m:
        return False
    ids = {s.strip().upper() for s in m.group(1).split(",")}
    return "ALL" in ids or f.rule in ids


def _file_disabled_rules(ctx: FileContext) -> set:
    out = set()
    for line in ctx.lines:
        m = _PRAGMA_FILE.search(line)
        if m:
            out |= {s.strip().upper() for s in m.group(1).split(",")}
    return out


class Baseline:
    """Committed fingerprints of audited pre-existing findings.

    A finding is suppressed when an unconsumed (file, rule, snippet) entry
    matches — line numbers are deliberately NOT part of the fingerprint so
    unrelated edits above a finding don't invalidate the baseline."""

    def __init__(self, entries: Counter = None):
        self.entries = Counter(entries or ())
        self._unused = Counter(self.entries)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path) as fh:
            data = json.load(fh)
        c = Counter()
        for e in data.get("findings", []):
            c[(e["file"], e["rule"], e["snippet"])] += int(e.get("count", 1))
        return cls(c)

    @classmethod
    def from_findings(cls, findings: Iterable[Finding]) -> "Baseline":
        c = Counter((f.path, f.rule, f.snippet) for f in findings)
        return cls(c)

    def suppresses(self, f: Finding) -> bool:
        key = (f.path, f.rule, f.snippet)
        if self._unused.get(key, 0) > 0:
            self._unused[key] -= 1
            return True
        return False

    def dump(self, path: str) -> None:
        findings = [{"file": k[0], "rule": k[1], "snippet": k[2], "count": n}
                    for k, n in sorted(self.entries.items())]
        with open(path, "w") as fh:
            json.dump({"version": 1, "findings": findings}, fh, indent=1)
            fh.write("\n")


# ------------------------------------------------------------------- running

def _iter_py_files(paths: Iterable[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git",
                                              ".jax_cache", ".bench_cache"))
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield os.path.join(root, name)


def _parse_source(path: str, rel: str, source: str) -> FileContext:
    import ast
    ctx = FileContext(path, rel, source)
    ctx.tree = ast.parse(source, filename=path)
    return ctx


def _check_ctx(ctx: FileContext, rules) -> List[Finding]:
    """Run ``rules`` over one parsed file, applying pragma suppression."""
    file_off = _file_disabled_rules(ctx)
    findings = []
    for rule in rules:
        if rule.rule_id in file_off or "ALL" in file_off:
            continue
        for f in rule.check(ctx):
            if not _inline_disabled(ctx, f):
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_file(path: str, rel: str = None, rules=None
              ) -> Tuple[List[Finding], Optional[str]]:
    """Lint one file standalone (same-file reachability semantics).
    Returns (findings, parse_error)."""
    from .rules import active_rules

    rules = rules if rules is not None else active_rules()
    rel = rel if rel is not None else os.path.relpath(path)
    try:
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
        ctx = _parse_source(path, rel, source)
    except (OSError, SyntaxError, ValueError) as e:
        return [], f"{rel}: cannot parse: {e}"
    return _check_ctx(ctx, rules), None


def lint_paths(paths: Iterable[str], rules=None, cache=None
               ) -> Tuple[List[Finding], List[str]]:
    """Lint a file set as one package: every file is parsed first, a
    whole-package call graph (``rules.common.PackageIndex``) is built and
    attached as ``ctx.package``, then rules run — so R007/R009/R012 see
    cross-module reachability. ``cache`` (a ``lint_cache.LintCache``) skips
    re-parsing when content hashes are unchanged."""
    from .rules import active_rules
    from .rules.common import PackageIndex

    rules = rules if rules is not None else active_rules()
    sources, errors = [], []
    for path in _iter_py_files(paths):
        rel = os.path.relpath(path).replace(os.sep, "/")
        try:
            with open(path, "r", encoding="utf-8") as fh:
                sources.append((path, rel, fh.read()))
        except OSError as e:
            errors.append(f"{rel}: cannot parse: {e}")

    if cache is not None and not errors:
        hit = cache.replay(sources, [r.rule_id for r in rules])
        if hit is not None:
            return hit, errors

    ctxs = []
    for path, rel, source in sources:
        try:
            ctxs.append(_parse_source(path, rel, source))
        except (SyntaxError, ValueError) as e:
            errors.append(f"{rel}: cannot parse: {e}")

    index = PackageIndex.build([(c.path, c.rel, c.tree) for c in ctxs])
    local_rules = [r for r in rules
                   if not getattr(r, "cross_module", False)]
    cross_rules = [r for r in rules if getattr(r, "cross_module", False)]

    findings: List[Finding] = []
    per_file = {}
    for ctx in ctxs:
        ctx.package = index
        if cache is not None:
            cached_local = cache.cached_local(
                ctx.rel, ctx.source, [r.rule_id for r in rules])
            local = cached_local if cached_local is not None \
                else _check_ctx(ctx, local_rules)
        else:
            local = _check_ctx(ctx, local_rules)
        cross = _check_ctx(ctx, cross_rules)
        per_file[ctx.rel] = (ctx.source, local, cross)
        findings.extend(local)
        findings.extend(cross)

    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    if cache is not None and not errors:
        cache.store(sources, [r.rule_id for r in rules], per_file)
    return findings, errors


# ----------------------------------------------------------------------- CLI

def _resolve_baseline(arg: Optional[str], no_baseline: bool) -> Optional[str]:
    if no_baseline:
        return None
    if arg:
        return arg
    return DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None


def stale_baseline_entries(baseline: "Baseline",
                           linted_rels) -> List[Tuple[tuple, int]]:
    """Baseline entries that matched nothing this run and whose file was
    either linted (so the finding demonstrably no longer exists) or is gone
    from disk. Entries for files outside a subset-path run are left alone —
    a `tpu-lint some/dir` invocation can't prove anything about the rest of
    the tree."""
    linted = set(linted_rels)
    stale = []
    for key, remaining in sorted(baseline._unused.items()):
        if remaining <= 0:
            continue
        rel = key[0]
        if rel in linted or not os.path.exists(rel):
            stale.append((key, remaining))
    return stale


def main(argv: Optional[List[str]] = None) -> int:
    from .rules import active_rules

    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.analysis",
        description="tpu-lint: JAX/TPU hygiene analyzer — AST tier (rules "
                    "R001-R013) and trace tier (--trace: jaxpr/HLO "
                    "contracts T001-...)")
    ap.add_argument("paths", nargs="*", default=["lightgbm_tpu"],
                    help="files or directories to lint")
    ap.add_argument("--trace", action="store_true",
                    help="run the trace-contract tier (jaxpr/HLO program "
                         "contracts) instead of the AST tier")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help=f"suppressions baseline (default: {DEFAULT_BASELINE} "
                         "in the current directory, when present; the trace "
                         "tier defaults to trace_lint_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline file")
    ap.add_argument("--write-baseline", nargs="?", const=DEFAULT_BASELINE,
                    default=None, metavar="FILE",
                    help="write current findings as the new baseline and exit 0")
    ap.add_argument("--update-baseline", action="store_true",
                    help="regenerate the default baseline file in place "
                         "(tpu_lint_baseline.json, or the trace baseline "
                         "under --trace) and exit 0")
    ap.add_argument("--select", default=None, metavar="R001,R004",
                    help="run only these rule ids (or contract ids under "
                         "--trace)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--no-cache", action="store_true",
                    help="disable the incremental AST cache "
                         "(.tpu_lint_cache.json)")
    ap.add_argument("--cache-file", default=None, metavar="FILE",
                    help="incremental cache location (default: "
                         ".tpu_lint_cache.json in the current directory)")
    ap.add_argument("--load", action="append", default=[], metavar="PYFILE",
                    help="(trace tier) exec extra contract-registration "
                         "files before running — used to plant fixture "
                         "violations in tests")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.trace:
        from .trace_lint import run_trace
        return run_trace(args)

    rules = active_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.rule_id}  {r.summary}")
        return 0
    if args.select:
        wanted = {s.strip().upper() for s in args.select.split(",")}
        unknown = wanted - {r.rule_id for r in rules}
        if unknown:
            print(f"unknown rule id(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.rule_id in wanted]

    cache = None
    if not args.no_cache:
        from .lint_cache import LintCache, DEFAULT_CACHE
        cache = LintCache(args.cache_file or DEFAULT_CACHE)

    findings, errors = lint_paths(args.paths, rules=rules, cache=cache)
    for err in errors:
        print(f"tpu-lint: {err}", file=sys.stderr)

    if args.update_baseline:
        args.write_baseline = args.baseline or DEFAULT_BASELINE
    if args.write_baseline:
        Baseline.from_findings(findings).dump(args.write_baseline)
        print(f"tpu-lint: wrote {len(findings)} finding(s) to "
              f"{args.write_baseline}")
        return 0

    linted_rels = {os.path.relpath(p).replace(os.sep, "/")
                   for p in _iter_py_files(args.paths)}
    baseline_path = _resolve_baseline(args.baseline, args.no_baseline)
    stale = []
    if baseline_path:
        try:
            baseline = Baseline.load(baseline_path)
        except (OSError, ValueError, KeyError) as e:
            print(f"tpu-lint: cannot load baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
        findings = [f for f in findings if not baseline.suppresses(f)]
        stale = stale_baseline_entries(baseline, linted_rels)

    if args.format == "json":
        print(json.dumps(
            {"findings": [asdict(f) for f in findings],
             "errors": errors,
             "stale_baseline": [
                 {"file": k[0], "rule": k[1], "snippet": k[2], "count": n}
                 for k, n in stale]}, indent=1))
    elif args.format == "sarif":
        from .sarif import render
        print(render(findings, "tpu-lint", rules=rules, errors=errors))
    else:
        for f in findings:
            print(f.format())
        for (frel, rule, snippet), n in stale:
            print(f"{frel}: stale baseline entry for {rule} "
                  f"(x{n}) no longer matches any finding: {snippet!r} — "
                  f"remove it or run --update-baseline")
        n = len(findings)
        suffix = f" (baseline: {baseline_path})" if baseline_path else ""
        print(f"tpu-lint: {n} finding(s){suffix}"
              + (f", {len(stale)} stale baseline entrie(s)" if stale else ""))
    if errors:
        return 2
    return 1 if findings or stale else 0
