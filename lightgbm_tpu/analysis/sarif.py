"""Minimal SARIF 2.1.0 emitter shared by the AST and trace lint tiers.

Emits one run with the findings as results; `level` maps severity
("error" -> error, "warn" -> warning). Trace-tier findings carry pseudo
URIs (``trace://entry@shape_class``) — SARIF viewers render them as
opaque locations, which is exactly right for a program-level contract.
"""
from __future__ import annotations

import json
from typing import Iterable, List

_LEVEL = {"error": "error", "warn": "warning"}


def render(findings: Iterable, tool_name: str, rules=None,
           errors: List[str] = ()) -> str:
    rule_meta = []
    seen = set()
    for r in rules or ():
        rid = getattr(r, "rule_id", None) or getattr(r, "id", None)
        if rid and rid not in seen:
            seen.add(rid)
            rule_meta.append({
                "id": rid,
                "shortDescription": {
                    "text": getattr(r, "summary", "")
                            or getattr(r, "title", "")},
            })
    results = []
    for f in findings:
        results.append({
            "ruleId": f.rule,
            "level": _LEVEL.get(getattr(f, "severity", "error"), "error"),
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(1, f.line),
                               "startColumn": max(1, f.col)},
                },
            }],
            "partialFingerprints": {
                "tpuLint/v1": f"{f.path}|{f.rule}|{f.snippet}",
            },
        })
    doc = {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {"name": tool_name,
                                "informationUri":
                                    "docs/Static-Analysis.md",
                                "rules": rule_meta}},
            "results": results,
            "invocations": [{
                "executionSuccessful": not errors,
                "toolExecutionNotifications": [
                    {"level": "error", "message": {"text": e}}
                    for e in errors],
            }],
        }],
    }
    return json.dumps(doc, indent=1)
