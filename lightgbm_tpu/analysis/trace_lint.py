"""Trace tier of tpu-lint: jaxpr/HLO contract checking.

``python -m lightgbm_tpu.analysis --trace`` builds the traced program for
every (entry, shape_class) cell a contract targets — against the SHIPPED
callables registered by the product modules' ``@trace_entry`` hooks — and
evaluates the declarative predicates in ``contracts/``: forbidden
primitives, required collectives cross-checked against
``collective_bytes()``, dtype discipline, donation effectiveness in the
compiled HLO, host transfers inside device loop bodies, primitive counts.

Findings use pseudo-paths ``trace://<entry>@<shape_class>`` and the check
kind token as the snippet, so the AST tier's baseline machinery
(fingerprints, ``--update-baseline``, stale-entry detection) applies
unchanged; the trace baseline lives in ``trace_lint_baseline.json`` and
ships EMPTY — the tree's own programs satisfy every contract.

Unlike the AST tier this imports jax; it pins the hermetic 8-device CPU
backend first so the data-parallel shape classes trace the same
collectives the test harness sees.
"""
from __future__ import annotations

import json
import os
import runpy
import sys
from dataclasses import asdict
from typing import List

TRACE_BASELINE = "trace_lint_baseline.json"


def _load_fixture(path: str) -> None:
    """Exec a contract-registration file (tests plant violating contracts
    and program builders through these)."""
    runpy.run_path(path, run_name=f"tpu_lint_fixture:{path}")


def run_trace(args) -> int:
    from ..utils.hermetic import force_cpu_backend
    force_cpu_backend(device_count=8)
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(os.getcwd(), ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except (AttributeError, ValueError):
        # older jax without the persistent-cache options: slower, not wrong
        pass

    from . import contracts as reg
    from .contracts import entries  # noqa: F401  (registers T001-T010)
    from .tpu_lint import Baseline, Finding, stale_baseline_entries

    for fixture in args.load:
        _load_fixture(fixture)

    contract_ids = sorted(reg.CONTRACTS)
    if args.list_rules:
        for cid in contract_ids:
            print(f"{cid}  {reg.CONTRACTS[cid].title}")
        return 0
    if args.select:
        wanted = {s.strip().upper() for s in args.select.split(",")}
        unknown = wanted - set(contract_ids)
        if unknown:
            print(f"unknown contract id(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        contract_ids = [c for c in contract_ids if c in wanted]

    findings: List[Finding] = []
    errors: List[str] = []
    evaluated = set()
    for cid in contract_ids:
        c = reg.CONTRACTS[cid]
        for t in c.targets:
            cell = f"trace://{c.entry}@{t.shape_class}"
            try:
                program = reg.build_program(c.entry, t.shape_class)
            except Exception as e:                    # builder/trace failure
                errors.append(f"{cell}: cannot build program for {cid}: "
                              f"{type(e).__name__}: {e}")
                continue
            evaluated.add(cell)
            for fingerprint, message in reg.evaluate(c, t, program):
                findings.append(Finding(
                    rule=cid, path=cell, line=1, col=1, message=message,
                    snippet=fingerprint, severity=c.severity))
    findings.sort(key=lambda f: (f.rule, f.path, f.snippet))

    write_baseline = args.write_baseline
    if args.update_baseline:
        write_baseline = args.baseline or TRACE_BASELINE
    if write_baseline:
        Baseline.from_findings(findings).dump(write_baseline)
        print(f"tpu-lint --trace: wrote {len(findings)} finding(s) to "
              f"{write_baseline}")
        return 0

    baseline_path = None
    if not args.no_baseline:
        baseline_path = args.baseline or (
            TRACE_BASELINE if os.path.exists(TRACE_BASELINE) else None)
    stale = []
    if baseline_path:
        try:
            baseline = Baseline.load(baseline_path)
        except (OSError, ValueError, KeyError) as e:
            print(f"tpu-lint: cannot load baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
        findings = [f for f in findings if not baseline.suppresses(f)]
        stale = stale_baseline_entries(baseline, evaluated)

    gating = [f for f in findings if f.severity == "error"]
    if args.format == "json":
        print(json.dumps(
            {"findings": [asdict(f) for f in findings],
             "errors": errors,
             "stale_baseline": [
                 {"file": k[0], "rule": k[1], "snippet": k[2], "count": n}
                 for k, n in stale]}, indent=1))
    elif args.format == "sarif":
        from .sarif import render
        rules = [reg.CONTRACTS[c] for c in sorted(reg.CONTRACTS)]
        print(render(findings, "tpu-lint-trace", rules=rules, errors=errors))
    else:
        for f in findings:
            print(f.format())
        for (cell, cid, snippet), n in stale:
            print(f"{cell}: stale baseline entry for {cid} (x{n}) no "
                  f"longer matches any finding: {snippet!r} — remove it "
                  f"or run --trace --update-baseline")
        suffix = f" (baseline: {baseline_path})" if baseline_path else ""
        print(f"tpu-lint --trace: {len(reg.CONTRACTS)} contract(s), "
              f"{len(evaluated)} cell(s), {len(findings)} finding(s)"
              f"{suffix}"
              + (f", {len(stale)} stale baseline entrie(s)" if stale else ""))
    for err in errors:
        print(f"tpu-lint: {err}", file=sys.stderr)
    if errors:
        return 2
    return 1 if gating or stale else 0
