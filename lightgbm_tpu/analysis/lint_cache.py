"""Incremental cache for the tpu-lint AST tier (`.tpu_lint_cache.json`).

The AST pass runs inside tier-1 verify on every invocation; most runs see
an unchanged tree. The cache keys each file by a sha256 of its content and
the whole run by a *package fingerprint* — a hash over the sorted
(relpath, content-hash) pairs — because the reachability rules
(R007/R009/R012, ``cross_module = True``) produce findings that depend on
OTHER files' contents:

- package fingerprint unchanged  -> every finding replays from the cache
  with **zero** ``ast.parse`` calls (the common verify-loop case);
- fingerprint changed            -> all files are parsed (the call graph
  needs every tree anyway), but per-file *local*-rule findings replay for
  files whose own hash is unchanged; cross-module rules re-run everywhere.

The cache also records the active rule-id list — a ``--select`` run neither
reads nor poisons a full-run cache. Findings are stored post-suppression
(pragmas live in file content, so the hash covers them). Parse errors are
never cached. The file is git-ignored; delete it any time.
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional, Tuple

DEFAULT_CACHE = ".tpu_lint_cache.json"
_SCHEMA = 1


def _sha(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8", "surrogatepass")).hexdigest()


def _fingerprint(hashes: Dict[str, str]) -> str:
    blob = "\n".join(f"{rel}\0{h}" for rel, h in sorted(hashes.items()))
    return _sha(blob)


class LintCache:
    def __init__(self, path: str = DEFAULT_CACHE):
        self.path = path
        self.data = {"schema": _SCHEMA, "rules": [], "fingerprint": "",
                     "files": {}}
        self._hashes: Dict[str, str] = {}
        try:
            with open(path) as fh:
                loaded = json.load(fh)
            if loaded.get("schema") == _SCHEMA:
                self.data = loaded
        except (OSError, ValueError):
            pass

    # ------------------------------------------------------------- queries

    def _hash_sources(self, sources: List[Tuple[str, str, str]]
                      ) -> Dict[str, str]:
        self._hashes = {rel: _sha(src) for _, rel, src in sources}
        return self._hashes

    def replay(self, sources: List[Tuple[str, str, str]],
               rule_ids: List[str]) -> Optional[list]:
        """All findings for an unchanged package, or None on any miss."""
        hashes = self._hash_sources(sources)
        if self.data.get("rules") != list(rule_ids):
            return None
        if self.data.get("fingerprint") != _fingerprint(hashes):
            return None
        from .tpu_lint import Finding
        out = []
        for rel in sorted(hashes):
            entry = self.data["files"].get(rel)
            if entry is None:
                return None
            for d in entry.get("local", []) + entry.get("cross", []):
                out.append(Finding(**d))
        out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        return out

    def cached_local(self, rel: str, source: str,
                     rule_ids: Optional[List[str]] = None) -> Optional[list]:
        """Local-rule findings for one unchanged file, else None."""
        if rule_ids is not None and self.data.get("rules") != list(rule_ids):
            return None
        entry = self.data["files"].get(rel)
        if entry is None:
            return None
        h = self._hashes.get(rel) or _sha(source)
        if entry.get("hash") != h:
            return None
        from .tpu_lint import Finding
        return [Finding(**d) for d in entry.get("local", [])]

    # -------------------------------------------------------------- update

    def store(self, sources: List[Tuple[str, str, str]],
              rule_ids: List[str],
              per_file: Dict[str, tuple]) -> None:
        """Record this run: per_file maps rel -> (source, local, cross)."""
        from dataclasses import asdict
        hashes = self._hashes or self._hash_sources(sources)
        files = {}
        for rel, (source, local, cross) in per_file.items():
            files[rel] = {"hash": hashes.get(rel, _sha(source)),
                          "local": [asdict(f) for f in local],
                          "cross": [asdict(f) for f in cross]}
        self.data = {"schema": _SCHEMA, "rules": list(rule_ids),
                     "fingerprint": _fingerprint(
                         {r: files[r]["hash"] for r in files}),
                     "files": files}
        try:
            tmp = self.path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(self.data, fh)
            os.replace(tmp, self.path)
        except OSError:
            pass  # read-only checkout: caching is best-effort
