"""Static + runtime hygiene tooling for the TPU GBDT codebase.

Two halves (docs/Static-Analysis.md):

- ``tpu_lint`` — an AST analyzer enforcing JAX/TPU hygiene rules R001-R013
  (traced control flow, host syncs in hot paths, dtype-promotion hazards,
  Pallas tiling contracts, bad static_argnums, import-time jnp execution).
  CLI: ``python -m lightgbm_tpu.analysis lightgbm_tpu/``. Pure stdlib — it
  never imports jax, so it runs anywhere in milliseconds.
- ``guards`` — a runtime context manager that counts jit cache misses per
  entrypoint and implicit host syncs, and fails when a steady-state
  training loop recompiles after warm-up (bench.py --smoke, tests).
"""
from .guards import GuardViolation, RecompileGuard, recompile_guard  # noqa: F401
from .tpu_lint import Finding, lint_paths, main  # noqa: F401
