"""ServingEngine: AOT-compiled, bucket-padded forest inference.

The production inference path (ROADMAP item 4, docs/Serving.md). A model
loaded from ANY interchange format — protobuf (``io/model_proto.py``, the
reference fork's headline feature), LightGBM text, JSON dump, or an
in-memory ``Booster`` — is stacked ONCE into the rank-encoded
``StackedForest`` arrays (``ops/predict.py``), placed on device once, and
walked through a per-engine jitted ``forest_walk_leaves`` whose input
shapes are drawn from a fixed **batch-size bucket ladder**: every request
is padded up to the smallest bucket that holds it, so million-user traffic
shapes — many small concurrent batches, never one big one — hit a finite,
warmed set of executables and NEVER recompile in steady state
(``bench.py --serve`` pins this under a RecompileGuard). ``warmup()``
compiles every bucket ahead of serving; with the persistent XLA compile
cache (``LGBM_TPU_COMPILE_CACHE_DIR``) a restarted server replays the
compiles from disk.

Numerics contract: traversal is integer-exact on device (rank compares);
leaf-value accumulation happens on the HOST in float64, sequentially in
tree order — served predictions are **bit-identical** to the training
booster's host ``predict()`` (pinned in tests/test_serving.py, including
the protobuf round trip). The one device->host sync per dispatch — the
result fetch — is the contract; tpu-lint R011 keeps any other host sync
out of this package (the sync below is baseline-exempt).

Resilience (docs/Serving.md "Resilience", serving/resilience.py): the
model lives in an immutable ``_ModelState`` snapshot read ONCE per
request, so a hot ``reload()`` — AOT-compile the candidate off to the
side, verify it bit-identical against its own booster on a held sample,
swap atomically, roll back on any failure — never mixes versions inside
a request. Device-dispatch failures land on a ``CircuitBreaker``: after
``serve_breaker_failures`` failures in ``serve_breaker_window_s`` the
engine degrades to the host predictor (correct answers, host throughput)
while a daemon probe re-warms the device path; ``health()`` reports
``ready|degraded|down`` for load-balancer integration.

Categorical forests cannot take the rank-encoded walk and serve through
the host predictor instead (one-time warning from
``ops/predict.forest_predict_raw`` — same engine API, host throughput).

Observability: every request lands in the process registry —
``serve.requests``/``serve.rows`` counters, ``serve.batch_fill_frac``
histogram, ``serve.latency_ms``/``serve.dispatch_ms`` quantile summaries
whose p50/p99 surface in ``observability.snapshot()`` — plus the
resilience series: ``serve.host_fallback``/``serve.breaker_trips``/
``serve.breaker_recoveries``/``serve.reloads``/``serve.reload_rollbacks``
counters and the ``serve.health``/``serve.model_version`` gauges.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import observability as obs
from ..config import Config
from ..utils.log import Log
from .resilience import (CircuitBreaker, DeadlineExceededError,
                         DeviceDispatchError, ReloadError, ServingClosedError)

_HEALTH_CODE = {"ready": 0, "degraded": 1, "down": 2}


def bucket_ladder(config) -> List[int]:
    """Resolve the batch-size bucket ladder from config.

    ``serve_buckets`` (comma list, strictly ascending) wins; empty = the
    powers-of-two ladder 1, 2, 4, ... up to ``serve_max_batch_rows`` —
    dense enough that padding never exceeds 2x (the batch_fill_frac floor
    is 0.5)."""
    if config.serve_buckets:
        out = [int(v) for v in str(config.serve_buckets).split(",") if v]
        return out
    out, b = [], 1
    while b < config.serve_max_batch_rows:
        out.append(b)
        b *= 2
    out.append(int(config.serve_max_batch_rows))
    return out


class _ModelState:
    """One immutable serving model: booster + stacked forests + device
    arrays + the per-state jitted walk. Requests snapshot the engine's
    current state ONCE and use only it, so an atomic state swap
    (``reload``) can never mix two model versions inside one request."""

    __slots__ = ("booster", "config", "trees", "num_class_models",
                 "num_iteration", "num_features", "forests",
                 "has_categorical", "dev", "walk", "version", "warmed")

    def __init__(self, booster, num_iteration: Optional[int], version: int):
        import jax
        import jax.numpy as jnp

        from ..ops.predict import StackedForest, forest_walk_leaves

        self.booster = booster
        self.config = booster.config
        K = max(booster.num_model_per_iteration, 1)
        self.num_class_models = K
        if num_iteration is None or num_iteration <= 0:
            num_iteration = booster.best_iteration \
                if booster.best_iteration > 0 else len(booster.trees) // K
        self.num_iteration = num_iteration
        self.trees = booster.trees[: num_iteration * K]
        self.num_features = booster.num_total_features
        self.forests = [StackedForest(self.trees[k::K], self.num_features)
                        for k in range(K)]
        self.has_categorical = any(f.has_categorical for f in self.forests)
        self.dev: List[Tuple] = []
        if not self.has_categorical:
            # device residency: the stacked arrays upload ONCE here and are
            # reused by every dispatch (forest_predict_raw re-uploads per
            # call — fine for a one-shot batch, wrong for a serving loop)
            for f in self.forests:
                self.dev.append(tuple(jnp.asarray(a) for a in (
                    f.split_feature, f.thr_rank, f.decision, f.left, f.right,
                    f.root_is_leaf, f.zero_rank)))
            # per-state jit: the cache holds exactly this model's
            # (class, bucket) signatures, so a RecompileGuard registered on
            # it pins the zero-recompile serving contract
            self.walk = jax.jit(forest_walk_leaves)
        else:
            self.walk = None
        self.version = version
        self.warmed = False


class ServingEngine:
    """Load-once, compile-ahead, dispatch-forever forest inference."""

    def __init__(self, model, params: Optional[Dict] = None,
                 num_iteration: Optional[int] = None, warmup: bool = True):
        from ..utils.cache import maybe_enable_compile_cache

        maybe_enable_compile_cache()
        booster = self._load_booster(model, params)
        self.config = booster.config
        self.buckets = sorted(bucket_ladder(self.config))
        self.max_bucket = self.buckets[-1]
        self._model = _ModelState(booster, num_iteration, version=1)
        self._reload_lock = threading.Lock()
        self._closed = False
        # fault-injection hook (serving/resilience.py DispatchChaos):
        # invoked at the top of every device dispatch when installed
        self.chaos = None
        self._breaker = CircuitBreaker(
            failures=self.config.serve_breaker_failures,
            window_s=self.config.serve_breaker_window_s)
        self._probe_stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        self._probe_lock = threading.Lock()   # owns _probe_running
        self._probe_running = False
        reg = obs.get_registry()
        reg.gauge("serve.buckets").set(len(self.buckets))
        reg.gauge("serve.max_batch_rows").set(self.max_bucket)
        reg.gauge("serve.num_trees").set(len(self._model.trees))
        reg.gauge("serve.model_version").set(self._model.version)
        reg.gauge("serve.health").set(_HEALTH_CODE["ready"])
        if warmup:
            self.warmup()

    @staticmethod
    def _load_booster(model, params: Optional[Dict]):
        from ..basic import Booster
        if isinstance(model, Booster):
            booster = model
            if params:
                booster.config = Config.from_params(
                    dict(booster.params, **params))
        else:
            path = str(model)
            # serve_* knobs ride in as Booster params; the loader's
            # apply_model_header merges the file's metadata (objective,
            # sigmoid, num_class) on top and rebuilds the Config once
            booster = Booster(params=dict(params or {}))
            # one format dispatcher: .proto / .json / text all resolve
            # inside load_model_file
            from ..io.model_text import load_model_file
            load_model_file(booster, path)
        booster._ensure_finalized()
        return booster

    # -------------------------------------------------- model-state access

    def model_snapshot(self) -> _ModelState:
        """The current model state, read once — callers that span several
        internal calls (the micro-batcher worker, verification) hold the
        SAME snapshot across all of them so a concurrent ``reload`` can
        never mix versions inside one request."""
        return self._model

    @property
    def booster(self):
        return self._model.booster

    @property
    def num_class_models(self) -> int:
        return self._model.num_class_models

    @property
    def num_iteration(self) -> int:
        return self._model.num_iteration

    @property
    def num_features(self) -> int:
        return self._model.num_features

    @property
    def has_categorical(self) -> bool:
        return self._model.has_categorical

    @property
    def model_version(self) -> int:
        return self._model.version

    @property
    def _trees(self):
        return self._model.trees

    @property
    def _forests(self):
        return self._model.forests

    # ------------------------------------------------------------- compile

    def jit_entrypoints(self):
        """(name, jitted callable) pairs for RecompileGuard registration
        — the CURRENT model's walk (re-register after a reload)."""
        m = self._model
        return [] if m.walk is None else [("serve.forest_walk", m.walk)]

    def warmup(self) -> int:
        """AOT-compile the forest walk for every (class, bucket) signature
        so the first real request — and every one after — dispatches a
        warm executable. Returns the number of signatures compiled. With
        the persistent compile cache enabled this replays from disk on
        restart. Captures a cost report per bucket when cost analysis is
        on (``cost.serve.forest_walk.b<N>.*`` gauges)."""
        return self._warm_state(self._model)

    def _warm_state(self, m: _ModelState) -> int:
        if m.walk is None or m.warmed:
            return 0
        from ..observability import costs as obs_costs
        n = 0
        with obs.span("serve.warmup", buckets=len(self.buckets),
                      model_version=m.version):
            for k, f in enumerate(m.forests):
                for B in self.buckets:
                    codes = np.zeros((B, m.num_features), np.int32)
                    mask = np.zeros((B, m.num_features), bool)
                    args = (*m.dev[k], codes, mask, mask)
                    if obs_costs.enabled():
                        obs_costs.capture_jit(
                            f"serve.forest_walk.b{B}", m.walk, args,
                            dims=dict(rows=B, trees=f.num_trees),
                            fingerprint=(k, B, m.num_features,
                                         f.num_trees, int(f.max_leaves)))
                    # the call compiles synchronously; the async result is
                    # deliberately dropped — warmup needs the executable,
                    # not the value
                    m.walk(*args)
                    n += 1
                    obs.inc("serve.bucket_compiles")
        m.warmed = True
        return n

    # ------------------------------------------------------------ dispatch

    def bucket_for(self, n: int) -> int:
        """Smallest ladder bucket holding ``n`` rows (requests beyond the
        top bucket are chunked by the caller)."""
        for b in self.buckets:
            if n <= b:
                return b
        return self.max_bucket

    def _dispatch(self, m: _ModelState, k: int, codes: np.ndarray,
                  is_nan: np.ndarray, is_zero: np.ndarray,
                  record: bool = True) -> np.ndarray:
        """One device dispatch of <= max_bucket rows for class ``k``,
        padded to the bucket: returns leaf indices [n, T]. A failure of
        the walk itself surfaces as ``DeviceDispatchError`` after landing
        on the circuit breaker (``record=False`` — probe / reload
        verification — keeps injected or candidate failures off the live
        breaker's books)."""
        n = codes.shape[0]
        B = self.bucket_for(n)
        if n < B:
            pad = B - n
            codes = np.concatenate(
                [codes, np.zeros((pad, codes.shape[1]), codes.dtype)])
            is_nan = np.concatenate(
                [is_nan, np.zeros((pad, is_nan.shape[1]), bool)])
            is_zero = np.concatenate(
                [is_zero, np.zeros((pad, is_zero.shape[1]), bool)])
        t0 = obs.clock()
        reg = obs.get_registry()
        try:
            if self.chaos is not None:
                self.chaos()
            # the contractual result sync: ONE device->host fetch per
            # dispatch (tpu-lint R011 baseline-exempt; everything else in
            # serving/ stays sync-free)
            leaves = np.asarray(m.walk(*m.dev[k], codes, is_nan, is_zero))
        except Exception as e:                                # noqa: BLE001
            if record:
                self._on_dispatch_failure(e)
            raise DeviceDispatchError(
                f"device forest walk failed for bucket {B}: "
                f"{type(e).__name__}: {e}") from e
        if record:
            self._breaker.record_success()
            reg.summary("serve.dispatch_ms").observe((obs.clock() - t0) * 1e3)
            reg.histogram("serve.batch_fill_frac").observe(n / B)
            reg.counter(f"serve.bucket.{B}").inc()
        return leaves[:n]

    # --------------------------------------------- degrade / probe / health

    def _on_dispatch_failure(self, err: BaseException) -> None:
        Log.warning("serve: device dispatch failed (%s: %s) — serving this "
                    "request via the host predictor",
                    type(err).__name__, err)
        if self._breaker.record_failure(err):
            Log.warning(
                "serve: circuit breaker OPEN after %d failure(s) in %.1fs — "
                "engine is DEGRADED (host predictor, bit-identical answers "
                "at host throughput) until the device probe succeeds",
                self._breaker.failures, self._breaker.window_s)
            obs.get_registry().gauge("serve.health").set(
                _HEALTH_CODE["degraded"])
            self._start_probe()

    def _start_probe(self) -> None:
        # _probe_running (not Thread.is_alive) gates the start: the probe
        # thread clears it under the same lock as its exit decision, so a
        # breaker re-trip can never observe a probe that has already
        # decided to die and skip starting a fresh one
        with self._probe_lock:
            if self._probe_running or self._closed:
                return
            self._probe_running = True
            self._probe_stop.clear()
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="lgbm-serve-probe", daemon=True)
            self._probe_thread.start()

    def _probe_loop(self) -> None:
        """Background device re-warm: while the breaker is open, try one
        real (smallest-bucket) dispatch every ``serve_probe_interval_s``;
        the first success closes the breaker and restores ``ready``."""
        interval = self.config.serve_probe_interval_s
        while True:
            stopped = self._probe_stop.wait(interval)
            if not stopped and not self._closed and self._breaker.is_open:
                try:
                    self._probe_once()
                except Exception as e:                        # noqa: BLE001
                    obs.inc("serve.probe_failures")
                    Log.debug("serve: device probe failed (%s: %s) — still "
                              "degraded", type(e).__name__, e)
                    continue
                self._breaker.reset()
                obs.get_registry().gauge("serve.health").set(
                    _HEALTH_CODE["ready"])
                Log.warning("serve: device probe succeeded — circuit "
                            "breaker closed, engine READY on the device "
                            "path again")
            # exit decision, atomic with _start_probe: a re-trip lands
            # either before this check (breaker open again -> keep
            # probing) or after _probe_running clears (-> fresh thread)
            with self._probe_lock:
                if stopped or self._closed or not self._breaker.is_open:
                    self._probe_running = False
                    return

    def _probe_once(self) -> None:
        m = self._model
        if m.walk is None:
            return
        B = self.buckets[0]
        codes = np.zeros((B, m.num_features), np.int32)
        mask = np.zeros((B, m.num_features), bool)
        self._dispatch(m, 0, codes, mask, mask, record=False)

    def health(self) -> str:
        """``ready`` | ``degraded`` | ``down`` — the load-balancer probe.
        ``degraded`` = the circuit breaker is open and requests serve
        via the host predictor (correct, slower); ``down`` = the engine
        was closed and admits nothing."""
        if self._closed:
            return "down"
        if self._breaker.is_open:
            return "degraded"
        return "ready"

    def close(self) -> None:
        """Stop the probe thread and refuse further requests
        (``health()`` -> ``down``). Idempotent."""
        # flags flip under _probe_lock so a concurrent _start_probe either
        # ran first (then t below is its thread and gets joined) or sees
        # _closed and refuses — it can never re-clear _probe_stop after us.
        # The join happens OUTSIDE the lock: the probe's exit decision
        # needs the same lock.
        with self._probe_lock:
            self._closed = True
            self._probe_stop.set()
            t = self._probe_thread
            self._probe_thread = None
        if t is not None:
            t.join(timeout=5.0)
        # after the join, no probe thread survives to overwrite this
        obs.get_registry().gauge("serve.health").set(_HEALTH_CODE["down"])

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ---------------------------------------------------------- hot reload

    def reload(self, model, params: Optional[Dict] = None,
               num_iteration: Optional[int] = None,
               verify_rows: int = 256) -> int:
        """Hot-swap the served model with verified rollback.

        The candidate is built and AOT-compiled OFF TO THE SIDE (the live
        model keeps serving), verified **bit-identical** against its own
        booster's host ``predict()`` on a held sample of ``verify_rows``
        rows (NaN/zero cells included), then swapped in atomically —
        requests hold a state snapshot, so in-flight batches finish on
        the old forest and every response matches exactly one model
        version. ANY failure (shape mismatch, compile error, verification
        mismatch) rolls back: the old model is still serving when the
        raised ``ReloadError`` reaches the caller. Returns the new
        model version. Counters: ``serve.reloads`` /
        ``serve.reload_rollbacks``."""
        if self._closed:
            raise ServingClosedError("reload() on a closed ServingEngine")
        with self._reload_lock:
            old = self._model
            try:
                booster = self._load_booster(model, params)
                cand = _ModelState(booster, num_iteration,
                                   version=old.version + 1)
                if cand.num_features != old.num_features:
                    raise ReloadError(
                        f"candidate expects {cand.num_features} features, "
                        f"live model serves {old.num_features} — a reload "
                        f"must stay request-compatible")
                if cand.num_class_models != old.num_class_models:
                    raise ReloadError(
                        f"candidate has {cand.num_class_models} class "
                        f"model(s), live model {old.num_class_models} — "
                        f"the response shape would change under callers")
                self._warm_state(cand)
                self._verify_state(cand, verify_rows)
            except Exception as e:
                obs.inc("serve.reload_rollbacks")
                Log.warning("serve: reload ROLLED BACK (still serving "
                            "model_version=%d): %s: %s",
                            old.version, type(e).__name__, e)
                if isinstance(e, ReloadError):
                    raise
                raise ReloadError(f"reload failed and rolled back: "
                                  f"{type(e).__name__}: {e}") from e
            # atomic swap: a plain attribute rebind — concurrent requests
            # already hold their snapshot and finish on the old forest
            self._model = cand
            obs.inc("serve.reloads")
            reg = obs.get_registry()
            reg.gauge("serve.model_version").set(cand.version)
            reg.gauge("serve.num_trees").set(len(cand.trees))
            Log.info("serve: hot reload -> model_version=%d (%d trees, "
                     "verified bit-identical on %d rows)",
                     cand.version, len(cand.trees), verify_rows)
            return cand.version

    def _verify_state(self, m: _ModelState, verify_rows: int) -> None:
        """Bit-identity gate: the candidate's DEVICE path (no fallback, no
        breaker accounting) must reproduce its own booster's host
        ``predict()`` exactly on a held sample with NaN and zero cells —
        the same contract ``bench.py --serve`` pins for the live path."""
        if verify_rows <= 0:
            return
        rng = np.random.RandomState(0x5EED)
        X = np.asarray(rng.randn(verify_rows, m.num_features) * 2.0,
                       np.float64)
        X[rng.rand(verify_rows, m.num_features) < 0.05] = np.nan
        X[rng.rand(verify_rows, m.num_features) < 0.05] = 0.0
        want = m.booster.predict(X)
        raw = self._predict_raw_for(m, X, allow_fallback=False, record=False)
        got = self._finish_for(m, raw, raw_score=False)
        if not np.array_equal(want, got, equal_nan=True):
            # both sides are host float64 numpy already (booster.predict /
            # _finish_for) — no materialization needed for the diagnostic
            diff = float(np.max(np.abs(np.nan_to_num(want)
                                       - np.nan_to_num(got))))
            raise ReloadError(
                f"candidate verification FAILED: device path differs from "
                f"its own Booster.predict on {verify_rows} held rows "
                f"(max abs diff {diff:g})")

    # ----------------------------------------------------------- prediction

    def _predict_host(self, m: _ModelState, X: np.ndarray,
                      record: bool = True, degraded: bool = False
                      ) -> np.ndarray:
        """Host predictor path: per-tree f64 accumulation in tree order —
        the categorical route and the circuit-breaker fallback (identical
        numbers to the device path by the bit-identity contract)."""
        K = m.num_class_models
        raw = np.zeros((K, X.shape[0]), np.float64)
        for i, t in enumerate(m.trees):
            raw[i % K] += t.predict(X)
        if record:
            obs.get_registry().counter("serve.rows").inc(X.shape[0])
            if degraded:
                obs.inc("serve.host_fallback")
        return raw

    def _predict_raw_for(self, m: _ModelState, X: np.ndarray,
                         deadline: Optional[float] = None,
                         allow_fallback: bool = True,
                         record: bool = True) -> np.ndarray:
        """Raw scores [K, N] f64 for a prepared f64 matrix — traversal on
        device (bucketed), leaf accumulation on host in f64 tree order
        (bit-identical to the host predictor). Degraded state or a
        device-dispatch failure reroutes the WHOLE request to the host
        predictor (same numbers); ``allow_fallback=False`` (verification)
        lets the failure surface instead."""
        N = X.shape[0]
        K = m.num_class_models
        if m.has_categorical or (allow_fallback and self._breaker.is_open):
            return self._predict_host(
                m, X, record=record, degraded=not m.has_categorical)
        raw = np.zeros((K, N), np.float64)
        try:
            for k, forest in enumerate(m.forests):
                if forest.num_trees == 0:
                    continue
                codes, is_nan, is_zero = forest.encode_rows(X)
                lv = None if forest.has_linear else forest.leaf_value64
                lo = 0
                while lo < N:
                    if deadline is not None and obs.clock() > deadline:
                        obs.inc("serve.deadline_exceeded")
                        raise DeadlineExceededError(
                            f"deadline passed after {lo} of {N} rows — "
                            f"dropping the dispatch")
                    n = min(N - lo, self.max_bucket)
                    leaves = self._dispatch(
                        m, k, codes[lo:lo + n], is_nan[lo:lo + n],
                        is_zero[lo:lo + n], record=record)
                    # sequential f64 accumulation in tree order — the exact
                    # operation order of Booster.predict's host loop.
                    # Linear-leaf forests route each tree's leaf indices
                    # through Tree.leaf_outputs (the ONE home of host
                    # linear evaluation): device traversal stays integer-
                    # exact, the dot product runs host f64, and served
                    # bits equal Booster.predict's
                    out = raw[k]
                    if forest.has_linear:
                        Xc = X[lo:lo + n]
                        for t, tr in enumerate(forest._trees):
                            out[lo:lo + n] += tr.leaf_outputs(
                                Xc, leaves[:, t])
                    else:
                        for t in range(forest.num_trees):
                            out[lo:lo + n] += lv[t, leaves[:, t]]
                    lo += n
        except DeviceDispatchError:
            if not allow_fallback:
                raise
            # graceful degradation: the device path failed mid-request;
            # the host predictor serves the same bits at host throughput
            return self._predict_host(m, X, record=record, degraded=True)
        if record:
            obs.get_registry().counter("serve.rows").inc(N)
        return raw

    def _finish_for(self, m: _ModelState, raw: np.ndarray,
                    raw_score: bool) -> np.ndarray:
        """Output transform — Booster.predict's tail, verbatim semantics."""
        K = m.num_class_models
        if m.config.boosting_normalized == "rf":
            raw = raw / max(len(m.trees) // K, 1)
        elif not raw_score:
            raw = m.booster._convert_output(raw)
        return raw[0] if K == 1 else raw.T

    # back-compat single-model entry points (hold one snapshot internally)
    def _predict_raw(self, X: np.ndarray) -> np.ndarray:
        return self._predict_raw_for(self._model, X)

    def _finish(self, raw: np.ndarray, raw_score: bool) -> np.ndarray:
        return self._finish_for(self._model, raw, raw_score)

    def predict(self, X, raw_score: bool = False,
                deadline_ms: Optional[float] = None) -> np.ndarray:
        """Serve one request: [N, F] (or a single row) -> predictions,
        bit-identical to ``Booster.predict`` on the same rows.
        ``deadline_ms`` (default ``serve_deadline_ms``; 0 = none) bounds
        the request — between chunk dispatches an expired deadline raises
        ``DeadlineExceededError`` instead of wasting further device
        time."""
        if self._closed:
            raise ServingClosedError("predict() on a closed ServingEngine")
        t0 = obs.clock()
        m = self._model
        dl = self.config.serve_deadline_ms if deadline_ms is None \
            else deadline_ms
        deadline = (t0 + dl / 1e3) if dl and dl > 0 else None
        X = self._as_matrix(X, m)
        out = self._finish_for(
            m, self._predict_raw_for(m, X, deadline=deadline), raw_score)
        reg = obs.get_registry()
        reg.counter("serve.requests").inc()
        reg.summary("serve.latency_ms").observe((obs.clock() - t0) * 1e3)
        return out

    def _as_matrix(self, X, m: Optional[_ModelState] = None) -> np.ndarray:
        # host input normalization (caller data, not a device value)
        m = m or self._model
        mat = np.asarray(X, np.float64)
        if mat.ndim == 1:
            mat = mat.reshape(1, -1)
        if mat.shape[1] != m.num_features:
            raise ValueError(
                f"request has {mat.shape[1]} features, model expects "
                f"{m.num_features}")
        return mat

    def describe(self) -> Dict:
        m = self._model
        return {"buckets": list(self.buckets),
                "num_trees": len(m.trees),
                "num_class_models": m.num_class_models,
                "num_features": m.num_features,
                "categorical_host_path": m.has_categorical,
                "warmed": m.warmed,
                "model_version": m.version,
                "health": self.health(),
                "breaker": self._breaker.state}
