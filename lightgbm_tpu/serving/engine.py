"""ServingEngine: AOT-compiled, bucket-padded forest inference.

The production inference path (ROADMAP item 4, docs/Serving.md). A model
loaded from ANY interchange format — protobuf (``io/model_proto.py``, the
reference fork's headline feature), LightGBM text, JSON dump, or an
in-memory ``Booster`` — is stacked ONCE into the rank-encoded
``StackedForest`` arrays (``ops/predict.py``), placed on device once, and
walked through a per-engine jitted ``forest_walk_leaves`` whose input
shapes are drawn from a fixed **batch-size bucket ladder**: every request
is padded up to the smallest bucket that holds it, so million-user traffic
shapes — many small concurrent batches, never one big one — hit a finite,
warmed set of executables and NEVER recompile in steady state
(``bench.py --serve`` pins this under a RecompileGuard). ``warmup()``
compiles every bucket ahead of serving; with the persistent XLA compile
cache (``LGBM_TPU_COMPILE_CACHE_DIR``) a restarted server replays the
compiles from disk.

Numerics contract: traversal is integer-exact on device (rank compares);
leaf-value accumulation happens on the HOST in float64, sequentially in
tree order — served predictions are **bit-identical** to the training
booster's host ``predict()`` (pinned in tests/test_serving.py, including
the protobuf round trip). The one device->host sync per dispatch — the
result fetch — is the contract; tpu-lint R011 keeps any other host sync
out of this package (the sync below is baseline-exempt).

Categorical forests cannot take the rank-encoded walk and serve through
the host predictor instead (one-time warning from
``ops/predict.forest_predict_raw`` — same engine API, host throughput).

Observability: every request lands in the process registry —
``serve.requests``/``serve.rows`` counters, ``serve.batch_fill_frac``
histogram, ``serve.latency_ms``/``serve.dispatch_ms`` quantile summaries
whose p50/p99 surface in ``observability.snapshot()`` — and warmup
captures a cost report per bucket when ``tpu_cost_analysis`` is on.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from .. import observability as obs
from ..config import Config
from ..utils.log import Log


def bucket_ladder(config) -> List[int]:
    """Resolve the batch-size bucket ladder from config.

    ``serve_buckets`` (comma list, strictly ascending) wins; empty = the
    powers-of-two ladder 1, 2, 4, ... up to ``serve_max_batch_rows`` —
    dense enough that padding never exceeds 2x (the batch_fill_frac floor
    is 0.5)."""
    if config.serve_buckets:
        out = [int(v) for v in str(config.serve_buckets).split(",") if v]
        return out
    out, b = [], 1
    while b < config.serve_max_batch_rows:
        out.append(b)
        b *= 2
    out.append(int(config.serve_max_batch_rows))
    return out


class ServingEngine:
    """Load-once, compile-ahead, dispatch-forever forest inference."""

    def __init__(self, model, params: Optional[Dict] = None,
                 num_iteration: Optional[int] = None, warmup: bool = True):
        import jax
        import jax.numpy as jnp

        from ..basic import Booster
        from ..ops.predict import StackedForest, forest_walk_leaves
        from ..utils.cache import maybe_enable_compile_cache

        maybe_enable_compile_cache()
        if isinstance(model, Booster):
            booster = model
            if params:
                booster.config = Config.from_params(
                    dict(booster.params, **params))
        else:
            path = str(model)
            # serve_* knobs ride in as Booster params; the loader's
            # apply_model_header merges the file's metadata (objective,
            # sigmoid, num_class) on top and rebuilds the Config once
            booster = Booster(params=dict(params or {}))
            # one format dispatcher: .proto / .json / text all resolve
            # inside load_model_file
            from ..io.model_text import load_model_file
            load_model_file(booster, path)
        booster._ensure_finalized()
        self.booster = booster
        self.config = booster.config
        K = max(booster.num_model_per_iteration, 1)
        self.num_class_models = K
        if num_iteration is None or num_iteration <= 0:
            num_iteration = booster.best_iteration \
                if booster.best_iteration > 0 else len(booster.trees) // K
        self.num_iteration = num_iteration
        self._trees = booster.trees[: num_iteration * K]
        self.num_features = booster.num_total_features

        self._forests = [StackedForest(self._trees[k::K], self.num_features)
                         for k in range(K)]
        self.has_categorical = any(f.has_categorical for f in self._forests)
        self.buckets = sorted(bucket_ladder(self.config))
        self.max_bucket = self.buckets[-1]
        self._dev: List[Tuple] = []
        if not self.has_categorical:
            # device residency: the stacked arrays upload ONCE here and are
            # reused by every dispatch (forest_predict_raw re-uploads per
            # call — fine for a one-shot batch, wrong for a serving loop)
            for f in self._forests:
                self._dev.append(tuple(jnp.asarray(a) for a in (
                    f.split_feature, f.thr_rank, f.decision, f.left, f.right,
                    f.root_is_leaf, f.zero_rank)))
            # per-engine jit: the cache holds exactly this engine's
            # (class, bucket) signatures, so a RecompileGuard registered on
            # it pins the zero-recompile serving contract
            self._walk = jax.jit(forest_walk_leaves)
        else:
            self._walk = None
        reg = obs.get_registry()
        reg.gauge("serve.buckets").set(len(self.buckets))
        reg.gauge("serve.max_batch_rows").set(self.max_bucket)
        reg.gauge("serve.num_trees").set(len(self._trees))
        self._warm = False
        if warmup:
            self.warmup()

    # ------------------------------------------------------------- compile

    def jit_entrypoints(self):
        """(name, jitted callable) pairs for RecompileGuard registration."""
        return [] if self._walk is None else [("serve.forest_walk",
                                               self._walk)]

    def warmup(self) -> int:
        """AOT-compile the forest walk for every (class, bucket) signature
        so the first real request — and every one after — dispatches a
        warm executable. Returns the number of signatures compiled. With
        the persistent compile cache enabled this replays from disk on
        restart. Captures a cost report per bucket when cost analysis is
        on (``cost.serve.forest_walk.b<N>.*`` gauges)."""
        if self._walk is None or self._warm:
            return 0
        from ..observability import costs as obs_costs
        n = 0
        with obs.span("serve.warmup", buckets=len(self.buckets)):
            for k, f in enumerate(self._forests):
                for B in self.buckets:
                    codes = np.zeros((B, self.num_features), np.int32)
                    mask = np.zeros((B, self.num_features), bool)
                    args = (*self._dev[k], codes, mask, mask)
                    if obs_costs.enabled():
                        obs_costs.capture_jit(
                            f"serve.forest_walk.b{B}", self._walk, args,
                            dims=dict(rows=B, trees=f.num_trees),
                            fingerprint=(k, B, self.num_features,
                                         f.num_trees, int(f.max_leaves)))
                    # the call compiles synchronously; the async result is
                    # deliberately dropped — warmup needs the executable,
                    # not the value
                    self._walk(*args)
                    n += 1
                    obs.inc("serve.bucket_compiles")
        self._warm = True
        return n

    # ------------------------------------------------------------ dispatch

    def bucket_for(self, n: int) -> int:
        """Smallest ladder bucket holding ``n`` rows (requests beyond the
        top bucket are chunked by the caller)."""
        for b in self.buckets:
            if n <= b:
                return b
        return self.max_bucket

    def _dispatch(self, k: int, codes: np.ndarray, is_nan: np.ndarray,
                  is_zero: np.ndarray) -> np.ndarray:
        """One device dispatch of <= max_bucket rows for class ``k``,
        padded to the bucket: returns leaf indices [n, T]."""
        n = codes.shape[0]
        B = self.bucket_for(n)
        if n < B:
            pad = B - n
            codes = np.concatenate(
                [codes, np.zeros((pad, codes.shape[1]), codes.dtype)])
            is_nan = np.concatenate(
                [is_nan, np.zeros((pad, is_nan.shape[1]), bool)])
            is_zero = np.concatenate(
                [is_zero, np.zeros((pad, is_zero.shape[1]), bool)])
        t0 = obs.clock()
        reg = obs.get_registry()
        # the contractual result sync: ONE device->host fetch per dispatch
        # (tpu-lint R011 baseline-exempt; everything else in serving/ stays
        # sync-free)
        leaves = np.asarray(self._walk(*self._dev[k], codes, is_nan, is_zero))
        reg.summary("serve.dispatch_ms").observe((obs.clock() - t0) * 1e3)
        reg.histogram("serve.batch_fill_frac").observe(n / B)
        reg.counter(f"serve.bucket.{B}").inc()
        return leaves[:n]

    def _predict_raw(self, X: np.ndarray) -> np.ndarray:
        """Raw scores [K, N] f64 for a prepared f64 matrix — traversal on
        device (bucketed), leaf accumulation on host in f64 tree order
        (bit-identical to the host predictor)."""
        N = X.shape[0]
        K = self.num_class_models
        raw = np.zeros((K, N), np.float64)
        if self.has_categorical:
            for i, t in enumerate(self._trees):
                raw[i % K] += t.predict(X)
            obs.get_registry().counter("serve.rows").inc(N)
            return raw
        for k, forest in enumerate(self._forests):
            if forest.num_trees == 0:
                continue
            codes, is_nan, is_zero = forest.encode_rows(X)
            lv = forest.leaf_value64
            lo = 0
            while lo < N:
                n = min(N - lo, self.max_bucket)
                leaves = self._dispatch(k, codes[lo:lo + n],
                                        is_nan[lo:lo + n], is_zero[lo:lo + n])
                # sequential f64 accumulation in tree order — the exact
                # operation order of Booster.predict's host loop
                out = raw[k]
                for t in range(forest.num_trees):
                    out[lo:lo + n] += lv[t, leaves[:, t]]
                lo += n
        obs.get_registry().counter("serve.rows").inc(N)
        return raw

    def _finish(self, raw: np.ndarray, raw_score: bool) -> np.ndarray:
        """Output transform — Booster.predict's tail, verbatim semantics."""
        K = self.num_class_models
        if self.config.boosting_normalized == "rf":
            raw = raw / max(len(self._trees) // K, 1)
        elif not raw_score:
            raw = self.booster._convert_output(raw)
        return raw[0] if K == 1 else raw.T

    def predict(self, X, raw_score: bool = False) -> np.ndarray:
        """Serve one request: [N, F] (or a single row) -> predictions,
        bit-identical to ``Booster.predict`` on the same rows."""
        t0 = obs.clock()
        X = self._as_matrix(X)
        out = self._finish(self._predict_raw(X), raw_score)
        reg = obs.get_registry()
        reg.counter("serve.requests").inc()
        reg.summary("serve.latency_ms").observe((obs.clock() - t0) * 1e3)
        return out

    def _as_matrix(self, X) -> np.ndarray:
        # host input normalization (caller data, not a device value)
        mat = np.asarray(X, np.float64)
        if mat.ndim == 1:
            mat = mat.reshape(1, -1)
        if mat.shape[1] != self.num_features:
            raise ValueError(
                f"request has {mat.shape[1]} features, model expects "
                f"{self.num_features}")
        return mat

    def describe(self) -> Dict:
        return {"buckets": list(self.buckets),
                "num_trees": len(self._trees),
                "num_class_models": self.num_class_models,
                "num_features": self.num_features,
                "categorical_host_path": self.has_categorical,
                "warmed": self._warm}
