"""Serving resilience primitives: typed errors, circuit breaker, chaos.

Serving a heavy-traffic inference path is judged on tail behavior under
overload, not peak throughput: an unbounded queue turns a burst into an
OOM, a caller with no deadline turns a hung dispatch into a wedged
thread pool, and a bad model swap with no rollback turns a deploy into
an outage. This module holds the pieces the engine and micro-batcher
compose into the detect -> degrade -> recover loop (docs/Serving.md
"Resilience"; the serving twin of the training-side self-healing in
docs/Fault-Tolerance.md):

- **Typed errors** — ``ServerOverloadedError`` (load shed at admission),
  ``DeadlineExceededError`` (per-request deadline missed),
  ``ServingClosedError`` (request against a closed batcher/engine),
  ``ReloadError`` (hot reload failed verification and rolled back),
  ``DeviceDispatchError`` (the device walk itself raised). All subclass
  ``ServingError(RuntimeError)`` so a load balancer's handler can treat
  "serving said no" uniformly while retry policy keys on the subclass:
  sheds are retryable-elsewhere, deadline misses are not.
- **CircuitBreaker** — counts device-dispatch failures in a sliding
  window; ``serve_breaker_failures`` failures inside
  ``serve_breaker_window_s`` trip it open (the engine then serves via
  the host predictor — degraded, never down) until a background probe
  re-warms the device path and resets it.
- **DispatchChaos** — deterministic fault injection for the dispatch
  path (one-shot exception bursts, slow-dispatch hangs, per-dispatch
  slowdowns), driven by ``bench.py --serve-chaos`` and the resilience
  test suite. A hook, not a monkeypatch: the engine calls it at the top
  of every device dispatch when installed, so injected faults travel
  the exact production error path.

Everything here is jax-free and lock-cheap: the breaker takes one lock
per *failure* (successes touch a plain bool), and the error types cost
nothing until raised.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional

from .. import observability as obs


# ------------------------------------------------------------- typed errors

class ServingError(RuntimeError):
    """Base of every typed serving-resilience error (docs/Serving.md)."""


class ServerOverloadedError(ServingError):
    """Admission refused: the micro-batcher queue is at
    ``serve_max_queue_rows``. The request was NEVER queued — shed load
    retries on another replica, it does not camp on this one."""


class DeadlineExceededError(ServingError):
    """The request's deadline (``serve_deadline_ms`` or the per-call
    override) passed before a result was produced. Raised at dequeue
    (expired requests never waste a dispatch) and to a caller whose
    wait outlived its deadline."""


class ServingClosedError(ServingError):
    """``predict()`` against a closed ``MicroBatcher``/``ServingEngine``.
    Raised immediately at admission — a request must never enqueue into
    a dead worker and hang its caller."""


class ReloadError(ServingError):
    """Hot model reload failed (feature-shape mismatch, warmup failure,
    or bit-identity verification mismatch) and was ROLLED BACK — the old
    model is still serving when this reaches the caller."""


class DeviceDispatchError(ServingError):
    """The device forest walk raised. Internal signal: the engine
    records it on the circuit breaker and serves the request via the
    host predictor instead — callers only ever see it from a
    verification path that forbids fallback."""


# ---------------------------------------------------------- circuit breaker

class CircuitBreaker:
    """Sliding-window failure counter gating the device dispatch path.

    States (``state`` property): ``closed`` (device path live) and
    ``open`` (tripped — the engine serves degraded via the host
    predictor while a probe re-warms the device). ``failures``
    consecutive-or-not device failures inside ``window_s`` seconds trip
    it; ``reset()`` (the probe's success) closes it again.
    ``failures <= 0`` disables the breaker entirely — ``record_failure``
    never trips and ``is_open`` stays False.

    Thread-safe: dispatch workers, the micro-batcher worker, and the
    probe thread all touch it. The hot path (``is_open`` on every
    request) is a plain attribute read."""

    def __init__(self, failures: int = 5, window_s: float = 30.0,
                 clock=None):
        self.failures = int(failures)
        self.window_s = float(window_s)
        self._clock = clock or obs.clock
        self._lock = threading.Lock()
        self._fail_times: List[float] = []
        self._open = False
        self.trips = 0

    @property
    def is_open(self) -> bool:
        return self._open

    @property
    def state(self) -> str:
        return "open" if self._open else "closed"

    def record_failure(self, err: Optional[BaseException] = None) -> bool:
        """Record one device-dispatch failure; returns True iff THIS
        failure tripped the breaker open (the caller starts the probe
        exactly once per trip)."""
        if self.failures <= 0:
            return False
        now = self._clock()
        with self._lock:
            self._fail_times.append(now)
            lo = now - self.window_s
            self._fail_times = [t for t in self._fail_times if t >= lo]
            if not self._open and len(self._fail_times) >= self.failures:
                self._open = True
                self.trips += 1
                obs.inc("serve.breaker_trips")
                return True
        return False

    def record_success(self) -> None:
        """A device dispatch completed — age the window out lazily (only
        when there is something to forget; the steady state costs one
        bool read)."""
        if not self._fail_times:
            return
        lo = self._clock() - self.window_s
        with self._lock:
            self._fail_times = [t for t in self._fail_times if t >= lo]

    def reset(self) -> None:
        """Close the breaker (the probe's device dispatch succeeded)."""
        with self._lock:
            self._fail_times = []
            if self._open:
                self._open = False
                obs.inc("serve.breaker_recoveries")


# ----------------------------------------------------------- fault injection

class ChaosDispatchError(RuntimeError):
    """The injected dispatch failure (NOT a ServingError on purpose: it
    stands in for whatever the runtime would really raise — an XLA
    error, a dead device — and must travel the generic handler)."""


class DispatchChaos:
    """Deterministic dispatch-path fault injector (bench.py
    --serve-chaos, tests/test_serving_resilience.py).

    Installed as ``engine.chaos = DispatchChaos()``; the engine invokes
    it at the top of every device dispatch (requests, probes, and
    reload verification alike — injected faults see the same path real
    ones do). Modes compose:

    - ``arm_failures(n)``    — the next ``n`` dispatches raise
      ``ChaosDispatchError``;
    - ``arm_hang(seconds, n=1)`` — the next ``n`` dispatches sleep
      ``seconds`` first (the slow-dispatch / wedged-device shape that
      deadlines exist for);
    - ``slowdown_s`` attribute — EVERY dispatch sleeps this long (an
      artificial capacity cap so an open-loop bench can drive a CPU
      harness into genuine overload).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._fail_next = 0
        self._hang_next = 0
        self._hang_s = 0.0
        self.slowdown_s = 0.0
        self.dispatches = 0
        self.injected_failures = 0
        self.injected_hangs = 0

    def arm_failures(self, n: int) -> None:
        with self._lock:
            self._fail_next = int(n)

    def arm_hang(self, seconds: float, n: int = 1) -> None:
        with self._lock:
            self._hang_s = float(seconds)
            self._hang_next = int(n)

    def __call__(self) -> None:
        with self._lock:
            self.dispatches += 1
            hang = 0.0
            if self._hang_next > 0:
                self._hang_next -= 1
                self.injected_hangs += 1
                hang = self._hang_s
            fail = False
            if self._fail_next > 0:
                self._fail_next -= 1
                self.injected_failures += 1
                fail = True
        delay = hang + self.slowdown_s
        if delay > 0:
            time.sleep(delay)
        if fail:
            raise ChaosDispatchError("injected dispatch failure "
                                     f"#{self.injected_failures}")
