"""Production inference subsystem (docs/Serving.md).

- ``ServingEngine`` (engine.py)  — load a model from any interchange
  format (protobuf / text / JSON / in-memory Booster), stack it once,
  AOT-compile the rank-encoded forest walk per batch-size bucket, and
  dispatch padded requests with zero steady-state recompiles. Served
  predictions are bit-identical to ``Booster.predict``.
- ``MicroBatcher`` (batcher.py)  — thread-safe coalescing of concurrent
  small ``predict()`` calls into one device dispatch under a max-wait
  deadline, with per-request de-interleaving of results.
- load generators (loadgen.py)   — closed-loop and open-loop (Poisson)
  drivers + latency stats, shared by ``bench.py --serve`` and the CLI's
  ``task=serve_bench``.

Every request feeds the process-wide metrics registry: ``serve.requests``
/ ``serve.rows`` counters, ``serve.queue_depth`` gauges,
``serve.batch_fill_frac`` histogram, and the ``serve.latency_ms`` /
``serve.dispatch_ms`` quantile summaries whose p50/p99 surface in
``observability.snapshot()`` — the live serving probe.
"""
from .batcher import MicroBatcher                                # noqa: F401
from .engine import ServingEngine, bucket_ladder                 # noqa: F401
