"""Production inference subsystem (docs/Serving.md).

- ``ServingEngine`` (engine.py)  — load a model from any interchange
  format (protobuf / text / JSON / in-memory Booster), stack it once,
  AOT-compile the rank-encoded forest walk per batch-size bucket, and
  dispatch padded requests with zero steady-state recompiles. Served
  predictions are bit-identical to ``Booster.predict``. Resilience:
  circuit-breaker degradation to the host predictor with a background
  device re-warm probe, ``health()`` (``ready|degraded|down``), and hot
  ``reload()`` with bit-identity verification and rollback.
- ``MicroBatcher`` (batcher.py)  — thread-safe coalescing of concurrent
  small ``predict()`` calls into one device dispatch under a max-wait
  deadline, with per-request de-interleaving of results, bounded-queue
  admission control (``ServerOverloadedError`` load shedding),
  per-request deadlines (``DeadlineExceededError``), and typed shutdown
  (``ServingClosedError``).
- resilience primitives (resilience.py) — the typed error family,
  ``CircuitBreaker``, and the ``DispatchChaos`` fault injector driven by
  ``bench.py --serve-chaos``.
- load generators (loadgen.py)   — closed-loop and open-loop (Poisson)
  drivers + latency stats, shared by ``bench.py --serve`` /
  ``--serve-chaos`` and the CLI's ``task=serve_bench``.

Every request feeds the process-wide metrics registry: ``serve.requests``
/ ``serve.rows`` counters, ``serve.queue_depth`` / ``serve.queue_rows``
gauges, ``serve.batch_fill_frac`` histogram, the ``serve.latency_ms`` /
``serve.dispatch_ms`` quantile summaries whose p50/p99 surface in
``observability.snapshot()`` — and the resilience series
(``serve.shed``, ``serve.deadline_exceeded``, ``serve.breaker_trips``,
``serve.reloads``, ``serve.health``, ``serve.model_version``).
"""
from .batcher import MicroBatcher                                # noqa: F401
from .engine import ServingEngine, bucket_ladder                 # noqa: F401
from .resilience import (CircuitBreaker, DeadlineExceededError,  # noqa: F401
                         DeviceDispatchError, DispatchChaos, ReloadError,
                         ServerOverloadedError, ServingClosedError,
                         ServingError)
