"""Load generators for the serving bench (bench.py --serve, cli serve_bench).

Two canonical load shapes (the inference-serving literature's pair):

- **closed loop** — ``concurrency`` workers each issue the next request the
  moment the previous one returns. Measures the engine's capacity frontier:
  rows/s at a fixed concurrency x batch-size shape, with per-request
  latency distributions.
- **open loop** — requests arrive on a seeded Poisson process at
  ``rate_rps`` regardless of completions (the million-user shape: arrival
  rate is set by the users, not by the server). Latency here includes queue
  delay, which is what an SLO actually experiences; a saturated server
  shows unbounded p99 here long before the closed loop does.

Both return plain dicts of latencies + throughput; ``latency_stats``
reduces a latency list to p50/p90/p99/mean/max (nearest-rank, matching the
registry's Summary). Wall-clock comes from ``observability.clock()`` (the
sanctioned source — tpu-lint R008).
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

import numpy as np

from .. import observability as obs


def latency_stats(lats_ms: List[float]) -> Dict:
    """Nearest-rank latency stats of a per-request latency list (ms) —
    the quantile selection IS ``Summary._quantiles_of`` (one
    implementation: bench p99 and snapshot p99 cannot disagree on
    semantics)."""
    from ..observability.metrics import Summary
    if not lats_ms:
        return {"n": 0, "p50_ms": None, "p90_ms": None, "p99_ms": None,
                "mean_ms": None, "max_ms": None}
    data = sorted(lats_ms)
    n = len(data)
    q = Summary._quantiles_of(data)
    return {"n": n, "p50_ms": round(q["p50"], 3),
            "p90_ms": round(q["p90"], 3), "p99_ms": round(q["p99"], 3),
            "mean_ms": round(sum(data) / n, 3), "max_ms": round(data[-1], 3)}


def _request_slices(X: np.ndarray, batch_rows: int):
    """Rotating request batches over a pool matrix (wraps around)."""
    N = X.shape[0]
    lo = 0
    while True:
        if lo + batch_rows <= N:
            yield X[lo:lo + batch_rows]
            lo = (lo + batch_rows) % N
        else:
            yield X[:batch_rows] if batch_rows <= N else X
            lo = batch_rows % max(N, 1)


def run_closed_loop(predict: Callable, X: np.ndarray, batch_rows: int,
                    concurrency: int, requests_per_worker: int,
                    stop_on_error: bool = True) -> Dict:
    """``concurrency`` workers, back-to-back requests of ``batch_rows``
    rows each; returns latencies + aggregate rows/s.
    ``stop_on_error=False`` records the error and keeps the worker going —
    the chaos-harness mode, where typed per-request errors (sheds,
    deadline misses) are the measurement, not a failure."""
    lats: List[List[float]] = [[] for _ in range(concurrency)]
    errors: List[str] = []
    err_lock = threading.Lock()
    start_gate = threading.Barrier(concurrency + 1)

    def worker(w: int):
        gen = _request_slices(X, batch_rows)
        start_gate.wait()
        for _ in range(requests_per_worker):
            Xr = next(gen)
            t0 = obs.clock()
            try:
                predict(Xr)
            except Exception as e:                            # noqa: BLE001
                with err_lock:
                    errors.append(repr(e))
                if stop_on_error:
                    return
                continue
            lats[w].append((obs.clock() - t0) * 1e3)

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(concurrency)]
    for t in threads:
        t.start()
    start_gate.wait()
    t0 = obs.clock()
    for t in threads:
        t.join()
    wall = obs.clock() - t0
    all_lats = [v for per in lats for v in per]
    # _request_slices caps a request at the pool size: rows/s must count
    # what was actually served, not the requested batch_rows
    eff = min(batch_rows, X.shape[0])
    rows = len(all_lats) * eff
    out = {"mode": "closed", "batch_rows": batch_rows,
           "concurrency": concurrency, "requests": len(all_lats),
           "wall_s": round(wall, 4),
           "rows_per_s": round(rows / wall, 1) if wall > 0 else None,
           "errors": errors, **latency_stats(all_lats)}
    if eff != batch_rows:
        out["batch_rows_effective"] = eff
    return out


def run_open_loop(predict: Callable, X: np.ndarray, batch_rows: int,
                  rate_rps: float, duration_s: float, seed: int = 0,
                  workers: Optional[int] = None,
                  stop_on_error: bool = True) -> Dict:
    """Poisson arrivals at ``rate_rps`` for ``duration_s`` seconds; a
    worker pool large enough to not throttle arrivals issues the requests.
    Latency includes any queue delay (open-loop semantics). The arrival
    schedule is a seeded RNG — reruns replay the same offered load.
    ``stop_on_error=False`` keeps the worker issuing after a per-request
    error (recorded in ``errors``) — the overload-chaos mode, where sheds
    and deadline misses are expected outcomes of the offered load."""
    import time as _time   # sleep only; wall-clock stays observability.clock

    rng = np.random.RandomState(seed)
    n_req = max(1, int(rate_rps * duration_s))
    arrivals = np.cumsum(rng.exponential(1.0 / rate_rps, size=n_req))
    workers = workers or max(4, min(32, int(rate_rps * 0.25) + 4))
    lats: List[float] = []
    lat_lock = threading.Lock()
    errors: List[str] = []
    next_idx = [0]
    idx_lock = threading.Lock()
    t_start = [0.0]
    start_gate = threading.Barrier(workers + 1)

    def worker(w: int):
        gen = _request_slices(X, batch_rows)
        start_gate.wait()
        while True:
            with idx_lock:
                i = next_idx[0]
                if i >= n_req:
                    return
                next_idx[0] += 1
            Xr = next(gen)
            # latency is measured from the SCHEDULED arrival, not from
            # dispatch: when the server falls behind, the arrival->issue
            # backlog is part of what the user waits for — measuring from
            # dispatch is the classic coordinated-omission bug and would
            # pin p99 at ~service time exactly when the server saturates
            t_sched = t_start[0] + arrivals[i]
            delay = t_sched - obs.clock()
            if delay > 0:
                _time.sleep(delay)
            try:
                predict(Xr)
            except Exception as e:                            # noqa: BLE001
                with lat_lock:
                    errors.append(repr(e))
                if stop_on_error:
                    return
                continue
            with lat_lock:
                lats.append((obs.clock() - t_sched) * 1e3)

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(workers)]
    for t in threads:
        t.start()
    t_start[0] = obs.clock()
    start_gate.wait()
    for t in threads:
        t.join()
    wall = obs.clock() - t_start[0]
    eff = min(batch_rows, X.shape[0])
    out = {"mode": "open", "batch_rows": batch_rows,
           "offered_rps": round(rate_rps, 1),
           "achieved_rps": round(len(lats) / wall, 1) if wall > 0 else None,
           "requests": len(lats),
           "rows_per_s": round(len(lats) * eff / wall, 1)
           if wall > 0 else None,
           "wall_s": round(wall, 4), "seed": seed,
           "errors": errors, **latency_stats(lats)}
    if eff != batch_rows:
        out["batch_rows_effective"] = eff
    return out
