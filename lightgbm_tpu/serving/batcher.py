"""MicroBatcher: coalesce concurrent small predict() calls into one dispatch.

Million-user traffic is many SMALL concurrent requests; dispatching each
alone wastes the device on 1-row walks and pays per-dispatch overhead N
times. The micro-batcher is the classic serving answer (the dynamic
batching of every production inference server): callers enqueue requests
from any thread, ONE worker thread coalesces whatever is queued — up to
``serve_max_batch_rows`` rows, waiting at most ``serve_max_wait_ms`` past
the oldest request's arrival — into a single engine dispatch, then
de-interleaves the result rows back to each caller's Future.

Guarantees (pinned by the ordering fuzz in tests/test_serving.py and the
resilience suite in tests/test_serving_resilience.py):
- every caller receives exactly its own rows' predictions, bit-identical
  to a direct ``engine.predict`` of the same rows (per-row math is
  independent of what the request was batched with), computed by exactly
  ONE model version (the worker snapshots the engine's model state per
  batch, so a concurrent hot reload never splits a request);
- requests are served FIFO — a request is never passed over by a later
  one (whole requests are taken from the queue head until the row budget
  is hit);
- a worker-side failure is delivered to every affected caller's Future,
  never swallowed.

Resilience (docs/Serving.md "Resilience"):
- **admission control** — the queue is bounded at
  ``serve_max_queue_rows`` rows; a request that would overflow it is
  REFUSED with ``ServerOverloadedError`` before it is ever queued
  (``serve.shed`` counter) — shed load retries elsewhere instead of
  camping on a saturated replica. The live backlog is the
  ``serve.queue_rows`` gauge.
- **deadlines** — each request carries ``serve_deadline_ms`` (or a
  per-call ``deadline_ms`` override; 0 = none). An expired request is
  dropped at DEQUEUE without wasting a dispatch, and a caller's wait is
  bounded by its own deadline even when the dispatch under it hangs —
  both paths raise ``DeadlineExceededError``
  (``serve.deadline_exceeded`` counter, counted once per request).
- **typed shutdown** — ``predict()`` after ``close()`` raises
  ``ServingClosedError`` immediately (it must never enqueue into a dead
  worker and hang the caller), and ``close()`` fails every still-queued
  Future with the same error.

Latency accounting: per-request wall-clock (enqueue -> result ready,
queueing included) feeds the ``serve.latency_ms`` summary; queue depth and
batch fill fraction land in ``serve.queue_depth`` / ``serve.queue_peak``
gauges and the ``serve.batch_fill_frac`` histogram.
"""
from __future__ import annotations

import threading
from collections import deque
from concurrent.futures import Future, InvalidStateError
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Optional

import numpy as np

from .. import observability as obs
from .resilience import (DeadlineExceededError, ServerOverloadedError,
                         ServingClosedError)


class _Request:
    __slots__ = ("X", "raw_score", "future", "t_enq", "deadline")

    def __init__(self, X, raw_score, t_enq, deadline):
        self.X = X
        self.raw_score = raw_score
        self.future: Future = Future()
        self.t_enq = t_enq
        self.deadline = deadline          # absolute obs.clock() time or None


class MicroBatcher:
    """Thread-safe request queue in front of a ``ServingEngine``."""

    def __init__(self, engine, max_batch_rows: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 max_queue_rows: Optional[int] = None,
                 deadline_ms: Optional[float] = None):
        self.engine = engine
        cfg = engine.config
        self.max_batch_rows = int(max_batch_rows
                                  if max_batch_rows is not None
                                  else cfg.serve_max_batch_rows)
        self.max_wait_s = (max_wait_ms if max_wait_ms is not None
                           else cfg.serve_max_wait_ms) / 1e3
        # admission bound: rows the queue may hold; 0 = unbounded
        self.max_queue_rows = int(max_queue_rows
                                  if max_queue_rows is not None
                                  else cfg.serve_max_queue_rows)
        self.deadline_ms = float(deadline_ms if deadline_ms is not None
                                 else cfg.serve_deadline_ms)
        self._cv = threading.Condition()
        self._queue: deque = deque()
        self._rows_queued = 0
        # earliest queued deadline, maintained incrementally so the
        # coalescing wait never rescans the queue (O(Q) per wakeup under
        # a small-request flood is exactly the overload path admission
        # control protects); recomputed only when requests leave the queue
        self._min_deadline: Optional[float] = None
        self._stop = False
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="lgbm-serve-batcher")
        self._worker.start()

    # -------------------------------------------------------------- client

    def _resolve_deadline(self, deadline_ms, now: float) -> Optional[float]:
        dl = self.deadline_ms if deadline_ms is None else float(deadline_ms)
        return (now + dl / 1e3) if dl > 0 else None

    def predict(self, X, raw_score: bool = False,
                deadline_ms: Optional[float] = None) -> np.ndarray:
        """Enqueue one request and block until its rows come back (at most
        until its deadline). Raises ``ServingClosedError`` after
        ``close()``, ``ServerOverloadedError`` when admission would
        overflow ``serve_max_queue_rows`` (the request is NOT queued),
        and ``DeadlineExceededError`` when the deadline passes first."""
        now = obs.clock()
        req = _Request(self.engine._as_matrix(X), raw_score, now,
                       self._resolve_deadline(deadline_ms, now))
        n = req.X.shape[0]
        reg = obs.get_registry()
        with self._cv:
            if self._stop:
                raise ServingClosedError(
                    "predict() on a closed MicroBatcher")
            # admission control: shed rather than queue unboundedly. A
            # request bigger than the whole bound still admits onto an
            # EMPTY queue (the engine chunks it) — otherwise it could
            # never be served at all.
            if self.max_queue_rows > 0 and self._queue \
                    and self._rows_queued + n > self.max_queue_rows:
                reg.counter("serve.shed").inc()
                raise ServerOverloadedError(
                    f"queue full: {self._rows_queued} rows queued "
                    f"(+{n} would exceed serve_max_queue_rows="
                    f"{self.max_queue_rows}) — request shed, not queued")
            self._queue.append(req)
            self._rows_queued += n
            if req.deadline is not None and (
                    self._min_deadline is None
                    or req.deadline < self._min_deadline):
                self._min_deadline = req.deadline
            depth = len(self._queue)
            reg.gauge("serve.queue_depth").set(depth)
            reg.gauge("serve.queue_rows").set(self._rows_queued)
            peak = reg.gauge("serve.queue_peak")
            if peak.value is None or depth > peak.value:
                peak.set(depth)
            self._cv.notify_all()
        try:
            if req.deadline is None:
                out = req.future.result()
            else:
                # the caller's wait is bounded by ITS deadline even when
                # the dispatch under it hangs — a wedged device must not
                # wedge every caller thread with it
                out = req.future.result(
                    timeout=max(req.deadline - obs.clock(), 0.0) + 1e-3)
        except _FutureTimeout:
            # cancel claims the future so the dequeue-side expiry check
            # cannot double-count this request; when the worker won the
            # race instead, the result landed — fall through so it is
            # accounted like any other served request
            if req.future.cancel():
                reg.counter("serve.deadline_exceeded").inc()
                raise DeadlineExceededError(
                    f"request deadline passed after "
                    f"{(obs.clock() - req.t_enq) * 1e3:.1f} ms waiting on "
                    f"the batcher") from None
            out = req.future.result(timeout=0)
        reg.counter("serve.requests").inc()
        reg.summary("serve.latency_ms").observe(
            (obs.clock() - req.t_enq) * 1e3)
        return out

    def close(self) -> None:
        """Stop the worker; every still-queued request's Future fails with
        ``ServingClosedError`` (a queued caller unblocks immediately —
        never hangs on a dead worker). Idempotent."""
        with self._cv:
            self._stop = True
            dropped = list(self._queue)
            self._queue.clear()
            self._rows_queued = 0
            self._min_deadline = None
            reg = obs.get_registry()
            reg.gauge("serve.queue_depth").set(0)
            reg.gauge("serve.queue_rows").set(0)
            self._cv.notify_all()
        for r in dropped:
            if not r.future.done():
                r.future.set_exception(ServingClosedError(
                    "MicroBatcher closed with the request still queued"))
        self._worker.join(timeout=10.0)

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -------------------------------------------------------------- worker

    def _recompute_min_deadline(self) -> None:
        """Under the lock: rebuild the earliest-deadline cache after
        requests left the queue (batch pop or expiry sweep)."""
        self._min_deadline = min(
            (r.deadline for r in self._queue if r.deadline is not None),
            default=None)

    def _fail_expired(self, now: float) -> None:
        """Under the lock: drop every queued request whose deadline has
        passed — it gets ``DeadlineExceededError`` WITHOUT costing a
        dispatch. (Counted here unless the caller's own bounded wait
        already counted it.)"""
        if self._min_deadline is None or now <= self._min_deadline:
            return
        keep, reg = deque(), obs.get_registry()
        for r in self._queue:
            if r.deadline is not None and now > r.deadline:
                self._rows_queued -= r.X.shape[0]
                try:
                    r.future.set_exception(DeadlineExceededError(
                        f"deadline passed after "
                        f"{(now - r.t_enq) * 1e3:.1f} ms in the queue — "
                        f"request dropped at dequeue, no dispatch spent"))
                    reg.counter("serve.deadline_exceeded").inc()
                except InvalidStateError:
                    pass    # the caller's bounded wait already claimed it
            else:
                keep.append(r)
        self._queue = keep
        self._recompute_min_deadline()
        reg.gauge("serve.queue_rows").set(self._rows_queued)

    def _take_batch(self):
        """Under the lock: wait for work, hold the coalescing window, pop
        whole requests FIFO up to the row budget. Expired requests are
        failed in place, never dispatched. Returns [] on shutdown."""
        with self._cv:
            while not self._queue and not self._stop:
                self._cv.wait(0.1)
            if not self._queue:
                return []
            deadline = self._queue[0].t_enq + self.max_wait_s
            while self._rows_queued < self.max_batch_rows and not self._stop:
                now = obs.clock()
                # never coalesce past a queued request's own deadline
                wait_until = deadline
                if self._min_deadline is not None \
                        and self._min_deadline < wait_until:
                    wait_until = self._min_deadline
                remaining = wait_until - now
                if remaining <= 0:
                    break
                self._cv.wait(remaining)
            self._fail_expired(obs.clock())
            batch, rows = [], 0
            while self._queue:
                n = self._queue[0].X.shape[0]
                if batch and rows + n > self.max_batch_rows:
                    break
                req = self._queue.popleft()
                batch.append(req)
                rows += n
            self._rows_queued -= rows
            self._recompute_min_deadline()
            reg = obs.get_registry()
            reg.gauge("serve.queue_depth").set(len(self._queue))
            reg.gauge("serve.queue_rows").set(self._rows_queued)
            return batch

    def _run(self) -> None:
        while True:
            batch = self._take_batch()
            if not batch:
                if self._stop:
                    return
                continue
            try:
                if len(batch) == 1:
                    Xc = batch[0].X
                else:
                    Xc = np.concatenate([r.X for r in batch], axis=0)
                # ONE model snapshot per batch: a hot reload mid-batch
                # cannot split a request across model versions
                m = self.engine.model_snapshot()
                raw = self.engine._predict_raw_for(m, Xc)     # [K, N_total]
                lo = 0
                for r in batch:
                    n = r.X.shape[0]
                    try:
                        r.future.set_result(self.engine._finish_for(
                            m, raw[:, lo:lo + n].copy(), r.raw_score))
                    except InvalidStateError:
                        pass     # caller abandoned it at its deadline
                    lo += n
            except BaseException as e:                        # noqa: BLE001
                # a dispatch failure belongs to the CALLERS — deliver it to
                # every waiting Future (R010: never swallowed)
                for r in batch:
                    try:
                        r.future.set_exception(e)
                    except InvalidStateError:
                        pass     # caller abandoned it at its deadline
