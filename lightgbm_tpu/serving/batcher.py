"""MicroBatcher: coalesce concurrent small predict() calls into one dispatch.

Million-user traffic is many SMALL concurrent requests; dispatching each
alone wastes the device on 1-row walks and pays per-dispatch overhead N
times. The micro-batcher is the classic serving answer (the dynamic
batching of every production inference server): callers enqueue requests
from any thread, ONE worker thread coalesces whatever is queued — up to
``serve_max_batch_rows`` rows, waiting at most ``serve_max_wait_ms`` past
the oldest request's arrival — into a single engine dispatch, then
de-interleaves the result rows back to each caller's Future.

Guarantees (pinned by the ordering fuzz in tests/test_serving.py):
- every caller receives exactly its own rows' predictions, bit-identical
  to a direct ``engine.predict`` of the same rows (per-row math is
  independent of what the request was batched with);
- requests are served FIFO — a request is never passed over by a later
  one (whole requests are taken from the queue head until the row budget
  is hit);
- a worker-side failure is delivered to every affected caller's Future,
  never swallowed.

Latency accounting: per-request wall-clock (enqueue -> result ready,
queueing included) feeds the ``serve.latency_ms`` summary; queue depth and
batch fill fraction land in ``serve.queue_depth`` / ``serve.queue_peak``
gauges and the ``serve.batch_fill_frac`` histogram.
"""
from __future__ import annotations

import threading
from collections import deque
from concurrent.futures import Future
from typing import Optional

import numpy as np

from .. import observability as obs


class _Request:
    __slots__ = ("X", "raw_score", "future", "t_enq")

    def __init__(self, X, raw_score, t_enq):
        self.X = X
        self.raw_score = raw_score
        self.future: Future = Future()
        self.t_enq = t_enq


class MicroBatcher:
    """Thread-safe request queue in front of a ``ServingEngine``."""

    def __init__(self, engine, max_batch_rows: Optional[int] = None,
                 max_wait_ms: Optional[float] = None):
        self.engine = engine
        cfg = engine.config
        self.max_batch_rows = int(max_batch_rows
                                  if max_batch_rows is not None
                                  else cfg.serve_max_batch_rows)
        self.max_wait_s = (max_wait_ms if max_wait_ms is not None
                           else cfg.serve_max_wait_ms) / 1e3
        self._cv = threading.Condition()
        self._queue: deque = deque()
        self._rows_queued = 0
        self._stop = False
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="lgbm-serve-batcher")
        self._worker.start()

    # -------------------------------------------------------------- client

    def predict(self, X, raw_score: bool = False) -> np.ndarray:
        """Enqueue one request and block until its rows come back."""
        req = _Request(self.engine._as_matrix(X), raw_score, obs.clock())
        reg = obs.get_registry()
        with self._cv:
            if self._stop:
                raise RuntimeError("MicroBatcher is closed")
            self._queue.append(req)
            self._rows_queued += req.X.shape[0]
            depth = len(self._queue)
            reg.gauge("serve.queue_depth").set(depth)
            peak = reg.gauge("serve.queue_peak")
            if peak.value is None or depth > peak.value:
                peak.set(depth)
            self._cv.notify_all()
        out = req.future.result()
        reg.counter("serve.requests").inc()
        reg.summary("serve.latency_ms").observe(
            (obs.clock() - req.t_enq) * 1e3)
        return out

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._worker.join(timeout=10.0)

    def __enter__(self) -> "MicroBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -------------------------------------------------------------- worker

    def _take_batch(self):
        """Under the lock: wait for work, hold the coalescing window, pop
        whole requests FIFO up to the row budget. Returns [] on shutdown."""
        with self._cv:
            while not self._queue and not self._stop:
                self._cv.wait(0.1)
            if not self._queue:
                return []
            deadline = self._queue[0].t_enq + self.max_wait_s
            while self._rows_queued < self.max_batch_rows and not self._stop:
                remaining = deadline - obs.clock()
                if remaining <= 0:
                    break
                self._cv.wait(remaining)
            batch, rows = [], 0
            while self._queue:
                n = self._queue[0].X.shape[0]
                if batch and rows + n > self.max_batch_rows:
                    break
                req = self._queue.popleft()
                batch.append(req)
                rows += n
            self._rows_queued -= rows
            obs.get_registry().gauge("serve.queue_depth").set(
                len(self._queue))
            return batch

    def _run(self) -> None:
        while True:
            batch = self._take_batch()
            if not batch:
                if self._stop:
                    return
                continue
            try:
                if len(batch) == 1:
                    Xc = batch[0].X
                else:
                    Xc = np.concatenate([r.X for r in batch], axis=0)
                raw = self.engine._predict_raw(Xc)            # [K, N_total]
                lo = 0
                for r in batch:
                    n = r.X.shape[0]
                    r.future.set_result(
                        self.engine._finish(raw[:, lo:lo + n].copy(),
                                            r.raw_score))
                    lo += n
            except BaseException as e:                        # noqa: BLE001
                # a dispatch failure belongs to the CALLERS — deliver it to
                # every waiting Future (R010: never swallowed)
                for r in batch:
                    if not r.future.done():
                        r.future.set_exception(e)
