"""Plotting utilities (reference: python-package/lightgbm/plotting.py).

Same public surface — ``plot_importance``, ``plot_metric``, ``plot_tree``,
``create_tree_digraph`` — re-implemented against this package's Booster
introspection API (``feature_importance``, ``dump_model``, the
``record_evaluation`` callback dict). ``plot_tree`` renders the tree with
pure matplotlib (a recursive in-order layout) instead of shelling out to
graphviz's ``dot`` binary, which keeps it dependency-free on TPU pods;
``create_tree_digraph`` still returns a ``graphviz.Digraph`` for users who
have graphviz installed.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .basic import Booster


def _check_matplotlib():
    try:
        import matplotlib.pyplot as plt
        return plt
    except ImportError as e:  # pragma: no cover
        raise ImportError("You must install matplotlib for plotting") from e


def plot_importance(booster, ax=None, height: float = 0.2, xlim=None, ylim=None,
                    title: str = "Feature importance",
                    xlabel: str = "Feature importance",
                    ylabel: str = "Features",
                    importance_type: str = "split",
                    max_num_features: Optional[int] = None,
                    ignore_zero: bool = True, figsize=None, grid: bool = True,
                    **kwargs):
    """Horizontal-bar feature importance (reference plotting.py:22)."""
    plt = _check_matplotlib()
    if isinstance(booster, Booster):
        importance = booster.feature_importance(importance_type=importance_type)
        feature_names = booster.feature_name()
    elif hasattr(booster, "booster_"):            # sklearn estimator
        importance = booster.booster_.feature_importance(importance_type=importance_type)
        feature_names = booster.booster_.feature_name()
    else:
        raise TypeError("booster must be Booster or LGBMModel")

    pairs = sorted(zip(feature_names, importance), key=lambda t: t[1])
    if ignore_zero:
        pairs = [p for p in pairs if p[1] != 0]
    if not pairs:
        raise ValueError("Booster's feature_importance is empty")
    if max_num_features is not None and max_num_features > 0:
        pairs = pairs[-max_num_features:]
    labels, values = zip(*pairs)

    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y, str(x), va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        ax.set_xlim(xlim)
    else:
        ax.set_xlim(0, max(values) * 1.1)
    if ylim is not None:
        ax.set_ylim(ylim)
    else:
        ax.set_ylim(-1, len(values))
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster, metric: Optional[str] = None,
                dataset_names: Optional[List[str]] = None,
                ax=None, xlim=None, ylim=None,
                title: str = "Metric during training",
                xlabel: str = "Iterations", ylabel: str = "auto",
                figsize=None, grid: bool = True):
    """Plot one recorded eval metric across training (reference :131).

    ``booster`` is the dict produced by the ``record_evaluation`` callback
    (a Booster itself keeps no eval history, matching the reference which
    raises for Booster input too).
    """
    plt = _check_matplotlib()
    if isinstance(booster, dict):
        eval_results = booster
    elif hasattr(booster, "evals_result_"):       # sklearn estimator
        eval_results = booster.evals_result_
    else:
        raise TypeError(
            "booster must be a dict from record_evaluation or a fitted LGBMModel")
    if not eval_results:
        raise ValueError("eval results cannot be empty")

    if dataset_names is None:
        dataset_names = list(eval_results.keys())
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize)

    first = eval_results[dataset_names[0]]
    if metric is None:
        if len(first) > 1:
            raise ValueError("more than one metric available, pick one with metric=")
        metric = next(iter(first))
    elif metric not in first:
        raise ValueError(f"specific metric {metric!r} not recorded")

    num_iters = 0
    for name in dataset_names:
        results = eval_results[name][metric]
        num_iters = max(num_iters, len(results))
        ax.plot(range(len(results)), results, label=name)

    ax.legend(loc="best")
    if xlim is not None:
        ax.set_xlim(xlim)
    else:
        ax.set_xlim(0, num_iters)
    if ylim is not None:
        ax.set_ylim(ylim)
    if ylabel == "auto":
        ylabel = metric
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def _tree_dump(booster, tree_index: int) -> Dict[str, Any]:
    if hasattr(booster, "booster_"):
        booster = booster.booster_
    if not isinstance(booster, Booster):
        raise TypeError("booster must be Booster or LGBMModel")
    model = booster.dump_model()
    if tree_index >= len(model["tree_info"]):
        raise IndexError(f"tree_index {tree_index} out of range "
                         f"({len(model['tree_info'])} trees)")
    return model["tree_info"][tree_index]


def _fmt(value, precision: int = 3) -> str:
    # categorical thresholds are "||"-joined strings in the dump
    return value if isinstance(value, str) else f"{value:.{precision}g}"


def _node_label(node: Dict[str, Any], show_info: List[str],
                feature_names: Optional[List[str]], precision: int = 3) -> str:
    if "split_index" in node:
        f = node["split_feature"]
        fname = feature_names[f] if feature_names else f"f{f}"
        lines = [f"{fname} {node['decision_type']} "
                 f"{_fmt(node['threshold'], precision)}"]
        if "split_gain" in show_info:
            lines.append(f"gain: {_fmt(node['split_gain'], precision)}")
        if "internal_value" in show_info:
            lines.append(f"value: {_fmt(node['internal_value'], precision)}")
        if "internal_count" in show_info:
            lines.append(f"count: {node['internal_count']:g}")
    else:
        # a stump iteration dumps bare {'leaf_value': v} with no index
        idx = node.get("leaf_index", 0)
        lines = [f"leaf {idx}: {_fmt(node['leaf_value'], precision)}"]
        if "leaf_count" in show_info and "leaf_count" in node:
            lines.append(f"count: {node['leaf_count']:g}")
    return "\n".join(lines)


def create_tree_digraph(booster, tree_index: int = 0,
                        show_info: Optional[List[str]] = None,
                        name: Optional[str] = None,
                        comment: Optional[str] = None,
                        filename: Optional[str] = None,
                        directory: Optional[str] = None,
                        format: Optional[str] = None,
                        engine: Optional[str] = None,
                        encoding: Optional[str] = None,
                        graph_attr=None, node_attr=None, edge_attr=None,
                        body=None, strict: bool = False):
    """Graphviz Digraph of one tree (reference plotting.py:308)."""
    try:
        from graphviz import Digraph
    except ImportError as e:  # pragma: no cover
        raise ImportError("You must install graphviz for create_tree_digraph") from e
    show_info = show_info or []
    tree = _tree_dump(booster, tree_index)
    b = booster.booster_ if hasattr(booster, "booster_") else booster
    feature_names = b.feature_name()

    graph = Digraph(name=name, comment=comment, filename=filename,
                    directory=directory, format=format, engine=engine,
                    encoding=encoding, graph_attr=graph_attr,
                    node_attr=node_attr, edge_attr=edge_attr, body=body,
                    strict=strict)

    def add(node, parent=None, decision=None):
        if "split_index" in node:
            nid = f"split{node['split_index']}"
            graph.node(nid, label=_node_label(node, show_info, feature_names))
            add(node["left_child"], nid, "yes")
            add(node["right_child"], nid, "no")
        else:
            nid = f"leaf{node.get('leaf_index', 0)}"
            graph.node(nid, label=_node_label(node, show_info, feature_names))
        if parent is not None:
            graph.edge(parent, nid, decision)

    add(tree["tree_structure"])
    return graph


def plot_tree(booster, ax=None, tree_index: int = 0, figsize=None,
              show_info: Optional[List[str]] = None, precision: int = 3,
              **kwargs):
    """Draw one tree with matplotlib (reference plotting.py:387 renders via
    graphviz ``dot``; here a self-contained recursive layout: leaves are
    placed at consecutive x positions in-order, internal nodes centered
    over their children, depth on the y axis)."""
    plt = _check_matplotlib()
    show_info = show_info or []
    tree = _tree_dump(booster, tree_index)
    b = booster.booster_ if hasattr(booster, "booster_") else booster
    feature_names = b.feature_name()

    pos: Dict[int, tuple] = {}
    labels: Dict[int, str] = {}
    edges = []                 # (parent_id, child_id, text)
    next_x = [0.0]
    next_id = [0]

    def layout(node, depth):
        nid = next_id[0]
        next_id[0] += 1
        labels[nid] = _node_label(node, show_info, feature_names, precision)
        if "split_index" in node:
            lid = layout(node["left_child"], depth + 1)
            rid = layout(node["right_child"], depth + 1)
            x = (pos[lid][0] + pos[rid][0]) / 2
            edges.append((nid, lid, "yes"))
            edges.append((nid, rid, "no"))
        else:
            x = next_x[0]
            next_x[0] += 1.0
        pos[nid] = (x, -float(depth))
        return nid

    layout(tree["tree_structure"], 0)

    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize or (max(6, next_x[0] * 1.5), 6))
    for p, c, text in edges:
        (x0, y0), (x1, y1) = pos[p], pos[c]
        ax.plot([x0, x1], [y0, y1], "-", color="0.6", zorder=1)
        ax.text((x0 + x1) / 2, (y0 + y1) / 2, text, fontsize=7, color="0.4")
    for nid, (x, y) in pos.items():
        ax.text(x, y, labels[nid], ha="center", va="center", fontsize=8, zorder=2,
                bbox=dict(boxstyle="round", facecolor="lightyellow", edgecolor="0.5"))
    ax.set_axis_off()
    ax.set_title(f"Tree {tree_index}")
    return ax
