"""Objective functions: score -> (gradient, hessian), as JAX-traceable math.

Reference: src/objective/*.hpp + factory objective_function.cpp:11-33. Each
objective exposes:
- `gradients(score[K,N], label[N], weight[N]|None) -> (g[K,N], h[K,N])`,
  traced into the boosting-iteration jit (the reference's GetGradients OMP
  loops become fused elementwise XLA; lambdarank's per-query pairwise loops
  become padded-bucket batched matrices),
- `convert_output(raw)` — sigmoid/softmax/exp transform (objective_function.h),
- host-side `init(...)` for label checks / class counts / query structure,
- `boost_from_average_score()` (gbdt.cpp:357-377 + GetCustomAverage).

Scores are laid out [num_models, num_data] — the reference's k*num_data+i
flattening (multiclass_objective.hpp:60-75) as a 2-D array.
"""
from __future__ import annotations

import math
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import Config
from .dataset import Metadata
from .utils.log import Log


def _apply_weight(g, h, weight):
    if weight is None:
        return g, h
    return g * weight, h * weight


class Objective:
    """Base objective (reference: include/LightGBM/objective_function.h)."""

    name = "custom"
    num_models = 1
    is_constant_hessian = False

    def __init__(self, config: Config):
        self.config = config

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.num_data = num_data

    def gradients(self, score: jnp.ndarray, label: jnp.ndarray,
                  weight: Optional[jnp.ndarray]) -> Tuple[jnp.ndarray, jnp.ndarray]:
        raise NotImplementedError

    def convert_output(self, raw: jnp.ndarray) -> jnp.ndarray:
        return raw

    def boost_from_average_score(self) -> Optional[float]:
        """Init score when boost_from_average applies; None otherwise."""
        return None

    def _weighted_label_mean(self, metadata: Metadata) -> float:
        label = metadata.label.astype(np.float64)
        if metadata.weight is not None:
            w = metadata.weight.astype(np.float64)
            return float((label * w).sum() / w.sum())
        return float(label.mean())


class RegressionL2(Objective):
    """regression / l2 / mse (regression_objective.hpp:13-75)."""
    name = "regression"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.is_constant_hessian = metadata.weight is None
        self._avg = self._weighted_label_mean(metadata)

    def gradients(self, score, label, weight):
        g = score - label[None, :]
        h = jnp.ones_like(g)
        return _apply_weight(g, h, weight)

    def boost_from_average_score(self):
        return self._avg


def _gaussian_hessian(score, label, grad, eta, weight=None):
    """ApproximateHessianWithGaussian (utils/common.h:486-495)."""
    w = 1.0 if weight is None else weight
    diff = score - label
    x = jnp.abs(diff)
    a = 2.0 * jnp.abs(grad) * w
    c = jnp.maximum((jnp.abs(score) + jnp.abs(label)) * eta, 1.0e-10)
    return w * jnp.exp(-x * x / (2.0 * c * c)) * a / (c * jnp.sqrt(2.0 * jnp.pi))


class RegressionL1(Objective):
    """regression_l1 / mae (regression_objective.hpp:80-147)."""
    name = "regression_l1"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self._avg = self._weighted_label_mean(metadata)

    def gradients(self, score, label, weight):
        label = label[None, :]
        diff = score - label
        sign = jnp.where(diff >= 0.0, 1.0, -1.0)
        if weight is not None:
            g = sign * weight
            h = _gaussian_hessian(score, label, g, self.config.gaussian_eta, weight)
        else:
            g = sign
            h = _gaussian_hessian(score, label, g, self.config.gaussian_eta)
        return g, h

    def boost_from_average_score(self):
        return self._avg


class RegressionHuber(Objective):
    """huber (regression_objective.hpp:151-233)."""
    name = "huber"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self._avg = self._weighted_label_mean(metadata)

    def gradients(self, score, label, weight):
        label = label[None, :]
        delta = self.config.huber_delta
        diff = score - label
        inner = jnp.abs(diff) <= delta
        g_out = jnp.where(diff >= 0.0, delta, -delta)
        if weight is not None:
            g = jnp.where(inner, diff * weight, g_out * weight)
            h = jnp.where(inner, weight,
                          _gaussian_hessian(score, label, g_out * weight,
                                            self.config.gaussian_eta, weight))
        else:
            g = jnp.where(inner, diff, g_out)
            h = jnp.where(inner, 1.0,
                          _gaussian_hessian(score, label, g_out, self.config.gaussian_eta))
        return g, h

    def boost_from_average_score(self):
        return self._avg


class RegressionFair(Objective):
    """fair (regression_objective.hpp:237-297)."""
    name = "fair"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self._avg = self._weighted_label_mean(metadata)

    def gradients(self, score, label, weight):
        c = self.config.fair_c
        x = score - label[None, :]
        g = c * x / (jnp.abs(x) + c)
        h = c * c / (jnp.abs(x) + c) ** 2
        return _apply_weight(g, h, weight)

    def boost_from_average_score(self):
        return self._avg


class RegressionPoisson(Objective):
    """poisson (regression_objective.hpp:301-399): internal score is log-rate."""
    name = "poisson"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        label = metadata.label
        if label.min() < 0.0:
            Log.fatal("[poisson]: at least one target label is negative.")
        if label.sum() == 0.0:
            Log.fatal("[poisson]: sum of labels is zero.")
        self._init_score = math.log(self._weighted_label_mean(metadata))

    def gradients(self, score, label, weight):
        ef = jnp.exp(score)
        g = ef - label[None, :]
        h = ef
        return _apply_weight(g, h, weight)

    def convert_output(self, raw):
        return jnp.exp(raw)

    def boost_from_average_score(self):
        return self._init_score


class BinaryLogloss(Objective):
    """binary (binary_objective.hpp:13-180)."""
    name = "binary"

    def __init__(self, config: Config, positive_class: Optional[int] = None):
        super().__init__(config)
        if config.sigmoid <= 0.0:
            Log.fatal("Sigmoid parameter %f should be greater than zero", config.sigmoid)
        if config.is_unbalance and abs(config.scale_pos_weight - 1.0) > 1e-6:
            Log.fatal("Cannot set is_unbalance and scale_pos_weight at the same time.")
        self.positive_class = positive_class  # for OVA sub-objectives

    def _is_pos(self, label: np.ndarray) -> np.ndarray:
        if self.positive_class is not None:
            return label.astype(np.int32) == self.positive_class
        return label > 0

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        pos = self._is_pos(metadata.label)
        cnt_pos = int(pos.sum())
        cnt_neg = num_data - cnt_pos
        self.need_train = True
        if cnt_pos == 0 or cnt_neg == 0:
            Log.warning("Only contain one class.")
            self.need_train = False
        Log.info("Number of positive: %d, number of negative: %d", cnt_pos, cnt_neg)
        w_neg, w_pos = 1.0, 1.0
        if self.config.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                w_neg = cnt_pos / cnt_neg
            else:
                w_pos = cnt_neg / cnt_pos
        w_pos *= self.config.scale_pos_weight
        self.label_weights = (w_neg, w_pos)

    def gradients(self, score, label, weight):
        sig = self.config.sigmoid
        if self.positive_class is not None:
            is_pos = label.astype(jnp.int32) == self.positive_class
        else:
            is_pos = label > 0
        y = jnp.where(is_pos, 1.0, -1.0)
        lw = jnp.where(is_pos, self.label_weights[1], self.label_weights[0])
        response = -y * sig / (1.0 + jnp.exp(y * sig * score))
        abs_resp = jnp.abs(response)
        g = response * lw
        h = abs_resp * (sig - abs_resp) * lw
        if not self.need_train:
            g = jnp.zeros_like(g)
            h = jnp.zeros_like(h)
        return _apply_weight(g, h, weight)

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-self.config.sigmoid * raw))


class MulticlassSoftmax(Objective):
    """multiclass softmax (multiclass_objective.hpp:16-140)."""
    name = "multiclass"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_models = config.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        li = metadata.label.astype(np.int64)
        if li.min() < 0 or li.max() >= self.num_models:
            Log.fatal("Label must be in [0, %d), but found %d in label",
                      self.num_models, int(li.min() if li.min() < 0 else li.max()))

    def gradients(self, score, label, weight):
        p = jax.nn.softmax(score, axis=0)                 # [K, N]
        onehot = (label.astype(jnp.int32)[None, :]
                  == jnp.arange(self.num_models, dtype=jnp.int32)[:, None])
        g = p - onehot.astype(p.dtype)
        h = 2.0 * p * (1.0 - p)
        return _apply_weight(g, h, weight)

    def convert_output(self, raw):
        return jax.nn.softmax(raw, axis=0)


class MulticlassOVA(Objective):
    """multiclassova (multiclass_objective.hpp:139+): K independent binary."""
    name = "multiclassova"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_models = config.num_class
        self.subs = [BinaryLogloss(config, positive_class=k)
                     for k in range(self.num_models)]

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        for sub in self.subs:
            sub.init(metadata, num_data)

    def gradients(self, score, label, weight):
        gs, hs = [], []
        for k, sub in enumerate(self.subs):
            g, h = sub.gradients(score[k:k + 1], label, weight)
            gs.append(g)
            hs.append(h)
        return jnp.concatenate(gs, axis=0), jnp.concatenate(hs, axis=0)

    def convert_output(self, raw):
        return 1.0 / (1.0 + jnp.exp(-self.config.sigmoid * raw))


class CrossEntropy(Objective):
    """xentropy (xentropy_objective.hpp:39-137): labels in [0,1]."""
    name = "xentropy"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        label = metadata.label
        if label.min() < 0.0 or label.max() > 1.0:
            Log.fatal("[xentropy]: label must be in [0, 1]")
        if metadata.weight is not None:
            if metadata.weight.min() < 0.0:
                Log.fatal("[xentropy]: at least one weight is negative.")
            if metadata.weight.sum() == 0.0:
                Log.fatal("[xentropy]: sum of weights is zero.")
        pavg = min(max(self._weighted_label_mean(metadata), 1e-15), 1.0 - 1e-15)
        self._init_score = math.log(pavg / (1.0 - pavg))

    def gradients(self, score, label, weight):
        z = jax.nn.sigmoid(score)
        g = z - label[None, :]
        h = z * (1.0 - z)
        return _apply_weight(g, h, weight)

    def convert_output(self, raw):
        return jax.nn.sigmoid(raw)

    def boost_from_average_score(self):
        return self._init_score


class CrossEntropyLambda(Objective):
    """xentlambda (xentropy_objective.hpp:143-260)."""
    name = "xentlambda"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        label = metadata.label
        if label.min() < 0.0 or label.max() > 1.0:
            Log.fatal("[xentlambda]: label must be in [0, 1]")
        if metadata.weight is not None and metadata.weight.min() <= 0.0:
            Log.fatal("[xentlambda]: at least one weight is non-positive.")
        sumy = float(label.astype(np.float64).sum())
        havg = sumy / num_data
        self._init_score = math.log(max(math.expm1(havg), 1e-15))

    def gradients(self, score, label, weight):
        label = label[None, :]
        if weight is None:
            z = jax.nn.sigmoid(score)
            return z - label, z * (1.0 - z)
        w = weight
        epf = jnp.exp(score)
        hhat = jnp.log1p(epf)
        z = 1.0 - jnp.exp(-w * hhat)
        enf = jnp.exp(-score)
        g = (1.0 - label / z) * w / (1.0 + enf)
        c = 1.0 / (1.0 - z)
        d = 1.0 + epf
        a = w * epf / (d * d)
        d2 = c - 1.0
        b = (c / (d2 * d2)) * (1.0 + w * epf - c)
        h = a * (1.0 + label * b)
        return g, h

    def convert_output(self, raw):
        return jnp.log1p(jnp.exp(raw))

    def boost_from_average_score(self):
        return self._init_score


# ---------------------------------------------------------------------------
# lambdarank
# ---------------------------------------------------------------------------

DEFAULT_LABEL_GAIN_SIZE = 31


def default_label_gain() -> List[float]:
    """2^i - 1 (reference: config.cpp label_gain default)."""
    return [float((1 << i) - 1) for i in range(DEFAULT_LABEL_GAIN_SIZE)]


class LambdarankNDCG(Objective):
    """lambdarank (rank_objective.hpp:19-208).

    TPU formulation: queries are padded to power-of-two bucket lengths and
    processed as batched [Qchunk, M, M] pairwise matrices — the reference's
    per-query double loop (rank_objective.hpp:113-160) with the sigmoid lookup
    table replaced by direct computation. A host-precomputed permutation maps
    bucket layout back to row order with gathers only (no TPU scatters).
    """
    name = "lambdarank"

    QUERY_CHUNK_BUDGET = 1 << 22  # pairwise f32 elements per chunk (~16MB)

    def __init__(self, config: Config):
        super().__init__(config)
        if config.sigmoid <= 0.0:
            Log.fatal("Sigmoid param %f should be greater than zero", config.sigmoid)
        gains = config.label_gain or default_label_gain()
        self.label_gain = np.asarray(gains, dtype=np.float64)
        self.optimize_pos_at = config.max_position

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            Log.fatal("Lambdarank tasks require query information")
        qb = metadata.query_boundaries.astype(np.int64)
        label = metadata.label.astype(np.int64)
        if label.min() < 0 or label.max() >= len(self.label_gain):
            Log.fatal("Label (%d) excceed the max label gain size", int(label.max()))
        self.num_queries = len(qb) - 1
        sizes = np.diff(qb)
        # inverse max DCG at k per query (dcg_calculator.cpp semantics)
        inv_max_dcg = np.zeros(self.num_queries, dtype=np.float64)
        gains = self.label_gain
        for q in range(self.num_queries):
            ls = np.sort(label[qb[q]:qb[q + 1]])[::-1][: self.optimize_pos_at]
            dcg = float((gains[ls] / np.log2(np.arange(len(ls)) + 2.0)).sum())
            inv_max_dcg[q] = 1.0 / dcg if dcg > 0.0 else 0.0

        # bucket queries by padded length
        max_m = int(sizes.max()) if len(sizes) else 1
        self.buckets = []
        pos_of_row = np.zeros(num_data, dtype=np.int64)
        base = 0
        m = 1
        while m < 8:
            m *= 2
        bucket_lengths = []
        while True:
            bucket_lengths.append(m)
            if m >= max_m:
                break
            m *= 2
        for m in bucket_lengths:
            qsel = np.nonzero((sizes <= m) & (sizes > (m // 2 if m > bucket_lengths[0] else 0)))[0]
            if len(qsel) == 0:
                continue
            doc_idx = np.full((len(qsel), m), num_data, dtype=np.int64)  # sentinel
            for r, q in enumerate(qsel):
                n = int(sizes[q])
                doc_idx[r, :n] = np.arange(qb[q], qb[q + 1])
                pos_of_row[qb[q]:qb[q + 1]] = base + r * m + np.arange(n)
            self.buckets.append({
                "doc_idx": jnp.asarray(doc_idx, jnp.int32),
                "mask": jnp.asarray(doc_idx < num_data),
                "inv_max_dcg": jnp.asarray(inv_max_dcg[qsel], jnp.float32),
                "m": m,
                "base": base,
            })
            base += doc_idx.size
        self.total_slots = base
        self._pos_of_row_np = pos_of_row
        self.pos_of_row = jnp.asarray(pos_of_row, jnp.int32)
        self.label_gain_dev = jnp.asarray(self.label_gain, jnp.float32)
        # set by gbdt.set_row_layout under pre-partitioned block layouts
        self._row_positions_dev = None
        self._slot_of_device_row = None

    def set_row_layout(self, positions: np.ndarray, npad: int) -> None:
        """Pre-partitioned device layout hook (boosting/gbdt.py): global row
        g lives at padded-device position positions[g], with per-process
        block padding interleaved. Rebuilds the two gathers so gradients()
        reads scores from and writes grad/hess to the real positions —
        the reference analog is Metadata::CheckOrPartition re-indexing
        queries onto the local used-row set (src/io/metadata.cpp:97-127)."""
        positions = np.asarray(positions, np.int64)
        self._row_positions_dev = jnp.asarray(positions, jnp.int32)
        slot = np.full(npad, self.total_slots, dtype=np.int64)  # -> zero slot
        slot[positions] = self._pos_of_row_np
        self._slot_of_device_row = jnp.asarray(slot, jnp.int32)

    def _query_grads(self, s, l, mask, inv_max_dcg):
        """One padded query: s,l,mask [M]; returns (g, h) [M] in doc order."""
        M = s.shape[0]
        sig = self.config.sigmoid
        neg = jnp.float32(-1e30)
        s_m = jnp.where(mask, s, neg)
        order = jnp.argsort(-s_m)                       # sorted positions -> doc slot
        s_s = s_m[order]
        l_s = jnp.where(mask[order], l[order], 0).astype(jnp.int32)
        valid_s = mask[order]
        gain = self.label_gain_dev[l_s]
        disc = 1.0 / jnp.log2(jnp.arange(M, dtype=jnp.float32) + 2.0)
        n_valid = jnp.sum(valid_s.astype(jnp.int32))
        best = s_s[0]
        worst = s_s[jnp.maximum(n_valid - 1, 0)]

        ds = s_s[:, None] - s_s[None, :]                # high=i, low=j
        pair_ok = (l_s[:, None] > l_s[None, :]) & valid_s[:, None] & valid_s[None, :]
        dcg_gap = gain[:, None] - gain[None, :]
        paired_disc = jnp.abs(disc[:, None] - disc[None, :])
        delta_ndcg = dcg_gap * paired_disc * inv_max_dcg
        delta_ndcg = jnp.where(best != worst,
                               delta_ndcg / (0.01 + jnp.abs(ds)), delta_ndcg)
        p_lambda = 2.0 / (1.0 + jnp.exp(2.0 * sig * ds))
        p_hess = p_lambda * (2.0 - p_lambda)
        lam = jnp.where(pair_ok, -p_lambda * delta_ndcg, 0.0)
        hes = jnp.where(pair_ok, 2.0 * p_hess * delta_ndcg, 0.0)
        g_sorted = lam.sum(axis=1) - lam.sum(axis=0)
        h_sorted = hes.sum(axis=1) + hes.sum(axis=0)
        # unsort back to doc-slot order
        g = jnp.zeros(M, jnp.float32).at[order].set(g_sorted)
        h = jnp.zeros(M, jnp.float32).at[order].set(h_sorted)
        return g, h

    def gradients(self, score, label, weight):
        # scores may arrive padded to a chunk multiple (boosting/gbdt.py);
        # the query structure only covers the first num_data rows (or, under
        # a pre-partitioned block layout, the positions set_row_layout gave)
        n = self.num_data
        if self._row_positions_dev is not None:
            s_flat = score[0][self._row_positions_dev]
            l_flat = label[self._row_positions_dev]
        else:
            s_flat = score[0, :n]
            l_flat = label[:n]
        s_ext = jnp.concatenate([s_flat, jnp.zeros(1, s_flat.dtype)])
        l_ext = jnp.concatenate([l_flat, jnp.zeros(1, label.dtype)])
        parts = []
        for b in self.buckets:
            m = b["m"]
            chunk_q = max(1, self.QUERY_CHUNK_BUDGET // (m * m))
            di, mask, imd = b["doc_idx"], b["mask"], b["inv_max_dcg"]
            nq = di.shape[0]
            pad_q = (-nq) % chunk_q
            if pad_q:
                di = jnp.concatenate([di, jnp.full((pad_q, m), n, jnp.int32)])
                mask = jnp.concatenate([mask, jnp.zeros((pad_q, m), bool)])
                imd = jnp.concatenate([imd, jnp.zeros(pad_q, jnp.float32)])
            sq = s_ext[di]
            lq = l_ext[di]

            def batch(args):
                sqc, lqc, maskc, imdc = args
                return jax.vmap(self._query_grads)(sqc, lqc, maskc, imdc)

            gq, hq = jax.lax.map(
                batch,
                (sq.reshape(-1, chunk_q, m), lq.reshape(-1, chunk_q, m),
                 mask.reshape(-1, chunk_q, m), imd.reshape(-1, chunk_q)))
            parts.append((gq.reshape(-1)[: nq * m], hq.reshape(-1)[: nq * m]))
        g_cat = jnp.concatenate([p[0] for p in parts])
        h_cat = jnp.concatenate([p[1] for p in parts])
        if self._slot_of_device_row is not None:
            # one gather lands grad/hess at their device positions; padding
            # rows point at the appended zero slot
            gx = jnp.concatenate([g_cat, jnp.zeros(1, g_cat.dtype)])
            hx = jnp.concatenate([h_cat, jnp.zeros(1, h_cat.dtype)])
            g = gx[self._slot_of_device_row]
            h = hx[self._slot_of_device_row]
            pad = score.shape[1] - self._slot_of_device_row.shape[0]
        else:
            g = g_cat[self.pos_of_row]
            h = h_cat[self.pos_of_row]
            pad = score.shape[1] - n
        if pad:
            g = jnp.concatenate([g, jnp.zeros(pad, g.dtype)])
            h = jnp.concatenate([h, jnp.zeros(pad, h.dtype)])
        g = g[None, :]
        h = h[None, :]
        if weight is not None:
            g = g * weight
            h = h * weight
        return g, h


OBJECTIVE_ALIASES = {
    "regression": "regression", "regression_l2": "regression", "mean_squared_error": "regression",
    "mse": "regression", "l2": "regression", "l2_root": "regression", "rmse": "regression",
    "regression_l1": "regression_l1", "mean_absolute_error": "regression_l1",
    "l1": "regression_l1", "mae": "regression_l1",
    "huber": "huber", "fair": "fair", "poisson": "poisson",
    "binary": "binary",
    "multiclass": "multiclass", "softmax": "multiclass",
    "multiclassova": "multiclassova", "multiclass_ova": "multiclassova",
    "ova": "multiclassova", "ovr": "multiclassova",
    "xentropy": "xentropy", "cross_entropy": "xentropy",
    "xentlambda": "xentlambda", "cross_entropy_lambda": "xentlambda",
    "lambdarank": "lambdarank",
    "none": "none", "null": "none", "custom": "none", "na": "none",
}

_OBJECTIVE_CLASSES = {
    "regression": RegressionL2,
    "regression_l1": RegressionL1,
    "huber": RegressionHuber,
    "fair": RegressionFair,
    "poisson": RegressionPoisson,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "xentropy": CrossEntropy,
    "xentlambda": CrossEntropyLambda,
    "lambdarank": LambdarankNDCG,
}


def create_objective(config: Config) -> Optional[Objective]:
    """Factory (reference: objective_function.cpp:11-33)."""
    name = OBJECTIVE_ALIASES.get(config.objective)
    if name is None:
        Log.fatal("Unknown objective type name: %s", config.objective)
    if name == "none":
        return None
    return _OBJECTIVE_CLASSES[name](config)
