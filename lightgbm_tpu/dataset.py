"""Binned Dataset: the training matrix as a dense device-resident bin matrix.

Reference counterpart: include/LightGBM/dataset.h:280 (Dataset),
dataset.h:36-248 (Metadata), src/io/dataset_loader.cpp (construction flow).

TPU-first inversion of the reference design: instead of per-feature-group
Bin objects with sparse/dense/4-bit variants and leaf-ordered copies
(src/io/dense_bin.hpp, sparse_bin.hpp, ordered_sparse_bin.hpp), the whole
dataset is ONE dense `uint8/uint16 [num_data, num_features]` array in HBM.
Sparsity is irrelevant to the MXU histogram kernel (a zero bin costs the same
as any bin), so the sparse/dense split and `sparse_threshold` become no-ops
kept only for config compatibility. Per-feature bin counts stay variable;
`bin_offsets` flattens (feature, bin) into one axis for split scans.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from .binning import (BIN_CATEGORICAL, BIN_NUMERICAL, MISSING_NAN, MISSING_NONE,
                      MISSING_ZERO, BinMapper, sample_for_binning)
from .config import Config
from .utils.log import Log


class Metadata:
    """Labels / weights / query boundaries / init scores
    (reference: dataset.h:36-248, src/io/metadata.cpp)."""

    def __init__(self, num_data: int):
        self.num_data = num_data
        self.label = np.zeros(num_data, dtype=np.float32)
        self.weight: Optional[np.ndarray] = None
        self.query_boundaries: Optional[np.ndarray] = None
        self.query_weights: Optional[np.ndarray] = None
        self.init_score: Optional[np.ndarray] = None

    def set_label(self, label: Sequence[float]) -> None:
        label = np.asarray(label, dtype=np.float32).reshape(-1)
        if len(label) != self.num_data:
            Log.fatal("Length of label (%d) != num_data (%d)", len(label), self.num_data)
        self.label = label

    def set_weight(self, weight: Optional[Sequence[float]]) -> None:
        if weight is None:
            self.weight = None
            return
        weight = np.asarray(weight, dtype=np.float32).reshape(-1)
        if len(weight) != self.num_data:
            Log.fatal("Length of weight (%d) != num_data (%d)", len(weight), self.num_data)
        self.weight = weight

    def set_group(self, group: Optional[Sequence[int]]) -> None:
        """`group` is per-query sizes (python API) -> boundaries
        (reference: metadata.cpp SetQuery)."""
        if group is None:
            self.query_boundaries = None
            return
        group = np.asarray(group, dtype=np.int64).reshape(-1)
        boundaries = np.concatenate([[0], np.cumsum(group)])
        if boundaries[-1] != self.num_data:
            Log.fatal("Sum of query counts (%d) != num_data (%d)", boundaries[-1], self.num_data)
        self.query_boundaries = boundaries.astype(np.int32)

    def set_init_score(self, init_score: Optional[Sequence[float]]) -> None:
        if init_score is None:
            self.init_score = None
            return
        self.init_score = np.asarray(init_score, dtype=np.float64).reshape(-1)

    @property
    def num_queries(self) -> int:
        return 0 if self.query_boundaries is None else len(self.query_boundaries) - 1


@dataclass
class FeatureInfo:
    """Construction-time info for one used (non-trivial) feature."""
    real_index: int            # column in the raw input
    mapper: BinMapper


@dataclass
class DeferredBinning:
    """Raw dense rows held in place of a materialized ``X_binned``
    (``tpu_ingest=device|auto``): the booster bins them ON DEVICE
    (ops/ingest.py) straight into the residency layout, and the host bin
    matrix only ever exists if some consumer explicitly reads the
    ``X_binned`` property (EFB materialization, save_binary, streaming
    residency — each a transparent host fallback through the oracle).
    ``raw`` stays referenced while deferred — the memory trade is the raw
    f32/f64 matrix instead of u8/u16 codes, bounded by the same host RAM
    that held the raw input to begin with."""
    raw: np.ndarray            # [num_data, num_total_features] dense
    code_dtype: np.dtype       # uint8 | uint16 — decided at construction


class MetadataDuckTyping:
    """Duck-typed reference-Dataset surface over ``self.metadata`` — custom
    objectives and eval functions written against the reference contract
    (fobj(preds, train_data) -> grad, hess; feval(preds, eval_data);
    reference basic.py Dataset.get_label) receive objects with this mixin
    from the boosting loop."""

    def get_label(self):
        return self.metadata.label

    def get_weight(self):
        return self.metadata.weight

    def get_group(self):
        qb = self.metadata.query_boundaries
        return None if qb is None else np.diff(qb)

    def get_init_score(self):
        return self.metadata.init_score


class ConstructedDataset(MetadataDuckTyping):
    """The binned dataset (reference Dataset, dataset.h:280).

    Attributes
    ----------
    X_binned : np.ndarray [num_data, num_features] uint8|uint16
        per-feature bin codes of the used (non-trivial) features.
    mappers : list[BinMapper], one per used feature.
    real_feature_idx : used feature -> raw column index
        (reference: dataset.h:552 real_feature_idx_).
    used_feature_map : raw column -> used feature index or -1
        (reference: dataset.h:543 used_feature_map_).
    bin_offsets : int32 [num_features+1]
        flattened (feature, bin) offsets; total_bins = bin_offsets[-1].
    """

    def __init__(self, X_binned: Optional[np.ndarray],
                 features: List[FeatureInfo],
                 num_total_features: int, metadata: Metadata,
                 feature_names: List[str], config: Config,
                 deferred: Optional[DeferredBinning] = None):
        # X_binned=None defers host binning (DeferredBinning): shape and
        # code dtype are pinned NOW so every metadata read stays free of a
        # materialization, and the X_binned property bins lazily through
        # the host oracle only if something actually needs host codes
        self._X_binned = X_binned
        self._deferred = deferred if X_binned is None else None
        if X_binned is not None:
            self._shape = tuple(X_binned.shape)
            self._code_dtype = X_binned.dtype
        else:
            assert deferred is not None
            self._shape = (metadata.num_data, max(len(features), 1))
            self._code_dtype = np.dtype(deferred.code_dtype)
        self.mappers = [f.mapper for f in features]
        self.real_feature_idx = np.array([f.real_index for f in features], dtype=np.int32)
        self.used_feature_map = np.full(num_total_features, -1, dtype=np.int32)
        for inner, f in enumerate(features):
            self.used_feature_map[f.real_index] = inner
        self.num_total_features = num_total_features
        self.metadata = metadata
        self.feature_names = feature_names
        self.config = config
        counts = np.array([m.num_bin for m in self.mappers], dtype=np.int64)
        self.bin_offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int32)
        self.num_bins_per_feature = counts.astype(np.int32)
        # raw f32 slice of the used features (linear_tree=true only,
        # ops/linear.py): the per-leaf ridge fits read raw values, which
        # binning otherwise discards — construct_dataset fills it when the
        # config asks for linear trees; None everywhere else (zero cost)
        self.X_raw: Optional[np.ndarray] = None
        # sharded device residency (boosting/gbdt.py): the padded binned
        # code matrix placed on the booster's mesh, cached per placement
        # key so the dataset's device residency is first-class — every
        # booster built over the same mesh/padding reuses the SAME device
        # buffers instead of re-uploading N*F bytes per construction
        self._device_cache: Dict[tuple, object] = {}

    # -- lazy bin matrix (tpu_ingest: ops/ingest.py) --------------------------

    @property
    def X_binned(self) -> np.ndarray:
        """The host bin matrix. Under deferred ingest the first read
        materializes it through the host oracle (single pass per column,
        value_to_bin ``out=``) — every legacy consumer keeps working, it
        just pays host binning the way it always did."""
        if self._X_binned is None:
            self._X_binned = self._materialize_host()
        return self._X_binned

    @X_binned.setter
    def X_binned(self, value: np.ndarray) -> None:
        self._X_binned = value
        self._deferred = None
        self._shape = tuple(value.shape)
        self._code_dtype = value.dtype

    @property
    def deferred(self) -> bool:
        """True while binning is deferred (no host ``X_binned`` exists)."""
        return self._X_binned is None

    @property
    def code_dtype(self) -> np.dtype:
        """Bin-code dtype — readable without materializing."""
        return self._code_dtype

    def deferred_raw(self) -> Optional[np.ndarray]:
        """The raw matrix backing a still-deferred dataset (None once
        materialized) — the device ingest input."""
        return self._deferred.raw if self._deferred is not None else None

    def bin_rows(self, rows: np.ndarray) -> np.ndarray:
        """Host-oracle codes of specific rows, BYTE-identical to
        ``np.ascontiguousarray(self.X_binned[rows])`` whether or not the
        matrix is materialized — the checkpoint data fingerprint and the
        EFB planning sample read through this so their bytes are invariant
        to ``tpu_ingest`` (the knob is checkpoint-VOLATILE)."""
        if self._X_binned is not None:
            return np.ascontiguousarray(self._X_binned[rows])
        sub = self._deferred.raw[rows]
        out = np.zeros((sub.shape[0], self.num_features), self._code_dtype)
        for inner, real in enumerate(self.real_feature_idx):
            self.mappers[inner].value_to_bin(sub[:, real], out=out[:, inner])
        return out

    def _materialize_host(self) -> np.ndarray:
        d = self._deferred
        Log.info("deferred binning: materializing host X_binned "
                 "(%d x %d %s) through the host oracle",
                 self._shape[0], self._shape[1], self._code_dtype)
        X = bin_dense_host(d.raw, self.mappers,
                           np.asarray(self.real_feature_idx),
                           self._code_dtype, self._shape[0])
        self._deferred = None
        return X

    # -- shape ----------------------------------------------------------------

    @property
    def num_data(self) -> int:
        return int(self._shape[0])

    @property
    def num_features(self) -> int:
        return int(self._shape[1])

    @property
    def total_bins(self) -> int:
        return int(self.bin_offsets[-1])

    @property
    def max_num_bin(self) -> int:
        return int(self.num_bins_per_feature.max()) if self.num_features else 1

    # -- feature metadata for the split kernels -------------------------------

    def feature_meta_arrays(self) -> Dict[str, np.ndarray]:
        """Static per-feature arrays consumed by the split-finding kernel."""
        F = self.num_features
        is_categorical = np.array(
            [m.bin_type == BIN_CATEGORICAL for m in self.mappers], dtype=bool)
        missing_code = np.array(
            [{MISSING_NONE: 0, MISSING_ZERO: 1, MISSING_NAN: 2}[m.missing_type]
             for m in self.mappers], dtype=np.int32)
        default_bin = np.array([m.default_bin for m in self.mappers], dtype=np.int32)
        return {
            "is_categorical": is_categorical,
            "missing_code": missing_code,
            "default_bin": default_bin,
            "num_bins": self.num_bins_per_feature,
            "bin_offsets": self.bin_offsets,
        }

    # -- sharded device residency (docs/TPU-Performance.md, multichip) --------

    def device_put_cached(self, key: tuple, build):
        """Device residency cache for this dataset's immutable training
        arrays (the binned code matrix and the padding mask).

        ``key`` must capture everything that determines the placed array —
        the ParallelContext residency key (mesh devices + strategy axis),
        padded shape, dtype, and the EFB bundle signature — and ``build()``
        materializes it (host pad + ``device_put``/``NamedSharding``). The
        first booster pays the host->device transfer; every later booster
        over the same mesh gets the SAME on-device buffers (safe because
        these arrays travel as non-donated step constants,
        boosting/gbdt.py ``_STEP_CONSTS``). Mutable metadata (labels,
        weights) is deliberately NOT cached — ``set_label`` after
        construction must keep working.

        One entry per logical name (``key[0]``): switching the same Dataset
        to a different mesh/strategy/padding evicts the previous placement
        rather than pinning a second full device copy for the Dataset's
        lifetime (live boosters keep their own references; only the cache
        slot is bounded)."""
        arr = self._device_cache.get(key)
        if arr is None:
            for stale in [k for k in self._device_cache if k[0] == key[0]]:
                del self._device_cache[stale]
            arr = build()
            self._device_cache[key] = arr
        return arr

    # -- alignment (valid sets share the train mappers) -----------------------

    def bin_raw(self, data: np.ndarray) -> np.ndarray:
        """Bin a raw feature matrix with THIS dataset's mappers (the analog of
        LoadFromFileAlignWithOtherDataset, dataset_loader.cpp:221)."""
        out = np.zeros((data.shape[0], self.num_features), dtype=self.code_dtype)
        if hasattr(data, "tocsc"):
            csc = data.tocsc()
            for inner, real in enumerate(self.real_feature_idx):
                m = self.mappers[inner]
                rows, vals = _csc_column(csc, real)
                # default_bin IS the zero bin (asserted at mapper
                # construction) — no per-column value_to_bin(0) re-run
                out[:, inner] = out.dtype.type(m.default_bin)
                if len(rows):
                    out[rows, inner] = m.value_to_bin(vals)
            return out
        data = np.asarray(data)
        for inner, real in enumerate(self.real_feature_idx):
            self.mappers[inner].value_to_bin(data[:, real], out=out[:, inner])
        return out

    # -- binary serialization (reference: Dataset::SaveBinaryFile,
    #    dataset.cpp:496; auto-detect load, dataset_loader.cpp:265) ----------

    def save_binary(self, path: str) -> None:
        import pickle
        with open(path, "wb") as fh:
            pickle.dump({
                "format": "lightgbm_tpu.dataset.v1",
                "X_binned": self.X_binned,
                "mappers": self.mappers,
                "real_feature_idx": self.real_feature_idx,
                "num_total_features": self.num_total_features,
                "feature_names": self.feature_names,
                "label": self.metadata.label,
                "weight": self.metadata.weight,
                "query_boundaries": self.metadata.query_boundaries,
                "init_score": self.metadata.init_score,
                "config": self.config.to_dict(),
                "X_raw": self.X_raw,
            }, fh, protocol=pickle.HIGHEST_PROTOCOL)

    @classmethod
    def load_binary(cls, path: str) -> "ConstructedDataset":
        import pickle
        with open(path, "rb") as fh:
            blob = pickle.load(fh)
        if blob.get("format") != "lightgbm_tpu.dataset.v1":
            Log.fatal("Not a lightgbm_tpu binary dataset file: %s", path)
        meta = Metadata(blob["X_binned"].shape[0])
        meta.set_label(blob["label"])
        meta.set_weight(blob["weight"])
        meta.query_boundaries = blob["query_boundaries"]
        meta.init_score = blob["init_score"]
        features = [FeatureInfo(int(r), m)
                    for r, m in zip(blob["real_feature_idx"], blob["mappers"])]
        ds = cls(blob["X_binned"], features, blob["num_total_features"], meta,
                 blob["feature_names"], Config.from_params(blob["config"]))
        ds.X_raw = blob.get("X_raw")   # present iff saved under linear_tree
        return ds


def _map_find_bin(active: List[int], find_one) -> Dict[int, "BinMapper"]:
    """``find_one`` over every feature in ``active`` on a thread pool —
    numpy releases the GIL in the unique/searchsorted passes that dominate
    ``BinMapper.find_bin``, so quantile finding goes parallel across
    features (ROADMAP item 1's host half). The result dict's insertion
    order is EXACTLY ``active`` order regardless of completion order
    (``Executor.map`` yields in input order; pinned by test)."""
    workers = min(16, os.cpu_count() or 1, len(active))
    if workers <= 1:
        return {j: find_one(j) for j in active}
    from concurrent.futures import ThreadPoolExecutor
    with ThreadPoolExecutor(workers) as pool:
        return dict(zip(active, pool.map(find_one, active)))


def _find_bins(active: List[int], find_one,
               config: Optional[Config] = None) -> Dict[int, "BinMapper"]:
    """Run FindBin for every active feature — feature-sharded across hosts
    under DISTRIBUTED TRAINING (reference distributed bin finding:
    feature-partitioned FindBin + Allgather of serialized BinMappers,
    dataset_loader.cpp:820-899). Each process computes the mappers of the
    features it owns (round-robin by rank) and the pickled shards are
    exchanged host-side through jax's coordination-service KV store, so
    every process ends with identical mappers.

    Gated on the lightgbm network config (num_machines > 1), NOT on ambient
    jax state: a user's multi-process jax program that trains on a subset
    of ranks must not enter a collective here."""
    if config is None or getattr(config, "num_machines", 1) <= 1:
        return _map_find_bin(active, find_one)
    from .parallel import comm
    client = comm.distributed_client()
    import jax
    if client is None or jax.process_count() <= 1:
        return _map_find_bin(active, find_one)

    rank, world = jax.process_index(), jax.process_count()
    timeout_ms = int(getattr(config, "time_out", 120)) * 60 * 1000
    mine = _map_find_bin([j for j in active if j % world == rank], find_one)
    # host_allgather owns the KV exchange end to end — per-peer retry with
    # bounded backoff, typed PeerLostError attribution, chaos injection,
    # done-barrier + key cleanup (R013: raw client calls stay in comm.py)
    shards = comm.host_allgather(mine, "binmappers", timeout_ms=timeout_ms)
    out: Dict[int, BinMapper] = {}
    for shard in shards:
        out.update(shard)
    return out


def _csc_column(csc, j: int) -> Tuple[np.ndarray, np.ndarray]:
    """(row_indices, float64_values) of column ``j`` via indptr slicing —
    works for both scipy.sparse csc_matrix and the newer csc_array (which
    has no ``getcol``)."""
    lo, hi = csc.indptr[j], csc.indptr[j + 1]
    return csc.indices[lo:hi], np.asarray(csc.data[lo:hi], dtype=np.float64)


def _parse_column_spec(spec: str, feature_names: List[str]) -> List[int]:
    """Parse 'name:a,name:b' or '0,1,2' column specs
    (reference: dataset_loader.cpp column resolution)."""
    if not spec:
        return []
    out = []
    for tok in str(spec).split(","):
        tok = tok.strip()
        if not tok:
            continue
        if tok.startswith("name:"):
            name = tok[5:]
            if name not in feature_names:
                Log.fatal("Column name %s not found", name)
            out.append(feature_names.index(name))
        else:
            out.append(int(tok))
    return out


def construct_dataset(
    data: np.ndarray,
    label: Optional[Sequence[float]],
    config: Config,
    weight: Optional[Sequence[float]] = None,
    group: Optional[Sequence[int]] = None,
    init_score: Optional[Sequence[float]] = None,
    feature_names: Optional[List[str]] = None,
    categorical_features: Optional[Sequence[Union[int, str]]] = None,
) -> ConstructedDataset:
    """Build a ConstructedDataset from a raw numpy matrix.

    Mirrors DatasetLoader::ConstructBinMappersFromTextData
    (dataset_loader.cpp:748-903): sample -> FindBin per feature -> drop
    trivial features -> materialize bin codes.
    """
    sparse = hasattr(data, "tocsc")
    if sparse:
        data = data.tocsc()            # columnwise access for binning
    else:
        data = np.ascontiguousarray(data)
    if data.ndim != 2:
        Log.fatal("Training data must be 2-dimensional")
    num_data, num_total_features = data.shape
    if feature_names is None:
        feature_names = [f"Column_{i}" for i in range(num_total_features)]

    # resolve categorical / ignored columns
    cat_set = set()
    if categorical_features is not None:
        for c in categorical_features:
            cat_set.add(feature_names.index(c) if isinstance(c, str) else int(c))
    cat_set.update(_parse_column_spec(config.categorical_column, feature_names))
    ignore_set = set(_parse_column_spec(config.ignore_column, feature_names))

    # sampling (dataset_loader.cpp:688-746)
    _, per_feature_samples = sample_for_binning(
        data, config.bin_construct_sample_cnt, config.data_random_seed)
    total_sample_cnt = min(num_data, config.bin_construct_sample_cnt)
    # reference: filter_cnt = min_data_in_leaf * sample / num_data (dataset_loader.cpp:495)
    filter_cnt = int(config.min_data_in_leaf * total_sample_cnt / max(num_data, 1))

    def _find_one(j: int) -> BinMapper:
        mapper = BinMapper()
        bin_type = BIN_CATEGORICAL if j in cat_set else BIN_NUMERICAL
        mapper.find_bin(per_feature_samples[j], total_sample_cnt,
                        config.max_bin, config.min_data_in_bin, filter_cnt,
                        bin_type, config.use_missing, config.zero_as_missing)
        return mapper

    active = [j for j in range(num_total_features) if j not in ignore_set]
    mappers_by_idx = _find_bins(active, _find_one, config)
    features: List[FeatureInfo] = [
        FeatureInfo(j, mappers_by_idx[j]) for j in active
        if not mappers_by_idx[j].is_trivial]
    if not features:
        Log.warning("There are no meaningful features, as all feature values are constant.")

    dtype = np.uint8 if all(f.mapper.num_bin <= 256 for f in features) else np.uint16

    deferred = _maybe_defer(data, features, config, dtype, num_data, sparse)
    if deferred is not None:
        X_binned = None
    elif sparse:
        X_binned = np.zeros((num_data, max(len(features), 1)), dtype=dtype)

        def _bin_column(inner_f):
            # bin the implicit zeros once, scatter only the stored values
            # (the float matrix is never densified; the dense uint8 bin
            # matrix IS the design's storage — dataset.py:6-14); the zero
            # bin is default_bin (asserted at mapper construction), and
            # the fancy-index assignment casts to the output dtype in one
            # pass
            inner, f = inner_f
            rows, vals = _csc_column(data, f.real_index)
            X_binned[:, inner] = dtype(f.mapper.default_bin)
            if len(rows):
                X_binned[rows, inner] = f.mapper.value_to_bin(vals)

        if num_data * max(len(features), 1) > 8_000_000 and len(features) > 1:
            from concurrent.futures import ThreadPoolExecutor
            workers = min(16, os.cpu_count() or 1, len(features))
            with ThreadPoolExecutor(workers) as pool:
                list(pool.map(_bin_column, enumerate(features)))
        else:
            for item in enumerate(features):
                _bin_column(item)
    else:
        X_binned = bin_dense_host(
            data, [f.mapper for f in features],
            np.array([f.real_index for f in features], np.int64),
            dtype, num_data)

    metadata = Metadata(num_data)
    if label is not None:
        metadata.set_label(label)
    metadata.set_weight(weight)
    metadata.set_group(group)
    metadata.set_init_score(init_score)

    ds = ConstructedDataset(X_binned, features, num_total_features, metadata,
                            feature_names, config, deferred=deferred)
    if getattr(config, "linear_tree", False):
        ds.X_raw = extract_raw_slice(
            data, [f.real_index for f in features], num_data)
    return ds


def bin_dense_host(data: np.ndarray, mappers, real_indices: np.ndarray,
                   dtype, num_data: int) -> np.ndarray:
    """Dense host binning: one ``value_to_bin`` pass per column, written
    straight into the output dtype (``out=``) — no int32 intermediate +
    astype + assignment-copy chain. This IS the host oracle the device
    ingest path (ops/ingest.py) is tested against bit-for-bit, and the
    lazy materialization target of a deferred dataset."""
    F = max(len(real_indices), 1)
    X_binned = np.zeros((num_data, F), dtype=dtype)
    big = num_data * F > 8_000_000

    def _bin_column(inner: int):
        col = data[:, real_indices[inner]]
        if big:
            # one contiguous copy per column: value_to_bin makes several
            # full passes and a stride-F read thrashes cache on each
            col = np.ascontiguousarray(col)
        mappers[inner].value_to_bin(col, out=X_binned[:, inner])

    # numpy releases the GIL in the heavy passes — threads help on
    # multi-core hosts (the analog of the reference's OMP row-parallel push
    # loop, dataset_loader.cpp:906-1101) and pick 1 worker on 1-core boxes
    if big and len(real_indices) > 1:
        from concurrent.futures import ThreadPoolExecutor
        workers = min(16, os.cpu_count() or 1, len(real_indices))
        with ThreadPoolExecutor(workers) as pool:
            list(pool.map(_bin_column, range(len(real_indices))))
    else:
        for inner in range(len(real_indices)):
            _bin_column(inner)
    return X_binned


# minimum rows before tpu_ingest=auto defers to device binning: below this
# the jit compile + chunk dispatch overhead outweighs the host pass
_AUTO_DEFER_MIN_ROWS = 65536


def _maybe_defer(data, features, config: Config, dtype, num_data: int,
                 sparse: bool) -> Optional[DeferredBinning]:
    """Decide at construction whether to SKIP host binning and hand the
    booster raw rows for on-device ingest (ops/ingest.py). Numpy-only:
    the eligibility check never touches jax. ``device`` defers whenever
    the input is eligible (warns and falls back otherwise); ``auto``
    additionally requires enough rows to amortize the compile."""
    mode = getattr(config, "tpu_ingest", "host")
    if mode not in ("device", "auto") or sparse or not features:
        return None
    from .ops.ingest import device_ingest_blocker
    blocker = device_ingest_blocker(data, [f.mapper for f in features])
    if blocker is None and mode == "auto" and num_data < _AUTO_DEFER_MIN_ROWS:
        blocker = (f"tpu_ingest=auto defers only at >= "
                   f"{_AUTO_DEFER_MIN_ROWS} rows (got {num_data})")
    if blocker is not None:
        if mode == "device":
            Log.warning("tpu_ingest=device: falling back to host binning "
                        "(%s)", blocker)
        else:
            Log.debug("tpu_ingest=auto: host binning (%s)", blocker)
        return None
    Log.debug("tpu_ingest=%s: deferring binning to device ingest "
              "(%d rows x %d features)", mode, num_data, len(features))
    return DeferredBinning(raw=data, code_dtype=np.dtype(dtype))


def extract_raw_slice(data, real_indices, num_data: int) -> np.ndarray:
    """[N, used_features] f32 raw values (NaN preserved) for linear-tree
    fits — the used-feature column slice of the input, densified from
    sparse inputs column-by-column (implicit zeros stay numeric 0.0, so
    only true NaNs take the constant-leaf fallback)."""
    out = np.zeros((num_data, max(len(real_indices), 1)), np.float32)
    if hasattr(data, "tocsc"):
        csc = data.tocsc()
        for inner, real in enumerate(real_indices):
            rows, vals = _csc_column(csc, real)
            if len(rows):
                out[rows, inner] = vals.astype(np.float32)
        return out
    data = np.asarray(data)
    for inner, real in enumerate(real_indices):
        out[:, inner] = np.asarray(data[:, real], np.float32)
    return out
