"""Training callbacks (reference: python-package/lightgbm/callback.py:49-210)."""
from __future__ import annotations

import collections
from typing import Callable, Dict, List

from .utils.log import Log

CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


def log_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    """print_evaluation in the reference. The period check is
    interval-CROSSING, not modulo: under fused multi-tree steps
    (tree_batch>1) callbacks only see batch-boundary iteration numbers,
    which may never hit an exact multiple of ``period`` (identical firing
    at tree_batch=1)."""
    state = {"last": 0}

    def _callback(env: CallbackEnv) -> None:
        if env.iteration + 1 < state["last"]:
            # the callback object was reused across train() calls (common
            # CV/fold loops): iterations restarted below the recorded
            # crossing point, so reset — otherwise every later run logs
            # nothing until it passes the previous run's last iteration
            state["last"] = 0
        if (period > 0 and env.evaluation_result_list
                and env.iteration + 1 - state["last"] >= period):
            state["last"] = env.iteration + 1
            result = "\t".join(
                f"{name}'s {metric}: {value:g}"
                for name, metric, value, _ in env.evaluation_result_list)
            Log.info("[%d]\t%s", env.iteration + 1, result)
    _callback.order = 10
    return _callback


print_evaluation = log_evaluation


def record_evaluation(eval_result: Dict) -> Callable:
    def _callback(env: CallbackEnv) -> None:
        for name, metric, value, _ in env.evaluation_result_list:
            eval_result.setdefault(name, collections.OrderedDict()) \
                       .setdefault(metric, []).append(value)
    _callback.order = 20
    return _callback


def reset_parameter(**kwargs) -> Callable:
    """Per-iteration parameter schedules (reference callback.py reset_parameter).
    Supports learning_rate as list or callable(iteration)."""
    def _callback(env: CallbackEnv) -> None:
        new_params = {}
        for key, value in kwargs.items():
            if callable(value):
                new_params[key] = value(env.iteration - env.begin_iteration)
            elif isinstance(value, (list, tuple)):
                new_params[key] = value[env.iteration - env.begin_iteration]
            else:
                new_params[key] = value
        if new_params:
            env.model.reset_parameter(new_params)
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True) -> Callable:
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List = []
    cmp_op: List[Callable] = []

    def _init(env: CallbackEnv) -> None:
        if not env.evaluation_result_list:
            Log.fatal("For early stopping, at least one dataset and eval metric "
                      "is required for evaluation")
        for _name, _metric, _value, hib in env.evaluation_result_list:
            best_iter.append(0)
            if hib:
                best_score.append(float("-inf"))
                cmp_op.append(lambda a, b: a > b)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lambda a, b: a < b)
            best_score_list.append(None)

    def _callback(env: CallbackEnv) -> None:
        if not env.evaluation_result_list:
            return  # non-eval iteration (metric_freq > 1)
        if not best_score:
            _init(env)
        for i, (name, metric, value, _hib) in enumerate(env.evaluation_result_list):
            if best_score_list[i] is None or cmp_op[i](value, best_score[i]):
                best_score[i] = value
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            elif env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    Log.info("Early stopping, best iteration is: [%d]", best_iter[i] + 1)
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if first_metric_only:
                break
    _callback.order = 30
    return _callback
