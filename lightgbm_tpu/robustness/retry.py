"""Bounded retry with exponential backoff + jitter for host-side comm ops.

The coordination-service KV exchanges in ``parallel/comm.py``
(``host_allgather``, ``init_distributed``) previously made exactly one
attempt: a transient coordination-service hiccup — routine during pod
startup and preemption churn — killed the whole run. Every attempt here is
logged (never swallowed), the final failure carries the operation
description, and the backoff schedule is tunable through environment
variables so operators can match it to their cluster's restart behavior:

- ``LGBM_TPU_COMM_RETRIES``        total attempts (default 3)
- ``LGBM_TPU_COMM_BACKOFF_BASE``   first delay, seconds (default 0.5)
- ``LGBM_TPU_COMM_BACKOFF_MAX``    delay ceiling, seconds (default 30)
- ``LGBM_TPU_COMM_BACKOFF_JITTER`` jitter fraction on top (default 0.25)
- ``LGBM_TPU_COMM_JITTER_SEED``    seed the jitter RNG (chaos runs replay
                                   the exact backoff schedule; unset =
                                   process-global randomness)

The terminal failure names the operation AND the cost of trying: the
attempt count and the cumulative backoff wall-clock ride in both the final
warning and the raised ``CommRetryError``, so a post-mortem shows how long
was burned retrying before the run died. Deterministic tests pass an
explicitly seeded ``rng`` and a fake ``sleep`` (or set the seed env knob).
"""
from __future__ import annotations

import os
import random
import time
from typing import Callable, Optional, Tuple, Type

from ..utils.log import Log


class CommRetryError(RuntimeError):
    """All retry attempts of a communication operation failed."""


class CommTimeoutError(CommRetryError):
    """A communication operation timed out waiting on a peer; the message
    names the tag/sequence and both ranks involved."""


class PeerLostError(CommTimeoutError):
    """A specific peer rank is gone — its heartbeat lease expired or it
    never answered inside the collective deadline. ``rank`` names the lost
    peer so fleet restart policy can attribute the failure (exit code 145
    at the top level, vs the generic hang's 142)."""

    def __init__(self, message: str, *, rank: int):
        super().__init__(message)
        self.rank = int(rank)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        Log.warning("%s is not an integer; using default %d", name, default)
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        Log.warning("%s is not a number; using default %g", name, default)
        return default


def comm_attempts() -> int:
    """Total attempts the ``LGBM_TPU_COMM_RETRIES`` knob currently specifies
    — callers splitting a fixed timeout budget across attempts (the
    ``host_allgather`` gets) read it through this."""
    return max(1, _env_int("LGBM_TPU_COMM_RETRIES", 3))


def retry_call(fn: Callable, *, what: str,
               attempts: Optional[int] = None,
               base_delay: Optional[float] = None,
               max_delay: Optional[float] = None,
               jitter: Optional[float] = None,
               retry_on: Tuple[Type[BaseException], ...] = (Exception,),
               sleep: Callable[[float], None] = time.sleep,
               rng: Optional[random.Random] = None):
    """Call ``fn()`` with bounded retries; backoff doubles per attempt.

    ``what`` names the operation in log lines and the terminal error
    (e.g. ``"host_allgather get tag='efb_sample' seq=3 rank=0<-2"``).
    Defaults come from the ``LGBM_TPU_COMM_*`` env knobs, read at call
    time so tests and operators can adjust a live process.
    """
    attempts = attempts if attempts is not None else comm_attempts()
    base = base_delay if base_delay is not None else \
        _env_float("LGBM_TPU_COMM_BACKOFF_BASE", 0.5)
    ceil = max_delay if max_delay is not None else \
        _env_float("LGBM_TPU_COMM_BACKOFF_MAX", 30.0)
    jit = jitter if jitter is not None else \
        _env_float("LGBM_TPU_COMM_BACKOFF_JITTER", 0.25)
    if rng is None:
        # seedable jitter: with LGBM_TPU_COMM_JITTER_SEED set (the chaos
        # harness pins it) every retry_call draws the identical backoff
        # schedule, so a failing chaos run replays bit-for-bit. A
        # malformed seed is WARNED about, never silently ignored — the
        # operator asked for replayability and would not get it
        seed = os.environ.get("LGBM_TPU_COMM_JITTER_SEED")
        rng = random
        if seed:
            try:
                rng = random.Random(int(seed))
            except ValueError:
                Log.warning("LGBM_TPU_COMM_JITTER_SEED=%r is not an "
                            "integer; backoff jitter is UNSEEDED (this "
                            "run will not replay exactly)", seed)
    from ..observability import get_registry
    reg = get_registry()
    last: Optional[BaseException] = None
    total_wait = 0.0
    for attempt in range(attempts):
        try:
            return fn()
        except retry_on as e:                                # noqa: PERF203
            last = e
            if attempt == attempts - 1:
                break
            delay = min(base * (2.0 ** attempt), ceil)
            delay *= 1.0 + jit * rng.random()
            total_wait += delay
            # telemetry: every retry is counted (the JSONL stream carries
            # the counter snapshot; the warning below carries the story)
            reg.counter("comm.retries").inc()
            Log.warning("%s failed (attempt %d/%d: %s: %s) — retrying in "
                        "%.3fs", what, attempt + 1, attempts,
                        type(last).__name__, last, delay)
            sleep(delay)
    reg.counter("comm.failures").inc()
    reg.histogram("comm.retry_wait_seconds").observe(total_wait)
    # the terminal failure must not hide what the retrying COST: the
    # attempt count and cumulative backoff ride in the log and the error
    Log.warning("%s failed permanently: %d attempt(s), %.3fs cumulative "
                "backoff (%s: %s)", what, attempts, total_wait,
                type(last).__name__, last)
    raise CommRetryError(
        f"{what} failed after {attempts} attempt(s) and {total_wait:.3f}s "
        f"of backoff: {type(last).__name__}: {last}") from last
