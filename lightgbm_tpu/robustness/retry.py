"""Bounded retry with exponential backoff + jitter for host-side comm ops.

The coordination-service KV exchanges in ``parallel/comm.py``
(``host_allgather``, ``init_distributed``) previously made exactly one
attempt: a transient coordination-service hiccup — routine during pod
startup and preemption churn — killed the whole run. Every attempt here is
logged (never swallowed), the final failure carries the operation
description, and the backoff schedule is tunable through environment
variables so operators can match it to their cluster's restart behavior:

- ``LGBM_TPU_COMM_RETRIES``        total attempts (default 3)
- ``LGBM_TPU_COMM_BACKOFF_BASE``   first delay, seconds (default 0.5)
- ``LGBM_TPU_COMM_BACKOFF_MAX``    delay ceiling, seconds (default 30)
- ``LGBM_TPU_COMM_BACKOFF_JITTER`` jitter fraction on top (default 0.25)

Deterministic tests pass an explicitly seeded ``rng`` and a fake ``sleep``.
"""
from __future__ import annotations

import os
import random
import time
from typing import Callable, Optional, Tuple, Type

from ..utils.log import Log


class CommRetryError(RuntimeError):
    """All retry attempts of a communication operation failed."""


class CommTimeoutError(CommRetryError):
    """A communication operation timed out waiting on a peer; the message
    names the tag/sequence and both ranks involved."""


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        Log.warning("%s is not an integer; using default %d", name, default)
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        Log.warning("%s is not a number; using default %g", name, default)
        return default


def comm_attempts() -> int:
    """Total attempts the ``LGBM_TPU_COMM_RETRIES`` knob currently specifies
    — callers splitting a fixed timeout budget across attempts (the
    ``host_allgather`` gets) read it through this."""
    return max(1, _env_int("LGBM_TPU_COMM_RETRIES", 3))


def retry_call(fn: Callable, *, what: str,
               attempts: Optional[int] = None,
               base_delay: Optional[float] = None,
               max_delay: Optional[float] = None,
               jitter: Optional[float] = None,
               retry_on: Tuple[Type[BaseException], ...] = (Exception,),
               sleep: Callable[[float], None] = time.sleep,
               rng: Optional[random.Random] = None):
    """Call ``fn()`` with bounded retries; backoff doubles per attempt.

    ``what`` names the operation in log lines and the terminal error
    (e.g. ``"host_allgather get tag='efb_sample' seq=3 rank=0<-2"``).
    Defaults come from the ``LGBM_TPU_COMM_*`` env knobs, read at call
    time so tests and operators can adjust a live process.
    """
    attempts = attempts if attempts is not None else comm_attempts()
    base = base_delay if base_delay is not None else \
        _env_float("LGBM_TPU_COMM_BACKOFF_BASE", 0.5)
    ceil = max_delay if max_delay is not None else \
        _env_float("LGBM_TPU_COMM_BACKOFF_MAX", 30.0)
    jit = jitter if jitter is not None else \
        _env_float("LGBM_TPU_COMM_BACKOFF_JITTER", 0.25)
    rng = rng if rng is not None else random
    from ..observability import get_registry
    reg = get_registry()
    last: Optional[BaseException] = None
    for attempt in range(attempts):
        try:
            return fn()
        except retry_on as e:                                # noqa: PERF203
            last = e
            if attempt == attempts - 1:
                break
            delay = min(base * (2.0 ** attempt), ceil)
            delay *= 1.0 + jit * rng.random()
            # telemetry: every retry is counted (the JSONL stream carries
            # the counter snapshot; the warning below carries the story)
            reg.counter("comm.retries").inc()
            Log.warning("%s failed (attempt %d/%d: %s: %s) — retrying in "
                        "%.3fs", what, attempt + 1, attempts,
                        type(last).__name__, last, delay)
            sleep(delay)
    reg.counter("comm.failures").inc()
    raise CommRetryError(
        f"{what} failed after {attempts} attempt(s): "
        f"{type(last).__name__}: {last}") from last
