"""Non-finite guards for the boosting step (``nan_policy`` semantics).

An exploding objective (custom fobj bugs, extreme init_score, lr schedules
gone wrong) poisons gradients/hessians with NaN/Inf; one poisoned iteration
silently corrupts every later tree. The guard is compiled INTO the training
step when ``nan_policy != "none"`` (boosting/gbdt.py):

- detection reduces g/h/leaf outputs to three device booleans inside the
  jitted step — the only extra host traffic is one tiny flag fetch per
  iteration, and only while the guard is enabled;
- under ``raise``/``skip_iter`` every step output is hardware-gated
  (``jnp.where(bad, input, output)``) so a poisoned iteration leaves
  scores/masks bit-identical to their pre-step values — host-side recovery
  is pure bookkeeping (pop the no-op iteration), never NaN arithmetic;
- ``clip`` sanitizes g/h and leaf outputs in-step (NaN -> 0,
  +/-Inf -> +/-CLIP_CAP) and logs that it fired.

Policies (config ``nan_policy``): ``none`` (default — guard compiled out,
the step program is byte-identical to the unguarded one), ``raise`` (fail
the run loudly, state left clean and checkpointable), ``skip_iter`` (drop
the iteration via the rollback_one_iter bookkeeping and continue),
``clip`` (sanitize and continue).
"""
from __future__ import annotations

import jax.numpy as jnp

NAN_POLICIES = ("none", "raise", "skip_iter", "clip")


class NonFiniteError(RuntimeError):
    """nan_policy="raise": non-finite values detected in the boosting step.
    Raised AFTER the poisoned iteration's no-op bookkeeping is popped, so the
    booster state is clean and checkpointable at the failure point."""

# finite stand-in for +/-Inf under nan_policy=clip: large enough to keep
# ordering signal, small enough that squares/sums stay inside f32
CLIP_CAP = 1e30

FLAG_NAMES = ("gradients", "hessians", "leaf outputs")


def nonfinite_flag(x) -> jnp.ndarray:
    """Device scalar bool: any element of ``x`` is NaN/Inf."""
    return ~jnp.all(jnp.isfinite(x))


def clip_nonfinite(x, cap: float = CLIP_CAP):
    """NaN -> 0, +/-Inf -> +/-cap, finite values untouched."""
    return jnp.clip(jnp.nan_to_num(x, nan=0.0, posinf=cap, neginf=-cap),
                    -cap, cap)
