"""Atomic checkpoint store for booster training state.

Layout: one pickle file per snapshot inside ``checkpoint_dir``,

    ckpt_0000000001.pkl
    ckpt_0000000002.pkl
    ...

with monotonically increasing checkpoint ids (the id is derived from the
files already present, so a resumed process keeps counting where the killed
one stopped). Writes are write-temp-then-``os.replace`` with an fsync of
the file in between and an fsync of the DIRECTORY after the rename (the
rename itself lives in the parent directory's metadata — without the
directory fsync a crash right after ``os.replace`` can roll the rename
back and lose the snapshot): a preemption mid-write can never leave a
truncated file behind that parses as a checkpoint — at worst an orphaned
``*.tmp.*`` that the next save sweeps up. ``keep_last_n`` prunes old
snapshots after every successful save (0 keeps everything).

Every snapshot is wrapped in an integrity envelope: an 8-byte magic, the
CRC32 of the payload bytes, and the payload length, followed by the pickled
payload. ``load`` verifies the checksum before unpickling, so a truncated
or bit-flipped snapshot fails loudly instead of resuming silently-wrong
state; ``latest_verified`` walks BACK through the lineage to the newest
snapshot that verifies (the ``resume_from="auto"`` fallback — a corrupt
latest costs one checkpoint interval, not the run). Files written before
the envelope existed (bare pickles) still load, flagged as legacy.
``python -m lightgbm_tpu.robustness.checkpoint --verify DIR`` audits a
checkpoint directory from the shell (jax-free, safe on a live run).

Each payload carries a **config fingerprint** — a SHA-256 over the
training-semantics subset of the Config — and resume fails loudly when the
fingerprint of the resuming booster differs, naming the mismatched fields.
Run-control fields (paths, verbosity, the checkpoint knobs themselves,
``num_iterations`` so a run can be resumed *longer*) are excluded from the
fingerprint.

The payload schema (``FORMAT_VERSION`` 1)::

    {"format_version": 1, "checkpoint_id": int,
     "config_fingerprint": str, "config": {trainable-subset dict},
     "iteration": int, "state": {GBDT.checkpoint_state()},
     "booster": {...}, "eval_history": {...}}
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
import struct
import zlib
from typing import Dict, List, Optional, Tuple

from ..utils.log import Log

FORMAT_VERSION = 1

# Integrity envelope (format 2 on disk; the payload schema is unchanged):
#   magic(8) | crc32-of-payload(u32 LE) | payload-length(u64 LE) | payload
# A pre-envelope snapshot is a bare pickle (first byte \x80) — still
# readable, but carries no checksum to verify against.
ENVELOPE_MAGIC = b"LGBMCKP2"
_ENVELOPE = struct.Struct("<8sIQ")

_FILE_RE = re.compile(r"^ckpt_(\d{10})\.pkl$")

# Config fields with no bearing on the trained model's content: two runs
# differing only here are resumable into each other. Everything else is
# fingerprinted — a silent objective/num_leaves/seed change across a resume
# is exactly the corruption this check exists to catch.
VOLATILE_CONFIG_FIELDS = frozenset({
    # run control / IO
    "task", "data", "valid_data", "init_score_file",
    "valid_init_score_file", "snapshot_freq", "output_model",
    "output_result", "convert_model", "convert_model_language",
    "input_model", "model_format", "num_iteration_predict",
    "is_predict_leaf_index", "is_predict_contrib", "is_predict_raw_score",
    "is_save_binary_file", "verbose", "num_threads",
    # resuming a run LONGER than originally planned is the point
    "num_iterations",
    # checkpointing's own knobs (tpu_reshard_on_resume included: it gates
    # HOW a resume re-lays-out state, not what the model trains to — the
    # device-count check itself lives in restore_checkpoint_state)
    "checkpoint_dir", "checkpoint_interval", "checkpoint_keep_last_n",
    "resume_from", "tpu_reshard_on_resume",
    # out-of-core transport knobs (docs/Fault-Tolerance.md "resume with a
    # different shard size"): residency and shard size change WHERE the
    # codes live and how they move, never the math — the shard size
    # divides the padded per-device rows, so chunk boundaries, the bagging
    # RNG shapes, and every histogram fold are identical across values.
    # The one behavioral coupling (stream forces tpu_row_compact=false) is
    # covered by tpu_row_compact itself staying fingerprinted.
    "tpu_residency", "tpu_stream_shard_rows", "tpu_hbm_budget_bytes",
    # device-side ingest (ops/ingest.py): changes WHERE binning runs and
    # how raw rows travel, never the codes — device ingest is bit-identical
    # to host binning (tests/test_ingest.py) or it falls back to host
    "tpu_ingest", "tpu_ingest_chunk_rows", "tpu_ingest_prefetch",
    # self-healing knobs (robustness/watchdog.py, ops/stream.py CRC check):
    # detection-and-recovery policy, never training math — a snapshot from
    # a watchdog-aborted run resumes under any watchdog/verify settings
    "hang_timeout_s", "hang_median_factor", "hang_action",
    "tpu_stream_verify",
    # distributed fault tolerance (robustness/distributed.py): heartbeat
    # cadence, lease deadlines, and the elastic-resume permission are
    # detection/recovery policy — a gang snapshot resumes under any of
    # them (elastic in particular MUST be settable on the restart that
    # shrinks the fleet)
    "gang_heartbeat_interval_s", "gang_lease_timeout_s", "elastic",
    # cluster wiring: the restarted pod gets fresh addresses/ports
    "machines", "machine_list_file", "local_listen_port", "time_out",
    # profiling/telemetry (observability/: spans, exporters, profiler window)
    "tpu_time_tag", "tpu_profile_dir", "tpu_profile_iters", "telemetry_dir",
    # cost/memory introspection (observability/costs.py, snapshot dumps)
    "tpu_cost_analysis", "dump_snapshot",
    # serving knobs (lightgbm_tpu/serving): bucket ladder, batcher policy,
    # and the resilience knobs (admission bound, deadlines, circuit
    # breaker, probe cadence) shape INFERENCE dispatch only — a checkpoint
    # trained under any of them resumes under any other
    "serve_max_batch_rows", "serve_max_wait_ms", "serve_buckets",
    "serve_max_queue_rows", "serve_deadline_ms", "serve_breaker_failures",
    "serve_breaker_window_s", "serve_probe_interval_s",
    # linear-tree loudness knob (config.py): warning cadence only — the
    # model-changing linear knobs (linear_tree / linear_lambda /
    # linear_max_features) deliberately STAY fingerprinted
    "tpu_linear_warn_fallback",
})


class CheckpointError(RuntimeError):
    """A checkpoint could not be written, located, parsed, or validated."""


def _fsync_dir(directory: str) -> None:
    """fsync a directory's metadata (renames/unlinks inside it). Best-effort
    on platforms whose directories cannot be opened — logged, never raised:
    the snapshot itself is already fsynced and atomic either way."""
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError as e:
        Log.debug("cannot open %s for directory fsync: %s", directory, e)
        return
    try:
        os.fsync(fd)
    except OSError as e:
        Log.debug("directory fsync failed for %s: %s", directory, e)
    finally:
        os.close(fd)


def fingerprinted_config(config) -> Dict:
    """The training-semantics subset of ``config`` that the fingerprint
    covers (and that is stored in the payload for mismatch diagnostics)."""
    return {k: v for k, v in config.to_dict().items()
            if k not in VOLATILE_CONFIG_FIELDS}


def config_fingerprint(config) -> str:
    """SHA-256 over the canonical JSON of the non-volatile config fields."""
    blob = json.dumps(fingerprinted_config(config), sort_keys=True,
                      default=str)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


def config_mismatch_fields(stored: Dict, config) -> List[str]:
    """Field names whose stored value differs from ``config``'s."""
    current = fingerprinted_config(config)
    keys = set(stored) | set(current)
    return sorted(k for k in keys
                  if stored.get(k, "<missing>") != current.get(k, "<missing>"))


class CheckpointManager:
    """Directory of atomically-written, monotonically-numbered snapshots."""

    def __init__(self, directory: str, keep_last_n: int = 3):
        if not directory:
            raise CheckpointError("checkpoint_dir is empty — set "
                                  "checkpoint_dir=... (docs/Fault-Tolerance.md)")
        if keep_last_n < 0:
            raise CheckpointError(f"keep_last_n must be >= 0, got {keep_last_n}")
        self.directory = directory
        self.keep_last_n = keep_last_n

    # ------------------------------------------------------------- listing

    def list_checkpoints(self) -> List[Tuple[int, str]]:
        """``[(checkpoint_id, path)]`` sorted ascending by id."""
        if not os.path.isdir(self.directory):
            return []
        out = []
        for name in os.listdir(self.directory):
            m = _FILE_RE.match(name)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.directory, name)))
        out.sort()
        return out

    def latest(self) -> Optional[str]:
        cks = self.list_checkpoints()
        return cks[-1][1] if cks else None

    # -------------------------------------------------------------- saving

    def save(self, payload: Dict) -> str:
        """Write one snapshot atomically; returns the final path. The write
        is a telemetry span + counter (``checkpoint.writes``): checkpoint
        cadence and cost show up next to the training spans they interleave
        with (docs/Observability.md)."""
        from .. import observability as _obs
        os.makedirs(self.directory, exist_ok=True)
        existing = self.list_checkpoints()
        ckpt_id = (existing[-1][0] + 1) if existing else 1
        payload = dict(payload)
        payload["format_version"] = FORMAT_VERSION
        payload["checkpoint_id"] = ckpt_id
        path = os.path.join(self.directory, f"ckpt_{ckpt_id:010d}.pkl")
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with _obs.span("checkpoint", checkpoint_id=ckpt_id,
                           iteration=payload.get("iteration")):
                raw = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
                header = _ENVELOPE.pack(ENVELOPE_MAGIC,
                                        zlib.crc32(raw) & 0xFFFFFFFF,
                                        len(raw))
                with open(tmp, "wb") as fh:
                    fh.write(header)
                    fh.write(raw)
                    fh.flush()
                    os.fsync(fh.fileno())
                os.replace(tmp, path)
                # make the RENAME durable too: the new directory entry lives
                # in the parent dir's metadata, which the file fsync above
                # does not cover — a crash here must not resurrect the old
                # directory state and lose the snapshot
                _fsync_dir(self.directory)
        except OSError as e:
            _obs.inc("checkpoint.write_failures")
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise CheckpointError(f"cannot write checkpoint {path}: {e}") from e
        _obs.inc("checkpoint.writes")
        self._prune()
        self._sweep_tmp()
        return path

    def _prune(self) -> None:
        if self.keep_last_n <= 0:
            return
        cks = self.list_checkpoints()
        for _id, path in cks[:-self.keep_last_n]:
            try:
                os.unlink(path)
            except OSError as e:
                Log.warning("cannot prune old checkpoint %s: %s", path, e)

    def _sweep_tmp(self) -> int:
        """Remove orphaned temp files from writers killed mid-snapshot
        (a ``kill -9`` during ``save`` leaves ``*.pkl.tmp.<pid>`` behind —
        never a half-written ``ckpt_*.pkl``). Returns how many were swept;
        the directory is fsynced after a sweep so the unlinks are durable."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return 0
        swept = 0
        for name in names:
            if ".pkl.tmp." in name:
                try:
                    os.unlink(os.path.join(self.directory, name))
                    swept += 1
                except OSError as e:
                    Log.debug("cannot sweep orphaned tmp %s: %s", name, e)
        if swept:
            Log.info("swept %d orphaned checkpoint tmp file(s) from %s "
                     "(a previous writer was killed mid-snapshot)",
                     swept, self.directory)
            _fsync_dir(self.directory)
        return swept

    # ------------------------------------------------------------- loading

    @staticmethod
    def resolve(path_or_dir: str) -> str:
        """A checkpoint file path, or the latest snapshot of a directory."""
        if os.path.isdir(path_or_dir):
            latest = CheckpointManager(path_or_dir).latest()
            if latest is None:
                raise CheckpointError(
                    f"no checkpoints (ckpt_*.pkl) found in {path_or_dir}")
            return latest
        if not os.path.exists(path_or_dir):
            raise CheckpointError(f"checkpoint {path_or_dir} does not exist")
        return path_or_dir

    def latest_verified(self) -> Optional[str]:
        """The newest snapshot that passes :func:`verify_checkpoint`,
        walking BACK through the lineage (``resume_from="auto"``): a
        truncated or bit-flipped latest costs one checkpoint interval
        instead of the run. Corrupt snapshots are skipped with a warning
        (and counted as ``fault.checkpoint_corrupt``) but left on disk for
        forensics. Returns None when the directory holds no snapshots at
        all; raises when snapshots exist but NONE verifies — silently
        retraining from scratch over an all-corrupt lineage is exactly the
        surprise this walk exists to prevent."""
        from .. import observability as _obs
        cks = self.list_checkpoints()
        for ckpt_id, path in reversed(cks):
            ok, detail = verify_checkpoint(path)
            if ok:
                return path
            _obs.inc("fault.checkpoint_corrupt")
            Log.warning("checkpoint %s failed verification (%s) — falling "
                        "back to the previous snapshot", path, detail)
        if cks:
            raise CheckpointError(
                f"all {len(cks)} snapshot(s) in {self.directory} failed "
                f"verification — refusing to silently retrain from scratch; "
                f"inspect with `python -m lightgbm_tpu.robustness.checkpoint "
                f"--verify {self.directory}` and delete the directory to "
                f"start fresh deliberately")
        return None

    @staticmethod
    def _read_payload_bytes(path: str) -> Tuple[bytes, bool]:
        """(payload bytes, had_envelope) — envelope parsed and CRC-verified
        when present; a pre-envelope file returns its raw bytes."""
        try:
            with open(path, "rb") as fh:
                data = fh.read()
        except OSError as e:
            raise CheckpointError(
                f"cannot read checkpoint {path}: {e}") from e
        if not data.startswith(ENVELOPE_MAGIC):
            # legacy bare pickle (pre-integrity-envelope) — no checksum to
            # check against; the pickle parse + schema checks still apply
            Log.debug("checkpoint %s predates the integrity envelope "
                      "(no checksum to verify)", path)
            return data, False
        if len(data) < _ENVELOPE.size:
            raise CheckpointError(
                f"{path} is shorter than its envelope header "
                f"(corrupt or truncated snapshot?)")
        _magic, crc, length = _ENVELOPE.unpack_from(data)
        raw = data[_ENVELOPE.size:]
        if len(raw) != length:
            raise CheckpointError(
                f"{path} payload is {len(raw)} bytes but the envelope "
                f"records {length} (corrupt or truncated snapshot?)")
        actual = zlib.crc32(raw) & 0xFFFFFFFF
        if actual != crc:
            raise CheckpointError(
                f"{path} failed its integrity check: payload crc32 "
                f"{actual:#010x} != recorded {crc:#010x} (corrupt or "
                f"truncated snapshot? bit rot?)")
        return raw, True

    @staticmethod
    def _validate_payload(raw: bytes, path: str) -> Dict:
        """Unpickle + schema-validate already-CRC-verified payload bytes."""
        try:
            payload = pickle.loads(raw)
        except (pickle.UnpicklingError, EOFError, AttributeError,
                ImportError, IndexError, ValueError, MemoryError) as e:
            raise CheckpointError(
                f"cannot load checkpoint {path}: {type(e).__name__}: {e} "
                f"(corrupt or truncated snapshot?)") from e
        if not isinstance(payload, dict) or "format_version" not in payload:
            raise CheckpointError(
                f"{path} is not a lightgbm_tpu checkpoint (no format_version)")
        if payload["format_version"] != FORMAT_VERSION:
            raise CheckpointError(
                f"{path} has format_version={payload['format_version']}; "
                f"this build reads version {FORMAT_VERSION}")
        for key in ("config_fingerprint", "config", "state", "iteration"):
            if key not in payload:
                raise CheckpointError(f"{path} is missing the {key!r} field "
                                      f"— corrupt snapshot?")
        return payload

    @staticmethod
    def load(path_or_dir: str) -> Dict:
        """Load, checksum-verify, and schema-validate one snapshot (fails
        loudly on truncation/corruption — a half-written or bit-flipped
        pickle must never resume)."""
        path = CheckpointManager.resolve(path_or_dir)
        raw, _ = CheckpointManager._read_payload_bytes(path)
        return CheckpointManager._validate_payload(raw, path)


# ------------------------------------------------------------- verification

def verify_checkpoint(path: str) -> Tuple[bool, str]:
    """Full integrity check of one snapshot FILE: envelope checksum,
    pickle parse, schema validation — one read of the file. Returns
    ``(ok, detail)`` — never raises, so lineage walks and the ``--verify``
    CLI can report every snapshot's state."""
    try:
        raw, had_envelope = CheckpointManager._read_payload_bytes(path)
        payload = CheckpointManager._validate_payload(raw, path)
    except CheckpointError as e:
        return False, str(e)
    detail = (f"iteration {payload.get('iteration')}, checkpoint_id "
              f"{payload.get('checkpoint_id')}")
    if not had_envelope:
        detail += " [legacy: no checksum envelope]"
    return True, detail


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m lightgbm_tpu.robustness.checkpoint --verify DIR|FILE``:
    audit every snapshot's integrity from the shell (jax-free — safe to run
    against a live training run's checkpoint directory).

    A directory holding gang epoch manifests (``manifest_*.json`` from
    ``robustness/distributed.py``) is audited at the MANIFEST level too:
    every listed shard must be present with the crc32 the manifest records.
    A manifest whose shard set disagrees is CORRUPT, and when no manifest
    verifies the gang has nothing consistent to resume from — exit 2 even
    if stray snapshot files happen to parse (a shard without its committed
    manifest is exactly the mixed-iteration resume the protocol forbids).

    Exit codes: 0 = every snapshot (and manifest) verifies; 1 = corrupt
    item(s) present but a verified resume target exists (named on stdout);
    2 = no usable snapshot (none found, or all corrupt)."""
    import argparse
    import sys
    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.robustness.checkpoint",
        description="Verify checkpoint snapshot integrity "
                    "(docs/Fault-Tolerance.md)")
    ap.add_argument("--verify", required=True, metavar="DIR_OR_FILE",
                    help="checkpoint directory (or one snapshot file)")
    args = ap.parse_args(argv)

    target = args.verify
    manifests = []
    if os.path.isfile(target):
        entries = [(None, target)]
    else:
        # gang manifests are audited lazily so the CLI stays jax-free and
        # single-process directories pay nothing for the import
        from .distributed import audit_manifest_dir
        manifests = audit_manifest_dir(target) if os.path.isdir(target) else []
        entries = CheckpointManager(target).list_checkpoints() \
            if os.path.isdir(target) else []
        if not entries and not manifests:
            print(f"no checkpoints (ckpt_*.pkl) or gang manifests "
                  f"(manifest_*.json) found under {target}", file=sys.stderr)
            return 2
    newest_ok, n_bad = None, 0
    for _ckpt_id, path in entries:
        ok, detail = verify_checkpoint(path)
        print(f"{os.path.basename(path):<24} "
              f"{'OK     ' if ok else 'CORRUPT'}  {detail}")
        if ok:
            newest_ok = path
        else:
            n_bad += 1
    if manifests:
        # gang semantics override loose files: the resume target is the
        # newest manifest whose WHOLE shard set verifies
        newest_ok = None
        for _epoch, path, ok, detail in manifests:
            print(f"{os.path.basename(path):<24} "
                  f"{'OK     ' if ok else 'CORRUPT'}  {detail}")
            if ok:
                newest_ok = path
            else:
                n_bad += 1
    if newest_ok is None:
        print("no verified %s — nothing to resume from"
              % ("gang manifest" if manifests else "snapshot"),
              file=sys.stderr)
        return 2
    print(f"resume target: {newest_ok}")
    return 1 if n_bad else 0


if __name__ == "__main__":
    raise SystemExit(main())
