"""Atomic checkpoint store for booster training state.

Layout: one pickle file per snapshot inside ``checkpoint_dir``,

    ckpt_0000000001.pkl
    ckpt_0000000002.pkl
    ...

with monotonically increasing checkpoint ids (the id is derived from the
files already present, so a resumed process keeps counting where the killed
one stopped). Writes are write-temp-then-``os.replace`` with an fsync in
between: a preemption mid-write can never leave a truncated file behind
that parses as a checkpoint — at worst an orphaned ``*.tmp.*`` that the
next save sweeps up. ``keep_last_n`` prunes old snapshots after every
successful save (0 keeps everything).

Each payload carries a **config fingerprint** — a SHA-256 over the
training-semantics subset of the Config — and resume fails loudly when the
fingerprint of the resuming booster differs, naming the mismatched fields.
Run-control fields (paths, verbosity, the checkpoint knobs themselves,
``num_iterations`` so a run can be resumed *longer*) are excluded from the
fingerprint.

The payload schema (``FORMAT_VERSION`` 1)::

    {"format_version": 1, "checkpoint_id": int,
     "config_fingerprint": str, "config": {trainable-subset dict},
     "iteration": int, "state": {GBDT.checkpoint_state()},
     "booster": {...}, "eval_history": {...}}
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
from typing import Dict, List, Optional, Tuple

from ..utils.log import Log

FORMAT_VERSION = 1

_FILE_RE = re.compile(r"^ckpt_(\d{10})\.pkl$")

# Config fields with no bearing on the trained model's content: two runs
# differing only here are resumable into each other. Everything else is
# fingerprinted — a silent objective/num_leaves/seed change across a resume
# is exactly the corruption this check exists to catch.
VOLATILE_CONFIG_FIELDS = frozenset({
    # run control / IO
    "task", "data", "valid_data", "init_score_file",
    "valid_init_score_file", "snapshot_freq", "output_model",
    "output_result", "convert_model", "convert_model_language",
    "input_model", "model_format", "num_iteration_predict",
    "is_predict_leaf_index", "is_predict_contrib", "is_predict_raw_score",
    "is_save_binary_file", "verbose", "num_threads",
    # resuming a run LONGER than originally planned is the point
    "num_iterations",
    # checkpointing's own knobs (tpu_reshard_on_resume included: it gates
    # HOW a resume re-lays-out state, not what the model trains to — the
    # device-count check itself lives in restore_checkpoint_state)
    "checkpoint_dir", "checkpoint_interval", "checkpoint_keep_last_n",
    "resume_from", "tpu_reshard_on_resume",
    # out-of-core transport knobs (docs/Fault-Tolerance.md "resume with a
    # different shard size"): residency and shard size change WHERE the
    # codes live and how they move, never the math — the shard size
    # divides the padded per-device rows, so chunk boundaries, the bagging
    # RNG shapes, and every histogram fold are identical across values.
    # The one behavioral coupling (stream forces tpu_row_compact=false) is
    # covered by tpu_row_compact itself staying fingerprinted.
    "tpu_residency", "tpu_stream_shard_rows", "tpu_hbm_budget_bytes",
    # cluster wiring: the restarted pod gets fresh addresses/ports
    "machines", "machine_list_file", "local_listen_port", "time_out",
    # profiling/telemetry (observability/: spans, exporters, profiler window)
    "tpu_time_tag", "tpu_profile_dir", "tpu_profile_iters", "telemetry_dir",
    # cost/memory introspection (observability/costs.py, snapshot dumps)
    "tpu_cost_analysis", "dump_snapshot",
})


class CheckpointError(RuntimeError):
    """A checkpoint could not be written, located, parsed, or validated."""


def fingerprinted_config(config) -> Dict:
    """The training-semantics subset of ``config`` that the fingerprint
    covers (and that is stored in the payload for mismatch diagnostics)."""
    return {k: v for k, v in config.to_dict().items()
            if k not in VOLATILE_CONFIG_FIELDS}


def config_fingerprint(config) -> str:
    """SHA-256 over the canonical JSON of the non-volatile config fields."""
    blob = json.dumps(fingerprinted_config(config), sort_keys=True,
                      default=str)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


def config_mismatch_fields(stored: Dict, config) -> List[str]:
    """Field names whose stored value differs from ``config``'s."""
    current = fingerprinted_config(config)
    keys = set(stored) | set(current)
    return sorted(k for k in keys
                  if stored.get(k, "<missing>") != current.get(k, "<missing>"))


class CheckpointManager:
    """Directory of atomically-written, monotonically-numbered snapshots."""

    def __init__(self, directory: str, keep_last_n: int = 3):
        if not directory:
            raise CheckpointError("checkpoint_dir is empty — set "
                                  "checkpoint_dir=... (docs/Fault-Tolerance.md)")
        if keep_last_n < 0:
            raise CheckpointError(f"keep_last_n must be >= 0, got {keep_last_n}")
        self.directory = directory
        self.keep_last_n = keep_last_n

    # ------------------------------------------------------------- listing

    def list_checkpoints(self) -> List[Tuple[int, str]]:
        """``[(checkpoint_id, path)]`` sorted ascending by id."""
        if not os.path.isdir(self.directory):
            return []
        out = []
        for name in os.listdir(self.directory):
            m = _FILE_RE.match(name)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.directory, name)))
        out.sort()
        return out

    def latest(self) -> Optional[str]:
        cks = self.list_checkpoints()
        return cks[-1][1] if cks else None

    # -------------------------------------------------------------- saving

    def save(self, payload: Dict) -> str:
        """Write one snapshot atomically; returns the final path. The write
        is a telemetry span + counter (``checkpoint.writes``): checkpoint
        cadence and cost show up next to the training spans they interleave
        with (docs/Observability.md)."""
        from .. import observability as _obs
        os.makedirs(self.directory, exist_ok=True)
        existing = self.list_checkpoints()
        ckpt_id = (existing[-1][0] + 1) if existing else 1
        payload = dict(payload)
        payload["format_version"] = FORMAT_VERSION
        payload["checkpoint_id"] = ckpt_id
        path = os.path.join(self.directory, f"ckpt_{ckpt_id:010d}.pkl")
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with _obs.span("checkpoint", checkpoint_id=ckpt_id,
                           iteration=payload.get("iteration")):
                with open(tmp, "wb") as fh:
                    pickle.dump(payload, fh,
                                protocol=pickle.HIGHEST_PROTOCOL)
                    fh.flush()
                    os.fsync(fh.fileno())
                os.replace(tmp, path)
        except OSError as e:
            _obs.inc("checkpoint.write_failures")
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise CheckpointError(f"cannot write checkpoint {path}: {e}") from e
        _obs.inc("checkpoint.writes")
        self._prune()
        self._sweep_tmp()
        return path

    def _prune(self) -> None:
        if self.keep_last_n <= 0:
            return
        cks = self.list_checkpoints()
        for _id, path in cks[:-self.keep_last_n]:
            try:
                os.unlink(path)
            except OSError as e:
                Log.warning("cannot prune old checkpoint %s: %s", path, e)

    def _sweep_tmp(self) -> None:
        """Remove orphaned temp files from writers killed mid-snapshot."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        for name in names:
            if ".pkl.tmp." in name:
                try:
                    os.unlink(os.path.join(self.directory, name))
                except OSError:
                    pass

    # ------------------------------------------------------------- loading

    @staticmethod
    def resolve(path_or_dir: str) -> str:
        """A checkpoint file path, or the latest snapshot of a directory."""
        if os.path.isdir(path_or_dir):
            latest = CheckpointManager(path_or_dir).latest()
            if latest is None:
                raise CheckpointError(
                    f"no checkpoints (ckpt_*.pkl) found in {path_or_dir}")
            return latest
        if not os.path.exists(path_or_dir):
            raise CheckpointError(f"checkpoint {path_or_dir} does not exist")
        return path_or_dir

    @staticmethod
    def load(path_or_dir: str) -> Dict:
        """Load and schema-validate one snapshot (fails loudly on
        truncation/corruption — a half-written pickle must never resume)."""
        path = CheckpointManager.resolve(path_or_dir)
        try:
            with open(path, "rb") as fh:
                payload = pickle.load(fh)
        except (OSError, pickle.UnpicklingError, EOFError, AttributeError,
                ImportError, IndexError, ValueError) as e:
            raise CheckpointError(
                f"cannot load checkpoint {path}: {type(e).__name__}: {e} "
                f"(corrupt or truncated snapshot?)") from e
        if not isinstance(payload, dict) or "format_version" not in payload:
            raise CheckpointError(
                f"{path} is not a lightgbm_tpu checkpoint (no format_version)")
        if payload["format_version"] != FORMAT_VERSION:
            raise CheckpointError(
                f"{path} has format_version={payload['format_version']}; "
                f"this build reads version {FORMAT_VERSION}")
        for key in ("config_fingerprint", "config", "state", "iteration"):
            if key not in payload:
                raise CheckpointError(f"{path} is missing the {key!r} field "
                                      f"— corrupt snapshot?")
        return payload
